"""Stdlib HTTP scaffolding — ONE home for the pod's wire servers
(ISSUE 14 satellite; transport hardening ISSUE 20).

Both network faces of the serving plane — the telemetry scrape surface
(``serve/telemetry.py``, ISSUE 12) and the gateway control plane
(``serve/gateway.py``, ISSUE 14) — are zero-dependency
``ThreadingHTTPServer`` daemons with the same obligations:

- **Quiet logs**: a wire surface must never block or spam the pod's
  stderr (``log_message`` is a no-op).
- **Send policy**: every response carries ``Content-Type`` +
  ``Content-Length``; a client that vanished mid-response
  (``BrokenPipeError``/``ConnectionResetError``) is swallowed, a handler
  bug is a 500 with the exception name in the body, never a wedged
  socket or a traceback-spew.
- **Ephemeral-port publish**: ``port=0`` binds an ephemeral port, and
  each server publishes its bound URL as an ``*.endpoint`` info label
  (``telemetry.endpoint`` / ``gateway.endpoint``) right after
  construction — a pod's own wire addresses belong in its telemetry,
  and with port 0 they are otherwise only knowable from inside (the
  PR-10 ``telemetry.endpoint`` contract, now shared).  Subclasses
  register the label with a literal name so the metric-docs lint
  (``tools/check_metric_docs.py``) sees it.
- **Bounded-time contract** (by construction, not enforcement):
  handlers compute from in-memory state — books, samples, handles —
  and never touch a device, take a session lock, or wait on a
  dispatch, so a wedged tenant can never hang a request.

Wire hardening (ISSUE 20; docs/API.md "Wire hardening") — three
optional knobs, each off (0/None) by default so every existing server
keeps its exact behavior until it arms them:

- ``read_timeout`` — per-connection socket read deadline.  A peer that
  trickles its request slower than the deadline (the slow-loris shape)
  is answered a best-effort ``408`` and reaped, counted on
  ``net.slowloris_reaped``.  WebSocket upgrades DISARM the reaper (the
  leg owns its own deadline/keepalive policy from there).
- ``body_cap`` — :func:`read_body`'s default Content-Length bound; an
  oversized declaration is a ``413`` (never a 500), counted on
  ``net.oversize_rejected``.
- ``max_connections`` — concurrent-connection bound; past it, a new
  connection is answered a raw ``503`` and closed before a handler
  thread is ever spawned, counted on ``net.connections_shed``.

Subclasses implement :meth:`handle`; everything above stays here
instead of growing a second hand-rolled copy per server.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from distributed_gol_tpu.obs import metrics as metrics_lib

#: Default Content-Length bound of :func:`read_body` when neither the
#: caller nor the server armed one (a 65536² board upload is ~0.5 GiB
#: of PGM; anything past 64 MiB through a control endpoint is a bug).
DEFAULT_BODY_CAP = 1 << 26


class BodyTooLarge(ValueError):
    """A request body whose declared length exceeds the cap — the
    routing layer answers 413 (and bumps ``net.oversize_rejected``)
    instead of the generic 500."""


class _ReapingFile:
    """The slow-loris reaper: wraps a handler's ``rfile`` so a read
    deadline expiring mid-request is COUNTED and answered a
    best-effort 408 before the stdlib's quiet TimeoutError close path
    runs.  :func:`ws.server_upgrade` disarms it — a WebSocket leg owns
    its own deadline/keepalive policy."""

    def __init__(self, inner, connection, on_timeout):
        self._inner = inner
        self._connection = connection
        self._on_timeout = on_timeout
        self.armed = True

    def disarm(self) -> None:
        self.armed = False

    def _reap(self) -> None:
        if not self.armed:
            return
        self.armed = False  # count one reap per connection
        self._on_timeout()
        try:
            self._connection.sendall(
                b"HTTP/1.1 408 Request Timeout\r\n"
                b"Content-Length: 0\r\nConnection: close\r\n\r\n"
            )
        except OSError:
            pass

    def readline(self, *args):
        try:
            return self._inner.readline(*args)
        except TimeoutError:
            self._reap()
            raise

    def read(self, *args):
        try:
            return self._inner.read(*args)
        except TimeoutError:
            self._reap()
            raise

    def readinto(self, b):
        try:
            return self._inner.readinto(b)
        except TimeoutError:
            self._reap()
            raise

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _BoundedThreadingHTTPServer(ThreadingHTTPServer):
    """ThreadingHTTPServer with an optional concurrent-connection
    bound: past ``gol_conn_slots``, a new connection gets a raw 503
    and is closed on the ACCEPT thread — no handler thread, no parse,
    no queue."""

    gol_conn_slots: threading.Semaphore | None = None
    gol_on_shed = None

    def process_request(self, request, client_address):
        slots = self.gol_conn_slots
        if slots is not None and not slots.acquire(blocking=False):
            if self.gol_on_shed is not None:
                self.gol_on_shed()
            try:
                request.sendall(
                    b"HTTP/1.1 503 Service Unavailable\r\n"
                    b"Content-Length: 0\r\nConnection: close\r\n\r\n"
                )
            except OSError:
                pass
            self.shutdown_request(request)
            return
        super().process_request(request, client_address)

    def process_request_thread(self, request, client_address):
        try:
            super().process_request_thread(request, client_address)
        finally:
            if self.gol_conn_slots is not None:
                self.gol_conn_slots.release()


class StdlibHTTPServer:
    """The scaffolding base: bind, serve from daemon threads, publish
    the endpoint, tear down.  ``request_counter`` (optional) is bumped
    once per request before routing — the ``telemetry.scrapes`` /
    ``gateway.requests`` families ride it.  ``read_timeout`` /
    ``body_cap`` / ``max_connections`` arm the wire hardening (module
    docstring); all default off."""

    #: Thread name of the accept loop; subclasses override.
    thread_name = "gol-http"

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry=None,
        request_counter=None,
        read_timeout: float | None = None,
        body_cap: int = DEFAULT_BODY_CAP,
        max_connections: int = 0,
    ):
        self.registry = (
            registry if registry is not None else metrics_lib.REGISTRY
        )
        self._request_counter = request_counter
        self._read_timeout = read_timeout if read_timeout else None
        self._body_cap = int(body_cap)
        # The wire-hardening families (ISSUE 20), one registration site
        # for every server that rides this scaffolding.
        self._m_slowloris = self.registry.counter("net.slowloris_reaped")
        self._m_oversize = self.registry.counter("net.oversize_rejected")
        self._m_conn_shed = self.registry.counter("net.connections_shed")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # A wire surface must never block on the pod's logs.
            def log_message(self, fmt, *args):  # noqa: ARG002
                pass

            def setup(self):
                super().setup()
                self.gol_body_cap = outer._body_cap
                if outer._read_timeout is not None:
                    self.connection.settimeout(outer._read_timeout)
                    self.rfile = _ReapingFile(
                        self.rfile,
                        self.connection,
                        outer._m_slowloris.inc,
                    )

            def _send(
                self, code: int, body: bytes, ctype: str, headers=()
            ) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for name, value in headers:
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, obj, headers=()) -> None:
                self._send(
                    code, json.dumps(obj).encode(), "application/json",
                    headers,
                )

            def do_GET(self):  # noqa: N802 — http.server contract
                outer._route(self, "GET")

            def do_POST(self):  # noqa: N802
                outer._route(self, "POST")

            def do_DELETE(self):  # noqa: N802
                outer._route(self, "DELETE")

        self._httpd = _BoundedThreadingHTTPServer((host, port), Handler)
        if max_connections:
            self._httpd.gol_conn_slots = threading.Semaphore(
                int(max_connections)
            )
            self._httpd.gol_on_shed = self._m_conn_shed.inc
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=self.thread_name,
            daemon=True,
        )
        self._thread.start()

    # -- routing ---------------------------------------------------------------
    def _route(self, request, method: str) -> None:
        if self._request_counter is not None:
            self._request_counter.inc()
        split = urlsplit(request.path)
        path = split.path.rstrip("/") or "/"
        query = {
            k: v[-1] for k, v in parse_qs(split.query).items()
        }
        try:
            if not self.handle(request, method, path, query):
                request._send(404, b"not found\n", "text/plain")
        except BodyTooLarge as e:
            self._m_oversize.inc()
            try:
                request._send_json(413, {"error": str(e)})
            except OSError:
                pass
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-response
        except TimeoutError:
            # The read deadline fired inside a handler's body read: the
            # reaper already counted it and answered 408 — re-raise so
            # the stdlib's handle_one_request closes the connection.
            raise
        except Exception as e:  # noqa: BLE001 — a handler bug is a 500
            body = f"{type(e).__name__}: {e}\n".encode()
            try:
                request._send(500, body, "text/plain")
            except OSError:
                pass

    def handle(self, request, method: str, path: str, query: dict) -> bool:
        """Route one request.  ``request`` is the live handler (use its
        ``_send`` / ``_send_json``; ``rfile``/``wfile``/``connection``
        for protocol upgrades).  Return False for "no such route" — the
        scaffolding sends the 404."""
        raise NotImplementedError

    # -- lifecycle -------------------------------------------------------------
    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_body(request, cap: int | None = None) -> bytes:
    """The request body per its Content-Length (empty when absent),
    refused past ``cap`` — a wire surface reads bounded input only.
    ``cap=None`` uses the server's armed ``body_cap`` (falling back to
    :data:`DEFAULT_BODY_CAP`); the refusal is a 413 through the
    routing layer (:class:`BodyTooLarge`), never a 500."""
    if cap is None:
        cap = getattr(request, "gol_body_cap", DEFAULT_BODY_CAP)
    length = int(request.headers.get("Content-Length") or 0)
    if length < 0 or length > cap:
        raise BodyTooLarge(
            f"request body of {length} bytes exceeds the {cap}-byte cap"
        )
    return request.rfile.read(length) if length else b""
