"""Stdlib HTTP scaffolding — ONE home for the pod's wire servers
(ISSUE 14 satellite).

Both network faces of the serving plane — the telemetry scrape surface
(``serve/telemetry.py``, ISSUE 12) and the gateway control plane
(``serve/gateway.py``, ISSUE 14) — are zero-dependency
``ThreadingHTTPServer`` daemons with the same obligations:

- **Quiet logs**: a wire surface must never block or spam the pod's
  stderr (``log_message`` is a no-op).
- **Send policy**: every response carries ``Content-Type`` +
  ``Content-Length``; a client that vanished mid-response
  (``BrokenPipeError``/``ConnectionResetError``) is swallowed, a handler
  bug is a 500 with the exception name in the body, never a wedged
  socket or a traceback-spew.
- **Ephemeral-port publish**: ``port=0`` binds an ephemeral port, and
  each server publishes its bound URL as an ``*.endpoint`` info label
  (``telemetry.endpoint`` / ``gateway.endpoint``) right after
  construction — a pod's own wire addresses belong in its telemetry,
  and with port 0 they are otherwise only knowable from inside (the
  PR-10 ``telemetry.endpoint`` contract, now shared).  Subclasses
  register the label with a literal name so the metric-docs lint
  (``tools/check_metric_docs.py``) sees it.
- **Bounded-time contract** (by construction, not enforcement):
  handlers compute from in-memory state — books, samples, handles —
  and never touch a device, take a session lock, or wait on a
  dispatch, so a wedged tenant can never hang a request.

Subclasses implement :meth:`handle`; everything above stays here
instead of growing a second hand-rolled copy per server.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit

from distributed_gol_tpu.obs import metrics as metrics_lib


class StdlibHTTPServer:
    """The scaffolding base: bind, serve from daemon threads, publish
    the endpoint, tear down.  ``request_counter`` (optional) is bumped
    once per request before routing — the ``telemetry.scrapes`` /
    ``gateway.requests`` families ride it."""

    #: Thread name of the accept loop; subclasses override.
    thread_name = "gol-http"

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry=None,
        request_counter=None,
    ):
        self.registry = (
            registry if registry is not None else metrics_lib.REGISTRY
        )
        self._request_counter = request_counter
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # A wire surface must never block on the pod's logs.
            def log_message(self, fmt, *args):  # noqa: ARG002
                pass

            def _send(
                self, code: int, body: bytes, ctype: str, headers=()
            ) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for name, value in headers:
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, code: int, obj, headers=()) -> None:
                self._send(
                    code, json.dumps(obj).encode(), "application/json",
                    headers,
                )

            def do_GET(self):  # noqa: N802 — http.server contract
                outer._route(self, "GET")

            def do_POST(self):  # noqa: N802
                outer._route(self, "POST")

            def do_DELETE(self):  # noqa: N802
                outer._route(self, "DELETE")

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=self.thread_name,
            daemon=True,
        )
        self._thread.start()

    # -- routing ---------------------------------------------------------------
    def _route(self, request, method: str) -> None:
        if self._request_counter is not None:
            self._request_counter.inc()
        split = urlsplit(request.path)
        path = split.path.rstrip("/") or "/"
        query = {
            k: v[-1] for k, v in parse_qs(split.query).items()
        }
        try:
            if not self.handle(request, method, path, query):
                request._send(404, b"not found\n", "text/plain")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-response
        except Exception as e:  # noqa: BLE001 — a handler bug is a 500
            body = f"{type(e).__name__}: {e}\n".encode()
            try:
                request._send(500, body, "text/plain")
            except OSError:
                pass

    def handle(self, request, method: str, path: str, query: dict) -> bool:
        """Route one request.  ``request`` is the live handler (use its
        ``_send`` / ``_send_json``; ``rfile``/``wfile``/``connection``
        for protocol upgrades).  Return False for "no such route" — the
        scaffolding sends the 404."""
        raise NotImplementedError

    # -- lifecycle -------------------------------------------------------------
    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_body(request, cap: int = 1 << 26) -> bytes:
    """The request body per its Content-Length (empty when absent),
    refused past ``cap`` — a wire surface reads bounded input only."""
    length = int(request.headers.get("Content-Length") or 0)
    if length < 0 or length > cap:
        raise ValueError(f"request body of {length} bytes exceeds the cap")
    return request.rfile.read(length) if length else b""
