"""Minimal RFC 6455 WebSocket — the streaming leg of the gateway
(ISSUE 14), in the same zero-dependency stdlib style as the
``ThreadingHTTPServer`` scrape surface (``serve/httpd.py``).

Scope: exactly what a pod's controller/spectator legs need —
server-side upgrade inside a ``BaseHTTPRequestHandler``, client-side
connect over a raw socket, text/binary messages, fragmented-message
assembly, auto-ponged pings, masked client frames (the RFC mandate),
bounded frame sizes, and a clean close handshake.  No extensions, no
subprotocol negotiation, no compression — a spectator stream's payload
is already delta-encoded (``engine/frames.py``).

Both ends of ``tools/gol_client.py`` ⇄ ``serve/gateway.py`` speak this
one codec, so the wire format cannot drift between them.
"""

from __future__ import annotations

import base64
import hashlib
import os
import socket
import struct
import threading

#: RFC 6455 §1.3 handshake GUID.
GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

OP_CONT = 0x0
OP_TEXT = 0x1
OP_BINARY = 0x2
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA

#: Frames over this are refused (a spectator keyframe of a 65536²
#: pooled viewport is far below it; anything bigger is a protocol bug).
MAX_PAYLOAD = 1 << 26


class WsClosed(ConnectionError):
    """The peer closed (or the socket died) — the detach signal."""


class WsTimeout(WsClosed):
    """The peer stopped SENDING without closing (recv deadline or
    keepalive budget exhausted) — a half-open connection.  Subclasses
    :class:`WsClosed` so every existing detach path already handles it;
    catch it first to count/react to stalls specifically (ISSUE 20)."""


def accept_key(key: str) -> str:
    """RFC 6455 §4.2.2: the Sec-WebSocket-Accept for a client key."""
    digest = hashlib.sha1((key + GUID).encode()).digest()
    return base64.b64encode(digest).decode()


def _mask(data, key) -> "bytes | bytearray":
    """XOR-mask ``data`` with the 4-byte ``key`` (involutive).  A
    ``bytearray`` is masked IN PLACE and returned — the receive path
    unmasks each payload inside the buffer it was read into, so a
    frame costs one allocation, not one per mask pass."""
    n = len(data)
    if not n:
        return data
    rep = (bytes(key) * (n // 4 + 1))[:n]
    word = int.from_bytes(data, "little") ^ int.from_bytes(rep, "little")
    if isinstance(data, bytearray):
        data[:] = word.to_bytes(n, "little")
        return data
    return word.to_bytes(n, "little")


def _frame_head(opcode: int, n: int, mask_bit: int) -> bytearray:
    """The frame header for an ``n``-byte payload (no mask key)."""
    head = bytearray([0x80 | opcode])
    if n < 126:
        head.append(mask_bit | n)
    elif n < 1 << 16:
        head.append(mask_bit | 126)
        head += struct.pack(">H", n)
    else:
        head.append(mask_bit | 127)
        head += struct.pack(">Q", n)
    return head


def encode_server_frame(opcode: int, payload) -> bytes:
    """One complete UNMASKED (server→client) frame: header + payload in
    a single buffer.  The relay's single-serialize/multi-write seam —
    encode the frame ONCE, then :meth:`WebSocket.send_raw` the same
    ``memoryview`` into every downstream socket.  Byte-identical to
    what ``_send`` puts on the wire from a server endpoint."""
    n = len(payload)
    if n > MAX_PAYLOAD:
        raise ValueError(f"payload of {n} bytes exceeds MAX_PAYLOAD")
    head = _frame_head(opcode, n, 0)
    head += payload
    return bytes(head)


class WebSocket:
    """One connected endpoint over buffered binary file objects
    (``rfile``/``wfile`` of an HTTP handler, or ``socket.makefile``
    pairs on the client).  ``send_*`` are thread-safe (one lock — the
    gateway's reader thread pongs while the pump thread streams);
    ``recv`` is single-consumer."""

    def __init__(
        self, rfile, wfile, *, mask: bool, sock=None,
        max_payload: int = MAX_PAYLOAD,
    ):
        self._r = rfile
        self._w = wfile
        self._mask_frames = mask
        self._sock = sock
        self._send_lock = threading.Lock()
        self._close_sent = False
        self.closed = False
        #: Inbound frame-size cap (outbound keeps the module constant —
        #: what WE send is already bounded by construction).
        self.max_payload = max_payload
        #: Keepalive state (:meth:`enable_keepalive`): 0 = off.
        self._keepalive_seconds = 0.0
        self._keepalive_misses = 3
        self._keepalive_budget = 0
        self._mid_frame = False

    # -- send ------------------------------------------------------------------
    def send_text(self, text: str) -> int:
        return self._send(OP_TEXT, text.encode())

    def send_binary(self, payload) -> int:
        return self._send(OP_BINARY, payload)

    def ping(self, payload: bytes = b"") -> None:
        self._send(OP_PING, payload)

    def send_raw(self, frame) -> int:
        """Write a pre-encoded frame (:func:`encode_server_frame`)
        verbatim — the multi-write half of the relay's
        single-serialize/multi-write fan-out.  Only legal on an
        unmasked (server) endpoint: a masked one needs a fresh key —
        and a fresh serialization — per frame."""
        if self._mask_frames:
            raise ValueError("send_raw requires an unmasked (server) "
                             "endpoint")
        with self._send_lock:
            if self.closed:
                raise WsClosed("websocket is closed")
            try:
                self._w.write(frame)
                self._w.flush()
            except (OSError, ValueError) as e:
                self.closed = True
                raise WsClosed(f"send failed: {e}") from e
        return len(frame)

    def _send(self, opcode: int, payload) -> int:
        n = len(payload)
        if n > MAX_PAYLOAD:
            raise ValueError(f"payload of {n} bytes exceeds MAX_PAYLOAD")
        head = _frame_head(opcode, n, 0x80 if self._mask_frames else 0)
        if self._mask_frames:
            key = os.urandom(4)
            head += key
            # Mask a COPY (bytes in, bytes out): the caller's buffer is
            # not ours to scramble, even involutively.
            payload = _mask(bytes(payload), key)
        with self._send_lock:
            if self.closed:
                raise WsClosed("websocket is closed")
            try:
                # Two buffered writes, one flush: no header+payload
                # concatenation copy on the hot path.
                self._w.write(head)
                if n:
                    self._w.write(payload)
                self._w.flush()
            except (OSError, ValueError) as e:
                self.closed = True
                raise WsClosed(f"send failed: {e}") from e
        return n

    # -- receive ---------------------------------------------------------------
    def enable_keepalive(self, seconds: float, misses: int = 3) -> None:
        """Arm recv-deadline keepalive: :meth:`recv` blocks at most
        ``seconds`` per read; a timeout at a frame BOUNDARY sends a
        ping and keeps waiting, and after ``misses`` consecutive
        silent intervals (no frame of any kind — a live peer's auto-
        pong answers well inside one) raises :class:`WsTimeout` — the
        stalled-not-closed peer detected within ``seconds * misses``.
        A timeout MID-frame raises immediately (a peer that died
        between a header and its payload is not coming back).  The
        socket timeout also bounds sends, so a peer that stops READING
        cannot park a sender forever either."""
        if seconds <= 0:
            raise ValueError("keepalive seconds must be positive")
        if misses < 1:
            raise ValueError("keepalive misses must be >= 1")
        self._keepalive_seconds = seconds
        self._keepalive_misses = misses
        self._keepalive_budget = misses
        self.settimeout(seconds)

    def disable_keepalive(self) -> None:
        """Suspend the keepalive machinery (an explicit
        ``settimeout`` poll owns the deadline from here); the
        configuration is remembered — :attr:`keepalive` still reports
        it, and :meth:`enable_keepalive` re-arms."""
        self._keepalive_budget = 0

    @property
    def keepalive(self) -> tuple[float, int] | None:
        """The configured ``(seconds, misses)``, or None if keepalive
        was never armed — how a caller that interleaves explicit
        ``settimeout`` polls re-arms the stream's standing policy."""
        if self._keepalive_seconds > 0:
            return (self._keepalive_seconds, self._keepalive_misses)
        return None

    def recv(self) -> tuple[int, bytes]:
        """The next complete MESSAGE as ``(opcode, payload)`` —
        fragments assembled, pings auto-ponged, pongs swallowed.  A
        close frame (or socket EOF) raises :class:`WsClosed` after
        echoing the close handshake; a recv deadline past the
        keepalive budget (:meth:`enable_keepalive`) raises
        :class:`WsTimeout`."""
        opcode, buf = None, b""
        silent = 0
        while True:
            try:
                op, fin, payload = self._read_frame()
            except WsTimeout:
                if not self._keepalive_budget or self._mid_frame:
                    self.closed = True
                    raise
                silent += 1
                if silent >= self._keepalive_budget:
                    self.closed = True
                    raise WsTimeout(
                        f"keepalive timeout: no frame in "
                        f"{silent * self._keepalive_seconds:g}s"
                    ) from None
                try:
                    self.ping()
                except WsClosed:
                    raise WsTimeout("keepalive ping failed") from None
                continue
            silent = 0
            if op == OP_PING:
                try:
                    self._send(OP_PONG, payload)
                except WsClosed:
                    pass
                continue
            if op == OP_PONG:
                continue
            if op == OP_CLOSE:
                self.close()
                raise WsClosed("peer closed")
            if op in (OP_TEXT, OP_BINARY):
                opcode, buf = op, payload
            elif op == OP_CONT and opcode is not None:
                buf += payload
            else:
                raise WsClosed(f"protocol error: unexpected opcode {op:#x}")
            if fin:
                return opcode, buf

    def _read_frame(self) -> tuple[int, bool, bytes]:
        self._mid_frame = False
        head = self._read_exact(2)
        self._mid_frame = True  # header started: a stall now is fatal
        try:
            fin = bool(head[0] & 0x80)
            op = head[0] & 0x0F
            if head[0] & 0x70:
                # RSV bits without a negotiated extension (we negotiate
                # none) are a protocol error, not garbage to forward.
                raise WsClosed(
                    f"protocol error: reserved bits set ({head[0]:#04x})"
                )
            masked = bool(head[1] & 0x80)
            n = head[1] & 0x7F
            if op >= OP_CLOSE and (not fin or n > 125):
                # RFC 6455 §5.5: control frames must be unfragmented
                # with payloads <= 125 bytes.
                raise WsClosed(
                    f"protocol error: fragmented/oversized control "
                    f"frame ({op:#x})"
                )
            if n == 126:
                n = struct.unpack(">H", self._read_exact(2))[0]
            elif n == 127:
                n = struct.unpack(">Q", self._read_exact(8))[0]
            if n > self.max_payload:
                raise WsClosed(
                    f"frame of {n} bytes exceeds the {self.max_payload}"
                    f"-byte cap"
                )
            key = self._read_exact(4) if masked else None
            payload = self._read_exact(n)
            if key is not None:
                payload = _mask(payload, key)  # in place: payload is ours
            return op, fin, payload
        finally:
            self._mid_frame = False

    def _read_exact(self, n: int) -> bytearray:
        """Read exactly ``n`` bytes into ONE preallocated buffer
        (``readinto`` over a memoryview) — the unmask pass then runs in
        place, so a received frame costs a single payload-sized
        allocation end to end.  A socket deadline expiring raises
        :class:`WsTimeout` WITHOUT poisoning the endpoint (the
        keepalive path resumes reading); any other failure closes."""
        out = bytearray(n)
        view = memoryview(out)
        got = 0
        while got < n:
            try:
                k = self._r.readinto(view[got:])
            except TimeoutError as e:
                if got:
                    # A torn read: bytes arrived, then silence — the
                    # peer died mid-frame; keepalive must not resume
                    # into a misaligned stream.
                    self._mid_frame = True
                # CPython's SocketIO poisons itself after one timeout
                # (every later read raises "cannot read from timed out
                # object") — clear the flag so the keepalive path can
                # actually resume reading after its ping.
                raw = getattr(self._r, "raw", None)
                if getattr(raw, "_timeout_occurred", False):
                    raw._timeout_occurred = False
                raise WsTimeout(f"read deadline expired: {e}") from e
            except (OSError, ValueError) as e:
                self.closed = True
                raise WsClosed(f"read failed: {e}") from e
            if not k:
                self.closed = True
                raise WsClosed("socket EOF")
            got += k
        return out

    # -- lifecycle -------------------------------------------------------------
    def settimeout(self, seconds: float | None) -> None:
        if self._sock is not None:
            self._sock.settimeout(seconds)

    def abort(self) -> None:
        """Hard-close the underlying socket, no close handshake — the
        only way another thread can unblock a reader parked in
        :meth:`recv` (the relay's teardown, and how the chaos suite
        kills an upstream mid-stream).  Idempotent."""
        self.closed = True
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._sock.close()
            except OSError:
                pass

    def close(self, code: int = 1000) -> None:
        """Send the close frame (once) and mark the endpoint closed.
        Idempotent; safe from any thread."""
        with self._send_lock:
            if self._close_sent:
                self.closed = True
                return
            self._close_sent = True
            try:
                payload = struct.pack(">H", code)
                head = bytearray([0x80 | OP_CLOSE])
                if self._mask_frames:
                    key = os.urandom(4)
                    head += bytes([0x80 | len(payload)]) + key
                    payload = _mask(payload, key)
                else:
                    head.append(len(payload))
                self._w.write(bytes(head) + payload)
                self._w.flush()
            except (OSError, ValueError):
                pass
            self.closed = True


# -- server side ---------------------------------------------------------------

def server_upgrade(request, max_payload: int = MAX_PAYLOAD) -> WebSocket | None:
    """Upgrade a live ``BaseHTTPRequestHandler`` request to a WebSocket
    (RFC 6455 §4.2).  Returns the server-side endpoint, or None after
    answering 400 when the request is not a well-formed upgrade.  The
    caller owns the connection from here on and must not send a normal
    HTTP response."""
    upgrade = (request.headers.get("Upgrade") or "").lower()
    key = request.headers.get("Sec-WebSocket-Key")
    if upgrade != "websocket" or not key:
        request._send(400, b"websocket upgrade required\n", "text/plain")
        return None
    response = (
        "HTTP/1.1 101 Switching Protocols\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Accept: {accept_key(key)}\r\n"
        "\r\n"
    )
    request.wfile.write(response.encode())
    request.wfile.flush()
    request.close_connection = True  # the socket is ours until EOF
    # The HTTP layer's read deadline / slow-loris reaper stops at the
    # upgrade boundary: a WebSocket leg owns its own deadline/keepalive
    # policy (enable_keepalive / settimeout) from here on.
    disarm = getattr(request.rfile, "disarm", None)
    if disarm is not None:
        disarm()
    try:
        request.connection.settimeout(None)
    except OSError:
        pass
    return WebSocket(
        request.rfile, request.wfile, mask=False,
        sock=request.connection, max_payload=max_payload,
    )


# -- client side ---------------------------------------------------------------

def client_connect(
    host: str,
    port: int,
    path: str,
    timeout: float = 30.0,
    recv_buffer: int | None = None,
) -> WebSocket:
    """Dial ``ws://host:port{path}``: TCP connect, upgrade handshake,
    verified accept key.  Client frames are masked per the RFC.
    ``recv_buffer`` pins SO_RCVBUF before connecting (how the chaos
    tests simulate a slow consumer deterministically)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    if recv_buffer is not None:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, recv_buffer)
    sock.settimeout(timeout)
    try:
        sock.connect((host, port))
    except BaseException:
        sock.close()
        raise
    key = base64.b64encode(os.urandom(16)).decode()
    req = (
        f"GET {path} HTTP/1.1\r\n"
        f"Host: {host}:{port}\r\n"
        "Upgrade: websocket\r\n"
        "Connection: Upgrade\r\n"
        f"Sec-WebSocket-Key: {key}\r\n"
        "Sec-WebSocket-Version: 13\r\n"
        "\r\n"
    )
    rfile = sock.makefile("rb")
    wfile = sock.makefile("wb")
    try:
        wfile.write(req.encode())
        wfile.flush()
        status = rfile.readline(4096).decode("latin-1")
        if " 101 " not in status:
            raise WsClosed(f"upgrade refused: {status.strip()!r}")
        accept = None
        while True:
            line = rfile.readline(4096).decode("latin-1")
            if line in ("\r\n", "\n", ""):
                break
            name, _, value = line.partition(":")
            if name.strip().lower() == "sec-websocket-accept":
                accept = value.strip()
        if accept != accept_key(key):
            raise WsClosed("handshake accept-key mismatch")
    except BaseException:
        sock.close()
        raise
    return WebSocket(rfile, wfile, mask=True, sock=sock)


__all__ = [
    "MAX_PAYLOAD",
    "WebSocket",
    "WsClosed",
    "WsTimeout",
    "accept_key",
    "client_connect",
    "encode_server_frame",
    "server_upgrade",
]
