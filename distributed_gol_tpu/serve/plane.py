"""The fault-isolated multi-tenant serving plane (ISSUE 6 tentpole).

The reference system's broker is a long-lived process many controllers
come and go against (``Broker.Publish/Pause/CheckStates/Quit``,
controller detach/resume — PAPER.md §1); its rebuild so far served ONE
run at a time.  :class:`ServePlane` lifts the PR-2/PR-5 resilience
ladder one level: one pod process multiplexes N independent sessions —
each with its own board, :class:`~distributed_gol_tpu.engine.params.
Params`, scoped checkpoint directory, and event stream — with
robustness as the headline contract:

- **Admission control + backpressure** (``serve/admission.py``): a
  capacity budget with bounded queues and explicit load-shedding
  (:class:`AdmissionRejected` with a retry-after hint — never an
  unbounded queue, never an OOM), plus per-session deadlines that
  propagate into the existing dispatch watchdog
  (``Params.dispatch_deadline_seconds``).
- **Per-session fault isolation**: every session runs under its own
  controller/supervisor ladder on its own worker; one tenant's terminal
  ``DispatchError``/``CorruptionDetected``/restart-exhaustion parks
  *that* session (checkpoint + flight record in its scoped directory)
  while every other tenant keeps dispatching — no cross-tenant abort,
  no pod exit (asserted by the chaos matrix, ``tests/test_serve.py``).
- **Graceful pod drain**: SIGTERM (``install``) stops admissions, sheds
  the waiting queue, routes the PR-5 ``GracefulStop`` latch into every
  resident session — each emergency-checkpoints through the existing
  ``Controller._checkpoint_now`` path (fsync-durable) and exits
  paused-and-resumable — and the pod exits cleanly; a restarted pod
  re-adopts every tenant via the ``Session.check_states`` scan.
- **Health surface** (:meth:`ServePlane.health`): readiness/liveness
  derived from the obs registry (watchdog fires, supervisor restarts,
  queue depths, per-tenant ``tenant=`` metric labels) so an external
  balancer can eject a sick pod.

Concurrency shape: an asyncio loop (one daemon thread) owns session
lifecycle — admission hand-off, slot scheduling, completion — while the
blocking controller runs execute on a bounded executor
(``max_sessions`` workers).  The public API is thread-safe and
synchronous (``submit``/``drain``/``health``); an async network
front-end (ROADMAP item 1's HTTP/WebSocket face) plugs into the same
loop.  The **scheduler seam** is :meth:`ServePlane._launch`: today it
maps one admitted session onto one worker thread; the ROADMAP's
batched-board vmap lever replaces its body with a shared batched
dispatcher (grouping same-shape boards into one device launch) without
touching the admission, isolation, drain, or health contracts.
"""

from __future__ import annotations

import asyncio
import json
import queue
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from dataclasses import replace
from pathlib import Path
from typing import Callable, Optional

from distributed_gol_tpu.engine import gol
from distributed_gol_tpu.engine.events import (
    CheckpointSaved,
    DispatchError,
    EventQueue,
    FinalTurnComplete,
    MetricsReport,
    TurnComplete,
    TurnsCompleted,
)
from distributed_gol_tpu.engine.params import Params
from distributed_gol_tpu.engine.session import Session
from distributed_gol_tpu.engine.supervisor import GracefulStop
from distributed_gol_tpu.obs import flight as flight_lib
from distributed_gol_tpu.obs import metrics as metrics_lib
from distributed_gol_tpu.obs import tracing
from distributed_gol_tpu.obs.slo import SLOTracker
from distributed_gol_tpu.obs.timeseries import TelemetrySampler
from distributed_gol_tpu.parallel import mesh as mesh_lib
from distributed_gol_tpu.serve.admission import (
    ADMIT_RUN,
    AdmissionController,
    AdmissionRejected,
    ServeConfig,
)

#: Handle lifecycle: ``queued`` → ``running`` → one terminal state.
TERMINAL_STATES = ("completed", "parked", "drained", "failed", "shed")


class SessionHandle:
    """One tenant's run through the plane: identity, live status, the
    event stream, and the terminal digest.

    ``events`` is the session's own stream (the per-tenant analog of the
    reference's one events channel).  When the submitter brought a queue
    it owns draining it (the plane only TEES the producer side through
    the digest — see :class:`_DigestTee`); otherwise the stream reduces
    inline to the digest at the producer and retains nothing
    (:class:`_DigestSink`).  Either way the **digest** fields (``final``,
    ``report``, ``errors``, ``checkpoint_turns``, ``last_turn``) are
    populated — they are what the drain receipt and terminal
    classification read — and bounded, so a session's events can never
    grow the pod's memory.  The stream is guaranteed to end with the
    ``None`` sentinel (possibly one extra trailing sentinel on
    plane-terminated paths — consumers stop at the first, so it is
    invisible to the standard drain loop)."""

    # Caps on retained digest entries — a postmortem tail, not an
    # unbounded log (the digest's whole point is O(1) memory/session):
    # first 32 DispatchErrors, last 32 checkpoint turns.
    _MAX_ERRORS = 32
    _MAX_CHECKPOINTS = 32

    def __init__(
        self,
        tenant: str,
        params: Params,
        session: Session,
        events: queue.Queue,
        owns_events: bool,
    ):
        self.tenant = tenant
        self.params = params
        self.session = session
        self.events = events
        #: Optional control/streaming seams (ISSUE 14): a keyboard-
        #: equivalent key queue routed into the controller (the wire
        #: gateway's pause/resume/quit leg) and a FramePlane the run
        #: publishes every rendered turn to (the spectator leg).
        self.keys: queue.Queue | None = None
        self.frame_plane = None
        #: The request trace (ISSUE 15): created (or accepted from the
        #: gateway's ``traceparent`` handling) at submit, activated on
        #: the worker context for the whole run, ended at terminal
        #: classification.  Always present on plane-submitted sessions.
        self.trace = None
        self._submit_ns = 0  # tracing clock at submit (queue-wait span)
        self._h_qwait = None  # the tenant's queue-wait SLI histogram
        self.stop = GracefulStop()
        self.status = "queued"
        #: The admission verdict at submit time ("run" = a slot was
        #: free, "queue" = parked in the bounded wait queue) — stable,
        #: unlike ``status``, which advances as the session runs.
        self.admitted_as = "run"
        self.error: str | None = None
        #: Whether a fresh run on this tenant's session would resume
        #: (a paused checkpoint is parked) — truthful in every terminal
        #: state, including ``failed``.
        self.resumable = False
        self.t_submit = time.perf_counter()
        self.t_start: float | None = None
        self.t_end: float | None = None
        # -- digest (populated only when the plane owns the stream) --
        self.final: FinalTurnComplete | None = None
        self.report: MetricsReport | None = None
        self.errors: list[DispatchError] = []
        self.checkpoint_turns: deque[int] = deque(maxlen=self._MAX_CHECKPOINTS)
        self.last_turn = 0
        self._owns_events = owns_events
        self._done = threading.Event()
        self._backend = None
        self._backend_factory = None

    @property
    def done(self) -> bool:
        return self.status in TERMINAL_STATES

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the session reaches a terminal state."""
        return self._done.wait(timeout)

    @property
    def duration(self) -> float | None:
        """Running wall-clock (start → terminal), excluding queue wait."""
        if self.t_start is None or self.t_end is None:
            return None
        return self.t_end - self.t_start

    def _finish(self, status: str, error: str | None = None) -> None:
        self.status = status
        if error is not None:
            self.error = error
        if self.t_end is None and self.t_start is not None:
            self.t_end = time.perf_counter()
        self.resumable = self.session.paused
        # A caller-owned stream never fed the digest, so ``last_turn``
        # would read 0 however far the run got — the parked checkpoint's
        # turn is the progress oracle the drain receipt needs.
        parked = self.session.parked_turn
        if parked is not None:
            self.last_turn = max(self.last_turn, parked)
        self._done.set()

    def _digest(self, event) -> None:
        if isinstance(event, (TurnComplete, TurnsCompleted)):
            self.last_turn = event.completed_turns
        elif isinstance(event, FinalTurnComplete):
            self.final = event
            self.last_turn = event.completed_turns
        elif isinstance(event, MetricsReport):
            self.report = event
        elif isinstance(event, DispatchError):
            if len(self.errors) < self._MAX_ERRORS:
                self.errors.append(event)
        elif isinstance(event, CheckpointSaved):
            self.checkpoint_turns.append(event.completed_turns)

    def __repr__(self) -> str:
        return (
            f"SessionHandle(tenant={self.tenant!r}, status={self.status!r}, "
            f"turn={self.last_turn}, resumable={self.resumable})"
        )


class _DigestSink(EventQueue):
    """The PLANE-owned event stream (ISSUE 8 serving-overhead fix):
    events digest inline at the producer and are retained nowhere —
    the bounded digest was always the only consumer of an unwatched
    stream, and the PR-6 drain thread that consumed it cost one extra
    thread plus a wakeup per event per session (measurable GIL churn
    at n16 with batched cohorts).  Subclasses :class:`EventQueue` so
    the controller keeps its one-entry ``put_turns`` batching; a
    caller-supplied queue still gets the :class:`_DigestTee` treatment
    (every event forwarded)."""

    def __init__(self, handle: SessionHandle):
        super().__init__()
        self._handle = handle

    def put(self, item, block: bool = True, timeout: float | None = None):
        if item is None:
            # The terminal sentinel IS retained: a consumer waiting on a
            # plane-owned stream (the shed-queue contract promises them
            # a terminated stream) still observes the end.
            super().put(item, block, timeout)
        else:
            self._handle._digest(item)

    def put_turns(self, first: int, last: int) -> None:
        if last >= first:
            self._handle.last_turn = last


class _DigestTee(EventQueue):
    """Producer-side wrapper around a CALLER-owned event queue: digests
    every event into the handle, then forwards it to the caller's queue
    untouched — the drain receipt and terminal classification see the
    run's progress without the plane consuming a stream it does not own.

    Subclasses :class:`EventQueue` so the controller keeps batching
    TurnComplete ranges (``put_turns``) when the caller's queue can
    expand them; a caller bringing a plain ``queue.Queue`` gets the
    per-event fallback, exactly as if it were handed to ``gol.run``
    directly.  Only the producer side is ever used (the caller reads
    their own queue object)."""

    def __init__(self, handle: SessionHandle, inner: queue.Queue):
        super().__init__()
        self._handle = handle
        self._inner = inner

    def put(self, item, block: bool = True, timeout: float | None = None):
        if item is not None:
            self._handle._digest(item)
        self._inner.put(item, block, timeout)

    def put_turns(self, first: int, last: int) -> None:
        if last >= first:
            self._handle.last_turn = last
        if isinstance(self._inner, EventQueue):
            self._inner.put_turns(first, last)
        else:
            for t in range(first, last + 1):
                self._inner.put(TurnComplete(t))

    def qsize(self) -> int:
        return self._inner.qsize()

    def empty(self) -> bool:
        return self._inner.empty()


class ServePlane:
    """The pod: N tenants, one backend process, robustness contracts as
    in the module doc.  Use as a context manager (``close`` drains)."""

    def __init__(
        self,
        config: ServeConfig | None = None,
        checkpoint_root: str | Path | None = None,
        metrics: bool = True,
    ):
        self.config = config if config is not None else ServeConfig()
        self._root = Path(checkpoint_root) if checkpoint_root else None
        self._lock = threading.Lock()
        self._state = threading.Condition(self._lock)
        self._admission = AdmissionController(self.config)
        # Batched dispatch cohorts (ISSUE 8): the coalescer that groups
        # resident same-key sessions into shared launches.  None = the
        # PR-6 solo-launch plane, byte-for-byte.
        self.batcher = None
        if self.config.batched:
            from distributed_gol_tpu.serve.batcher import CohortBatcher

            self.batcher = CohortBatcher(self.config, metrics=metrics)
        self._handles: dict[str, SessionHandle] = {}  # latest per tenant
        # Pre-drain hooks (ISSUE 14): callables invoked at the top of
        # begin_drain, BEFORE admissions close and the queue sheds —
        # how the network gateway stops accepting wire submissions
        # before the pod starts refusing them (install() SIGTERM closes
        # the gateway first).  Hooks must be fast, non-blocking, and
        # idempotent (see add_drain_hook).
        self._drain_hooks: list[Callable[[], None]] = []
        # Terminal handles in completion order — the eviction ring that
        # keeps a churning-tenant pod's memory bounded (``_on_done``).
        self._retired: deque[tuple[str, SessionHandle]] = deque()
        self._closed = False
        # -- observability (the health surface's substrate) --
        self.metrics = metrics_lib.registry_for(metrics)
        self._metrics_start = self.metrics.snapshot(include_lazy=False)
        self._c_admitted = self.metrics.counter("serve.admitted")
        self._c_rejected = self.metrics.counter("serve.rejected")
        self._c_drains = self.metrics.counter("serve.drains")
        self._c_outcome = {
            s: self.metrics.counter(f"serve.sessions_{s}")
            for s in TERMINAL_STATES
        }
        self._g_resident = self.metrics.gauge("serve.resident_sessions")
        self._g_queued = self.metrics.gauge("serve.queued_sessions")
        self._g_cells = self.metrics.gauge("serve.resident_cells")
        self._g_resident.set(0)
        self._g_queued.set(0)
        self._g_cells.set(0)
        # -- continuous telemetry + SLOs (ISSUE 12) --
        # The plane-level flight ring: SLO alert transitions land here
        # (``slo_alert``/``slo_resolved``), introspectable via
        # ``plane.flight.records()`` — distinct from the per-session
        # rings each controller dumps on ITS terminal path.
        self.flight = flight_lib.FlightRecorder(256 if metrics else 0)
        # Request-scoped tracing (ISSUE 15): the plane applies its
        # config's knobs to the process-wide store — sampling rate,
        # /traces ring depth, per-trace span cap.  (One store per
        # process; the last-constructed plane's config wins, like the
        # registry's process-wide instruments.)
        tracing.TRACER.configure(
            sample_rate=self.config.trace_sample_rate,
            ring_depth=self.config.trace_ring_depth,
            max_spans=self.config.trace_max_spans,
        )
        self.slo: SLOTracker | None = None
        objectives = self.config.slo_objectives()
        if metrics and objectives is not None:
            self.slo = SLOTracker(objectives, self.metrics, self.flight)
        self.sampler: TelemetrySampler | None = None
        if metrics and self.config.telemetry_sample_seconds > 0:
            self.sampler = TelemetrySampler(
                registry=self.metrics,
                interval=self.config.telemetry_sample_seconds,
                depth=self.config.telemetry_ring_depth,
                lazy_every=self.config.telemetry_lazy_every,
                on_sample=self._on_sample,
            ).start()
        # -- the asyncio control plane --
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(
            target=self._loop.run_forever, name="gol-serve-plane", daemon=True
        )
        self._loop_thread.start()
        self._executor = ThreadPoolExecutor(
            max_workers=self.config.max_sessions,
            thread_name_prefix="gol-serve-run",
        )

    # -- context manager -------------------------------------------------------
    def __enter__(self) -> "ServePlane":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- admission (leg 1) -----------------------------------------------------
    def submit(
        self,
        tenant: str,
        params: Params,
        events: queue.Queue | None = None,
        deadline_seconds: float | None = None,
        backend=None,
        backend_factory: Optional[Callable] = None,
        keys: queue.Queue | None = None,
        frame_plane=None,
        trace=None,
    ) -> SessionHandle:
        """Admit one session or shed it (:class:`AdmissionRejected`).

        Never blocks on capacity: the decision is immediate — run now,
        wait in the bounded queue, or reject with a retry-after hint.
        ``deadline_seconds`` (or the config default) propagates into the
        session's ``Params.dispatch_deadline_seconds`` watchdog, so a
        wedged dispatch surfaces as that tenant's own ``DispatchTimeout``
        instead of silently pinning a pod worker.  ``backend`` /
        ``backend_factory`` are the chaos seams (``testing/faults``).

        ``keys`` (ISSUE 14) is a keyboard-equivalent control queue
        routed into the session's controller — 'p'/'q'/'k' semantics
        exactly as the CLI viewer's listener; ``frame_plane`` attaches
        a spectator fan-out hub the run publishes every rendered turn
        to (frame-mode sessions only — see ``serve/frames.py``).  Both
        are how the network gateway drives a resident session.

        ``trace`` (ISSUE 15) is the request's ``obs.tracing.Trace`` —
        the gateway creates it from the inbound ``traceparent`` so the
        wire-handling span precedes admission; direct submitters get one
        minted here.  The plane OWNS its end: the admission verdict is a
        span, queue wait is a span + the ``sli.queue_wait_seconds``
        SLI, the whole run is activated under it, and terminal
        classification ends it (failure/watchdog/restart traces are
        tail-retained regardless of head sampling)."""
        overrides: dict = {"tenant": tenant}
        if deadline_seconds is not None:
            # An explicit per-request deadline always wins.
            overrides["dispatch_deadline_seconds"] = deadline_seconds
        elif (
            self.config.default_deadline_seconds
            and not params.dispatch_deadline_seconds
        ):
            # The config default applies only to sessions that did not
            # bring their own (admission.py's documented contract).
            overrides["dispatch_deadline_seconds"] = (
                self.config.default_deadline_seconds
            )
        if params.tenant is not None and params.tenant != tenant:
            raise ValueError(
                f"params.tenant {params.tenant!r} contradicts the "
                f"submission tenant {tenant!r}"
            )
        params = replace(params, **overrides)
        cells = params.image_width * params.image_height
        if trace is None:
            trace = tracing.TRACER.start_trace("gol.request", tenant=tenant)
        admit_ns = tracing.clock_ns()
        with self._lock:
            if self._closed:
                self._c_rejected.inc()
                self._reject_trace(trace, admit_ns, "pod is closed")
                raise AdmissionRejected("pod is closed")
            # Degraded-mode sync (ISSUE 7): a resident supervisor that
            # condemned devices onto the process-wide blacklist shrank
            # the silicon this pod schedules onto; every admission
            # decision re-reads the healthy fraction so the cell budget
            # tracks reality, not the config's full-health assumption.
            self._admission.capacity_factor = mesh_lib.capacity_fraction()
            try:
                verdict = self._admission.admit(tenant, cells)
            except AdmissionRejected as e:
                self._c_rejected.inc()
                self._reject_trace(trace, admit_ns, e.reason)
                raise
            session = Session(self._root / tenant) if self._root else Session()
            handle = SessionHandle(
                tenant,
                params,
                session,
                events if events is not None else EventQueue(),
                owns_events=events is None,
            )
            if events is not None:
                # Tee the producer side through the digest so the drain
                # receipt and classification see progress the plane
                # never consumes (the caller keeps reading their queue).
                handle.events = _DigestTee(handle, events)
            else:
                # Unwatched stream: digest inline, retain nothing — no
                # per-session drain thread (see _DigestSink).
                handle.events = _DigestSink(handle)
            handle._backend = backend
            handle._backend_factory = backend_factory
            handle.keys = keys
            handle.frame_plane = frame_plane
            if (
                self.batcher is not None
                and backend is None
                and backend_factory is None
            ):
                # Batched pods default every session's backend to a
                # cohort member (solo Backend where the Params can't
                # cohort); explicit backend/factory submissions — the
                # chaos seams — keep what they brought.
                handle._backend_factory = (
                    lambda p, attempt: self.batcher.member_backend(p)
                )
            handle.admitted_as = verdict
            handle.trace = trace
            handle._submit_ns = admit_ns
            handle._h_qwait = self.metrics.histogram(
                metrics_lib.labelled("sli.queue_wait_seconds", tenant)
            )
            trace.record_span(
                "gol.admission", admit_ns, tracing.clock_ns(),
                verdict=verdict, cells=cells,
            )
            tracing.TRACER.bind_tenant(tenant, trace)
            self._handles[tenant] = handle
            self._c_admitted.inc()
            self._sync_gauges()
        if verdict == ADMIT_RUN:
            self._launch(handle)
        return handle

    @staticmethod
    def _reject_trace(trace, admit_ns: int, reason: str) -> None:
        """A shed submission still yields a complete (tiny) trace: the
        admission span carries the rejection, the trace ends
        ``rejected`` — head sampling decides retention (a shed request
        is a normal outcome, not an error)."""
        trace.record_span(
            "gol.admission", admit_ns, tracing.clock_ns(),
            verdict="rejected", reason=reason,
        )
        tracing.TRACER.end_trace(trace, status="rejected", error=reason)

    # -- scheduling ------------------------------------------------------------
    def _launch(self, handle: SessionHandle) -> None:
        """THE SCHEDULER SEAM: turn one admitted session into device
        work.  Today: one asyncio task awaiting one bounded-executor
        worker running the session's own controller/supervisor — which
        is what makes fault isolation structural.  The ROADMAP's
        batched-board vmap lever replaces this body (group same-shape
        resident boards into one vmapped launch) behind the same
        admission/drain/health contracts."""
        asyncio.run_coroutine_threadsafe(self._run_async(handle), self._loop)

    async def _run_async(self, handle: SessionHandle) -> None:
        try:
            await self._loop.run_in_executor(
                self._executor, self._run_session, handle
            )
        finally:
            self._on_done(handle)

    def _run_session(self, handle: SessionHandle) -> None:
        """One session end-to-end on a pod worker — every exception is
        absorbed here (classified into the handle's terminal state):
        a tenant's failure must never propagate into the plane."""
        handle.status = "running"
        handle.t_start = time.perf_counter()
        trace = handle.trace
        if trace is not None:
            # The queue-wait SLI (ISSUE 15): submit → this worker
            # picking the session up, observed for EVERY admission —
            # run-now sessions contribute their (near-zero) wait so the
            # queue-wait SLO's bad fraction is over all admissions, not
            # just the queued tail.  The timeline span is recorded only
            # when the session actually queued (a µs-wide span on every
            # run-now request would be noise).
            now_ns = tracing.clock_ns()
            if handle._h_qwait is not None:
                handle._h_qwait.observe(
                    (now_ns - handle._submit_ns) / 1e9
                )
            if handle.admitted_as != ADMIT_RUN:
                trace.record_span(
                    "gol.queue.wait", handle._submit_ns, now_ns
                )
        exc: BaseException | None = None
        try:
            # Activate the request trace on THIS worker context: the
            # controller, supervisor, and every obs.spans call site
            # attach to it with no parameter threading.
            with tracing.activate(trace), tracing.span(
                "gol.session.run", tenant=handle.tenant
            ):
                gol.run(
                    handle.params,
                    handle.events,
                    key_presses=handle.keys,
                    session=handle.session,
                    backend=handle._backend,
                    backend_factory=handle._backend_factory,
                    stop=handle.stop,
                    frame_plane=handle.frame_plane,
                )
        except BaseException as e:  # noqa: BLE001 — isolation boundary
            exc = e
        finally:
            # Terminal-stream guarantee: the engine emits its own
            # sentinel on every path except a failed first build; one
            # extra trailing sentinel is invisible to consumers (they
            # stop at the first; the plane-owned _DigestSink drops it).
            handle.events.put(None)
        self._classify(handle, exc)

    def _classify(self, handle: SessionHandle, exc: BaseException | None):
        """Map one finished run onto the handle's terminal state.  The
        session's own ``paused`` flag is the resumability oracle (a
        terminal park, an emergency checkpoint, and a 'q' detach all
        leave it set; a completed run consumed/discarded everything)."""
        completed_all = (
            handle.final is not None
            and handle.final.completed_turns >= handle.params.turns
        )
        if exc is None:
            if handle.stop.requested and not completed_all:
                handle._finish("drained")
            elif handle.session.paused:
                handle._finish("parked")
            else:
                handle._finish("completed")
        else:
            handle._finish(
                "parked" if handle.session.paused else "failed",
                error=f"{type(exc).__name__}: {exc}",
            )
        if handle.trace is not None:
            # Tail retention (ISSUE 15): a request that ended in a
            # failure (terminal park or raw failure) keeps its trace
            # even when head sampling dropped it — error traces are
            # never lost.  Clean terminals keep the head decision.
            if handle.status in ("failed", "parked"):
                handle.trace.flag(handle.status)
            tracing.TRACER.end_trace(
                handle.trace, status=handle.status, error=handle.error
            )

    def _on_done(self, handle: SessionHandle) -> None:
        """Free the slot, promote the longest-waiting admission (unless
        draining, which shed the queue), publish gauges."""
        if self.batcher is not None:
            # Cohort membership follows the plane's books: a terminal
            # session leaves its cohort so rounds stop waiting for it.
            self.batcher.retire(handle.tenant)
        with self._state:
            self._admission.release(handle.tenant)
            self._c_outcome[handle.status].inc()
            # Bound the terminal-handle books: evict oldest-completed
            # beyond the budget — handle, digest, and (outside the lock)
            # the tenant's labelled metrics instruments.  A tenant that
            # was resubmitted keeps its CURRENT handle; only the stale
            # terminal one leaves the ring.
            self._retired.append((handle.tenant, handle))
            evicted: list[str] = []
            while len(self._retired) > self.config.max_retained_handles:
                t, old = self._retired.popleft()
                if self._handles.get(t) is old:
                    del self._handles[t]
                    evicted.append(t)
            promoted = None
            if not self._admission.draining:
                nxt = self._admission.pop_waiting()
                if nxt is not None:
                    promoted = self._handles.get(nxt[0])
            self._sync_gauges()
            if self.sampler is not None:
                # Terminal-event freshness tick, BEFORE waiters wake: a
                # session just ended, so any health()/scrape issued after
                # wait_idle returns must see its final counters
                # (restarts, watchdog fires, outcome) without waiting
                # out the sampling interval.  Steady-state cost stays
                # one snapshot per interval — sessions ending is the
                # cold path.  lazy=False is load-bearing: this runs
                # under the plane lock health() also takes, so it must
                # never land on a lazy-cadence tick whose callback
                # gauges could block on the very wedged device the
                # session just died of.
                self.sampler.sample_now(lazy=False)
            self._state.notify_all()
        for t in evicted:
            self.metrics.clear_tenant(t)
            # The tracer's tenant binding rides the same eviction ring
            # (ISSUE 15): a churning-tenant pod stays bounded-memory.
            tracing.TRACER.unbind_tenant(t)
        if promoted is not None:
            self._launch(promoted)

    def _sync_gauges(self) -> None:
        self._g_resident.set(len(self._admission.resident))
        self._g_queued.set(self._admission.queued)
        self._g_cells.set(self._admission.resident_cells)

    def _own_counter(self, counter, name: str):
        """Exact current value of a plane-owned counter relative to the
        plane-start baseline (the registry is process-wide; a previous
        plane's counts must not leak into this one's health)."""
        base = self._metrics_start.data.get("counters", {}).get(name, 0)
        return getattr(counter, "value", 0) - base

    # -- drain (leg 3) ---------------------------------------------------------
    def begin_drain(self, signum=None, frame=None) -> None:
        """The non-blocking half of a graceful drain: close admissions,
        shed the waiting queue (their streams are terminated so no
        consumer hangs), and raise every resident session's
        ``GracefulStop`` latch — each controller emergency-checkpoints
        at its next turn boundary (the fsync-durable ``_checkpoint_now``
        path) and exits paused-and-resumable.

        Takes the plane's (non-reentrant) lock, so it must NOT run
        directly inside a signal handler — the signal could land while
        the main thread holds that lock (mid-``submit``) and deadlock
        the drain.  :meth:`install` therefore routes signals through a
        trampoline that runs it on a fresh thread."""
        # Close the wire face FIRST (outside the lock — a hook may be
        # answering a request that wants plane state): new gateway
        # submissions 503 before the plane sheds anything.
        for hook in list(self._drain_hooks):
            try:
                hook()
            except Exception:  # noqa: BLE001 — a hook bug must not block drain
                pass
        with self._state:
            if self._admission.draining:
                return
            self._admission.draining = True
            self._c_drains.inc()
            shed = [self._handles[t] for t in self._admission.shed_waiting()]
            running = [
                self._handles[t] for t in list(self._admission.resident)
            ]
            self._sync_gauges()
            self._state.notify_all()
        for handle in shed:
            handle._finish("shed", error="pod drained before a slot freed")
            self._c_outcome["shed"].inc()
            handle.events.put(None)  # terminal event for any waiting consumer
        for handle in running:
            handle.stop.request(signum)

    def drain(self, timeout: float | None = None) -> dict:
        """Blocking graceful drain: :meth:`begin_drain`, then wait (up to
        ``timeout``, default the config's ``drain_timeout_seconds``) for
        every resident session to reach a terminal state.  Returns a
        summary ``{tenant: {status, turn, resumable}}`` — the drain
        contract's receipt: with a checkpoint root, every ``drained``
        tenant is re-adoptable by a fresh pod."""
        self.begin_drain()
        deadline = time.monotonic() + (
            timeout if timeout is not None else self.config.drain_timeout_seconds
        )
        with self._state:
            while self._admission.resident:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._state.wait(timeout=remaining):
                    break
            handles = dict(self._handles)
        return {
            t: {
                "status": h.status,
                "turn": h.last_turn,
                "resumable": h.resumable,
            }
            for t, h in handles.items()
        }

    def install(self, signals=None) -> Callable[[], None]:
        """Route SIGTERM (default) to :meth:`begin_drain`; returns a
        restore callable, like ``GracefulStop.install``.  The handler
        itself only spawns the drain thread (never touches the plane's
        lock on the interrupted thread — see :meth:`begin_drain`); the
        process's main loop observes the drain via :meth:`wait_idle` /
        handle waits and exits when the pod is empty."""
        import signal as signal_mod

        from distributed_gol_tpu.engine.supervisor import route_signals

        if signals is None:
            signals = (signal_mod.SIGTERM,)

        def handler(signum, frame):
            threading.Thread(
                target=self.begin_drain,
                args=(signum,),
                name="gol-serve-drain",
                daemon=True,
            ).start()

        return route_signals(handler, signals)

    def add_drain_hook(self, hook: Callable[[], None]) -> None:
        """Register a pre-drain hook (see ``_drain_hooks``).  Hooks must
        be fast and idempotent: a repeated drain signal re-invokes them
        even though the drain itself is once-only."""
        self._drain_hooks.append(hook)

    def wait_idle(self, timeout: float | None = None) -> bool:
        """Block until no session is resident or queued."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._state:
            while self._admission.resident or self._admission.waiting:
                remaining = (
                    None if deadline is None else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return False
                if not self._state.wait(timeout=remaining):
                    return False
            return True

    def _on_sample(self, sampler) -> None:
        """The sampler's per-tick hook (sampler thread): evaluate the
        SLO objectives over the refreshed ring."""
        if self.slo is not None:
            self.slo.observe(sampler)

    def close(self, timeout: float | None = None) -> None:
        """Drain, then tear the control plane down (idempotent)."""
        with self._lock:
            if self._closed:
                return
        self.drain(timeout)
        with self._lock:
            self._closed = True
        if self.sampler is not None:
            self.sampler.stop()
        self._executor.shutdown(wait=False)
        self._loop.call_soon_threadsafe(self._loop.stop)
        self._loop_thread.join(timeout=10)

    # -- health (leg 4) --------------------------------------------------------
    def health(self) -> dict:
        """Readiness/liveness for an external balancer, derived from the
        plane's books plus the obs registry delta since plane start
        (watchdog fires, supervisor restarts, per-tenant dispatch
        counters via their ``tenant=`` labels).  ``ready`` = this pod
        can admit work now; ``live`` = the control plane itself is
        healthy (a not-live pod should be ejected/restarted; a
        not-ready-but-live pod is full or draining — route around it).

        With the sampler on (the default), the metrics half is read
        from the sampler's LATEST sample — one registry snapshot per
        sampling interval however often health is polled, values at
        most ``telemetry_sample_seconds`` stale (the ``telemetry``
        section publishes the actual age).  The per-call direct
        snapshot survives only as the sampler-off fallback (the
        pre-ISSUE-12 cost profile)."""
        devices_lost = mesh_lib.lost_device_count()
        with self._lock:
            self._admission.capacity_factor = mesh_lib.capacity_fraction()
            draining = self._admission.draining
            resident = len(self._admission.resident)
            queued = self._admission.queued
            resident_cells = self._admission.resident_cells
            effective_cells = self._admission.effective_total_cells
            ready = (
                not self._closed
                and not draining
                and self._admission.has_room()
            )
            statuses = {t: h.status for t, h in self._handles.items()}
            closed = self._closed
        latest = self.sampler.latest() if self.sampler is not None else None
        if latest is not None:
            snap = (
                metrics_lib.MetricsSnapshot(latest.snapshot)
                .delta(self._metrics_start)
                .to_dict()
            )
            telemetry = {
                "sampling": True,
                "sample_age_seconds": round(self.sampler.staleness, 3),
                "staleness_bound_seconds": self.sampler.interval,
            }
        else:
            snap = (
                self.metrics.snapshot(include_lazy=False)
                .delta(self._metrics_start)
                .to_dict()
            )
            telemetry = {"sampling": False}
        counters = snap.get("counters", {})
        snap_gauges = snap.get("gauges", {})
        snap_info = snap.get("info", {})
        tenants = {
            t: {
                "status": status,
                "dispatches": counters.get(
                    metrics_lib.labelled("controller.dispatches", t), 0
                ),
                "turns": counters.get(
                    metrics_lib.labelled("controller.turns", t), 0
                ),
            }
            for t, status in statuses.items()
        }
        return {
            "ready": ready,
            "live": not closed and self._loop_thread.is_alive(),
            # Degraded mode (ISSUE 7): this pod lost devices to the
            # blacklist (an elastic supervisor condemned them) and now
            # admits against the reduced capacity.  A balancer keeps
            # routing to a degraded-but-ready pod — it just holds less.
            "degraded": devices_lost > 0,
            "devices_lost": devices_lost,
            "draining": draining,
            "resident_sessions": resident,
            "queued_sessions": queued,
            "resident_cells": resident_cells,
            "capacity": {
                "max_sessions": self.config.max_sessions,
                "max_queued": self.config.max_queued,
                "max_total_cells": self.config.max_total_cells,
                "effective_total_cells": effective_cells,
            },
            "watchdog_fires": counters.get("faults.watchdog_fires", 0),
            "supervisor_restarts": counters.get("supervisor.restarts", 0),
            # The plane's OWN admission/outcome counters read exactly
            # (plain attribute reads on pre-bound instruments, minus the
            # plane-start baseline) — a rejection is visible in the very
            # next health() even between sampler ticks.
            "sessions_parked": self._own_counter(
                self._c_outcome["parked"], "serve.sessions_parked"
            ),
            "sessions_failed": self._own_counter(
                self._c_outcome["failed"], "serve.sessions_failed"
            ),
            "rejected": self._own_counter(self._c_rejected, "serve.rejected"),
            # Batched-cohort surface (ISSUE 8): physical launch economics
            # a balancer (or the bench) reads straight off health.
            "batched": self.batcher is not None,
            "batched_launches": counters.get("serve.batched_launches", 0),
            "batched_boards": counters.get("serve.batched_boards", 0),
            "cohort_evictions": counters.get("serve.cohort_evictions", 0),
            # Continuous-telemetry surface (ISSUE 12): how fresh the
            # metrics half of this response is, and the per-tenant SLO
            # table when objectives are armed.
            "telemetry": telemetry,
            "slo": self.slo.summary() if self.slo is not None else None,
            "slo_alerts": counters.get("serve.slo_alerts", 0),
            # Spectator fan-out economics (ISSUE 14 satellite): the
            # FramePlane counters, straight off the pod registry, so
            # tools/pod_top.py renders a sessions/spectators panel
            # without a second scrape.  ``subscribers`` is the lazy
            # gauge — None until a lazy sampler tick has run.
            "frames": {
                "publishes": counters.get("frames.publishes", 0),
                "fetches": counters.get("frames.fetches", 0),
                "frames_served": counters.get("frames.frames_served", 0),
                "bytes_shipped": counters.get("frames.bytes_shipped", 0),
                "subscribers": snap_gauges.get("frames.subscribers"),
            },
            # The wire face (ISSUE 14): who is attached and what the
            # gateway shipped — all-zero (endpoint None) on a pod
            # serving no gateway.
            "gateway": {
                "endpoint": snap_info.get("gateway.endpoint"),
                "sessions_submitted": counters.get(
                    "gateway.sessions_submitted", 0
                ),
                "rejected": counters.get("gateway.rejected", 0),
                "controllers": snap_gauges.get("gateway.controllers", 0),
                "spectators": snap_gauges.get("gateway.spectators", 0),
                "frames_streamed": counters.get("gateway.frames_streamed", 0),
                "bytes_streamed": counters.get("gateway.bytes_streamed", 0),
            },
            "tenants": tenants,
        }

    # -- re-adoption (the restarted-pod half of the drain contract) ------------
    def resumable_tenants(self) -> dict[str, dict]:
        """Scan the checkpoint root for tenants a fresh pod can re-adopt:
        ``{tenant: {turn, shape, rule}}`` for every tenant directory
        holding a paused (unconsumed) checkpoint sidecar.  Submitting a
        matching ``Params`` for such a tenant resumes it via the normal
        ``Session.check_states`` negotiation."""
        out: dict[str, dict] = {}
        if self._root is None or not self._root.is_dir():
            return out
        for tenant_dir in sorted(p for p in self._root.iterdir() if p.is_dir()):
            best: dict | None = None
            for sidecar in tenant_dir.glob("checkpoint*.json"):
                try:
                    meta = json.loads(sidecar.read_text())
                except (OSError, ValueError):
                    continue
                if not isinstance(meta, dict) or not meta.get("paused"):
                    continue
                turn = meta.get("turn")
                if not isinstance(turn, int):
                    continue
                if best is None or turn > best["turn"]:
                    best = {
                        "turn": turn,
                        "shape": meta.get("shape"),
                        "rule": meta.get("rule"),
                    }
            if best is not None:
                out[tenant_dir.name] = best
        return out

    # -- introspection ---------------------------------------------------------
    def handle(self, tenant: str) -> SessionHandle | None:
        with self._lock:
            return self._handles.get(tenant)

    def handles(self) -> dict[str, SessionHandle]:
        """A point-in-time copy of the tenant book (latest handle per
        tenant, resident and retained-terminal) — the gateway's session
        listing reads this."""
        with self._lock:
            return dict(self._handles)

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._admission.draining
