"""Pod federation: the health-probed broker tier (ISSUE 17).

PAPER.md §1's broker is the process that owns run state so controllers
can come and go; ROADMAP item 1 promotes it one level — from
fan-out-over-workers to fan-out-over-pods.  This module is that layer:
a :class:`Broker` fronts N gateway pods (``serve/gateway.py``), routes
``POST /v1/sessions`` by tenant → pod placement against each pod's
live ``/healthz`` capacity, and continuously health-probes the fleet —
the same condemn-don't-wait policy ``parallel/mesh.py`` applies to
devices, one level up: a pod that misses ``probe_miss_threshold``
consecutive probes is **condemned** and routed around, and a condemned
pod that answers ``rejoin_threshold`` consecutive probes rejoins the
placement ring.

Robustness legs (the tentpole's three):

1. **Failover** — a condemned pod's resident tenants are re-adopted on
   surviving pods from their newest intact durable checkpoints: the
   federation's one shared contract is the checkpoint root (every pod
   of a federation mounts the same root, and a tenant's directory has
   a single writer because only one pod runs a tenant at a time), so
   re-POSTing the tenant's original spec to any pod lands in the same
   ``root/<tenant>`` directory and the pod's normal
   ``Session.check_states`` negotiation resumes it **bit-identical**
   from the last parked turn (durable sidecars are written
   ``paused=True``, so even a SIGKILLed pod leaves adoptable state).
2. **Live migration** — ``POST /v1/migrate``: single-tenant (quit →
   parked checkpoint → readopt on the target) or whole-pod (drain
   receipt → readopt every parked tenant; queued admissions the drain
   shed are re-submitted fresh — "spilled" — to the warming side).
   Reshard-on-restore (PR 7) means source and target meshes need not
   match.
3. **Degraded routing** — placement scores live headroom
   (``effective_total_cells`` already reflects the device blacklist's
   capacity fraction) and deprioritises pods whose SLO burn is
   alerting; broker rejections carry an honest ``Retry-After`` derived
   from fleet headroom (pod-provided hints when the pods answered,
   the condemnation-recovery horizon when they did not).  A healed
   pod is **reconciled before readmission**: a SIGSTOP-partitioned
   pod resumes running its old sessions the instant it thaws, so any
   resident whose placement moved to a survivor during the outage is
   quit on the healed pod first — the single-writer invariant on
   ``root/<tenant>`` survives partition heal, not just pod death.

Cross-pod tracing (ISSUE 15, one level up): a submission's inbound
W3C ``traceparent`` starts the broker's request trace, and the broker
forwards ``trace.traceparent()`` to the pod — broker → pod → dispatch
is ONE trace id, two retained timelines joined by ``parent_span_id``.

Observability: ``broker.*`` counters on the process registry and a
bounded :class:`~distributed_gol_tpu.obs.flight.FlightRecorder` ring
(``GET /flight``) carrying the ``pod_condemned`` → ``failover`` /
``migration`` sequence — the fleet postmortem surface
``tools/pod_top.py --fleet`` and the chaos matrix read.

The broker never touches a device and holds no run state of its own
beyond the placement map — a restarted broker re-discovers residency
from the pods' session lists and orphaned checkpoints from the shared
root (``POST /v1/recover`` sweeps orphans onto live pods).
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path

from distributed_gol_tpu.obs import metrics as metrics_lib
from distributed_gol_tpu.obs import openmetrics
from distributed_gol_tpu.obs import tracing
from distributed_gol_tpu.obs.flight import FlightRecorder
from distributed_gol_tpu.serve.httpd import StdlibHTTPServer, read_body
from distributed_gol_tpu.serve.podclient import (
    PodClient,
    PodHTTPError,
    PodUnreachable,
)

_TERMINAL = ("completed", "failed", "parked", "shed", "rejected")


@dataclass(frozen=True)
class BrokerConfig:
    """The federation knobs, validated at construction.

    ``probe_miss_threshold`` consecutive probe misses condemn a pod
    (mirroring the device blacklist's consecutive-probe policy);
    ``rejoin_threshold`` consecutive healthy probes readmit it.  The
    prober's per-probe patience is ``probe_timeout_seconds`` — strictly
    bounded so one dead pod costs one timeout per cycle, never a wedged
    prober.  ``checkpoint_root`` is the shared root every pod of the
    federation mounts; None disables the orphan scan (failover then
    re-adopts optimistically, trusting the pods' own roots)."""

    probe_interval_seconds: float = 0.5
    probe_timeout_seconds: float = 2.0
    probe_miss_threshold: int = 3
    rejoin_threshold: int = 2
    checkpoint_root: str | Path | None = None
    failover: bool = True
    request_timeout_seconds: float = 30.0
    #: Fallback Retry-After when no pod supplied a hint: the horizon at
    #: which a condemned pod could have rejoined (interval * threshold).
    retry_after_seconds: float = 1.0
    flight_depth: int = 256
    #: TCP connect deadline for broker→pod calls, split from the read
    #: budget (ISSUE 20): a blackholed pod address fails in this bound
    #: instead of eating the whole request timeout.
    connect_timeout_seconds: float = 5.0
    #: Transport retry policy for control forwards (the PR-2 shape);
    #: probes always use attempts=1 — one miss is one datum.
    attempts: int = 2
    backoff_seconds: float = 0.05
    backoff_max_seconds: float = 1.0
    #: Ride the fleet observability collector (ISSUE 19) in-broker:
    #: scrape every pod's /metrics + /healthz on a cadence and serve
    #: the /fleet/* surface (aggregated metrics, stitched traces, the
    #: merged postmortem) from this broker's port.
    collector: bool = False
    collector_interval_seconds: float = 0.5
    collector_scrape_timeout_seconds: float = 2.0

    def __post_init__(self):
        if self.probe_interval_seconds <= 0:
            raise ValueError("probe_interval_seconds must be > 0")
        if self.probe_timeout_seconds <= 0:
            raise ValueError("probe_timeout_seconds must be > 0")
        if self.probe_miss_threshold < 1:
            raise ValueError("probe_miss_threshold must be >= 1")
        if self.rejoin_threshold < 1:
            raise ValueError("rejoin_threshold must be >= 1")
        if self.flight_depth < 0:
            raise ValueError("flight_depth must be >= 0")
        if self.attempts < 1:
            raise ValueError("attempts must be >= 1")
        if self.connect_timeout_seconds <= 0:
            raise ValueError("connect_timeout_seconds must be > 0")
        if self.collector_interval_seconds <= 0:
            raise ValueError("collector_interval_seconds must be > 0")
        if self.collector_scrape_timeout_seconds <= 0:
            raise ValueError("collector_scrape_timeout_seconds must be > 0")


@dataclass
class PodState:
    """The broker's book on one pod: its client, the prober's counters,
    and the last good health dict placement scores against."""

    endpoint: str
    client: PodClient
    condemned: bool = False
    misses: int = 0
    healthy_streak: int = 0
    health: dict | None = None
    health_age: float = 0.0  # monotonic stamp of the last good probe
    resident: set = field(default_factory=set)
    #: True while a failover worker is still re-placing this pod's
    #: stranded tenants; rejoin is deferred until it finishes so the
    #: heal-time reconcile sees the final placement map.
    failover_inflight: bool = False

    @property
    def status(self) -> str:
        """One placement-relevant word: condemned > draining >
        degraded > ready > full > unprobed."""
        if self.condemned:
            return "condemned"
        h = self.health
        if h is None:
            return "unprobed"
        if h.get("draining"):
            return "draining"
        if not h.get("live", True):
            return "not-live"
        if h.get("degraded"):
            return "degraded"
        return "ready" if h.get("ready") else "full"

    def summary(self) -> dict:
        h = self.health or {}
        cap = h.get("capacity") or {}
        slo = h.get("slo") or {}
        return {
            "endpoint": self.endpoint,
            "status": self.status,
            "condemned": self.condemned,
            "misses": self.misses,
            "ready": bool(h.get("ready")),
            "degraded": bool(h.get("degraded")),
            "draining": bool(h.get("draining")),
            "devices_lost": h.get("devices_lost", 0),
            "resident_sessions": h.get("resident_sessions", 0),
            "queued_sessions": h.get("queued_sessions", 0),
            "resident_cells": h.get("resident_cells", 0),
            "effective_total_cells": cap.get("effective_total_cells"),
            "slo_alerting": list(slo.get("alerting") or ()),
            "placed": sorted(self.resident),
        }


def scan_resumable(root: str | Path | None) -> dict[str, dict]:
    """The broker-side twin of ``ServePlane.resumable_tenants``: scan
    the SHARED checkpoint root for tenants holding a paused durable
    sidecar — ``{tenant: {turn, shape, rule}}``, newest turn per
    tenant.  Standalone (no plane, no jax) because the broker process
    never owns sessions; the scan is how failover confirms a condemned
    pod left adoptable state, and how a restarted broker finds orphans
    no live pod claims."""
    out: dict[str, dict] = {}
    if root is None:
        return out
    root = Path(root)
    if not root.is_dir():
        return out
    for tenant_dir in sorted(p for p in root.iterdir() if p.is_dir()):
        best: dict | None = None
        for sidecar in tenant_dir.glob("checkpoint*.json"):
            try:
                meta = json.loads(sidecar.read_text())
            except (OSError, ValueError):
                continue
            if not isinstance(meta, dict) or not meta.get("paused"):
                continue
            turn = meta.get("turn")
            if not isinstance(turn, int):
                continue
            if best is None or turn > best["turn"]:
                best = {
                    "turn": turn,
                    "shape": meta.get("shape"),
                    "rule": meta.get("rule"),
                }
        if best is not None:
            out[tenant_dir.name] = best
    return out


class Broker(StdlibHTTPServer):
    """The federation's front door.  Construct with the pod gateway
    endpoints; ``port=0`` binds ephemeral and publishes the URL as the
    ``broker.endpoint`` info label.

    Routes::

        GET  /healthz                      fleet aggregate (200 if any
                                           pod can admit, else 503)
        GET  /v1/pods                      per-pod detail (the fleet
                                           dashboard surface)
        POST /v1/sessions                  placement + forward
        GET  /v1/sessions                  merged CheckStates across pods
        GET  /v1/sessions/<t>[/state]      proxied to the owning pod
        POST /v1/sessions/<t>/pause|resume|quit   proxied control
        GET  /v1/sessions/<t>/placement    {tenant, pod} — the client's
                                           follow-the-placement hook
                                           (WS legs connect pod-direct)
        POST /v1/migrate                   {"tenant": t[, "to": url]} or
                                           {"pod": url[, "to": url]}
        POST /v1/recover                   sweep orphaned checkpoints
                                           onto live pods
        GET  /flight                       the broker's flight ring
        GET  /traces                       this process's trace surface
        GET  /metrics                      the broker's own registry
                                           (OpenMetrics)
        GET  /fleet/*                      the fleet observability
                                           surface (metrics, healthz,
                                           slo, traces/<id>, flight) —
                                           only with config.collector
    """

    thread_name = "gol-broker-http"

    def __init__(
        self,
        endpoints,
        config: BrokerConfig | None = None,
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        self.config = config or BrokerConfig()
        reg = metrics_lib.REGISTRY
        self.metrics = reg
        self._m_requests = reg.counter("broker.requests")
        self._m_routed = reg.counter("broker.sessions_routed")
        self._m_rejected = reg.counter("broker.rejected")
        self._m_probes = reg.counter("broker.probes")
        self._m_probe_misses = reg.counter("broker.probe_misses")
        self._m_condemned = reg.counter("broker.pods_condemned")
        self._m_rejoined = reg.counter("broker.pods_rejoined")
        self._m_failovers = reg.counter("broker.failovers")
        self._m_failovers_lost = reg.counter("broker.failovers_lost")
        self._m_migrations = reg.counter("broker.migrations")
        self._m_rejoin_quits = reg.counter("broker.rejoin_quits")
        self._g_pods_ready = reg.gauge("broker.pods_ready")
        self.flight = FlightRecorder(self.config.flight_depth)
        self._lock = threading.Lock()
        self._pods: list[PodState] = [
            PodState(
                endpoint=e,
                client=PodClient(
                    e,
                    timeout=self.config.request_timeout_seconds,
                    probe_timeout=self.config.probe_timeout_seconds,
                    attempts=self.config.attempts,
                    backoff_seconds=self.config.backoff_seconds,
                    backoff_max_seconds=self.config.backoff_max_seconds,
                    connect_timeout=self.config.connect_timeout_seconds,
                ),
            )
            for e in endpoints
        ]
        if not self._pods:
            raise ValueError("a broker needs at least one pod endpoint")
        #: tenant -> PodState: who runs it now.
        self._placements: dict[str, PodState] = {}
        #: tenant -> the spec doc the client POSTed, verbatim — what
        #: failover/migration re-submits (the pod's wire.py re-derives
        #: everything else, including the shared-root out_dir).
        self._specs: dict[str, dict] = {}
        self._failover_threads: list[threading.Thread] = []
        self._closed = threading.Event()
        self._probe_wake = threading.Event()
        super().__init__(port=port, host=host, registry=reg,
                         request_counter=self._m_requests)
        reg.info("broker.endpoint", self.url)
        #: The in-broker fleet observability collector (ISSUE 19):
        #: armed by config, scrapes the SAME pod endpoints the prober
        #: probes and serves /fleet/* off this broker's port.  The
        #: broker's own flight ring and the shared checkpoint root join
        #: the merged postmortem; local_name folds the broker's
        #: process-wide registry (and its retained traces) in.
        self.collector = None
        if self.config.collector:
            from distributed_gol_tpu.obs.fleet import FleetCollector

            self.collector = FleetCollector(
                list(endpoints),
                interval=self.config.collector_interval_seconds,
                scrape_timeout=self.config.collector_scrape_timeout_seconds,
                checkpoint_root=self.config.checkpoint_root,
                local_name="broker",
                local_flight=self.flight,
                registry=reg,
            )
        self._discover()
        self._prober = threading.Thread(
            target=self._probe_loop, name="gol-broker-prober", daemon=True
        )
        self._prober.start()

    # -- lifecycle -------------------------------------------------------------
    def close(self) -> None:
        self._closed.set()
        self._probe_wake.set()
        if self.collector is not None:
            self.collector.close()
        super().close()
        self._prober.join(timeout=5)
        with self._lock:
            pending = list(self._failover_threads)
        for worker in pending:
            worker.join(timeout=5)

    def _discover(self) -> None:
        """Broker-restart re-discovery: the placement map is soft state,
        rebuilt from the pods' own session lists (a pod that answers
        owns what it lists) — so a broker crash loses no federation
        state.  Specs are NOT recoverable from the wire; re-discovered
        tenants fail over on their sidecar-reconstructed spec (resumed
        to the parked turn — no lost work, no invented work)."""
        for pod in self._pods:
            try:
                doc = pod.client.sessions()
            except (PodUnreachable, PodHTTPError):
                continue
            for tenant, row in (doc.get("sessions") or {}).items():
                if row.get("status") in _TERMINAL and not row.get("resumable"):
                    continue
                with self._lock:
                    pod.resident.add(tenant)
                    self._placements[tenant] = pod
        with self._lock:
            n = len(self._placements)
        if n:
            self.flight.record("discover", tenants=n)

    # -- the prober (the device-blacklist policy, one level up) ----------------
    def _probe_loop(self) -> None:
        while not self._closed.is_set():
            self.probe_once()
            self._probe_wake.wait(self.config.probe_interval_seconds)
            self._probe_wake.clear()

    def probe_once(self) -> None:
        """One probe cycle over every pod (also callable directly —
        tests and the bench drive condemnation deterministically without
        racing the wall-clock loop)."""
        for pod in self._pods:
            if self._closed.is_set():
                return
            self._m_probes.inc()
            try:
                health = pod.client.health()
            except (PodUnreachable, PodHTTPError):
                self._m_probe_misses.inc()
                condemn = False
                with self._lock:
                    pod.misses += 1
                    pod.healthy_streak = 0
                    if (
                        not pod.condemned
                        and pod.misses >= self.config.probe_miss_threshold
                    ):
                        pod.condemned = True
                        condemn = True
                if condemn:
                    self._on_condemned(pod)
                continue
            rejoin_due = False
            with self._lock:
                pod.misses = 0
                pod.health = health
                pod.health_age = time.monotonic()
                if pod.condemned:
                    pod.healthy_streak += 1
                    # Rejoin waits for the failover worker: reconcile
                    # must see where the stranded tenants LANDED.
                    rejoin_due = (
                        pod.healthy_streak >= self.config.rejoin_threshold
                        and not pod.failover_inflight
                    )
            if rejoin_due and self._reconcile_rejoin(pod):
                with self._lock:
                    pod.condemned = False
                    pod.healthy_streak = 0
                self._m_rejoined.inc()
                self.flight.record("pod_rejoined", pod=pod.endpoint)
        with self._lock:
            ready = sum(
                1 for p in self._pods
                if not p.condemned
                and p.misses == 0
                and (p.health or {}).get("ready")
            )
        self._g_pods_ready.set(ready)

    def _reconcile_rejoin(self, pod: PodState) -> bool:
        """The split-brain guard on partition heal: while the pod was
        condemned its residents failed over to survivors, but a
        partitioned (not dead) pod kept RUNNING them — readmitting it
        as-is would leave two pods writing the same shared
        ``root/<tenant>`` checkpoint directory, breaking the
        single-writer invariant the bit-identical resume guarantee
        rests on.  So before the ring takes the pod back: quit every
        session it still holds whose placement now points at a
        DIFFERENT pod (counted in ``broker.rejoin_quits``), and
        re-adopt into the books any live session nobody else owns (the
        pod carried it through the partition — failover had lost it).
        Returns False — rejoin deferred to the next healthy probe, the
        streak intact — when the pod cannot answer or a stale quit
        fails."""
        try:
            doc = pod.client.sessions()
        except (PodUnreachable, PodHTTPError):
            return False
        ok = True
        for tenant, row in sorted((doc.get("sessions") or {}).items()):
            status = row.get("status")
            if status in _TERMINAL and not row.get("resumable"):
                continue
            with self._lock:
                owner = self._placements.get(tenant)
                readopt = owner is None
                if readopt:
                    self._placements[tenant] = pod
                    pod.resident.add(tenant)
            if readopt:
                self.flight.record(
                    "rejoin_readopt", tenant=tenant, pod=pod.endpoint
                )
                continue
            if owner is pod or status in _TERMINAL:
                continue  # rightful resident / parked (not writing)
            try:
                pod.client.control(tenant, "quit")
            except PodHTTPError as e:
                if e.status != 404:  # gone already = already not writing
                    ok = False
                    continue
            except PodUnreachable:
                ok = False
                continue
            self._m_rejoin_quits.inc()
            self.flight.record(
                "rejoin_quit",
                tenant=tenant,
                pod=pod.endpoint,
                owner=owner.endpoint,
            )
        return ok

    def _on_condemned(self, pod: PodState) -> None:
        """A pod crossed the miss threshold: record it, then fail its
        residents over to the survivors on a worker thread — each
        re-submission is a bounded-timeout HTTP sweep, and the prober
        must keep its cadence (health data going stale behind a slow
        failover would delay condemning OTHER failing pods).
        Config-gated: a broker run as a pure balancer can leave
        adoption to operators."""
        self._m_condemned.inc()
        with self._lock:
            stranded = sorted(pod.resident)
        self.flight.record(
            "pod_condemned",
            pod=pod.endpoint,
            misses=pod.misses,
            stranded=stranded,
        )
        if not self.config.failover or not stranded:
            return
        worker = threading.Thread(
            target=self._failover,
            args=(pod, stranded),
            name="gol-broker-failover",
            daemon=True,
        )
        with self._lock:
            pod.failover_inflight = True
            self._failover_threads = [
                t for t in self._failover_threads if t.is_alive()
            ]
            self._failover_threads.append(worker)
        worker.start()

    # -- leg 1: failover -------------------------------------------------------
    def _failover(self, dead: PodState, tenants) -> None:
        """Re-adopt a dead pod's residents on the survivors, newest
        durable checkpoint first.  Each tenant's re-submission is one
        flagged trace (``gol.broker.failover``) so the postmortem
        timeline is retained regardless of sampling.  A tenant that
        cannot be re-placed is dropped from the placement map — the
        client's next ``/placement`` or state poll gets an honest 404
        instead of 502s against the condemned endpoint (its spec is
        kept, so ``/v1/recover`` or a fresh submit restores it)."""
        try:
            orphans = scan_resumable(self.config.checkpoint_root)
            for tenant in tenants:
                info = orphans.get(tenant)
                doc = self._respec(tenant, info)
                if doc is None:
                    self._m_failovers_lost.inc()
                    self._drop_placement(tenant, dead)
                    self.flight.record(
                        "failover_lost",
                        tenant=tenant,
                        pod=dead.endpoint,
                        reason="no spec and no resumable checkpoint",
                    )
                    continue
                trace = tracing.TRACER.start_trace(
                    "gol.broker.failover", tenant=tenant
                )
                trace.flag("failover")
                target, receipt, err, _ = self._place(
                    tenant, doc, trace, exclude=(dead,)
                )
                if target is None:
                    tracing.TRACER.end_trace(
                        trace, status="failed", error=err
                    )
                    self._m_failovers_lost.inc()
                    self._drop_placement(tenant, dead)
                    self.flight.record(
                        "failover_lost",
                        tenant=tenant,
                        pod=dead.endpoint,
                        reason=err or "no adoptive pod",
                    )
                    continue
                tracing.TRACER.end_trace(trace, status="ok")
                self._m_failovers.inc()
                self.flight.record(
                    "failover",
                    tenant=tenant,
                    from_pod=dead.endpoint,
                    to_pod=target.endpoint,
                    checkpoint_turn=info["turn"] if info else None,
                    trace_id=trace.trace_id,
                )
            with self._lock:
                dead.resident.clear()
        finally:
            with self._lock:
                dead.failover_inflight = False

    def _drop_placement(self, tenant: str, pod: PodState) -> None:
        """Forget a placement that still points at ``pod`` — the
        tenant could not be re-placed, and stale books would keep
        proxying its control plane into a dead endpoint."""
        with self._lock:
            if self._placements.get(tenant) is pod:
                del self._placements[tenant]
            pod.resident.discard(tenant)

    def _respec(self, tenant: str, info: dict | None) -> dict | None:
        """The spec failover re-submits: the client's original doc when
        the broker routed it, else one reconstructed from the durable
        sidecar (shape + rule + parked turn — the session resumes AND
        parks at its checkpoint turn: re-discovered tenants lose no
        work and are never run past what their owner asked for)."""
        with self._lock:
            doc = self._specs.get(tenant)
        if doc is not None:
            return dict(doc)
        if info is None or not info.get("shape"):
            return None
        import base64

        import numpy as np

        from distributed_gol_tpu.engine import pgm

        h, w = info["shape"]
        params: dict = {"width": w, "height": h, "turns": info["turn"]}
        if info.get("rule"):
            params["rule"] = info["rule"]
        # The wire contract wants a board source, but the durable
        # checkpoint supplies the real board at adoption; an all-dead
        # upload makes a failed adoption loudly wrong (an empty board at
        # the parked turn) instead of silently plausible.
        dead = base64.b64encode(
            pgm.encode_pgm(np.zeros((h, w), dtype=np.uint8))
        ).decode()
        return {"tenant": tenant, "params": params, "board_b64": dead}

    # -- leg 2: live migration -------------------------------------------------
    def _migrate_tenant(
        self, tenant: str, to: str | None, wait_seconds: float = 30.0
    ) -> tuple[int, dict]:
        """Single-session migration: quit on the source parks the
        durable checkpoint; the readopt POST on the target resumes it
        bit-identical (reshard-on-restore absorbs mesh mismatch).  The
        quit is only issued once a plausible target exists (don't stop
        a healthy session just to discover the fleet is full), and a
        placement that still fails rolls back — the spec is re-submitted
        to the SOURCE pod, which readopts its own parked checkpoint, so
        the tenant is never left stopped with a stale placement."""
        with self._lock:
            source = self._placements.get(tenant)
            doc = self._specs.get(tenant)
        if source is None:
            return 404, {"error": f"no placement for {tenant!r}"}
        if doc is None:
            return 409, {"error": f"no stored spec for {tenant!r}"}
        target_only = None
        if to is not None:
            target_only = self._pod_by_endpoint(to)
            if target_only is None:
                return 404, {"error": f"unknown target pod {to!r}"}
            if target_only.condemned:
                return 409, {"error": f"target pod {to!r} is condemned"}
        elif not self._candidates(exclude=(source,)):
            return 503, {"error": "no admitting target pod in the ring"}
        try:
            source.client.control(tenant, "quit")
        except (PodUnreachable, PodHTTPError) as e:
            return 502, {"error": f"source quit failed: {e}"}
        parked = self._await_parked(source, tenant, wait_seconds)
        if not parked:
            return 504, {"error": f"{tenant!r} did not park in time"}
        trace = tracing.TRACER.start_trace(
            "gol.broker.migration", tenant=tenant
        )
        trace.flag("migration")
        target, receipt, err, _ = self._place(
            tenant, dict(doc), trace,
            exclude=(source,) if to is None else (),
            only=target_only,
        )
        if target is None:
            tracing.TRACER.end_trace(trace, status="failed", error=err)
            restored = self._restore_to_source(source, tenant, doc)
            self.flight.record(
                "migration_failed",
                tenant=tenant,
                from_pod=source.endpoint,
                restored=restored,
                error=err,
            )
            return 502, {
                "error": err or "no target pod",
                "restored": restored,
            }
        tracing.TRACER.end_trace(trace, status="ok")
        self._m_migrations.inc()
        self.flight.record(
            "migration",
            tenant=tenant,
            from_pod=source.endpoint,
            to_pod=target.endpoint,
            turn=parked.get("turn"),
            trace_id=trace.trace_id,
        )
        return 200, {
            "tenant": tenant,
            "from": source.endpoint,
            "to": target.endpoint,
            "turn": parked.get("turn"),
            "receipt": receipt,
        }

    def _restore_to_source(
        self, source: PodState, tenant: str, doc: dict
    ) -> bool:
        """Failed-migration rollback: the tenant is already quit and
        its parked checkpoint sits on the shared root, so re-submitting
        the spec to the source pod resumes it exactly where the aborted
        migration stopped it.  If even that fails the placement is
        dropped — an honest 404 beats books pointing at a stopped
        session."""
        try:
            source.client.submit(dict(doc))
        except (PodUnreachable, PodHTTPError):
            self._drop_placement(tenant, source)
            return False
        return True

    def _migrate_pod(self, endpoint: str, to: str | None) -> tuple[int, dict]:
        """Whole-pod migration: drain the source (its receipt lists
        every parked-resumable tenant and every shed queued admission),
        readopt the parked on the targets, spill the shed as fresh
        submissions — the warming-pod path."""
        source = self._pod_by_endpoint(endpoint)
        if source is None:
            return 404, {"error": f"unknown pod {endpoint!r}"}
        if to is not None and self._pod_by_endpoint(to) is None:
            return 404, {"error": f"unknown target pod {to!r}"}
        try:
            drained = source.client.drain(timeout=60.0)
        except (PodUnreachable, PodHTTPError) as e:
            return 502, {"error": f"drain failed: {e}"}
        receipt = drained.get("sessions") or {}
        moved, spilled, lost = [], [], []
        for tenant, row in sorted(receipt.items()):
            with self._lock:
                if self._placements.get(tenant) is not source:
                    continue
                doc = self._specs.get(tenant)
            if doc is None:
                continue
            if not (row.get("resumable") or row.get("status") == "shed"):
                continue  # completed/failed on the way down: nothing to move
            trace = tracing.TRACER.start_trace(
                "gol.broker.migration", tenant=tenant
            )
            trace.flag("migration")
            target, _, err, _ = self._place(
                tenant, dict(doc), trace,
                exclude=(source,) if to is None else (),
                only=self._pod_by_endpoint(to),
            )
            if target is None:
                tracing.TRACER.end_trace(trace, status="failed", error=err)
                # Honest books: the drained source no longer runs it.
                self._drop_placement(tenant, source)
                lost.append(tenant)
                continue
            tracing.TRACER.end_trace(trace, status="ok")
            self._m_migrations.inc()
            kind = "spill" if row.get("status") == "shed" else "migration"
            (spilled if kind == "spill" else moved).append(tenant)
            self.flight.record(
                kind,
                tenant=tenant,
                from_pod=source.endpoint,
                to_pod=target.endpoint,
                turn=row.get("turn"),
                trace_id=trace.trace_id,
            )
        with self._lock:
            source.resident.clear()
        return 200, {
            "pod": source.endpoint,
            "migrated": moved,
            "spilled": spilled,
            "lost": lost,
            "receipt": receipt,
        }

    def _await_parked(
        self, pod: PodState, tenant: str, wait_seconds: float
    ) -> dict | None:
        deadline = time.monotonic() + wait_seconds
        while time.monotonic() < deadline:
            try:
                state = pod.client.state(tenant)
            except (PodUnreachable, PodHTTPError):
                return None
            if state.get("status") in _TERMINAL:
                return state if state.get("resumable") else None
            time.sleep(0.05)
        return None

    def _pod_by_endpoint(self, endpoint: str | None) -> PodState | None:
        if endpoint is None:
            return None
        for pod in self._pods:
            if pod.endpoint == endpoint:
                return pod
        return None

    # -- leg 3: placement + degraded routing -----------------------------------
    def _candidates(self, exclude=()) -> list[PodState]:
        """Placement order: non-condemned, non-draining, live pods by
        descending effective headroom (cells the pod can still hold —
        the degraded capacity fraction is already inside
        ``effective_total_cells``), SLO-alerting pods last (burn-rate
        deprioritisation, not exclusion: a burning pod beats no pod)."""
        with self._lock:
            pods = [
                p for p in self._pods
                if p not in exclude
                and not p.condemned
                and p.health is not None
                and not p.health.get("draining")
                and p.health.get("live", True)
            ]

            def score(p: PodState) -> tuple:
                h = p.health or {}
                cap = h.get("capacity") or {}
                total = cap.get("effective_total_cells")
                headroom = (
                    float("inf") if total is None
                    else total - h.get("resident_cells", 0)
                )
                burning = bool((h.get("slo") or {}).get("alerting"))
                return (burning, -headroom, h.get("queued_sessions", 0))

            return sorted(pods, key=score)

    def _place(
        self,
        tenant: str,
        doc: dict,
        trace,
        exclude=(),
        only: PodState | None = None,
        hints: list | None = None,
    ) -> tuple[PodState | None, dict | None, str | None, tuple | None]:
        """Try candidates in placement order; a pod that sheds (429) or
        closes admissions (503) spills the submission to the next one
        (its ``retry_after`` hint collected into ``hints`` — the honest
        input to the broker's own Retry-After).  Returns
        ``(pod, receipt, None, None)`` or ``(None, None, why,
        permanent)``; a permanent pod answer (400/404/409) aborts the
        sweep — every other pod would refuse the same spec the same
        way — and comes back as ``permanent = (status, body)`` so the
        caller can relay the pod's verdict verbatim instead of masking
        a bad spec as a retryable 429."""
        t0 = tracing.clock_ns()
        pods = [only] if only is not None else self._candidates(exclude)
        trace.record_span(
            "gol.broker.place",
            t0,
            tracing.clock_ns(),
            tenant=tenant,
            candidates=len(pods),
        )
        last_err = "no admitting pod"
        for pod in pods:
            f0 = tracing.clock_ns()
            try:
                receipt = pod.client.submit(
                    dict(doc), traceparent=trace.traceparent()
                )
            except PodUnreachable as e:
                last_err = str(e)
                continue
            except PodHTTPError as e:
                last_err = f"{pod.endpoint}: HTTP {e.status}"
                if e.status in (429, 503):
                    if hints is not None and e.retry_after is not None:
                        hints.append(e.retry_after)
                    continue  # shed/draining: spill to the next pod
                body = dict(e.body) if isinstance(e.body, dict) else {
                    "error": str(e.body)
                }
                body["pod"] = pod.endpoint
                return None, None, last_err, (e.status, body)
            trace.record_span(
                "gol.broker.forward",
                f0,
                tracing.clock_ns(),
                tenant=tenant,
                pod=pod.endpoint,
            )
            with self._lock:
                old = self._placements.get(tenant)
                if old is not None and old is not pod:
                    old.resident.discard(tenant)
                self._placements[tenant] = pod
                self._specs[tenant] = dict(doc)
                pod.resident.add(tenant)
            return pod, receipt, None, None
        return None, None, last_err, None

    def _fleet_retry_after(self, hints) -> float:
        """Honest backpressure: the largest pod-provided 429 hint when
        any pod ANSWERED (fleet headroom speaks for itself), else the
        condemnation-recovery horizon — the earliest a condemned pod
        could rejoin and restore capacity."""
        hints = [h for h in hints if isinstance(h, (int, float)) and h > 0]
        if hints:
            return max(hints)
        horizon = self.config.probe_interval_seconds * (
            self.config.probe_miss_threshold + self.config.rejoin_threshold
        )
        return max(self.config.retry_after_seconds, horizon)

    # -- routing ---------------------------------------------------------------
    def fleet_health(self) -> dict:
        with self._lock:
            pods = [p.summary() for p in self._pods]
            placements = len(self._placements)
        ready = any(p["ready"] and not p["condemned"] for p in pods)
        return {
            "broker": True,
            "ready": ready,
            "live": True,
            "pods": pods,
            "pods_ready": sum(
                1 for p in pods if p["ready"] and not p["condemned"]
            ),
            "pods_condemned": sum(1 for p in pods if p["condemned"]),
            "placements": placements,
            "resident_sessions": sum(p["resident_sessions"] for p in pods),
            "queued_sessions": sum(p["queued_sessions"] for p in pods),
            "resident_cells": sum(p["resident_cells"] for p in pods),
        }

    def handle(self, request, method: str, path: str, query: dict) -> bool:
        if path == "/healthz" and method == "GET":
            health = self.fleet_health()
            request._send_json(200 if health["ready"] else 503, health)
            return True
        if path == "/v1/pods" and method == "GET":
            with self._lock:
                pods = [p.summary() for p in self._pods]
            request._send_json(200, {"pods": pods})
            return True
        if path == "/flight" and method == "GET":
            request._send_json(200, {"records": self.flight.records()})
            return True
        if path == "/traces" and method == "GET":
            code, obj = tracing.http_traces(query)
            request._send_json(code, obj)
            return True
        if path == "/metrics" and method == "GET":
            # The broker's OWN registry (a fleet collector scrapes this
            # like any node); the aggregated view is /fleet/metrics.
            text = openmetrics.render(self.metrics.snapshot().to_dict())
            request._send(200, text.encode(), openmetrics.CONTENT_TYPE)
            return True
        if self.collector is not None and path.startswith("/fleet"):
            return self.collector.handle_http(request, method, path, query)
        if path == "/v1/sessions":
            if method == "POST":
                return self._submit(request)
            if method == "GET":
                return self._list_sessions(request)
            return False
        if path == "/v1/migrate" and method == "POST":
            return self._migrate(request)
        if path == "/v1/recover" and method == "POST":
            return self._recover(request)
        parts = path.split("/")
        # /v1/sessions/<t>[/<action>]
        if len(parts) in (4, 5) and parts[1] == "v1" and parts[2] == "sessions":
            tenant = parts[3]
            action = parts[4] if len(parts) == 5 else None
            return self._proxy_session(request, method, tenant, action)
        return False

    def _submit(self, request) -> bool:
        try:
            doc = json.loads(read_body(request) or b"{}")
        except ValueError as e:
            request._send_json(400, {"error": f"body is not JSON: {e}"})
            return True
        tenant = doc.get("tenant") if isinstance(doc, dict) else None
        if not isinstance(tenant, str) or not tenant:
            request._send_json(400, {"error": "spec wants a tenant name"})
            return True
        trace = tracing.TRACER.start_trace(
            "gol.broker.request",
            traceparent=request.headers.get("traceparent"),
            tenant=tenant,
        )
        headers = [
            ("X-Gol-Trace-Id", trace.trace_id),
            ("traceparent", trace.traceparent()),
        ]
        hints: list = []
        pod, receipt, err, permanent = self._place(
            tenant, doc, trace, hints=hints
        )
        if pod is None:
            tracing.TRACER.end_trace(trace, status="rejected", error=err)
            self._m_rejected.inc()
            if permanent is not None:
                # A pod REFUSED the spec (bad spec, duplicate tenant…):
                # relay its status and body verbatim — retrying would
                # meet the same answer, so no Retry-After theatre.
                status, body = permanent
                request._send_json(status, body, headers=headers)
                return True
            retry_after = self._fleet_retry_after(hints)
            request._send_json(
                429,
                {
                    "error": err or "no admitting pod",
                    "retry_after": retry_after,
                },
                headers=[("Retry-After", f"{retry_after:g}")] + headers,
            )
            return True
        tracing.TRACER.end_trace(trace, status="routed")
        self._m_routed.inc()
        out = dict(receipt or {})
        out["pod"] = pod.endpoint
        out["broker_trace_id"] = trace.trace_id
        request._send_json(201, out, headers=headers)
        return True

    def _list_sessions(self, request) -> bool:
        out: dict = {}
        with self._lock:
            pods = list(self._pods)
        for pod in pods:
            if pod.condemned:
                continue
            try:
                doc = pod.client.sessions()
            except (PodUnreachable, PodHTTPError):
                continue
            for tenant, row in (doc.get("sessions") or {}).items():
                row = dict(row)
                row["pod"] = pod.endpoint
                out[tenant] = row
        request._send_json(200, {"sessions": out, "broker": True})
        return True

    def _proxy_session(self, request, method, tenant, action) -> bool:
        with self._lock:
            pod = self._placements.get(tenant)
        if pod is None:
            request._send_json(
                404, {"error": f"no placement for {tenant!r}"}
            )
            return True
        if method == "GET" and action == "placement":
            request._send_json(
                200,
                {
                    "tenant": tenant,
                    "pod": pod.endpoint,
                    "status": pod.status,
                },
            )
            return True
        if method == "GET" and action in (None, "state"):
            verb = ("GET", f"/v1/sessions/{tenant}/state")
        elif method == "POST" and action in ("pause", "resume", "quit"):
            verb = ("POST", f"/v1/sessions/{tenant}/{action}")
        else:
            return False
        try:
            doc = pod.client.request(*verb)
        except PodHTTPError as e:
            body = e.body if isinstance(e.body, dict) else {"error": str(e)}
            body["pod"] = pod.endpoint
            request._send_json(e.status, body)
            return True
        except PodUnreachable as e:
            request._send_json(
                502, {"error": str(e), "pod": pod.endpoint}
            )
            return True
        doc = dict(doc)
        doc["pod"] = pod.endpoint
        request._send_json(200, doc)
        return True

    def _migrate(self, request) -> bool:
        try:
            doc = json.loads(read_body(request) or b"{}")
        except ValueError as e:
            request._send_json(400, {"error": f"body is not JSON: {e}"})
            return True
        to = doc.get("to")
        if doc.get("tenant"):
            code, out = self._migrate_tenant(str(doc["tenant"]), to)
        elif doc.get("pod"):
            code, out = self._migrate_pod(str(doc["pod"]), to)
        else:
            code, out = 400, {"error": "migrate wants a tenant or a pod"}
        request._send_json(code, out)
        return True

    def _recover(self, request) -> bool:
        """Sweep the shared root for orphaned resumable checkpoints no
        live pod claims (the broker-restart-after-pod-loss hole: the
        dead pod is gone from the ring, so nothing condemns it) and
        readopt them — availability from durable state alone."""
        orphans = scan_resumable(self.config.checkpoint_root)
        with self._lock:
            owned = set(self._placements)
        adopted, lost = [], []
        for tenant, info in sorted(orphans.items()):
            if tenant in owned:
                continue
            doc = self._respec(tenant, info)
            if doc is None:
                lost.append(tenant)
                continue
            trace = tracing.TRACER.start_trace(
                "gol.broker.failover", tenant=tenant
            )
            trace.flag("recover")
            pod, _, err, _ = self._place(tenant, doc, trace)
            if pod is None:
                tracing.TRACER.end_trace(trace, status="failed", error=err)
                lost.append(tenant)
                continue
            tracing.TRACER.end_trace(trace, status="ok")
            self._m_failovers.inc()
            self.flight.record(
                "failover",
                tenant=tenant,
                from_pod=None,
                to_pod=pod.endpoint,
                checkpoint_turn=info["turn"],
                trace_id=trace.trace_id,
            )
            adopted.append(tenant)
        request._send_json(200, {"adopted": adopted, "lost": lost})
        return True

    # -- introspection (tests / bench) -----------------------------------------
    def placement(self, tenant: str) -> str | None:
        with self._lock:
            pod = self._placements.get(tenant)
            return pod.endpoint if pod else None

    def pod_states(self) -> list[dict]:
        with self._lock:
            return [p.summary() for p in self._pods]


__all__ = ["Broker", "BrokerConfig", "PodState", "scan_resumable"]
