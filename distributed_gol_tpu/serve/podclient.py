"""Pod-gateway client for the broker tier (ISSUE 17).

The broker (``serve/broker.py``) talks to its pods over exactly the
wire contract ``serve/gateway.py`` publishes — nothing side-channel —
through this bounded-timeout ``http.client`` wrapper.  Two disciplines
distinguish it from a generic HTTP helper:

- **Bounded time, always**: every call carries an explicit socket
  timeout (the broker's health-probe loop must never wedge on a dead
  pod — a pod that cannot answer inside the probe timeout IS the
  signal), and a connect/read failure is one typed outcome
  (:class:`PodUnreachable`), never a raw socket exception leaking into
  placement logic.
- **Deterministic retry/backoff** riding the PR-2 policy shape
  (``engine/controller.py::_backoff``): ``attempts`` tries with delay
  ``backoff_seconds * 2**(attempt-1)`` capped at
  ``backoff_max_seconds`` — a pure function of the attempt index, no
  jitter, so a scripted chaos test sees the same retry schedule every
  run.  Retries apply only to *transport* failures (unreachable /
  reset); an HTTP error status is a pod ANSWER and is surfaced
  immediately as :class:`PodHTTPError` — retrying a 429 is the
  caller's placement decision, not the transport's.

Zero dependencies beyond the stdlib, importable without jax — the
broker process never touches a device.
"""

from __future__ import annotations

import http.client
import json
import time
import uuid
from urllib.parse import urlsplit

#: Response-size bound (ISSUE 20): a broker control answer is JSON a
#: few KiB long; a pod (or a chaos proxy wearing its address) that
#: declares or streams more than this is answering garbage, and the
#: broker must not buffer it.
DEFAULT_RESPONSE_CAP = 1 << 24

#: The idempotency header ``POST /v1/sessions`` retries carry
#: (docs/API.md "Wire hardening"); the gateway replays the stored
#: receipt for a repeated key instead of double-placing the tenant.
IDEMPOTENCY_HEADER = "X-Gol-Idempotency-Key"


class PodUnreachable(RuntimeError):
    """The pod did not answer inside the bounded budget (connect
    refused, socket timeout, reset mid-response) — the transport-level
    outcome the prober's miss counter feeds on."""

    def __init__(self, endpoint: str, error: BaseException):
        self.endpoint = endpoint
        self.error = error
        super().__init__(f"{endpoint}: {type(error).__name__}: {error}")


class PodHTTPError(RuntimeError):
    """A non-2xx pod answer; carries status, parsed body, and the 429
    ``retry_after`` hint so the broker can relay honest backpressure."""

    def __init__(self, status: int, body):
        self.status = status
        self.body = body
        self.retry_after = None
        if isinstance(body, dict):
            self.retry_after = body.get("retry_after")
        super().__init__(f"HTTP {status}: {body}")


def backoff_delay(
    attempt: int,
    backoff_seconds: float,
    backoff_max_seconds: float,
) -> float:
    """The PR-2 retry-policy shape as one pure function: delay before
    retry ``attempt`` (1-based), exponential from ``backoff_seconds``
    and capped — shared by this client and ``tools/gol_client.py``'s
    429 loop so every wire retry schedule in the system is the same
    deterministic curve."""
    if attempt < 1 or backoff_seconds <= 0:
        return 0.0
    return min(backoff_seconds * (2 ** (attempt - 1)), backoff_max_seconds)


class PodClient:
    """One pod gateway, as a bounded-time object.

    ``timeout`` is the per-request socket budget for control calls;
    ``probe_timeout`` (defaults to ``timeout``) is the tighter budget
    :meth:`health` uses — probe liveness questions deserve probe-sized
    patience.  ``attempts``/``backoff_seconds``/``backoff_max_seconds``
    are the transport retry policy (attempts=1 disables retries, the
    prober's setting: one miss is one datum)."""

    def __init__(
        self,
        base_url: str,
        timeout: float = 30.0,
        probe_timeout: float | None = None,
        attempts: int = 1,
        backoff_seconds: float = 0.05,
        backoff_max_seconds: float = 1.0,
        connect_timeout: float | None = None,
        response_cap: int = DEFAULT_RESPONSE_CAP,
    ):
        split = urlsplit(base_url if "//" in base_url else f"//{base_url}")
        self.host = split.hostname or "127.0.0.1"
        self.port = split.port or 80
        self.base_url = f"http://{self.host}:{self.port}"
        self.timeout = timeout
        self.probe_timeout = probe_timeout if probe_timeout else timeout
        self.attempts = max(1, attempts)
        self.backoff_seconds = backoff_seconds
        self.backoff_max_seconds = backoff_max_seconds
        # Split budgets (ISSUE 20): TCP connect gets its own (usually
        # tighter) deadline — a blackholed address should fail in
        # connect_timeout, not eat the whole read budget.  Default:
        # min(read budget, 10 s).
        self.connect_timeout = (
            float(connect_timeout)
            if connect_timeout is not None
            else min(timeout, 10.0)
        )
        self.response_cap = int(response_cap)

    def __repr__(self) -> str:
        return f"PodClient({self.base_url})"

    # -- transport -------------------------------------------------------------
    def _once(
        self,
        method: str,
        path: str,
        body: dict | None,
        headers: dict | None,
        timeout: float,
    ):
        # Connect under its own deadline, then widen to the read
        # budget for the request/response exchange (the split-timeout
        # discipline: a blackholed pod fails fast, a slow answer gets
        # its full read budget).
        conn = http.client.HTTPConnection(
            self.host, self.port, timeout=min(self.connect_timeout, timeout)
        )
        try:
            conn.connect()
            if conn.sock is not None:
                conn.sock.settimeout(timeout)
            payload = json.dumps(body).encode() if body is not None else None
            send_headers = dict(headers or {})
            if payload:
                send_headers["Content-Type"] = "application/json"
            conn.request(method, path, body=payload, headers=send_headers)
            resp = conn.getresponse()
            cap = self.response_cap
            declared = int(resp.headers.get("Content-Length") or 0)
            if declared > cap:
                raise PodHTTPError(
                    resp.status,
                    {
                        "error": f"response of {declared} bytes exceeds "
                        f"the {cap}-byte cap"
                    },
                )
            raw = resp.read(cap + 1)
            if len(raw) > cap:
                raise PodHTTPError(
                    resp.status,
                    {"error": f"response exceeds the {cap}-byte cap"},
                )
            try:
                doc = json.loads(raw) if raw else {}
            except ValueError:
                doc = {"raw": raw.decode(errors="replace")}
            if resp.status >= 400:
                raise PodHTTPError(resp.status, doc)
            return doc
        finally:
            conn.close()

    def request(
        self,
        method: str,
        path: str,
        body: dict | None = None,
        headers: dict | None = None,
        timeout: float | None = None,
    ):
        """One bounded-time request with the deterministic transport
        retry ladder.  HTTP errors pass straight through (a pod that
        ANSWERED is reachable); only transport failures are retried."""
        budget = self.timeout if timeout is None else timeout
        last: BaseException | None = None
        for attempt in range(1, self.attempts + 1):
            try:
                return self._once(method, path, body, headers, budget)
            except PodHTTPError:
                raise
            except (OSError, http.client.HTTPException) as e:
                last = e
                if attempt < self.attempts:
                    time.sleep(
                        backoff_delay(
                            attempt,
                            self.backoff_seconds,
                            self.backoff_max_seconds,
                        )
                    )
        raise PodUnreachable(self.base_url, last)

    # -- the gateway verbs the broker needs ------------------------------------
    def health(self) -> dict:
        """``GET /healthz`` under the probe budget.  A 503 body that
        still carries the health dict is an ANSWER (not-ready-but-live
        pods report through it); anything else re-raises."""
        try:
            return self.request(
                "GET", "/healthz", timeout=self.probe_timeout
            )
        except PodHTTPError as e:
            if isinstance(e.body, dict) and "ready" in e.body:
                return e.body
            raise

    def submit(
        self,
        doc: dict,
        traceparent: str | None = None,
        idempotency_key: str | None = None,
    ) -> dict:
        """``POST /v1/sessions`` — the spec doc verbatim (the broker
        forwards what the client sent; ``serve/wire.py`` on the pod is
        the single schema authority).  ``traceparent`` rides as the W3C
        header so the pod joins the broker's trace.

        One ``X-Gol-Idempotency-Key`` is minted per *call* (not per
        attempt), so the internal transport-retry ladder — exactly the
        path a response that died mid-body takes — replays the stored
        receipt instead of double-placing the tenant.  Pass
        ``idempotency_key`` to span retries ABOVE this call (the
        broker's spill-and-retry)."""
        headers = {IDEMPOTENCY_HEADER: idempotency_key or uuid.uuid4().hex}
        if traceparent:
            headers["traceparent"] = traceparent
        return self.request("POST", "/v1/sessions", doc, headers=headers)

    def sessions(self) -> dict:
        return self.request("GET", "/v1/sessions")

    def state(self, tenant: str) -> dict:
        return self.request("GET", f"/v1/sessions/{tenant}/state")

    def control(self, tenant: str, action: str) -> dict:
        """``POST /v1/sessions/<t>/pause|resume|quit``."""
        return self.request("POST", f"/v1/sessions/{tenant}/{action}")

    def drain(self, timeout: float | None = None) -> dict:
        """``POST /v1/drain`` — returns the parked-resumable receipt the
        migration path readopts from.  The socket budget stretches to
        cover the drain itself."""
        path = "/v1/drain"
        if timeout is not None:
            path += f"?timeout={timeout:g}"
        budget = self.timeout + (timeout or 0.0)
        return self.request("POST", path, timeout=budget)


__all__ = [
    "DEFAULT_RESPONSE_CAP",
    "IDEMPOTENCY_HEADER",
    "PodClient",
    "PodHTTPError",
    "PodUnreachable",
    "backoff_delay",
]
