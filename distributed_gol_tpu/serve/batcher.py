"""Dispatch coalescing for the serving plane (ISSUE 8 tentpole).

The PR-6 plane multiplexed N sessions onto one pod but still paid one
full XLA launch per tenant per superstep — BENCH_SERVE_PR6 records the
result: 0.81x aggregate scaling at n16, per-launch overhead eating the
fan-out the reference system exists for (one broker amortising control
overhead across its workers, PAPER.md §1).  This module is the missing
amortiser: resident sessions whose Params agree on every
dispatch-relevant field (:func:`cohort_key`) form a **launch cohort**,
and each superstep the cohort's members rendezvous at the dispatch seam
— one :class:`~distributed_gol_tpu.engine.backend.BatchedBackend`
launch advances every member's board and reduces every member's count.

Design constraints, in order:

- **Isolation first.**  Each tenant keeps its own controller,
  supervisor ladder, event stream, checkpoint dir, and
  ``DispatchRecorder`` labels — the cohort exists only BELOW the
  dispatch seam, inside :class:`_CohortMember.run_turns_async`.  A
  member that stops showing up (faulted and burning its PR-2 retry
  budget, wedged, paused, or just slow) delays its cohort-mates by at
  most ``cohort_grace_seconds`` per round; once ``cohort_evict_misses``
  rounds have fired without it AND it has been absent from the seam
  for that many grace windows, it is EVICTED back to a solo launch
  (``solo=True`` — the inherited ``Backend.run_turns_async``;
  ``_Cohort._evict_stale`` records why both gates are needed), so the
  PR-6 chaos guarantees hold with batching on: a sick slot can never
  hold the cohort hostage, and a healthy cohort stays bit-identical to
  solo oracles either way (the batched forms are bit-identical per
  slot by construction).
- **Never a stall.**  Every wait in the rendezvous is bounded: a round
  fires on full membership, at the ``cohort_grace_seconds`` hard cap,
  or — when the optional ``cohort_quiesce_seconds`` early-fire lever
  is armed — once no new member has joined for a quiesce beat; and a
  long fire-guard bounds waiting on another member's in-flight launch
  (first-trace jit compiles).  A member that outwaits either simply
  runs its dispatch solo — correct, just unamortised.  A round that
  fires partial does NOT strand the cohort: rounds key on the
  requested turn count, so latecomers join the next open round and
  the halves re-merge within a superstep.
- **Membership follows the plane.**  Cohorts are (re)computed on admit
  (:meth:`CohortBatcher.member_backend`, the plane's default
  ``backend_factory``), and on park/drain/completion
  (:meth:`CohortBatcher.retire`, from the plane's ``_on_done``).

Obs: ``serve.batched_launches`` / ``serve.batched_boards`` count fired
rounds and the boards they carried (mean cohort size = boards/launches);
``serve.cohort_evictions`` counts the eviction ladder; solo fallbacks
show up as the members' ordinary ``backend.dispatches.*`` bumps — so
one snapshot separates physical launches from per-tenant logical
dispatches (the ``controller.dispatches{tenant=}`` series stays
truthful per tenant, pinned by test).
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import fields

from distributed_gol_tpu.engine.backend import Backend, BatchedBackend
from distributed_gol_tpu.engine.params import Params
from distributed_gol_tpu.obs import metrics as metrics_lib
from distributed_gol_tpu.obs import tracing

#: Params fields that cannot change what or when a session dispatches:
#: identity, filesystem scoping, and the board's INITIAL CONTENT (cohort
#: members differ by soup on purpose).  Every other field is part of the
#: cohort key — conservatively, so two same-shape tenants differing in
#: ANY dispatch-relevant knob (rule, engine, superstep, cadences such as
#: ``sdc_check_every_turns``…) can never silently share a launch.
_KEY_IRRELEVANT = frozenset(
    {"tenant", "out_dir", "images_dir", "soup_density", "soup_seed", "threads"}
)


def cohort_key(params: Params) -> tuple:
    """The launch-cohort grouping key: every dispatch-relevant Params
    field, as a hashable tuple.  Built by EXCLUSION (see
    ``_KEY_IRRELEVANT``) so a future Params field is cohort-splitting by
    default — the safe failure mode is a smaller cohort, never a wrong
    shared launch."""
    return tuple(
        (f.name, getattr(params, f.name))
        for f in fields(Params)
        if f.name not in _KEY_IRRELEVANT
    )


class _Round:
    """One rendezvous: the members who showed up for a dispatch of
    ``turns`` generations before it fired."""

    __slots__ = (
        "turns", "entries", "t0", "last_join", "state", "results", "error",
    )

    def __init__(self, turns: int):
        self.turns = turns
        self.entries: list[tuple[str, object]] = []  # (tenant, board)
        self.t0 = time.monotonic()
        self.last_join = self.t0
        self.state = "open"  # open -> firing -> fired
        self.results: dict[str, tuple] = {}
        self.error: BaseException | None = None


class _Cohort:
    """The members sharing one :func:`cohort_key` and the
    :class:`BatchedBackend` their rounds launch through."""

    def __init__(self, batcher: "CohortBatcher", key: tuple, params: Params):
        self._batcher = batcher
        self.key = key
        self._cond = threading.Condition()
        self.members: dict[str, "_CohortMember"] = {}
        self._rounds: dict[int, _Round] = {}
        self._fired = 0  # rounds fired over the cohort's life
        self.backend = BatchedBackend(params)

    def add(self, member: "_CohortMember") -> None:
        with self._cond:
            member.last_arrival = time.monotonic()
            member.seen_fire = self._fired
            self.members[member.params.tenant] = member

    def remove(self, tenant: str) -> bool:
        """Drop a member (retired/re-admitted elsewhere); waiters
        re-evaluate expected membership.  Returns whether the cohort is
        now empty (the batcher GCs it)."""
        with self._cond:
            self.members.pop(tenant, None)
            self._cond.notify_all()
            return not self.members

    def dispatch(self, member: "_CohortMember", board, turns: int):
        """The rendezvous: join (or open) the round for ``turns``, wait
        for the rest of the cohort up to the grace window, and either
        fire the batched launch on THIS thread or pick up the slot
        result another member's firing produced.  Returns the member's
        (board, count) pair, or None when the member must run solo
        (evicted mid-wait, launch failure, or fire-guard timeout)."""
        tenant = member.params.tenant
        with self._cond:
            if self.members.get(tenant) is not member or member.solo:
                # Retired, evicted, or replaced by a supervisor-rebuild
                # member: this instance dispatches solo (two backends
                # joining one round under one tenant name would collide
                # in the results map).
                return None
            rnd = self._rounds.get(turns)
            if rnd is None or rnd.state != "open":
                rnd = _Round(turns)
                self._rounds[turns] = rnd
            rnd.entries.append((tenant, board))
            rnd.last_join = time.monotonic()
            member.last_arrival = rnd.last_join
            member.seen_fire = self._fired
            if self._batcher.quiesce:
                # Joins reset waiters' quiescence clocks — only armed
                # pods pay the wakeup storm (B waiters × B joins); with
                # quiescence off, waiters need waking only at the fire,
                # and the member completing the membership fires it
                # itself (its own gather loop exits without waiting).
                self._cond.notify_all()
            deadline = rnd.t0 + self._batcher.grace
            # Fire on: full membership (instantly), the optional join-
            # quiescence window (no new arrival for a quiesce beat —
            # the early-fire lever; 0 = off, see ServeConfig), or the
            # grace deadline (hard cap).
            quiesce = self._batcher.quiesce
            while rnd.state == "open" and len(rnd.entries) < len(self.members):
                now = time.monotonic()
                wake = deadline
                if quiesce:
                    wake = min(wake, rnd.last_join + quiesce)
                if now >= wake:
                    break
                self._cond.wait(timeout=wake - now)
            if rnd.state != "open":
                # Another member is firing (or fired) this round; wait it
                # out under the long guard — first-trace compiles are
                # legitimate minutes on a TPU — then take the slot.
                guard = time.monotonic() + self._batcher.fire_guard_seconds
                while rnd.state != "fired":
                    remaining = guard - time.monotonic()
                    if remaining <= 0 or not self._cond.wait(timeout=remaining):
                        return None  # outwaited the guard: run solo
                if rnd.error is not None:
                    return None
                return rnd.results[tenant]
            # This thread fires the round.
            rnd.state = "firing"
            if self._rounds.get(turns) is rnd:
                del self._rounds[turns]
            entries = list(rnd.entries)
            present = {t for t, _ in entries}
            self._fired += 1
            evicted = self._evict_stale(present)
        for m in evicted:
            self._batcher._c_evicted.inc()
        t0_ns = tracing.clock_ns()
        try:
            outs, counts = self.backend.run_boards(
                [b for _, b in entries], turns
            )
            results = {
                t: (o, c)
                for (t, _), o, c in zip(entries, outs, counts)
            }
            error = None
        except Exception as e:  # noqa: BLE001 — members fall back solo
            results, error = {}, e
        self._record_launch_spans(entries, turns, t0_ns, error)
        with self._cond:
            rnd.results = results
            rnd.error = error
            rnd.state = "fired"
            if error is not None:
                # A failed batched launch demotes the whole round to solo
                # — permanently (the documented ``solo`` contract): a
                # build/trace failure at this arity is deterministic, and
                # without the demotion every later superstep would pay
                # the same doomed batched attempt before each member's
                # solo fallback.  Launch-SUCCEEDED device errors surface
                # at the members' count forces instead and never demote.
                for t, _ in entries:
                    m = self.members.pop(t, None)
                    if m is not None:
                        m.solo = True
            self._cond.notify_all()
        self._batcher._record_round(len(entries), error)
        if error is not None:
            return None
        return results[tenant]

    def _record_launch_spans(self, entries, turns, t0_ns, error) -> None:
        """One batched-launch span per MEMBER trace (ISSUE 15): every
        member's request timeline shows the shared launch, stamped with
        one ``launch`` id and cross-``links`` to the other members'
        trace ids — how an operator attributes one tenant's latency to a
        cohort-mate's compile or a shared device stall.  Cold-ish path
        (once per fired round); tenants without an active trace cost one
        dict lookup."""
        t1_ns = tracing.clock_ns()
        launch_id = self._batcher._next_launch_id()
        member_traces = [
            (t, tracing.TRACER.for_tenant(t)) for t, _ in entries
        ]
        member_traces = [
            (t, tr) for t, tr in member_traces if tr is not None and not tr.ended
        ]
        ids = [tr.trace_id for _, tr in member_traces]
        for tenant, tr in member_traces:
            tr.record_span(
                "gol.cohort.launch",
                t0_ns,
                t1_ns,
                launch=launch_id,
                boards=len(entries),
                turns=turns,
                error=type(error).__name__ if error is not None else None,
                links=[i for i in ids if i != tr.trace_id],
            )

    def _evict_stale(self, present: set[str]) -> list["_CohortMember"]:
        """Under the lock: the straggler/faulted-slot eviction ladder.
        A member absent from this fired round is evicted back to solo
        launches once BOTH hold: ``cohort_evict_misses`` rounds have
        fired since it last arrived at the dispatch seam, AND it has
        been absent for that many grace windows of wall clock.  The
        round gate means an actively-dispatching member desynced in
        *phase* (a split cohort's other half — its arrivals keep its
        fire watermark fresh) is never evicted; the time gate means a
        burst of partial rounds cannot evict a member that was simply
        descheduled for a beat.  A faulted member (burning its PR-2
        retry budget, wedged, parked) fails both and drops out.
        Returns the evicted members (counters bumped outside the
        lock)."""
        n = self._batcher.evict_after
        horizon = time.monotonic() - n * self._batcher.grace
        evicted = []
        for t in list(self.members):
            if t in present:
                continue
            m = self.members[t]
            if self._fired - m.seen_fire >= n and m.last_arrival < horizon:
                del self.members[t]
                m.solo = True
                evicted.append(m)
        return evicted


class _CohortMember(Backend):
    """A tenant's backend inside a launch cohort: the full solo
    :class:`Backend` surface — placement, viewer dispatches, cycle
    probes, and the PR-5 SDC probes (per-slot fingerprint legs) — with
    ONLY the dispatch seam routed through the cohort rendezvous.
    Evicted members (``solo=True``) run the inherited solo dispatch
    from then on; either path is bit-identical, so eviction is a
    performance decision, never a correctness one."""

    def __init__(self, params: Params, cohort: _Cohort):
        super().__init__(params)
        self._cohort = cohort
        #: Flipped by the eviction ladder (or a failed cohort launch):
        #: this member dispatches solo for the rest of its run.
        self.solo = False
        #: Eviction-ladder watermarks (maintained by the cohort under
        #: its lock): when this member last reached the dispatch seam,
        #: and the cohort's fired-round count at that moment.
        self.last_arrival = 0.0
        self.seen_fire = 0

    def run_turns_async(self, board, turns: int):
        if not self.solo and turns:
            res = self._cohort.dispatch(self, board, turns)
            if res is not None:
                return res
        return super().run_turns_async(board, turns)


class CohortBatcher:
    """The plane-wide coalescer: one :class:`_Cohort` per distinct
    :func:`cohort_key` among resident sessions (``ServeConfig.batched``
    turns it on).  Thread-safe; every method is safe to call from the
    plane's lock-free paths."""

    def __init__(self, config, metrics: bool = True):
        self.grace = config.cohort_grace_seconds
        self.quiesce = config.cohort_quiesce_seconds
        self.evict_after = config.cohort_evict_misses
        #: Bound on waiting for another member's in-flight launch: must
        #: cover a first-trace jit compile, after which the waiter falls
        #: back to a solo dispatch rather than stall its watchdog.
        self.fire_guard_seconds = 300.0
        self._lock = threading.Lock()
        self._cohorts: dict[tuple, _Cohort] = {}
        self._tenant_cohort: dict[str, _Cohort] = {}
        reg = metrics_lib.registry_for(metrics)
        self._c_launches = reg.counter("serve.batched_launches")
        self._c_boards = reg.counter("serve.batched_boards")
        self._c_failed = reg.counter("serve.batched_launch_failures")
        self._c_evicted = reg.counter("serve.cohort_evictions")
        self._g_cohorts = reg.gauge("serve.cohorts")
        self._g_cohorts.set(0)
        # Monotonic batched-launch id (ISSUE 15): stamped on the
        # ``gol.cohort.launch`` span in every member's request trace, so
        # the traces of one shared launch join on it.
        self._launch_ids = itertools.count(1)

    def _next_launch_id(self) -> int:
        return next(self._launch_ids)

    def member_backend(self, params: Params):
        """Build the backend for one admitted session: a cohort member
        when the Params can cohort (single-device; tenant-stamped), a
        plain solo :class:`Backend` otherwise.  The plane's default
        ``backend_factory`` — also the seam chaos tests wrap with
        ``FaultInjectionBackend``."""
        if params.tenant is None or params.mesh_shape != (1, 1):
            return Backend(params)
        key = cohort_key(params)
        with self._lock:
            cohort = self._cohorts.get(key)
            if cohort is None:
                cohort = self._cohorts[key] = _Cohort(self, key, params)
            prev = self._tenant_cohort.get(params.tenant)
            # Claim the cohort UNDER the batcher lock, before the (slow)
            # member construction below: the claim is what stops a
            # concurrent retire of the cohort's last member from GC-ing
            # it out of ``_cohorts`` in the window — which would orphan
            # this member and permanently split same-key tenants (the
            # GC predicate checks these claims).
            self._tenant_cohort[params.tenant] = cohort
            self._g_cohorts.set(len(self._cohorts))
        if prev is not None and prev is not cohort and prev.remove(params.tenant):
            self._gc(prev)
        try:
            member = _CohortMember(params, cohort)
        except Exception:
            # Failed build: release the claim so the cohort can GC.
            with self._lock:
                if self._tenant_cohort.get(params.tenant) is cohort:
                    del self._tenant_cohort[params.tenant]
            raise
        cohort.add(member)
        return member

    def retire(self, tenant: str) -> None:
        """A session reached a terminal state (completed, parked,
        drained, failed, shed): leave its cohort so rounds stop waiting
        for it.  Idempotent; unknown tenants are a no-op."""
        with self._lock:
            cohort = self._tenant_cohort.pop(tenant, None)
        if cohort is not None and cohort.remove(tenant):
            self._gc(cohort)

    def _gc(self, cohort: _Cohort) -> None:
        with self._lock:
            if (
                self._cohorts.get(cohort.key) is cohort
                and not cohort.members
                and cohort not in self._tenant_cohort.values()
            ):
                del self._cohorts[cohort.key]
            self._g_cohorts.set(len(self._cohorts))

    def _record_round(self, boards: int, error) -> None:
        if error is not None:
            self._c_failed.inc()
            return
        self._c_launches.inc()
        self._c_boards.inc(boards)

    # -- introspection (tests, health) -----------------------------------------
    @property
    def cohorts(self) -> int:
        with self._lock:
            return len(self._cohorts)

    def cohort_of(self, tenant: str):
        with self._lock:
            return self._tenant_cohort.get(tenant)
