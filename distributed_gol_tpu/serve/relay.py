"""Spectator relay tier (ISSUE 18) — broadcast-tree frame fan-out.

The FramePlane (ISSUE 11) fans ONE device fetch to N direct
subscribers, and the gateway (ISSUE 14) puts that stream on the wire —
but N is bounded by one pod's sockets and egress.  This module is the
tier that unbounds it: a :class:`RelayServer` is a standalone process
(stdlib + the existing ``serve/ws.py`` codec and ``serve/httpd.py``
scaffolding, never a device) that subscribes ONCE to an upstream
spectator stream — a gateway pod, or ANOTHER relay, so trees chain to
arbitrary depth — and re-fans the frames to M downstream WebSocket
clients.  Depth 2–3 of modest fan-out reaches 10⁶ viewers while the
pod still pays one device fetch and one spectator socket per subtree.

Hot-path contract (the perf_opt):

- **Header-only decode.**  Each upstream binary frame is parsed to its
  length-prefixed JSON header (``type``/``turn``/``rect``) and no
  further — payload bytes are never touched, let alone re-encoded.
- **Single-serialize / multi-write.**  The outgoing WebSocket frame is
  encoded ONCE per upstream message (``ws.encode_server_frame``) and
  the same buffer is written to every downstream socket
  (``WebSocket.send_raw`` over a ``memoryview``) — fan-out cost is M
  writes, not M serializations.
- **Re-keyframe cache.**  The last keyframe plus every delta since
  (bounded at ``cache_deltas``) is retained verbatim; late joiners and
  drop-recovered clients are served from it LOCALLY — zero upstream
  round trips, the pod never learns a viewer joined.  When the delta
  tail would overflow, the cache is *compacted*: the retained frames
  are folded into one synthesized keyframe (the single place the relay
  decodes payload bytes — amortized one band-apply per frame, and one
  keyframe encode per ``cache_deltas`` frames).
- **Stall isolation.**  Per-downstream bounded queues drop OLDEST on
  overflow and flag the client for a cache resync (keyframe + deltas,
  then live) — one stalled viewer never backpressures the tree, same
  contract as the FramePlane it mirrors.
- **Seq-gap resubscribe.**  An upstream disconnect triggers
  capped-exponential-backoff resubscription.  Frames may have been
  missed in the gap, so deltas are REFUSED until the new
  subscription's keyframe arrives (a fresh FramePlane subscriber — or
  a parent relay's cache — always keyframes first); relaying that
  keyframe verbatim is what re-keyframes the whole subtree.  The cache
  keeps serving late joiners across the outage.

Observability (grown for ISSUE 19's fleet plane): ``relay.*``
counters plus a ``relay.frame_staleness_seconds`` histogram (frame
age at ingest, from the pod's wall-clock ``ts`` header stamp — blobs
ride verbatim, so the last hop of a depth-N chain measures true
end-to-end staleness) on the relay's own registry; ``/healthz`` (body
carries ``"relay": true`` — what flips ``tools/pod_top.py`` into the
relay view), ``/metrics`` (OpenMetrics) and ``/traces``.  The relay
joins the stream's distributed trace from the upstream hello's
traceparent (``gol.relay.subscribe`` / ``.resubscribe`` /
``.cache_serve`` spans, a ``gol.relay.first_frame`` event) and
re-exports the traceparent downstream, so ``/fleet/traces`` stitches
pod, relay and broker legs on one id.  Downstream endpoint: ``GET
/v1/frames`` (upgrade) —
``/v1/sessions/<anything>/frames`` is an alias, so
``tools/gol_client.py`` spectates a relay with no client-side changes.
"""

from __future__ import annotations

import itertools
import json
import queue
import struct
import threading
import time
from urllib.parse import urlsplit

from distributed_gol_tpu.obs import metrics as metrics_lib
from distributed_gol_tpu.obs import openmetrics
from distributed_gol_tpu.obs import tracing
from distributed_gol_tpu.serve import ws as ws_lib
from distributed_gol_tpu.serve.httpd import StdlibHTTPServer
from distributed_gol_tpu.serve.ws import WsClosed, WsTimeout

#: Default per-downstream queue depth (frames) — the FramePlane default.
DEFAULT_QUEUE_DEPTH = 8

#: Default cached-delta bound before compaction.
DEFAULT_CACHE_DELTAS = 64

#: Resubscribe backoff curve: initial and cap, seconds.
BACKOFF_INITIAL = 0.25
BACKOFF_MAX = 5.0

#: Default upstream keepalive (ISSUE 20): frames can be arbitrarily
#: sparse (a paused session), so silence alone is not death — but an
#: upstream that answers neither frames NOR pongs inside this bound
#: times 3 misses is a half-open stall, treated exactly like a
#: disconnect (backoff-resubscribe, seq-gap latch re-anchors).
DEFAULT_KEEPALIVE = 20.0


def _parse_frame_header(blob) -> dict:
    """The JSON header of one spectator wire message — the ONLY part of
    an upstream frame the relay hot path decodes (payload bytes ride
    through verbatim)."""
    if len(blob) < 4:
        raise ValueError("frame message shorter than its length prefix")
    (hlen,) = struct.unpack_from(">I", blob)
    if 4 + hlen > len(blob):
        raise ValueError("frame header truncated")
    return json.loads(bytes(blob[4 : 4 + hlen]))


def _wire_blob(frame: bytes) -> bytes:
    """The spectator wire message inside a cached ws frame (strip the
    ws header) — the compaction path's inverse of
    ``ws.encode_server_frame``."""
    n7 = frame[1] & 0x7F
    off = 2 + (2 if n7 == 126 else 8 if n7 == 127 else 0)
    return frame[off:]


class _Downstream:
    """One relayed viewer: a bounded frame queue (drop-oldest) and the
    resync flag its pump services from the cache."""

    def __init__(self, cid: int, depth: int):
        self.id = cid
        self.frames: queue.Queue = queue.Queue(maxsize=max(1, depth))
        self.dropped = False  # overflowed: pump resyncs from the cache


class RelayServer(StdlibHTTPServer):
    """One relay node.  ``upstream`` is a spectator stream URL — a
    gateway leg (``http://pod/v1/sessions/<t>/frames?rect=...``) or
    another relay (``http://relay/v1/frames``).  ``port=0`` binds
    ephemeral and publishes the URL as the ``relay.endpoint`` info
    label on the relay's own registry."""

    thread_name = "gol-relay-http"

    def __init__(
        self,
        upstream: str,
        port: int = 0,
        host: str = "127.0.0.1",
        cache_deltas: int = DEFAULT_CACHE_DELTAS,
        queue_depth: int = DEFAULT_QUEUE_DEPTH,
        backoff_initial: float = BACKOFF_INITIAL,
        backoff_max: float = BACKOFF_MAX,
        connect_timeout: float = 10.0,
        keepalive_seconds: float = DEFAULT_KEEPALIVE,
        registry=None,
    ):
        self.upstream = upstream
        self._cache_max = max(1, int(cache_deltas))
        self._queue_depth = max(1, int(queue_depth))
        self._backoff_initial = backoff_initial
        self._backoff_max = backoff_max
        self._connect_timeout = connect_timeout
        self._keepalive_seconds = float(keepalive_seconds)

        self._lock = threading.Lock()
        self._clients: dict[int, _Downstream] = {}
        self._ids = itertools.count(1)
        #: The re-keyframe cache: (turn, encoded ws frame) anchor plus
        #: the verbatim delta tail since it.
        self._cache_key: tuple[int, bytes] | None = None
        self._cache_deltas: list[tuple[int, bytes]] = []
        #: Seq-gap latch: True while inbound deltas cannot be assumed
        #: contiguous with the cache (fresh start, post-reconnect) —
        #: they are refused until a keyframe re-anchors the stream.
        self._gap = True
        self._hello: dict = {"type": "hello", "tenant": None, "rect": None}
        #: Set on the FIRST upstream hello — downstream upgrades wait
        #: (bounded) on it so a chain built faster than its hellos
        #: propagate never caches a default (tenant-less) hello at a
        #: lower tier.  Stays set forever after; only the construction
        #: window can stall, and only until the upstream speaks.
        self._hello_seen = threading.Event()
        self._turn = 0
        self._connected = False
        self._ended = threading.Event()
        self._closing = False
        self._upstream_ws = None
        #: The relay's leg of the distributed trace: joined from the
        #: upstream hello's traceparent (same trace id as the gateway's
        #: ``gol.request`` — what ``/fleet/traces`` stitches on) and
        #: re-exported downstream so chained relays join the same trace.
        self._trace: tracing.Trace | None = None
        self._first_frame_pending = False
        self._t_subscribe_ns = tracing.clock_ns()

        reg = registry if registry is not None else metrics_lib.MetricsRegistry()
        self._m_frames_in = reg.counter("relay.frames_in")
        self._m_frames_out = reg.counter("relay.frames_out")
        self._m_bytes_in = reg.counter("relay.bytes_in")
        self._m_bytes_out = reg.counter("relay.bytes_out")
        self._m_drops = reg.counter("relay.drops")
        self._m_cache_serves = reg.counter("relay.cache_serves")
        self._m_resubscribes = reg.counter("relay.resubscribes")
        self._m_keepalive_drops = reg.counter("net.keepalive_drops")
        #: End-to-end frame age at ingest, from the ``ts`` wall-clock
        #: stamp pods put in the frame header — relays forward blobs
        #: verbatim, so a depth-N chain's last hop still measures true
        #: pod-to-here staleness.
        self._m_staleness = reg.histogram("relay.frame_staleness_seconds")
        self._g_clients = reg.gauge("relay.clients")
        self._g_clients.set(0)
        reg.info("relay.upstream", upstream)
        super().__init__(port=port, host=host, registry=reg)
        reg.info("relay.endpoint", self.url)
        self._thread_up = threading.Thread(
            target=self._upstream_loop, name="gol-relay-upstream", daemon=True
        )
        self._thread_up.start()

    # -- lifecycle -------------------------------------------------------------
    def close(self) -> None:
        self._closing = True
        u = self._upstream_ws
        if u is not None:
            u.abort()  # unblock the reader parked in recv
        t = self._trace
        if t is not None:
            self._trace = None
            tracing.TRACER.end_trace(t)
        super().close()

    # -- the upstream leg ------------------------------------------------------
    def _connect_upstream(self):
        u = urlsplit(self.upstream)
        path = u.path or "/v1/frames"
        if u.query:
            path += "?" + u.query
        return ws_lib.client_connect(
            u.hostname or "127.0.0.1",
            u.port or 80,
            path,
            timeout=self._connect_timeout,
        )

    def _upstream_loop(self) -> None:
        """Subscribe ONCE; on disconnect, capped-backoff resubscribe.
        Every (re)connection opens the seq-gap latch — the new
        subscription's first keyframe closes it and, relayed verbatim,
        re-keyframes the whole downstream subtree."""
        backoff = self._backoff_initial
        first = True
        while not self._closing and not self._ended.is_set():
            if not first:
                self._m_resubscribes.inc()
                t0 = tracing.clock_ns()
                time.sleep(backoff)
                if self._trace is not None:
                    self._trace.record_span(
                        "gol.relay.resubscribe",
                        t0,
                        tracing.clock_ns(),
                        backoff_seconds=backoff,
                    )
                backoff = min(backoff * 2, self._backoff_max)
            first = False
            self._t_subscribe_ns = tracing.clock_ns()
            try:
                wsock = self._connect_upstream()
            except (OSError, WsClosed, ValueError):
                continue
            self._upstream_ws = wsock
            # Frames can be arbitrarily sparse (a paused session), so
            # silence alone is not death — the keepalive pings through
            # it, and only an upstream that answers neither frames nor
            # pongs (the half-open stall) is dropped, riding the SAME
            # backoff-resubscribe + seq-gap path as a disconnect.
            # keepalive_seconds=0 restores the unbounded blocking read
            # (close()/abort() still unblocks it).
            if self._keepalive_seconds > 0:
                wsock.enable_keepalive(self._keepalive_seconds)
            else:
                wsock.settimeout(None)
            with self._lock:
                self._connected = True
                self._gap = True
            try:
                while not self._closing:
                    op, payload = wsock.recv()
                    if op == ws_lib.OP_TEXT:
                        self._on_text(payload)
                        if self._ended.is_set():
                            break
                        continue
                    self._ingest(payload)
                    backoff = self._backoff_initial
            except WsTimeout:
                # Stalled-not-closed upstream: count it, then recover
                # exactly like a disconnect.
                self._m_keepalive_drops.inc()
            except (WsClosed, OSError, ValueError):
                pass
            finally:
                with self._lock:
                    self._connected = False
                wsock.close()
        self._upstream_ws = None

    def _on_text(self, payload) -> None:
        try:
            msg = json.loads(payload)
        except ValueError:
            return
        kind = msg.get("type")
        if kind == "hello":
            trace = self._join_trace(
                msg.get("traceparent"), msg.get("tenant")
            )
            with self._lock:
                self._hello = {
                    "type": "hello",
                    "tenant": msg.get("tenant"),
                    "rect": msg.get("rect"),
                    "traceparent": (
                        trace.traceparent() if trace is not None
                        else None
                    ),
                }
                self._turn = max(self._turn, int(msg.get("turn") or 0))
            self._hello_seen.set()
        elif kind == "end":
            self._ended.set()
            # Wake every pump NOW (a None sentinel through the normal
            # queue) instead of waiting out its poll timeout — end
            # propagation stays prompt at any tree depth.
            with self._lock:
                for c in self._clients.values():
                    self._offer(c, None)

    def _join_trace(self, traceparent, tenant) -> tracing.Trace | None:
        """Join the stream's distributed trace from the upstream
        hello's traceparent — SAME trace id as the pod's
        ``gol.request`` (the ``/fleet/traces`` stitch key), this
        relay's spans riding as its own process lane.  A resubscribe
        to the same stream records a fresh subscribe span on the
        existing leg; a different stream retires the old leg first.
        An untraced upstream (no traceparent) records nothing."""
        old = self._trace
        parsed = tracing.parse_traceparent(traceparent)
        now = tracing.clock_ns()
        if old is not None:
            if parsed is not None and parsed[0] == old.trace_id:
                old.record_span(
                    "gol.relay.subscribe",
                    self._t_subscribe_ns,
                    now,
                    upstream=self.upstream,
                )
                return old
            self._trace = None
            tracing.TRACER.end_trace(old)
        if parsed is None:
            return None
        trace = tracing.TRACER.start_trace(
            "gol.relay.subscribe", traceparent=traceparent, tenant=tenant
        )
        trace.record_span(
            "gol.relay.subscribe",
            self._t_subscribe_ns,
            now,
            upstream=self.upstream,
        )
        self._trace = trace
        self._first_frame_pending = True
        return trace

    def _ingest(self, blob) -> None:
        """One upstream binary frame: header-only decode, cache update,
        single-serialize, fan-out.  The encoded ws frame is built ONCE;
        every downstream queue gets the same buffer."""
        header = _parse_frame_header(blob)
        kind = header.get("type")
        turn = int(header.get("turn") or 0)
        self._m_frames_in.inc()
        self._m_bytes_in.inc(len(blob))
        ts = header.get("ts")
        if isinstance(ts, (int, float)):
            self._m_staleness.observe(max(0.0, time.time() - ts))
        if self._first_frame_pending and self._trace is not None:
            self._first_frame_pending = False
            self._trace.add_event("gol.relay.first_frame", turn=turn)
        frame = ws_lib.encode_server_frame(ws_lib.OP_BINARY, blob)
        with self._lock:
            if kind == "keyframe":
                self._cache_key = (turn, frame)
                self._cache_deltas.clear()
                self._gap = False
                if header.get("rect") is not None:
                    self._hello["rect"] = header["rect"]
            elif kind == "delta":
                if self._gap or self._cache_key is None:
                    # Seq gap: a delta with no contiguous anchor cannot
                    # apply anywhere downstream — refuse it; the
                    # upstream re-keyframe re-anchors the stream.
                    self._m_drops.inc()
                    return
                self._cache_deltas.append((turn, frame))
                if len(self._cache_deltas) > self._cache_max:
                    self._compact_locked()
            else:
                return  # unknown frame kind: not relayed
            self._turn = turn
            mv = memoryview(frame)
            for c in self._clients.values():
                self._offer(c, mv)

    def _offer(self, c: _Downstream, frame) -> None:
        """Bounded fan-out put: drop OLDEST and flag the client for a
        cache resync — a stalled viewer loses frames, never stalls the
        tree.  Caller holds the relay lock (one producer; the lock is
        what makes cache snapshot + queue contents gap-free)."""
        while True:
            try:
                c.frames.put_nowait(frame)
                return
            except queue.Full:
                c.dropped = True
                self._m_drops.inc()
                try:
                    c.frames.get_nowait()
                except queue.Empty:
                    pass

    def _compact_locked(self) -> None:
        """Fold the cached delta tail into one synthesized keyframe so
        the cache stays bounded while late joiners are ALWAYS served —
        the only place the relay touches payload bytes, amortized one
        band-apply per frame plus one keyframe encode per
        ``cache_deltas`` frames.  Live streams never see the synthetic
        keyframe; it only anchors future cache serves."""
        import numpy as np

        from distributed_gol_tpu.engine import frames as frames_lib
        from distributed_gol_tpu.engine.events import FrameReady
        from distributed_gol_tpu.serve import wire

        key_turn, key_frame = self._cache_key
        ev = wire.decode_frame_event(_wire_blob(key_frame))
        buf = np.array(ev.frame, dtype=np.uint8, copy=True)
        turn, ts = key_turn, ev.ts
        for turn, frame in self._cache_deltas:
            delta = wire.decode_frame_event(_wire_blob(frame))
            frames_lib.apply_bands(buf, delta.bands)
            ts = delta.ts if delta.ts is not None else ts
        blob = wire.encode_frame_event(
            FrameReady(turn, buf, rect=ev.rect, ts=ts)
        )
        self._cache_key = (
            turn, ws_lib.encode_server_frame(ws_lib.OP_BINARY, blob)
        )
        self._cache_deltas.clear()

    def _cache_frames_locked(self) -> list:
        """Keyframe + delta tail, in ship order (caller holds the
        lock) — what a late joiner or a drop-recovered client is
        served.  Empty until the first upstream keyframe lands."""
        if self._cache_key is None:
            return []
        out = [self._cache_key[1]]
        out.extend(frame for _, frame in self._cache_deltas)
        return out

    # -- the downstream leg ----------------------------------------------------
    def handle(self, request, method: str, path: str, query: dict) -> bool:
        if path == "/healthz" and method == "GET":
            health = self.health()
            request._send_json(200 if health["ready"] else 503, health)
            return True
        if path == "/metrics" and method == "GET":
            text = openmetrics.render(self.registry.snapshot().to_dict())
            request._send(200, text.encode(), openmetrics.CONTENT_TYPE)
            return True
        if path == "/traces" and method == "GET":
            code, obj = tracing.http_traces(query)
            request._send_json(code, obj)
            return True
        if method == "GET" and (
            path == "/v1/frames"
            or (path.startswith("/v1/sessions/") and path.endswith("/frames"))
        ):
            return self._downstream_ws(request, query)
        return False

    def health(self) -> dict:
        with self._lock:
            cache = {
                "anchored": self._cache_key is not None,
                "keyframe_turn": (
                    self._cache_key[0] if self._cache_key else None
                ),
                "deltas": len(self._cache_deltas),
            }
            out = {
                "relay": True,
                "ready": self._connected or cache["anchored"],
                "connected": self._connected,
                "ended": self._ended.is_set(),
                "upstream": self.upstream,
                "endpoint": self.url,
                "tenant": self._hello.get("tenant"),
                "rect": self._hello.get("rect"),
                "turn": self._turn,
                "clients": len(self._clients),
                "cache": cache,
            }
        for name, counter in (
            ("frames_in", self._m_frames_in),
            ("frames_out", self._m_frames_out),
            ("bytes_in", self._m_bytes_in),
            ("bytes_out", self._m_bytes_out),
            ("drops", self._m_drops),
            ("cache_serves", self._m_cache_serves),
            ("resubscribes", self._m_resubscribes),
        ):
            out[name] = counter.value
        return out

    def _downstream_ws(self, request, query) -> bool:
        try:
            depth = max(1, int(query.get("queue", self._queue_depth)))
        except ValueError:
            request._send_json(400, {"error": "bad queue depth"})
            return True
        # Liveness over staleness, same as the gateway's spectator leg:
        # bound kernel send buffering so a stalled client's backpressure
        # reaches the drop-oldest queue within a few frames.
        try:
            import socket as socket_mod

            request.connection.setsockopt(
                socket_mod.SOL_SOCKET, socket_mod.SO_SNDBUF, 1 << 16
            )
        except OSError:
            pass
        wsock = ws_lib.server_upgrade(request)
        if wsock is None:
            return True
        # Bounded wait for the first upstream hello (see _hello_seen):
        # no-op after it ever arrived; a dead-at-birth upstream falls
        # through to the default hello after the timeout.
        self._hello_seen.wait(timeout=2.0)
        c = _Downstream(next(self._ids), depth)
        with self._lock:
            hello = dict(self._hello)
            hello["turn"] = self._turn
            hello["relay"] = True
            snapshot = self._cache_frames_locked()
            self._clients[c.id] = c
            self._g_clients.set(len(self._clients))
        dead = threading.Event()
        try:
            wsock.send_text(json.dumps(hello))
            self._serve_frames(wsock, snapshot, cached=True)
            self._start_reader(wsock, dead)
            while not dead.is_set() and not self._closing:
                if c.dropped:
                    # Drop recovery, served locally: snapshot the cache
                    # and clear the queue under the SAME lock the
                    # producer fans out under — everything fanned out
                    # after this snapshot is still in (or headed for)
                    # the queue, so the stream stays contiguous.
                    with self._lock:
                        snapshot = self._cache_frames_locked()
                        while True:
                            try:
                                c.frames.get_nowait()
                            except queue.Empty:
                                break
                        c.dropped = False
                    self._serve_frames(wsock, snapshot, cached=True)
                    continue
                try:
                    frame = c.frames.get(timeout=0.25)
                except queue.Empty:
                    if self._ended.is_set():
                        wsock.send_text(json.dumps({"type": "end"}))
                        break
                    continue
                if frame is None:  # end sentinel: drain then close out
                    if c.frames.empty() and self._ended.is_set():
                        wsock.send_text(json.dumps({"type": "end"}))
                        break
                    continue
                self._serve_frames(wsock, (frame,), cached=False)
        except (WsClosed, OSError):
            pass  # viewer left; the tree loses one leaf
        finally:
            with self._lock:
                self._clients.pop(c.id, None)
                self._g_clients.set(len(self._clients))
            wsock.close()
        return True

    def _serve_frames(self, wsock, frames, cached: bool) -> None:
        """Multi-write half of the hot path: pre-encoded frames go out
        verbatim.  ``cached`` counts re-keyframe-cache serves (late
        join, drop recovery) apart from live relay."""
        t0 = tracing.clock_ns() if cached and frames else None
        for frame in frames:
            n = wsock.send_raw(frame)
            self._m_frames_out.inc()
            self._m_bytes_out.inc(n)
            if cached:
                self._m_cache_serves.inc()
        if t0 is not None and self._trace is not None:
            self._trace.record_span(
                "gol.relay.cache_serve",
                t0,
                tracing.clock_ns(),
                frames=len(frames),
            )

    def _start_reader(self, wsock, dead) -> None:
        """Inbound frames from a viewer: the relay's streams are
        fixed-rect (one upstream subscription serves every leaf), so
        control frames are answered with an error, never forwarded —
        and a disconnect flags the pump."""

        def reader():
            try:
                while True:
                    wsock.recv()
                    wsock.send_text(json.dumps({
                        "type": "error",
                        "error": "relay streams are fixed-rect; "
                                 "set_viewport is not supported here",
                    }))
            except (WsClosed, OSError, ValueError):
                pass
            finally:
                dead.set()

        threading.Thread(
            target=reader, name="gol-relay-ws-reader", daemon=True
        ).start()


__all__ = [
    "BACKOFF_INITIAL",
    "BACKOFF_MAX",
    "DEFAULT_CACHE_DELTAS",
    "DEFAULT_QUEUE_DEPTH",
    "RelayServer",
]
