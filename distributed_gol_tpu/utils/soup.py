"""Seeded random-soup boards, generated in bounded memory.

One generator shared by the engine (``Params.soup_density``) and the
benchmark runner, so every consumer of a (density, seed) pair gets the
bit-identical board.  Generation is chunked in row blocks of float32
randoms: a naive ``np.where(rng.random((H, W)) < d, 255, 0)`` materialises
~17× the board size in float64/int64 temporaries — ~68 GB of host RAM for
the 65536² flagship board this feature exists to make practical.
"""

from __future__ import annotations

import numpy as np

_CHUNK_ROWS = 4096


def random_soup(
    height: int, width: int, density: float, seed: int = 0
) -> np.ndarray:
    """uint8 {0, 255} board with P(alive) = density, deterministic in
    (height, width, density, seed) — including across processes, which
    multi-host input loading relies on."""
    rng = np.random.default_rng(seed)
    out = np.empty((height, width), np.uint8)
    for y0 in range(0, height, _CHUNK_ROWS):
        y1 = min(height, y0 + _CHUNK_ROWS)
        block = rng.random((y1 - y0, width), dtype=np.float32) < density
        out[y0:y1] = block.astype(np.uint8) * np.uint8(255)
    return out
