"""Platform-selection guard shared by every process entry point.

Some TPU-terminal environments install a site hook that force-selects their
own PJRT platform via ``jax.config`` *after* JAX has parsed the
``JAX_PLATFORMS`` env var, so the env var alone silently stops working.
Every entry point (CLI, bench, driver hooks, tests) calls this once before
any backend initialises to make the env var authoritative again.
"""

from __future__ import annotations

import os


def honour_env_platforms() -> None:
    platforms = os.environ.get("JAX_PLATFORMS")
    if not platforms:
        return
    import jax

    try:
        jax.config.update("jax_platforms", platforms)
    except Exception:
        pass  # backends already initialized — too late to change, not fatal
