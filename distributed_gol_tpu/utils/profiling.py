"""Tracing/profiling hooks — the TPU analog of the reference's trace harness.

The reference wraps a run in Go's ``runtime/trace`` producing ``trace.out``
for ``go tool trace`` (``trace_test.go:12-29``) and prescribes pprof in its
report guidance.  The TPU equivalent is the XLA/JAX profiler: a trace
captures device kernel timelines (every Pallas launch, DMA, and collective)
viewable in Perfetto / TensorBoard.

Usage::

    from distributed_gol_tpu.utils.profiling import trace
    with trace("/tmp/gol-trace"):
        gol.run(params, events)
    # inspect with: tensorboard --logdir /tmp/gol-trace   (or Perfetto)

or from the CLI: ``python -m distributed_gol_tpu --trace /tmp/gol-trace``.

Degrades to a no-op (with a warning) when the jax build has no profiler
backend, so tracing never takes a run down.
"""

from __future__ import annotations

import contextlib
import warnings
from pathlib import Path

_UNRESOLVED = object()
_PROFILER = _UNRESOLVED  # the jax.profiler module, or None


def profiler():
    """THE ONE resolution/caching home for ``jax.profiler`` (ISSUE 15
    satellite): returns the module, or None on a stripped build —
    resolved once, cached, zero per-call import cost afterwards.  Both
    :func:`trace` and ``obs.spans`` degrade through this single seam, so
    profiler-less behaviour has one tested path."""
    global _PROFILER
    if _PROFILER is _UNRESOLVED:
        try:
            import jax

            _PROFILER = jax.profiler
        except Exception:  # stripped build: every consumer degrades
            _PROFILER = None
    return _PROFILER


def _reset_profiler_cache() -> None:
    """Testing hook: force the next :func:`profiler` call to re-resolve
    (pair with ``obs.spans._reset`` — its class cache sits above this)."""
    global _PROFILER
    _PROFILER = _UNRESOLVED


@contextlib.contextmanager
def trace(log_dir: str | Path):
    """Context manager writing a JAX profiler trace to ``log_dir``."""
    try:
        mod = profiler()
        if mod is None:
            raise RuntimeError("no jax profiler in this build")
        ctx = mod.trace(str(log_dir))
    except Exception as e:  # stripped build or unsupported backend
        # A scoped warning, not a bare stderr print (round-7 satellite):
        # the PR-3 warning policy escalates uncaptured project warnings to
        # errors under pytest, so a silently-untraced run in a test fails
        # loudly while library users can filter it like any other warning.
        warnings.warn(
            f"profiler unavailable ({e}); run continues untraced",
            RuntimeWarning,
            stacklevel=3,
        )
        ctx = contextlib.nullcontext()
    with ctx:
        yield


def has_trace_output(log_dir: str | Path) -> bool:
    """Whether ``log_dir`` contains profiler output (for tests/tooling)."""
    root = Path(log_dir)
    return root.is_dir() and any(root.rglob("*.xplane.pb"))
