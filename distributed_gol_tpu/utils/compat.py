"""JAX API compatibility: one home for names that moved across releases.

The package targets the current JAX API (``jax.shard_map`` with
``check_vma``, ``pltpu.CompilerParams``); older releases still in the
supported floor ship the same functionality under the pre-rename names
(``jax.experimental.shard_map.shard_map`` with ``check_rep``,
``pltpu.TPUCompilerParams``).  Every call site imports from here so the
version probe happens once, not per module.
"""

from __future__ import annotations

import functools

import jax
from jax.experimental.pallas import tpu as pltpu

# pltpu.CompilerParams (current) was pltpu.TPUCompilerParams before the
# Pallas TPU params rename; the fields used here (vmem_limit_bytes,
# dimension_semantics, collective_id) exist under both names.
CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

# lax.axis_size (current) did not exist before the shard_map graduation;
# psum of a constant 1 over the axis is the same static value there.
axis_size = getattr(jax.lax, "axis_size", None) or (
    lambda name: jax.lax.psum(1, name)
)

_new_shard_map = getattr(jax, "shard_map", None)

if _new_shard_map is not None:
    shard_map = _new_shard_map
else:
    from jax.experimental.shard_map import shard_map as _old_shard_map

    @functools.wraps(_old_shard_map)
    def shard_map(f=None, /, **kwargs):
        """``jax.shard_map`` signature on the pre-graduation API: the
        replication checker kwarg was called ``check_rep`` there."""
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:
            return functools.partial(shard_map, **kwargs)
        return _old_shard_map(f, **kwargs)
