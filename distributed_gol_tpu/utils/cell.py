"""The board-coordinate type (reference: ``util/cell.go:4-6``)."""

from __future__ import annotations

from typing import NamedTuple

import numpy as np


class Cell(NamedTuple):
    """An (x, y) coordinate on the board.

    ``x`` is the column, ``y`` the row — the same convention as the
    reference's ``util.Cell{X, Y}`` (``util/cell.go:4-6``), which tests
    compare as an order-insensitive multiset (``gol_test.go:58-86``).
    """

    x: int
    y: int


def alive_cells_from_board(board: np.ndarray) -> list[Cell]:
    """All alive cells of a {0, 255} uint8 board, row-major order.

    Equivalent of the reference's ``calculateAliveCells``
    (``gol/distributor.go:153-166``), but vectorised on the host: the board
    is fetched from device once and scanned with NumPy instead of a nested
    Go loop.
    """
    ys, xs = np.nonzero(np.asarray(board))
    return [Cell(int(x), int(y)) for x, y in zip(xs, ys)]


def board_from_alive_cells(
    cells: list[Cell] | list[tuple[int, int]], width: int, height: int
) -> np.ndarray:
    """Rebuild a {0, 255} uint8 board from a list of alive (x, y) cells."""
    board = np.zeros((height, width), dtype=np.uint8)
    for x, y in cells:
        board[y, x] = 255
    return board
