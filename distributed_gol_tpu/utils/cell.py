"""The board-coordinate type (reference: ``util/cell.go:4-6``)."""

from __future__ import annotations

from typing import NamedTuple, Sequence

import numpy as np


class Cell(NamedTuple):
    """An (x, y) coordinate on the board.

    ``x`` is the column, ``y`` the row — the same convention as the
    reference's ``util.Cell{X, Y}`` (``util/cell.go:4-6``), which tests
    compare as an order-insensitive multiset (``gol_test.go:58-86``).
    """

    x: int
    y: int


class AliveCells(Sequence):
    """Immutable sequence of alive cells backed by ONE (n, 2) int32 array.

    The reference returns ``[]util.Cell`` from ``calculateAliveCells``
    (``gol/distributor.go:153-166``) — a slice of structs, cheap in Go.  The
    Python equivalent (a tuple of ``Cell`` NamedTuples) materialises ~8M
    objects / ~0.5 GB for a 30%-soup 16384² board, so ``FinalTurnComplete``
    carries this array-backed view instead: same iteration/len/index/equality
    behaviour, O(1) construction from the fetched board, no per-cell objects
    until a caller actually asks for one.
    """

    __slots__ = ("_xy",)

    def __init__(self, xy: np.ndarray):
        xy = np.asarray(xy, dtype=np.int32)
        self._xy = xy.reshape(-1, 2)
        self._xy.setflags(write=False)

    @classmethod
    def from_board(cls, board: np.ndarray) -> "AliveCells":
        """Alive cells of a {0, 255} uint8 board, row-major order — the
        vectorised ``calculateAliveCells`` (``gol/distributor.go:153-166``).
        Flat-index + int32 divmod is ~3× faster than ``np.nonzero`` at the
        16384² finalize this exists for."""
        board = np.asarray(board)
        h, w = board.shape
        flat = np.flatnonzero(board)
        if board.size < 2**31:  # int32 flat index is exact; divmod is faster
            flat = flat.astype(np.int32, copy=False)
        xy = np.empty((flat.size, 2), np.int32)
        np.remainder(flat, w, out=xy[:, 0], casting="unsafe")
        np.floor_divide(flat, w, out=xy[:, 1], casting="unsafe")
        return cls(xy)

    @property
    def xy(self) -> np.ndarray:
        """The raw (n, 2) array of (x, y) pairs (read-only view)."""
        return self._xy

    def __len__(self) -> int:
        return self._xy.shape[0]

    def __getitem__(self, i):
        if isinstance(i, slice):
            return AliveCells(self._xy[i])
        x, y = self._xy[i]
        return Cell(int(x), int(y))

    def __iter__(self):
        for x, y in self._xy:
            yield Cell(int(x), int(y))

    def __eq__(self, other) -> bool:
        """Order-sensitive sequence equality against any iterable of (x, y)
        pairs — ``final.alive == ()`` stays valid for empty streams."""
        if isinstance(other, AliveCells):
            return np.array_equal(self._xy, other._xy)
        try:
            other_xy = np.asarray(list(other), dtype=np.int32).reshape(-1, 2)
        except (TypeError, ValueError):
            return NotImplemented
        return np.array_equal(self._xy, other_xy)

    def __ne__(self, other) -> bool:
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    # Unhashable, like the numpy array backing it: == compares equal to plain
    # cell sequences whose hashes we could never match without materialising.
    __hash__ = None

    def __repr__(self) -> str:
        return f"AliveCells(n={len(self)})"


def alive_cells_from_board(board: np.ndarray) -> list[Cell]:
    """All alive cells of a {0, 255} uint8 board, row-major order.

    Equivalent of the reference's ``calculateAliveCells``
    (``gol/distributor.go:153-166``), but vectorised on the host: the board
    is fetched from device once and scanned with NumPy instead of a nested
    Go loop.  Prefer ``AliveCells.from_board`` where the result may be large
    — this materialises a ``Cell`` per alive cell.
    """
    ys, xs = np.nonzero(np.asarray(board))
    return [Cell(int(x), int(y)) for x, y in zip(xs, ys)]


def board_from_alive_cells(
    cells: list[Cell] | list[tuple[int, int]], width: int, height: int
) -> np.ndarray:
    """Rebuild a {0, 255} uint8 board from a list of alive (x, y) cells."""
    board = np.zeros((height, width), dtype=np.uint8)
    for x, y in cells:
        board[y, x] = 255
    return board
