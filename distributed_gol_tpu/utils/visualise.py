"""ASCII board renderers for small-board test-failure diffs.

Role equivalent of the reference's ``util/visualise.go:21-48``
(``AliveCellsToString``): when a 16x16 golden-board assertion fails, print
the expected and actual boards side by side with box-drawing borders so the
failure is readable in a terminal.  Fresh implementation — renders from
either cell lists or uint8 boards, marks mismatched cells.
"""

from __future__ import annotations

from typing import Iterable, Sequence

import numpy as np

from distributed_gol_tpu.utils.cell import Cell, board_from_alive_cells

_ALIVE = "#"
_DEAD = "."
_WRONG = "X"  # alive where it should be dead, or vice versa


def _render(board: np.ndarray, diff: np.ndarray | None, title: str) -> list[str]:
    h, w = board.shape
    lines = [title.center(w + 2), "┌" + "─" * w + "┐"]
    for y in range(h):
        row = []
        for x in range(w):
            if diff is not None and diff[y, x]:
                row.append(_WRONG)
            else:
                row.append(_ALIVE if board[y, x] else _DEAD)
        lines.append("│" + "".join(row) + "│")
    lines.append("└" + "─" * w + "┘")
    return lines


def alive_cells_to_string(
    expected: Sequence[Cell] | Iterable[tuple[int, int]],
    actual: Sequence[Cell] | Iterable[tuple[int, int]],
    width: int,
    height: int,
) -> str:
    """Side-by-side expected/actual board diff with mismatches marked ``X``.

    Only sensible for small boards; tests use it at 16x16 like the
    reference's ``boardFail`` helper (``gol_test.go:49-56``).
    """
    exp = board_from_alive_cells(list(expected), width, height)
    act = board_from_alive_cells(list(actual), width, height)
    return boards_to_string(exp, act)


def boards_to_string(expected: np.ndarray, actual: np.ndarray) -> str:
    expected = np.asarray(expected)
    actual = np.asarray(actual)
    diff = expected != actual
    left = _render(expected, None, "expected")
    right = _render(actual, diff, "actual (X = wrong)")
    sep = "   "
    return "\n".join(l + sep + r for l, r in zip(left, right))


def board_to_string(board: np.ndarray, title: str = "board") -> str:
    return "\n".join(_render(np.asarray(board), None, title))
