"""Shared utilities (reference: ``util/`` — ``cell.go``, ``check.go``,
``visualise.go``)."""

from distributed_gol_tpu.utils.cell import AliveCells, Cell
from distributed_gol_tpu.utils.visualise import alive_cells_to_string

__all__ = ["AliveCells", "Cell", "alive_cells_to_string"]
