"""Quiet-measurement protocol: repeat-loop amplification + rate statistics.

The measurement environment this project publishes numbers from is
hostile: the host<->device tunnel charges ~110 ms per device->host sync
with >±2x wall-clock variance, async dispatch returns in ~0.3 ms, and
``block_until_ready`` can return without waiting (BASELINE.md
"Environment note").  A single-sample, single-dispatch timing therefore
measures the tunnel, not the device — which is how the round-5 S-margin
and C=128 kernel levers got dropped as "inside tunnel noise".

This module is the one home of the round-6 protocol every headline
artifact row rides:

- **Amplify**: multiply the term under test — chained async dispatches
  (:func:`chain`) or an on-device ``lax.fori_loop``
  (:func:`device_repeat`) — until one timed rep dwarfs the measured sync
  noise (:func:`pick_amplification`).
- **Repeat**: time ``reps`` independent amplified reps, one
  data-dependent sync each (:func:`quiet_rates`).
- **Record**: publish ``{reps, median, spread, rates}``
  (:func:`summarize`), never a bare single sample;
  :func:`check_headline_stats` is the artifact lint that enforces this on
  every headline row of a bench record (``require_headline_stats`` is the
  raising form bench.py runs on its own output).

Median convention: ``sorted(rates)[len(rates) // 2]`` — the upper median,
matching the shape of every recorded ``BENCH_ICI_PR1.json``-era row, so
cross-round artifact series stay comparable.  ``spread`` is
``(max - min) / median``: the full observed envelope, deliberately
pessimistic (a regression must beat the envelope, not a standard error).
"""

from __future__ import annotations

import math
import time
from typing import Callable, Sequence


class MalformedRecord(ValueError):
    """A bench record violated the headline-row stats contract."""


def median(xs: Sequence[float]) -> float:
    """Upper median (``sorted[n // 2]``) — the one convention every
    artifact row uses (see module docstring)."""
    if not xs:
        raise ValueError("median of an empty sequence")
    return sorted(xs)[len(xs) // 2]


def spread(xs: Sequence[float]) -> float:
    """Full relative envelope: ``(max - min) / median``."""
    m = median(xs)
    if not m:
        raise ValueError("spread undefined for zero median")
    return (max(xs) - min(xs)) / m


def summarize(rates: Sequence[float]) -> dict:
    """The ``{reps, median, spread, rates}`` block of one headline row.

    ``rates`` must be non-empty, finite and positive — a non-positive
    rate means the measurement harness failed, and publishing statistics
    over it would dress a broken run as data."""
    rates = [float(r) for r in rates]
    if not rates:
        raise MalformedRecord("no rates to summarize")
    for r in rates:
        if not math.isfinite(r) or r <= 0:
            raise MalformedRecord(f"non-positive or non-finite rate {r!r}")
    return {
        "reps": len(rates),
        "median": median(rates),
        "spread": spread(rates) if len(rates) > 1 else 0.0,
        "rates": sorted(rates),
    }


def sync_noise(sync: Callable[[], object], probes: int = 5) -> float:
    """Median wall-clock of ``sync()`` on an ALREADY-SETTLED value — the
    per-measurement noise floor the amplification must dwarf.  ``sync``
    must be a data-dependent fetch (``device_get`` of one element), not
    ``block_until_ready`` (which returns without waiting on tunnelled
    runtimes — bench.py's ``_sync`` is the reference implementation)."""
    times = []
    for _ in range(max(1, probes)):
        t0 = time.perf_counter()
        sync()
        times.append(time.perf_counter() - t0)
    return median(times)


def pick_amplification(
    unit_seconds: float,
    noise_seconds: float,
    target_seconds: float = 0.5,
    noise_mult: float = 20.0,
    cap: int = 4096,
) -> int:
    """How many chained units one timed rep needs so the rep wall-clock
    dwarfs both the dispatch-overhead target and ``noise_mult``× the
    measured sync noise.  ``unit_seconds`` is one warm unit (dispatch +
    sync) — amplification can only shrink the per-unit share of the
    noise, so sizing from the synced unit is conservative."""
    want = max(target_seconds, noise_mult * noise_seconds)
    if unit_seconds <= 0:
        return cap
    return max(1, min(cap, math.ceil(want / unit_seconds)))


def chain(run: Callable, board, n: int):
    """Issue ``n`` chained dispatches of ``run`` WITHOUT syncing — the
    host-side amplification form (async dispatch costs ~0.3 ms vs the
    ~110 ms sync, so chaining n dispatches under ONE data-dependent sync
    amortises the noise n×).  Returns the final (unforced) value."""
    for _ in range(n):
        board = run(board)
    return board


def device_repeat(run: Callable, turns: int, reps: int) -> Callable:
    """``lax.fori_loop`` amplification: ONE jitted dispatch containing
    ``reps`` supersteps of ``turns`` generations — zero per-iteration
    dispatch overhead, the strongest quiet form (used by
    ``tools/decompose.py`` to isolate per-launch terms from dispatch
    cost).  ``run`` must be a pure ``(board, turns) -> board`` superstep
    (the with_stats forms must be unwrapped first)."""
    import jax

    @jax.jit
    def repeated(board):
        return jax.lax.fori_loop(0, reps, lambda _, b: run(b, turns), board)

    return repeated


def quiet_rates(
    run: Callable,
    board,
    *,
    gens_per_call: int,
    sync: Callable[[object], object],
    reps: int = 5,
    target_seconds: float = 0.5,
    noise_mult: float = 20.0,
    amp_cap: int = 4096,
) -> tuple[object, dict]:
    """The whole protocol for one row: measure the sync noise, time one
    warm unit, pick the amplification, then time ``reps`` amplified reps
    (one data-dependent sync each).  Returns ``(board, stats)`` where
    ``stats`` is the :func:`summarize` block plus the protocol fields
    ``{amp, sync_noise_s, unit_s}`` so the artifact records HOW quiet the
    measurement was, not just its result."""
    noise = sync_noise(lambda: sync(board))
    t0 = time.perf_counter()
    board = run(board)
    sync(board)
    unit = time.perf_counter() - t0
    amp = pick_amplification(unit, noise, target_seconds, noise_mult, amp_cap)
    rates = []
    for _ in range(max(1, reps)):
        t0 = time.perf_counter()
        board = chain(run, board, amp)
        sync(board)
        rates.append(amp * gens_per_call / (time.perf_counter() - t0))
    stats = summarize(rates)
    stats.update(amp=amp, sync_noise_s=round(noise, 6), unit_s=round(unit, 6))
    return board, stats


# -- artifact lint ------------------------------------------------------------

def _check_row(row: dict, path: str, problems: list[str]) -> None:
    reps = row.get("reps")
    if not isinstance(reps, int) or reps < 1:
        problems.append(f"{path}: reps missing or not a positive int ({reps!r})")
        return
    med = row.get("median")
    if not isinstance(med, (int, float)) or not math.isfinite(med) or med <= 0:
        problems.append(f"{path}: median missing or non-positive ({med!r})")
    spr = row.get("spread")
    if spr is None:
        if reps > 1:
            problems.append(f"{path}: spread None with reps > 1")
    elif not isinstance(spr, (int, float)) or not math.isfinite(spr) or spr < 0:
        problems.append(f"{path}: spread not a finite non-negative number ({spr!r})")
    rates = row.get("rates")
    if rates is not None:
        if not isinstance(rates, (list, tuple)) or len(rates) != reps:
            problems.append(
                f"{path}: rates length {len(rates) if isinstance(rates, (list, tuple)) else 'n/a'}"
                f" != reps {reps}"
            )
    if "effective" in str(row.get("unit", "")):
        # Time-compression honesty (ISSUE 16): a row claiming EFFECTIVE
        # throughput (generations delivered, not dispatched) must also
        # publish the computed side — the dispatched-generations rate and
        # both turn totals — or the headline is a dressed-up skip count.
        cgs = row.get("computed_gens_per_s")
        if (
            not isinstance(cgs, (int, float))
            or not math.isfinite(cgs)
            or cgs <= 0
        ):
            problems.append(
                f"{path}: effective-rate row lacks a positive "
                f"computed_gens_per_s ({cgs!r})"
            )
        for fld in ("effective_turns", "computed_turns"):
            v = row.get(fld)
            if not isinstance(v, int) or v < 0:
                problems.append(
                    f"{path}: effective-rate row lacks integer {fld} ({v!r})"
                )


def check_headline_stats(record, path: str = "$") -> list[str]:
    """Walk a bench record; every headline row — any dict carrying a
    ``metric`` key — must carry a well-formed ``{reps, median, spread}``
    block (``rates``, when present, must have ``reps`` entries).  Returns
    the list of violations (empty = clean).  This is the machine form of
    the round-6 acceptance bar "no bare single-sample rates remain"."""
    problems: list[str] = []
    if isinstance(record, dict):
        if "metric" in record:
            _check_row(record, path, problems)
        for k, v in record.items():
            problems.extend(check_headline_stats(v, f"{path}.{k}"))
    elif isinstance(record, (list, tuple)):
        for i, v in enumerate(record):
            problems.extend(check_headline_stats(v, f"{path}[{i}]"))
    return problems


def require_headline_stats(record) -> None:
    """Raise :class:`MalformedRecord` when a headline row lacks its
    stats block — bench.py runs this on its own output before printing,
    so a protocol regression fails the run instead of shipping a bare
    number."""
    problems = check_headline_stats(record)
    if problems:
        raise MalformedRecord("; ".join(problems))
