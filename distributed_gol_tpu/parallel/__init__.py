"""SPMD sharded execution over a TPU device mesh.

This package is the TPU-native replacement for the reference's entire
distribution stack — broker fan-out (``broker/broker.go:37-56``), worker RPC
(``server/server.go:77-107``), and the full-board-broadcast-instead-of-halo
invariant (SURVEY.md §1): the board is sharded 2-D over a
``jax.sharding.Mesh``, each device exchanges 1-cell halos with its torus
neighbours via ``lax.ppermute`` over ICI, and alive counts are ``psum``
reductions — all inside one jitted SPMD program, no host on the data path.
"""

from distributed_gol_tpu.parallel.mesh import make_mesh, mesh_shape_for
from distributed_gol_tpu.parallel.halo import (
    sharded_step,
    sharded_steps_with_counts,
    sharded_superstep,
)

__all__ = [
    "make_mesh",
    "mesh_shape_for",
    "sharded_step",
    "sharded_steps_with_counts",
    "sharded_superstep",
]
