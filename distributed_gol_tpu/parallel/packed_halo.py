"""Sharded bit-packed stencil: halo exchange at uint32-word granularity.

Same communication topology as ``parallel/halo.py`` (neighbour-only
``lax.ppermute`` rings over the ("y", "x") mesh — the design that replaces
the reference's full-board broadcast, ``broker/broker.go:51``), but the
board is the 32-cells-per-word bitboard of ``ops/packed.py``:

- Each device owns an (h/ny, wp/nx) block of uint32 words.
- Row halos are one packed row each way (W/nx/8 bytes — already 8× smaller
  than the byte engine's halos).
- Column halos are one *word* column each way: the horizontal shift with
  cross-word carry (``packed._west``/``_east``) needs only the adjacent
  word, so a single uint32 column carries the 1-bit halo plus 31 bits of
  slack — word granularity is the natural ICI message unit here.
- Corners ride along by exchanging columns of the row-extended block,
  exactly as in the byte path.

Bit-identical to ``ops/packed.py`` on any mesh shape (a 1-sized axis
self-sends, which IS the torus wrap), which is in turn gated bit-identical
to ``ops/stencil.py`` and the golden oracles.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_gol_tpu.models.life import LifeRule
from distributed_gol_tpu.utils.compat import shard_map
from distributed_gol_tpu.ops.packed import _maj, apply_rule_planes
from distributed_gol_tpu.parallel.halo import (
    BOARD_SPEC,
    _exchange_and_extend,  # dtype-agnostic: one packed row/word-column per side
)


def packed_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for the (H, W // 32) uint32 bitboard."""
    return NamedSharding(mesh, BOARD_SPEC)


def _hshift(v: jax.Array) -> tuple[jax.Array, jax.Array]:
    """West/east 1-bit shifts of a column-extended plane (h, wp+2); the
    cross-word carry words are the extended columns, so no roll is needed.
    Returns (west, east) planes of shape (h, wp)."""
    west = (v[:, 1:-1] << 1) | (v[:, :-2] >> 31)
    east = (v[:, 1:-1] >> 1) | (v[:, 2:] << 31)
    return west, east


def _local_step(local: jax.Array, rule: LifeRule) -> jax.Array:
    """One packed generation of the local block via the halo-extended
    neighbourhood — the shard-local form of ``packed.step``: same adder
    network and rule application, but horizontal carries come from the
    exchanged word columns instead of ``jnp.roll``."""
    ext = _exchange_and_extend(local)  # (h+2, wp+2)
    centre = ext[1:-1, 1:-1]  # (h, wp)
    # Vertical 3-row adder across the full extended width, then horizontal.
    v0 = ext[:-2, :] ^ ext[1:-1, :] ^ ext[2:, :]  # (h, wp+2)
    v1 = _maj(ext[:-2, :], ext[1:-1, :], ext[2:, :])
    v0w, v0e = _hshift(v0)
    v1w, v1e = _hshift(v1)
    v0c, v1c = v0[:, 1:-1], v1[:, 1:-1]
    s0 = v0c ^ v0w ^ v0e
    c0 = _maj(v0c, v0w, v0e)
    s1 = v1c ^ v1w ^ v1e
    c1 = _maj(v1c, v1w, v1e)
    k = c0 & s1
    totals = (s0, c0 ^ s1, c1 ^ k, c1 & k)  # 9-cell total planes
    return apply_rule_planes(totals, centre, rule)


def _local_count(local: jax.Array, dtype=jnp.int32) -> jax.Array:
    return lax.psum(jnp.sum(lax.population_count(local), dtype=dtype), ("y", "x"))


def sharded_superstep(mesh: Mesh, rule: LifeRule):
    """Jitted (packed, turns) -> packed, all generations on device."""

    @partial(jax.jit, static_argnames=("turns",))
    def run(board, turns: int):
        @partial(shard_map, mesh=mesh, in_specs=BOARD_SPEC, out_specs=BOARD_SPEC)
        def inner(local):
            return lax.fori_loop(0, turns, lambda _, b: _local_step(b, rule), local)

        return inner(board)

    return run


def _counting_scan(mesh: Mesh, rule: LifeRule, dtype, turns: int):
    """The shard_map'd step+count scan shared by the packed and byte count
    drivers: (packed board) -> (packed board, int[turns] global counts)."""

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=BOARD_SPEC,
        out_specs=(BOARD_SPEC, P()),
    )
    def inner(local):
        def body(b, _):
            nb = _local_step(b, rule)
            return nb, _local_count(nb, dtype)

        return lax.scan(body, local, None, length=turns)

    return inner


def sharded_steps_with_counts(mesh: Mesh, rule: LifeRule):
    """(packed, turns) -> (packed, int[turns] global counts).  Counts are
    int32 below 2^31 board cells; at/above (65536²…) the trace runs under
    x64 so the psum accumulates in int64 instead of silently overflowing."""
    from distributed_gol_tpu.ops.packed import WORD, _count_dtype, _needs_wide_counts

    @partial(jax.jit, static_argnames=("turns",))
    def _run(board, turns: int):
        dtype = _count_dtype(board.size * WORD)
        return _counting_scan(mesh, rule, dtype, turns)(board)

    def run(board, turns: int):
        if _needs_wide_counts(board.size * WORD):
            with jax.enable_x64(True):
                return _run(board, turns)
        return _run(board, turns)

    return run


# -- byte-board drivers (engine-layer drop-ins, uint8 {0,255} in/out) ---------
#
# The board stays a sharded uint8 array at the engine layer (same put/fetch
# contract as every other engine); pack/unpack run inside the jit, pinned to
# the mesh sharding so packing is local to each device (no resharding).


def supports(shape: tuple[int, int], mesh_shape: tuple[int, int]) -> bool:
    h, w = shape
    ny, nx = mesh_shape
    return h % ny == 0 and w % nx == 0 and (w // nx) % 32 == 0


def make_superstep_bytes(mesh: Mesh, rule: LifeRule):
    from distributed_gol_tpu.ops.packed import pack, unpack

    inner = sharded_superstep(mesh, rule)

    @partial(jax.jit, static_argnames=("turns",))
    def run(board, turns: int):
        p = jax.lax.with_sharding_constraint(pack(board), packed_sharding(mesh))
        return unpack(inner(p, turns))

    return run


def make_steps_with_counts_bytes(mesh: Mesh, rule: LifeRule):
    from distributed_gol_tpu.ops.packed import (
        _count_dtype,
        _needs_wide_counts,
        pack,
        unpack,
    )

    @partial(jax.jit, static_argnames=("turns",))
    def _run(board, turns: int):
        p = jax.lax.with_sharding_constraint(pack(board), packed_sharding(mesh))
        final, counts = _counting_scan(mesh, rule, _count_dtype(board.size), turns)(p)
        return unpack(final), counts

    def run(board, turns: int):
        if _needs_wide_counts(board.size):
            with jax.enable_x64(True):
                return _run(board, turns)
        return _run(board, turns)

    return run
