"""Halo-exchange stencil step under ``shard_map``.

The reference has **no halo exchange** — it broadcasts the entire board to
every worker every turn (SURVEY.md §1 key invariant; ``broker/broker.go:51``,
``server/server.go:70-72``), which is exactly what stops it scaling.  Here
each device owns an (h/ny, w/nx) block and exchanges only its boundary
ring per generation:

1. rows along mesh axis ``y`` via ``lax.ppermute`` (neighbour-only, rides
   ICI — the same ring topology as ring attention);
2. columns along ``x`` using the *row-extended* block, so the four corner
   cells arrive for free in the second exchange — no separate diagonal
   sends.

Because the permutation is the cyclic shift over each axis, a 1-sized axis
sends to itself, which IS the toroidal wrap — so the same kernel is correct
on any mesh shape including (1, 1), and sharded output is bit-identical to
the single-device roll stencil (both are pure boolean algebra).

Alive counts are ``psum`` over both axes inside the same program
(reference analog: the broker's in-order barrier + host recount,
``broker/broker.go:168-174``, ``gol/distributor.go:185``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_gol_tpu.ops.stencil import apply_rule
from distributed_gol_tpu.utils.compat import axis_size, shard_map

BOARD_SPEC = P("y", "x")


def _shift_perm(axis_size: int, forward: bool) -> list[tuple[int, int]]:
    """Cyclic shift permutation; self-send when axis_size == 1 (= torus wrap)."""
    if forward:
        return [(i, (i + 1) % axis_size) for i in range(axis_size)]
    return [(i, (i - 1) % axis_size) for i in range(axis_size)]


def _exchange_and_extend(local: jax.Array) -> jax.Array:
    """(h, w) block -> (h+2, w+2) block with halo ring from torus neighbours."""
    ny = axis_size("y")
    nx = axis_size("x")
    # Row halos: my last row is my south neighbour's top halo.
    from_north = lax.ppermute(local[-1:, :], "y", _shift_perm(ny, forward=True))
    from_south = lax.ppermute(local[:1, :], "y", _shift_perm(ny, forward=False))
    ext = jnp.concatenate([from_north, local, from_south], axis=0)  # (h+2, w)
    # Column halos on the extended block: corners ride along.
    from_west = lax.ppermute(ext[:, -1:], "x", _shift_perm(nx, forward=True))
    from_east = lax.ppermute(ext[:, :1], "x", _shift_perm(nx, forward=False))
    return jnp.concatenate([from_west, ext, from_east], axis=1)  # (h+2, w+2)


def _local_step(local: jax.Array, table: jax.Array) -> jax.Array:
    """One generation of the local block, halo-exchanged, no wrap arithmetic:
    the separable 3x3 window sum over the extended block."""
    ext = _exchange_and_extend(local) & 1  # alive bits, (h+2, w+2)
    rows = ext[:-2, :] + ext[1:-1, :] + ext[2:, :]  # (h, w+2)
    counts = rows[:, :-2] + rows[:, 1:-1] + rows[:, 2:] - ext[1:-1, 1:-1]
    return apply_rule(ext[1:-1, 1:-1], counts, table)


def _local_count(local: jax.Array) -> jax.Array:
    return lax.psum(jnp.sum(local & 1, dtype=jnp.int32), ("y", "x"))


def board_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, BOARD_SPEC)


def sharded_step(mesh: Mesh):
    """Jitted one-generation step over ``mesh``: board -> board."""

    @jax.jit
    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(BOARD_SPEC, P()),
        out_specs=BOARD_SPEC,
    )
    def step(board, table):
        return _local_step(board, table)

    return step


def sharded_superstep(mesh: Mesh):
    """Jitted (board, table, turns) -> board, all generations on device."""

    @partial(jax.jit, static_argnames=("turns",))
    def run(board, table, turns: int):
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(BOARD_SPEC, P()),
            out_specs=BOARD_SPEC,
        )
        def inner(local, table):
            return lax.fori_loop(
                0, turns, lambda _, b: _local_step(b, table), local
            )

        return inner(board, table)

    return run


def sharded_steps_with_counts(mesh: Mesh):
    """Jitted (board, table, turns) -> (board, int32[turns] global counts).

    Counts are psum-reduced inside the program, so the host receives the
    full per-turn telemetry vector in one transfer per superstep — the
    replacement for the reference's per-turn O(N²) host recount
    (``gol/distributor.go:185-186``).
    """

    @partial(jax.jit, static_argnames=("turns",))
    def run(board, table, turns: int):
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(BOARD_SPEC, P()),
            out_specs=(BOARD_SPEC, P()),
        )
        def inner(local, table):
            def body(b, _):
                nb = _local_step(b, table)
                return nb, _local_count(nb)

            final, counts = lax.scan(body, local, None, length=turns)
            return final, counts

        return inner(board, table)

    return run
