"""Device-mesh construction.

The reference's topology is configuration-by-hardcoding: 4 worker IPs in
``broker/broker.go:192``.  Here the topology is a ``jax.sharding.Mesh`` with
axes ``("y", "x")`` — rows and columns of the board's 2-D domain
decomposition.  ``("y",)`` sharding alone reproduces the reference's
contiguous row strips (``broker/broker.go:37-56``); the 2-D form halves halo
bytes per device at scale.
"""

from __future__ import annotations

import math

import jax
from jax.sharding import Mesh

AXES = ("y", "x")


def make_mesh(shape: tuple[int, int], devices=None) -> Mesh:
    """A (ny, nx) mesh with axes ("y", "x") over the first ny*nx devices."""
    ny, nx = shape
    if devices is None:
        devices = jax.devices()
    n = ny * nx
    if len(devices) < n:
        raise ValueError(f"mesh {shape} needs {n} devices, have {len(devices)}")
    import numpy as np

    return Mesh(np.asarray(devices[:n]).reshape(ny, nx), AXES)


def mesh_shape_for(
    n_devices: int, height: int, width: int
) -> tuple[int, int]:
    """Pick a (ny, nx) factorisation of n_devices that divides the board and
    is as square as possible (minimises halo perimeter per device)."""
    best = None
    for ny in range(1, n_devices + 1):
        if n_devices % ny:
            continue
        nx = n_devices // ny
        if height % ny or width % nx:
            continue
        score = abs(math.log(ny) - math.log(nx))
        if best is None or score < best[0]:
            best = (score, (ny, nx))
    if best is None:
        raise ValueError(
            f"no factorisation of {n_devices} devices divides a "
            f"{height}x{width} board"
        )
    return best[1]
