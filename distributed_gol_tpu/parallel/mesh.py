"""Device-mesh construction + device health (the elastic-topology base).

The reference's topology is configuration-by-hardcoding: 4 worker IPs in
``broker/broker.go:192``.  Here the topology is a ``jax.sharding.Mesh`` with
axes ``("y", "x")`` — rows and columns of the board's 2-D domain
decomposition.  ``("y",)`` sharding alone reproduces the reference's
contiguous row strips (``broker/broker.go:37-56``); the 2-D form halves halo
bytes per device at scale.

ISSUE 7 adds the health half: a cheap per-device probe
(:func:`probe_devices` — one tiny jit put/compute/fetch round-trip per
device, bounded by the PR-2 dispatch watchdog so a wedged chip cannot hang
the classifier) and a **process-wide device blacklist**.  A device the
supervisor's elastic rung condemns (:func:`condemn`) stays out of every
later default-built mesh: :func:`make_mesh` with ``devices=None`` draws
from :func:`healthy_devices`, so a rebuilt backend — whether built by the
default ladder, a chaos ``backend_factory``, or a serving-plane tenant —
never lands back on a dead chip.  Blacklist lifetime is the process: a
condemned device is condemned for every subsequent run (clear with
:func:`clear_blacklist`, e.g. between bench reps); the observability
contract is the ``mesh.devices_lost`` counter, the
``mesh.device_blacklist`` info label, and the supervisor's
``device_blacklist`` flight record.
"""

from __future__ import annotations

import math
import threading

import jax
from jax.sharding import Mesh

AXES = ("y", "x")

# Process-wide blacklist of condemned device ids (``device.id``), guarded
# for the rare concurrent condemn (serving-plane tenants share it).
_BLACKLIST: set[int] = set()
_BLACKLIST_LOCK = threading.Lock()

#: Default per-device probe deadline — generous for a healthy device (the
#: round-trip is microseconds of compute) yet far below the coordination
#: service's multi-minute hard-kill the probe exists to pre-empt.
PROBE_DEADLINE_SECONDS = 5.0


def blacklisted() -> frozenset[int]:
    """The condemned device ids (a snapshot copy)."""
    with _BLACKLIST_LOCK:
        return frozenset(_BLACKLIST)


def condemn(devices) -> list[int]:
    """Add ``devices`` (device objects or raw ids) to the process-wide
    blacklist; returns the ids that are NEWLY condemned.  Bumps the
    ``mesh.devices_lost`` counter by that count and republishes the
    ``mesh.device_blacklist`` info label (comma-joined ids) on the
    process-wide registry, so supervisor restarts, serving-plane health,
    and flight/metrics artifacts all read one source of truth."""
    ids = [d if isinstance(d, int) else d.id for d in devices]
    with _BLACKLIST_LOCK:
        new = [i for i in ids if i not in _BLACKLIST]
        _BLACKLIST.update(new)
        label = ",".join(str(i) for i in sorted(_BLACKLIST))
    if new:
        from distributed_gol_tpu.obs import metrics as metrics_lib

        metrics_lib.REGISTRY.counter("mesh.devices_lost").inc(len(new))
        metrics_lib.REGISTRY.info("mesh.device_blacklist", label)
    return new


def clear_blacklist() -> None:
    """Forget every condemned device (tests; bench reps; an operator who
    physically replaced the chip).  The metrics label is reset too."""
    with _BLACKLIST_LOCK:
        had = bool(_BLACKLIST)
        _BLACKLIST.clear()
    if had:
        from distributed_gol_tpu.obs import metrics as metrics_lib

        metrics_lib.REGISTRY.info("mesh.device_blacklist", "")


def healthy_devices(devices=None) -> list:
    """``devices`` (default ``jax.devices()``) minus the blacklist — what
    every default-built mesh draws from."""
    if devices is None:
        devices = jax.devices()
    bad = blacklisted()
    return [d for d in devices if d.id not in bad]


def lost_device_count() -> int:
    """How many of this process's devices are condemned (the serving
    plane's ``degraded`` health field)."""
    bad = blacklisted()
    return sum(1 for d in jax.devices() if d.id in bad)


def capacity_fraction() -> float:
    """Healthy share of this process's devices, in [0, 1] — the factor a
    degraded serving pod scales its cell budget by (1.0 = full health)."""
    total = len(jax.devices())
    return (total - lost_device_count()) / total if total else 0.0


def probe_device(device, deadline_seconds: float = PROBE_DEADLINE_SECONDS) -> bool:
    """One cheap health check of ``device``: put a tiny array, run one
    jitted op on it, fetch, verify the round-trip.  Bounded by the PR-2
    dispatch watchdog (a wedged device must fail the probe in bounded
    time, not hang the classifier); any exception or timeout classifies
    the device unhealthy."""
    import numpy as np

    # Lazy import: the watchdog lives with the controller, and mesh.py
    # must stay importable below the engine layer.
    from distributed_gol_tpu.engine.controller import _Watchdog

    def attempt() -> bool:
        want = np.arange(8, dtype=np.uint8)
        x = jax.device_put(want, device)
        got = np.asarray(jax.device_get(x + np.uint8(1)))
        return bool((got == want + 1).all())

    try:
        return bool(_Watchdog(deadline_seconds).call(attempt))
    except Exception:  # noqa: BLE001 — timeout, runtime error: unhealthy
        return False


def probe_devices(
    devices=None, deadline_seconds: float = PROBE_DEADLINE_SECONDS
) -> tuple[list, list]:
    """Classify ``devices`` (default: the non-blacklisted devices) into
    ``(healthy, condemned)`` lists via :func:`probe_device`.  The
    supervisor's elastic rung runs this after a terminal failure; chaos
    tests inject a plan-consistent probe through the same seam
    (``Supervisor(device_probe=...)``)."""
    if devices is None:
        devices = healthy_devices()
    healthy, condemned = [], []
    for d in devices:
        (healthy if probe_device(d, deadline_seconds) else condemned).append(d)
    return healthy, condemned


def make_mesh(shape: tuple[int, int], devices=None) -> Mesh:
    """A (ny, nx) mesh with axes ("y", "x") over the first ny*nx devices.

    ``devices=None`` draws from :func:`healthy_devices` — blacklisted
    devices never enter a default-built mesh, which is what lets a
    supervisor rebuild (or a factory-built chaos backend, or a new
    serving-plane tenant) land on healthy silicon without every caller
    threading a device list."""
    ny, nx = shape
    if devices is None:
        devices = healthy_devices()
    n = ny * nx
    if len(devices) < n:
        lost = lost_device_count()
        hint = f" ({lost} blacklisted)" if lost else ""
        raise ValueError(
            f"mesh {shape} needs {n} devices, have {len(devices)}{hint}"
        )
    import numpy as np

    return Mesh(np.asarray(devices[:n]).reshape(ny, nx), AXES)


def _squarest_factorisation(
    n_devices: int, height: int, width: int, predicate=None
) -> tuple[int, int] | None:
    """The (ny, nx) factorisation of ``n_devices`` that divides the board
    and is as square as possible (minimises halo perimeter per device),
    restricted to shapes ``predicate`` accepts; None if no shape
    qualifies.  ONE selection loop for both the auto-shape and the
    elastic-reshard paths — a policy change here reaches both."""
    best = None
    for ny in range(1, n_devices + 1):
        if n_devices % ny:
            continue
        nx = n_devices // ny
        if height % ny or width % nx:
            continue
        if predicate is not None and not predicate(ny, nx):
            continue
        score = abs(math.log(ny) - math.log(nx))
        if best is None or score < best[0]:
            best = (score, (ny, nx))
    return best[1] if best else None


def mesh_shape_for(
    n_devices: int, height: int, width: int
) -> tuple[int, int]:
    """Pick a (ny, nx) factorisation of n_devices that divides the board and
    is as square as possible (minimises halo perimeter per device)."""
    shape = _squarest_factorisation(n_devices, height, width)
    if shape is None:
        raise ValueError(
            f"no factorisation of {n_devices} devices divides a "
            f"{height}x{width} board"
        )
    return shape


def largest_mesh_shape(
    n_devices: int, height: int, width: int, word_aligned: bool = True
) -> tuple[int, int]:
    """The largest mesh (most devices ≤ ``n_devices``) that still divides
    a ``height``×``width`` board — the elastic supervisor's reshard
    target after device loss.  ``word_aligned`` first prefers shapes the
    packed engine family can run ((width // nx) % 32 == 0, the
    word-granularity gate shared by ``packed_halo.supports`` and the
    round-7 2-D ``pallas_halo`` tier), so a shrink keeps the fast tiers
    whenever any healthy factorisation allows it — including 2-D → 2-D
    shrinks like (2, 4) → (2, 2), where the squarest-factorisation
    preference lands on another word-aligned 2-D mesh rather than a
    degenerate strip; with no such shape it falls back to any dividing
    factorisation (the roll engine supports every shape — bit-identical,
    slower).  Always succeeds for ``n_devices >= 1``: (1, 1) divides
    everything."""
    if n_devices < 1:
        raise ValueError("largest_mesh_shape needs >= 1 device")
    word_gate = lambda ny, nx: (width // nx) % 32 == 0  # noqa: E731
    passes = (word_gate, None) if word_aligned else (None,)
    for predicate in passes:
        for n in range(n_devices, 0, -1):
            shape = _squarest_factorisation(n, height, width, predicate)
            if shape is not None:
                return shape
    raise ValueError(  # unreachable: n == 1 always divides
        f"no mesh of <= {n_devices} devices divides {height}x{width}"
    )
