"""Sharded temporal blocking: the pallas-packed engine on a device mesh.

The single-device flagship kernel (``ops/pallas_packed.py``) advances a
VMEM tile T generations per HBM pass.  Under sharding the same idea moves
up one level: each device owns a contiguous row strip of the packed board
(mesh ``(ny, 1)`` — the 2-D analog is unnecessary because the strip is
already wp words wide on the lane axis), and **one halo exchange buys T
generations**: ``lax.ppermute`` ships ``pad = round8(T)`` boundary rows
each way over ICI, the kernel runs T generations on the halo-extended
strip, and the pad absorbs the T-deep data dependency exactly as it does
between VMEM tiles.  Communication per generation drops T× vs the per-turn
halo engines (``parallel/packed_halo.py``) — the same trade the reference
could never make because its broker re-broadcast the whole board every
turn (``broker/broker.go:37-56``, ``:157-180``).

Correctness structure:

- Inside a device, the kernel tiles the *extended* strip; each grid step
  DMAs one contiguous ``(tile_h + 2·pad, wp)`` window — no wrap arithmetic
  anywhere in the kernel (the mesh-edge wrap is the cyclic ``ppermute``
  permutation, which self-sends on a 1-sized axis, so ``ny = 1`` IS the
  single-device torus).
- Vertical in-tile rotates (``pltpu.roll``) wrap within the tile; that is
  wrong at tile edges, and absorbed by the pad exactly as in the
  single-device kernel (``ops/pallas_packed.py``).
- Horizontal wrap is the exact global lane rotate because every strip
  spans the full board width — the reason the mesh is (ny, 1).

Bit-identity vs the XLA packed halo engine (itself gated against the
golden oracles) is test-gated on virtual CPU meshes and on hardware via
``bench.py --verify``.

Round 6 adds the IN-KERNEL ICI exchange tier for the adaptive frontier
path: whole launch chunks run as one ``pallas_call`` per device with the
halo rows and interval state exchanged by ``pltpu.make_async_remote_copy``
inside the kernel (section marker "in-kernel ICI exchange tier" below) —
the ppermute strip form above remains the always-correct fallback,
selected by policy (``ici_tier_policy``) when the in-kernel tier is
unavailable.
"""

from __future__ import annotations

import dataclasses
import functools
import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from distributed_gol_tpu.models.life import CONWAY, LifeRule
from distributed_gol_tpu.ops.pallas_packed import (
    _EMPTY_LO,
    _LANES,
    _MAX_T,
    _SKIP_PERIOD,
    _adaptive_eligible,
    adaptive_launch_depth,
    default_skip_cap,
    _advance_window,
    _col_compute,
    _col_placement,
    _compiler_params,
    _copy_rect,
    _dma_route_out,
    _frontier_body,
    _frontier_placement,
    _frontier_plan,
    _hit_union,
    _off,
    _measure2,
    _nlaunch_chunks,
    _require_adaptive_eligible,
    _route_active,
    _round8,
    _tile_for_pad,
    _use_interpret,
    launch_turns,
)
from distributed_gol_tpu.parallel.halo import BOARD_SPEC, _shift_perm
from distributed_gol_tpu.utils.compat import axis_size, shard_map


def _xpad_words(wpl: int, interpret: bool) -> int:
    """x-direction halo width in packed words per side for the 2-D mesh
    kernels (the column analog of the ``pad`` rows), for a per-device
    tile ``wpl`` words wide.  Real hardware ships one full 128-lane
    quantum — Mosaic lane slices are 128-quantized (the measured
    column-blocking physics recorded in ``halo_bytes_2d_model``), so the
    quantum is the floor regardless of T, and the supports() gate already
    guarantees ``wpl ≥ 128``.  Interpret mode has no lane constraint:
    the halo is just wide enough for the deepest launch this tile can
    host (``T + 6`` cells, see :func:`_x_depth_cap`), clamped to the
    tile width so the exchange stays neighbour-only — which is what lets
    hermetic CPU tests run tiles a handful of words wide.  Pure function
    of (wpl, interpret), so every planner call site lands on the same
    halo for the same tile."""
    if not interpret:
        return _LANES
    cap = min(_MAX_T, 32 * wpl - _SKIP_PERIOD)
    return min(wpl, -(-(cap + _SKIP_PERIOD) // 32))


def _x_depth_cap(xpad: int) -> int:
    """Deepest launch an ``xpad``-word x-halo absorbs: the horizontal
    light cone of T generations plus the 6-generation measure must stay
    inside ``xpad · 32`` cells (the exact x-analog of ``pad ≥ T``).  On
    hardware (xpad = 128) this is 4090 — never binding for T ≤ 128; it
    only bites interpret-mode tiles a few words wide."""
    return 32 * xpad - _SKIP_PERIOD


def supports(pshape: tuple[int, int], mesh_shape: tuple[int, int]) -> bool:
    """Whether the packed (H, wp) board runs the sharded temporally-blocked
    kernel family on an (ny, nx) mesh.  Row meshes (nx == 1) run the strip
    kernels (full-width lane rotate = the exact torus x-wrap); 2-D meshes
    (round 7) run the x-extended tile kernels — each device owns an
    (h/ny, wp/nx) word-aligned tile ((W//nx) % 32 == 0, the same gate as
    ``parallel/mesh.py``) whose windows carry an ``_xpad_words`` column
    halo per side.  Strips/tiles must tile in VMEM at the minimum pad;
    widths must sit on the 128-lane quantum on real hardware (interpret
    mode has no lane constraint, so hermetic CPU tests can exercise every
    shape)."""
    h, wp = pshape
    ny, nx = mesh_shape
    if wp <= 0 or h % ny:
        return False
    h_loc = h // ny
    if h_loc % 8 or h_loc < 8:
        return False
    ip = _use_interpret()
    if nx == 1:
        if not ip and wp % _LANES:
            return False
        return _tile_for_pad(h_loc, wp, 8) is not None
    if wp % nx:
        return False
    wpl = wp // nx
    if not ip and wpl % _LANES:
        return False
    return _tile_for_pad(h_loc, wpl + 2 * _xpad_words(wpl, ip), 8) is not None


def _ext_kernel(
    x_hbm, o_ref, tile, sem, *, tile_h, pad, turns, rule, skip_stable, xpad=0
):
    """T generations of one (tile_h + 2·pad)-row window of the halo-extended
    strip.  The window is contiguous in the extended input — tile i's halo
    rows ARE its neighbours' boundary rows — so a single DMA loads it.

    ``xpad`` (2-D meshes, round 7): the extended input also carries an
    ``xpad``-word column halo per side, so the in-window lane rotate's
    wrap error lands in the halo and penetrates ≤ 1 cell/generation —
    absorbed by ``xpad·32 ≥ T`` cells exactly as the pad rows absorb the
    vertical dependency; only the centre columns are written back.  The
    skip proof survives unchanged: the probe compares the FULL extended
    window (halo columns included), which is the conservative direction —
    and when it passes, the same shrinking-interior induction pins the
    centre on both axes (both margins ≥ T)."""
    i = pl.program_id(0)
    copy = pltpu.make_async_copy(
        x_hbm.at[pl.ds(i * tile_h, tile_h + 2 * pad), :], tile.at[:], sem
    )
    copy.start()
    copy.wait()
    # Shared window body incl. the exact period-6 skip proof — the sharded
    # form is identical because the extended window already carries the
    # neighbour strips' boundary rows (ops/pallas_packed.py).
    out = _advance_window(tile[:], tile_h, pad, turns, rule, skip_stable)
    if xpad:
        o_ref[:] = out[pad : pad + tile_h, xpad : out.shape[1] - xpad]
    else:
        o_ref[:] = out[pad : pad + tile_h, :]


def _ext_kernel_adaptive(
    prev_ref, local, north, south, dst_prev, o_hbm, st_ref,
    tile, aux, merge, sems, *, tile_h, pad, grid, turns, rule
):
    """The adaptive strip launch: frontier-aware probe elision + active-row
    windowed compute + ping-pong write elision (sharded form; one tier
    body with the single-device kernel via ``_route_active``).

    ``prev_ref`` (SMEM, int32[grid + 2]) is the previous launch's skip
    bitmap EXTENDED with the neighbouring strips' edge-tile flags — the
    flags ride the same ``ppermute`` exchange as the halo rows, so tile
    i's window sources are exactly flags [i, i+1, i+2]: the north source
    (neighbour strip's last tile for i == 0, else local tile i−1), the
    tile itself, and the south source.  All three skipped ⇒ the window is
    bit-identical to the one whose probe passed last launch ⇒ elide.

    Round-4 I/O redesign: the strip is NOT pre-extended.  ``local`` is
    the device's (h_loc, wp) strip and ``north``/``south`` are the
    ``pad``-row ppermute'd neighbour boundaries; each tile assembles its
    own window by DMA (edge tiles pull their outer halo from the
    neighbour buffers), so the old ``_extend_rows`` concatenate — a full
    strip copy per launch — is gone.  ``dst_prev`` (the strip from two
    launches ago) is aliased onto ``o_hbm``; an elided tile does NOTHING
    (same S_k == S_{k-2} chain as the single-device kernel)."""
    del dst_prev  # same memory as o_hbm (aliased); contents ARE the output
    i = pl.program_id(0)
    elide = (prev_ref[i] + prev_ref[i + 1] + prev_ref[i + 2]) == 3

    @pl.when(elide)
    def _():
        st_ref[i] = 1

    @pl.when(jnp.logical_not(elide))
    def _():
        _dma_strip_window_in(
            local, north.at[:], south.at[:], tile, i, grid, tile_h, pad, sems
        )
        route, stable = _route_active(tile, aux, merge, tile_h, pad, turns, rule)
        st_ref[i] = stable
        _dma_route_out(route, tile, merge, aux, o_hbm, i, tile_h, pad, sems.at[0])


def _dma_strip_window_in(local, north, south, tile, i, grid, tile_h, pad, sems):
    """Assemble tile ``i``'s halo-extended window from the device strip
    and the neighbour boundary sources — one home for the adaptive and
    frontier strip kernels AND the in-kernel exchange megakernel (the
    sharded counterpart of ``pallas_packed._dma_window_in``).

    ``north``/``south`` are the (pad, wp) edge-halo SOURCES as sliceable
    ref handles: the ppermute output buffers (``ref.at[:]``, classic
    strip kernels) or the exchanged VMEM slot windows
    (``halo.at[pl.ds(slot * pad, pad), :]``, the in-kernel tier) — the
    window assembly is otherwise identical, so it must not fork."""
    center = pltpu.make_async_copy(
        local.at[pl.ds(i * tile_h, tile_h), :],
        tile.at[pl.ds(pad, tile_h), :],
        sems.at[0],
    )
    center.start()

    # Halo copies: start inside the source-selecting branches, wait
    # once after all starts — both branches of each pair move the
    # same (pad, wp) extent to the same destination on the same
    # semaphore, so a uniform wait descriptor overlaps all three
    # DMAs (the single-device kernel's shape).
    @pl.when(i == 0)
    def _():
        pltpu.make_async_copy(
            north, tile.at[pl.ds(0, pad), :], sems.at[1]
        ).start()

    @pl.when(i > 0)
    def _():
        # (i-1)*tile_h + (tile_h - pad) == i*tile_h - pad, but in the
        # multiplication-plus-8-multiple form Mosaic can prove
        # 8-aligned (the subtraction form fails the divisibility
        # check at compile time).
        pltpu.make_async_copy(
            local.at[pl.ds((i - 1) * tile_h + (tile_h - pad), pad), :],
            tile.at[pl.ds(0, pad), :],
            sems.at[1],
        ).start()

    @pl.when(i == grid - 1)
    def _():
        pltpu.make_async_copy(
            south, tile.at[pl.ds(pad + tile_h, pad), :], sems.at[2]
        ).start()

    @pl.when(i < grid - 1)
    def _():
        pltpu.make_async_copy(
            local.at[pl.ds((i + 1) * tile_h, pad), :],
            tile.at[pl.ds(pad + tile_h, pad), :],
            sems.at[2],
        ).start()

    pltpu.make_async_copy(
        north, tile.at[pl.ds(0, pad), :], sems.at[1]
    ).wait()
    pltpu.make_async_copy(
        south, tile.at[pl.ds(pad + tile_h, pad), :], sems.at[2]
    ).wait()
    center.wait()


def _ext_kernel_frontier(
    ps_ref, lo0e, hi0e, lo1e, hi1e, cloe, chie,
    local, north, south, dst_prev, o_hbm,
    st_ref, nlo0, nhi0, nlo1, nhi1, nclo, nchi,
    tile, aux, merge, colwin, sems,
    *, tile_h, pad, grid, turns, rule, sub_rows, col_window,
):
    """The frontier strip launch (round 5): the sharded counterpart of
    ``pallas_packed._kernel_frontier_mega``, sharing its whole compute
    branch (``_frontier_body``) — only the I/O differs.  One launch per
    call: the T-deep halo exchange between launches is the XLA-level
    ``ppermute`` in ``make_superstep``, and the tracked intervals ride
    the SAME exchange as extended arrays.

    ``lo0e``…``chie`` (SMEM, int32[grid + 2]) are the previous launch's
    per-tile intervals EXTENDED with the neighbour strips' edge-tile
    entries, pre-translated into THIS strip's row frame by the caller
    (the north neighbour's strip-local row r is this strip's row
    r − h_loc, so its entries arrive shifted by −h_loc; south by +h_loc;
    column entries are board-global words and ship unshifted).  Index
    k holds tile k − 1's intervals, so tile i's window sources are
    exactly entries [i, i+1, i+2] — the same adjacency layout as the
    round-3 bitmap extension in ``_ext_kernel_adaptive``.

    ``ps_ref`` (int32[grid]) is the previous launch's OWN stability
    bitmap (no exchange: only the copy-through decision reads it), and
    ``dst_prev`` — the strip from two launches ago — is aliased onto
    ``o_hbm``: the ping-pong write-elision contract of the adaptive
    strip kernel, unchanged."""
    del dst_prev  # same memory as o_hbm (aliased); contents ARE the output
    i = pl.program_id(0)
    t6 = turns + _SKIP_PERIOD
    w_lo = i * tile_h - pad
    w_hi = (i + 1) * tile_h + pad - 1
    c_lo = i * tile_h
    c_hi = (i + 1) * tile_h - 1

    ivals = []
    cvals = []
    for k in (i, i + 1, i + 2):
        ivals.append((lo0e[k], hi0e[k]))
        ivals.append((lo1e[k], hi1e[k]))
        cvals.append((cloe[k], chie[k]))
    hit, u_lo, u_hi, u_clo, u_chi = _hit_union(
        ivals, cvals, w_lo, w_hi, c_lo, c_hi, t6
    )

    @pl.when(jnp.logical_not(hit))
    def _():
        st_ref[i] = 1
        nlo0[i] = _EMPTY_LO
        nhi0[i] = -1
        nlo1[i] = _EMPTY_LO
        nhi1[i] = -1
        nclo[i] = _EMPTY_LO
        nchi[i] = -1

        @pl.when(ps_ref[i] == 0)
        def _():
            # Skipped, but not twice in a row: the output buffer holds
            # S_{k-2} ≠ S_k — copy the unchanged centre across.
            c_in = pltpu.make_async_copy(
                local.at[pl.ds(i * tile_h, tile_h), :],
                tile.at[pl.ds(pad, tile_h), :],
                sems.at[0],
            )
            c_in.start()
            c_in.wait()
            c_out = pltpu.make_async_copy(
                tile.at[pl.ds(pad, tile_h), :],
                o_hbm.at[pl.ds(i * tile_h, tile_h), :],
                sems.at[0],
            )
            c_out.start()
            c_out.wait()

    @pl.when(hit)
    def _():
        st_ref[i] = 0
        _dma_strip_window_in(
            local, north.at[:], south.at[:], tile, i, grid, tile_h, pad, sems
        )
        route, lo0, hi0, lo1, hi1, clo, chi = _frontier_body(
            tile, aux, merge, colwin, sems,
            u_lo, u_hi, u_clo, u_chi,
            i, tile_h, pad, turns, rule, sub_rows, col_window,
        )
        nlo0[i] = lo0
        nhi0[i] = hi0
        nlo1[i] = lo1
        nhi1[i] = hi1
        nclo[i] = clo
        nchi[i] = chi
        _dma_route_out(route, tile, merge, aux, o_hbm, i, tile_h, pad, sems.at[0])


# -- in-kernel ICI exchange tier (round 6) -----------------------------------
#
# The ppermute strip form above pays one XLA dispatch per launch: every T
# generations the program returns to XLA for a `lax.ppermute`, and the
# measured per-launch dispatch cost (~33 µs, BASELINE.md round 5) caps the
# settled (1,1)-mesh 16384² run at 397k gens/s while the single-device
# megakernel does ~1.07M on the same board.  This tier moves the exchange
# INSIDE the kernel: the whole dispatch chunk is ONE pallas_call per device
# (grid = (nlaunch, stripes), sequential), and between launches each device
# ships its `round8(T+6)` boundary rows plus the (6,) frontier-interval
# state of its edge stripes to its mesh neighbours with
# ``pltpu.make_async_remote_copy`` (``DeviceIdType.MESH``) — send/recv DMA
# semaphores, ping-pong (launch-parity) halo slots, and one barrier
# rendezvous before the first remote write.
#
# Exchange protocol, per launch l (prologue at grid step (l, 0)):
#
#   1. l == 0 (remote build): neighbour barrier — both neighbours must have
#      entered this kernel before our first message lands in their scratch.
#      l > 0: wait the previous launch's 4 sends — launch l writes the
#      buffer launch l−1 read, i.e. the buffer those sends sourced.
#   2. Start 4 sends from the read buffer (it holds S_l everywhere) and
#      the state slabs published at launch l−1: board-top→north's south
#      halo, board-bottom→south's north halo, top-stripe state→north,
#      bottom-stripe state→south.  All land in the receiver's slot l%2.
#   3. Wait the 4 matching recvs before any stripe reads a halo/slab.
#
# Slot-reuse soundness (slot p = l%2, reused at l+2): my reads of slot p
# during launch l happen before my prologue l+1 sends (sequential grid);
# the neighbour's launch-l+1 compute waits on those sends arriving; its
# l+2 send — the next writer of my slot p — comes after that compute.  So
# every write of slot p happens-after the previous read of slot p, with
# the recv-semaphore signal as the cross-device edge.  Devices stay within
# one launch of each other at the exchange points (each prologue waits for
# the neighbour's same-launch message), and each (direction, kind) channel
# has at most one outstanding message because a sender waits its own send
# semaphore before the next same-channel send.
#
# ny == 1 (the (1,1) mesh — the strip IS the torus) runs the SAME kernel
# built with plain ``make_async_copy`` loopback transfers: the torus wrap
# halo is the device's own opposite edge, so the exchange degenerates to
# local copies through the same slot buffers, and the whole launch
# sequencing/state protocol runs hermetically in interpret mode.  Only the
# literal remote-DMA lowering is hardware-only; `tools/hw_compile_gate.py`
# AOT-compiles those geometries on the attached chip.
#
# The interval state crosses the wire as an (8, 128) int32 SLAB per edge
# stripe (row k = scalar k broadcast over lanes): Mosaic has no scalar
# VMEM stores and no SMEM remote DMA contract, but vector fills, sublane-
# aligned slab DMAs, and per-row max-reductions all lower everywhere.

_STATE_SLAB = 8  # slab rows: 6 interval scalars + padding to the 8-row tile


def _encode_state6(vals):
    """Six int32 scalars -> (8, 128) int32 slab, row k = scalar k broadcast
    across lanes — the remote-DMA-able form of a stripe's interval state."""
    rows = jax.lax.broadcasted_iota(jnp.int32, (_STATE_SLAB, _LANES), 0)
    out = jnp.zeros((_STATE_SLAB, _LANES), jnp.int32)
    for k, v in enumerate(vals):
        out = jnp.where(rows == k, v, out)
    return out


def _decode_state6(slab):
    """(8, 128) int32 slab -> six scalars.  Every lane of a row holds the
    same value, so a per-row max-reduction recovers it exactly — the one
    vector→scalar path Mosaic lowers on every generation."""
    return [jnp.max(slab[k : k + 1, :]) for k in range(6)]


def _kernel_frontier_mega_strip(
    ids_ref, xa, xb, oa, ob, sk_ref, act_ref,
    tile, aux, merge, colwin,
    nhalo, shalo, tstate, bstate, nstate, sstate,
    ilo0, ihi0, ilo1, ihi1, iclo, ichi,
    rr8, rn8, rc128, rn128,
    acc, sems, xsems,
    *, tile_h, pad, grid, nlaunch, turns, rule, sub_rows, col_window, remote,
):
    """The sharded strip dispatch as ONE kernel — the strip-shaped form of
    ``pallas_packed._kernel_frontier_mega`` whose between-launch halo and
    interval-state exchange runs INSIDE the kernel (protocol at the top of
    this section).  ``ids_ref`` (SMEM int32[3]) carries the mesh
    coordinates of the north/south neighbours plus this device's x coord
    — computed by the shard_map wrapper so the kernel also AOT-compiles
    standalone (the hardware compile gate's requirement).  ``remote``
    selects real ``make_async_remote_copy`` exchange (ny > 1 on ICI) vs
    loopback ``make_async_copy`` through the same slot buffers (ny == 1 —
    the torus self-exchange, which is also the hermetic interpret-mode
    form).  Everything else — ping-pong aliased HBM boards, SMEM interval
    and change-rect state by launch parity, rectangle/classic/skip
    routing — is the single-device megakernel's protocol verbatim."""
    del xa, xb  # same memory as oa/ob (aliased); contents ARE the boards
    l = pl.program_id(0)
    i = pl.program_id(1)
    t6 = turns + _SKIP_PERIOD
    h_loc = grid * tile_h
    w_lo = i * tile_h - pad
    w_hi = (i + 1) * tile_h + pad - 1
    c_lo = i * tile_h
    c_hi = (i + 1) * tile_h - 1
    wp = tile.shape[1]
    wr = jax.lax.rem(l, 2)
    rd = 1 - wr
    even = wr == 0
    first = l == 0
    slot = wr  # exchange-slot parity of this launch

    @pl.when(first & (i == 0))
    def _():
        acc[0] = 0

    @pl.when(first)
    def _():
        # Per-stripe activity accumulator (ISSUE 11) — the strip form of
        # the single-device megakernel's: zeroed at launch 0, bumped by
        # put_state on a nonempty measured interval.
        act_ref[i] = 0

    def mk_exchange(rd_board, k):
        """Transfer k of the launch's exchange: 0 board-up, 1 board-down,
        2 state-up, 3 state-down.  'Up' ships my top edge to the north
        neighbour (arriving as ITS south halo / south state slab)."""
        srcs = (
            rd_board.at[pl.ds(0, pad), :],
            rd_board.at[pl.ds(h_loc - pad, pad), :],
            tstate.at[pl.ds(rd * _STATE_SLAB, _STATE_SLAB), :],
            bstate.at[pl.ds(rd * _STATE_SLAB, _STATE_SLAB), :],
        )
        dsts = (
            shalo.at[pl.ds(slot * pad, pad), :],
            nhalo.at[pl.ds(slot * pad, pad), :],
            sstate.at[pl.ds(slot * _STATE_SLAB, _STATE_SLAB), :],
            nstate.at[pl.ds(slot * _STATE_SLAB, _STATE_SLAB), :],
        )
        if not remote:
            return pltpu.make_async_copy(srcs[k], dsts[k], xsems.at[k])
        return pltpu.make_async_remote_copy(
            src_ref=srcs[k],
            dst_ref=dsts[k],
            send_sem=xsems.at[k],
            recv_sem=xsems.at[4 + k],
            device_id=(ids_ref[k % 2], ids_ref[2]),
            device_id_type=pltpu.DeviceIdType.MESH,
        )

    def prologue(rd_board):
        if remote:
            @pl.when(first)
            def _():
                # Rendezvous with both neighbours before the first remote
                # write lands in their scratch (see protocol notes).
                bar = pltpu.get_barrier_semaphore()
                for k in (0, 1):
                    pltpu.semaphore_signal(
                        bar,
                        inc=1,
                        device_id=(ids_ref[k], ids_ref[2]),
                        device_id_type=pltpu.DeviceIdType.MESH,
                    )
                pltpu.semaphore_wait(bar, 2)

            @pl.when(jnp.logical_not(first))
            def _():
                # Launch l overwrites the buffer launch l−1's sends read.
                for k in range(4):
                    mk_exchange(rd_board, k).wait_send()

            for k in range(4):
                mk_exchange(rd_board, k).start()
            for k in range(4):
                mk_exchange(rd_board, k).wait_recv()
        else:
            # Loopback (ny == 1): the torus halo is this device's own
            # opposite edge; same slots, plain copies, waited in place.
            ops = [mk_exchange(rd_board, k) for k in range(4)]
            for op in ops:
                op.start()
            for op in ops:
                op.wait()

    @pl.when(i == 0)
    def _():
        @pl.when(even)
        def _():
            prologue(oa)

        @pl.when(jnp.logical_not(even))
        def _():
            prologue(ob)

    # Neighbour interval sources: interior stripes read the previous
    # launch's SMEM state rows; edge stripes decode the exchanged slabs,
    # translated into this strip's frame (the north neighbour's strip row
    # r is this strip's row r − h_loc, south +h_loc; empty intervals
    # survive translation — lo > hi is offset-invariant; column entries
    # are board-global words and ship unshifted).
    n_dec = _decode_state6(nstate[pl.ds(slot * _STATE_SLAB, _STATE_SLAB), :])
    s_dec = _decode_state6(sstate[pl.ds(slot * _STATE_SLAB, _STATE_SLAB), :])
    edge_n = i == 0
    edge_s = i == grid - 1
    iprev = jnp.maximum(i - 1, 0)  # clamped: the edge case reads the slab
    inext = jnp.minimum(i + 1, grid - 1)

    def north(ref, k):
        return jnp.where(edge_n, n_dec[k] - h_loc, ref[rd, iprev])

    def south(ref, k):
        return jnp.where(edge_s, s_dec[k] + h_loc, ref[rd, inext])

    ivals = [
        (north(ilo0, 0), north(ihi0, 1)),
        (north(ilo1, 2), north(ihi1, 3)),
        (ilo0[rd, i], ihi0[rd, i]),
        (ilo1[rd, i], ihi1[rd, i]),
        (south(ilo0, 0), south(ihi0, 1)),
        (south(ilo1, 2), south(ihi1, 3)),
    ]
    cvals = [
        (jnp.where(edge_n, n_dec[4], iclo[rd, iprev]),
         jnp.where(edge_n, n_dec[5], ichi[rd, iprev])),
        (iclo[rd, i], ichi[rd, i]),
        (jnp.where(edge_s, s_dec[4], iclo[rd, inext]),
         jnp.where(edge_s, s_dec[5], ichi[rd, inext])),
    ]
    hit, u_lo, u_hi, u_clo, u_chi = _hit_union(
        ivals, cvals, w_lo, w_hi, c_lo, c_hi, t6
    )
    # Launch 0 of a chunk: no tracked state yet — force the full union
    # (the megakernel's probe-everything launch; exact intervals are
    # measured for launch 1 on).
    hit = hit | first
    u_lo = jnp.where(first, c_lo - t6, u_lo)
    u_hi = jnp.where(first, c_hi + t6, u_hi)
    p_r8 = rr8[rd, i]
    p_n8 = rn8[rd, i]
    p_c128 = rc128[rd, i]
    p_n128 = rn128[rd, i]

    def put_state(lo0, hi0, lo1, hi1, clo, chi, r8, n8, c128, n128):
        ilo0[wr, i] = lo0
        ihi0[wr, i] = hi0
        ilo1[wr, i] = lo1
        ihi1[wr, i] = hi1
        iclo[wr, i] = clo
        ichi[wr, i] = chi
        rr8[wr, i] = r8
        rn8[wr, i] = n8
        rc128[wr, i] = c128
        rn128[wr, i] = n128
        # Activity telemetry (one put_state per stripe per launch —
        # routes are mutually exclusive): launches where this stripe
        # measured a nonempty active interval.
        act_ref[i] = act_ref[i] + (
            jnp.asarray(lo0) <= jnp.asarray(hi0)
        ).astype(jnp.int32)
        # Edge stripes also publish the slab the next launch's exchange
        # ships to the neighbours (both slabs on a one-stripe strip).
        vec = _encode_state6((lo0, hi0, lo1, hi1, clo, chi))

        @pl.when(edge_n)
        def _():
            tstate[pl.ds(wr * _STATE_SLAB, _STATE_SLAB), :] = vec

        @pl.when(edge_s)
        def _():
            bstate[pl.ds(wr * _STATE_SLAB, _STATE_SLAB), :] = vec

    def copy_rect(src, dst, r8, n8, c128, n128):
        _copy_rect(
            src, dst, tile, sems.at[0], r8, n8, c128, n128,
            tile_h=tile_h, wp=wp, sub_rows=sub_rows, col_window=col_window,
        )

    @pl.when(jnp.logical_not(hit))
    def _():
        put_state(_EMPTY_LO, -1, _EMPTY_LO, -1, _EMPTY_LO, -1, 0, 0, 0, 0)
        acc[0] = acc[0] + 1

        @pl.when(p_n8 > 0)
        def _():
            # Skipped, but the previous launch changed something: copy
            # S_{l−1} (== S_l on a skipped stripe) across the ping-pong.
            @pl.when(even)
            def _():
                copy_rect(oa, ob, p_r8, p_n8, p_c128, p_n128)

            @pl.when(jnp.logical_not(even))
            def _():
                copy_rect(ob, oa, p_r8, p_n8, p_c128, p_n128)

    win_lo, m_lo, m_hi, windowed_ok = _frontier_placement(
        u_lo, u_hi, i, tile_h, pad, turns, sub_rows
    )
    # Window top in strip rows, kept in 8-row chunk units so Mosaic's
    # divisibility proof survives (the recorded round-4 rule).
    g8 = i * (tile_h // 8) - pad // 8 + win_lo // 8
    g_lo = g8 * 8
    if col_window is not None:
        win_c, c_ok, cw = _col_placement(u_clo, u_chi, turns, col_window, wp)
        # The rectangle route must stay inside the LOCAL strip: an edge
        # window reaching into the halo takes the classic route, whose
        # assembled window carries the exchanged rows.
        rect_ok = (
            hit
            & windowed_ok
            & c_ok
            & (g_lo >= 0)
            & (g_lo + sub_rows <= h_loc)
        )
    else:
        rect_ok = jnp.bool_(False)

    if col_window is not None:
        @pl.when(rect_ok)
        def _():
            @pl.when(even)
            def _():
                c = pltpu.make_async_copy(
                    oa.at[pl.ds(g_lo, sub_rows), pl.ds(win_c, col_window)],
                    colwin.at[:],
                    sems.at[0],
                )
                c.start()
                c.wait()

            @pl.when(jnp.logical_not(even))
            def _():
                c = pltpu.make_async_copy(
                    ob.at[pl.ds(g_lo, sub_rows), pl.ds(win_c, col_window)],
                    colwin.at[:],
                    sems.at[0],
                )
                c.start()
                c.wait()

            gT, g6, merged = _col_compute(
                colwin[:], turns, rule, cw, col_window, sub_rows
            )
            colwin[:] = merged
            lo0, hi0, lo1, hi1, clo, chi = _measure2(
                gT, g6, win_lo, m_lo, m_hi, w_lo,
                col_off=win_c, col_valid=(cw, col_window - cw),
            )
            r8 = jnp.maximum(g_lo, c_lo) // 8
            n8 = jnp.minimum(g_lo + sub_rows, c_lo + tile_h) // 8 - r8
            put_state(
                lo0, hi0, lo1, hi1, clo, chi,
                r8, n8, win_c // 128, col_window // 128,
            )

            def write_out(src_board, dst):
                @pl.when(p_n8 > 0)
                def _():
                    copy_rect(src_board, dst, p_r8, p_n8, p_c128, p_n128)

                full_span = n8 == sub_rows // 8

                @pl.when(full_span)
                def _():
                    c = pltpu.make_async_copy(
                        colwin.at[:],
                        dst.at[
                            pl.ds(g_lo, sub_rows), pl.ds(win_c, col_window)
                        ],
                        sems.at[0],
                    )
                    c.start()
                    c.wait()

                @pl.when(jnp.logical_not(full_span))
                def _():
                    def chunk(kk, _):
                        c = pltpu.make_async_copy(
                            colwin.at[pl.ds((r8 + kk - g8) * 8, 8), :],
                            dst.at[
                                pl.ds((r8 + kk) * 8, 8),
                                pl.ds(win_c, col_window),
                            ],
                            sems.at[0],
                        )
                        c.start()
                        c.wait()
                        return 0

                    jax.lax.fori_loop(0, n8, chunk, 0)

            @pl.when(even)
            def _():
                write_out(oa, ob)

            @pl.when(jnp.logical_not(even))
            def _():
                write_out(ob, oa)

    @pl.when(hit & jnp.logical_not(rect_ok))
    def _():
        # Edge-halo sources are the exchanged slot windows; the window
        # assembly itself is the classic strip kernels' (shared helper).
        n_src = nhalo.at[pl.ds(slot * pad, pad), :]
        s_src = shalo.at[pl.ds(slot * pad, pad), :]

        @pl.when(even)
        def _():
            _dma_strip_window_in(
                oa, n_src, s_src, tile, i, grid, tile_h, pad, sems
            )

        @pl.when(jnp.logical_not(even))
        def _():
            _dma_strip_window_in(
                ob, n_src, s_src, tile, i, grid, tile_h, pad, sems
            )

        route, lo0, hi0, lo1, hi1, clo, chi = _frontier_body(
            tile, aux, merge, colwin, sems,
            u_lo, u_hi, u_clo, u_chi,
            i, tile_h, pad, turns, rule, sub_rows, None,
        )
        put_state(
            lo0, hi0, lo1, hi1, clo, chi,
            c_lo // 8, tile_h // 8, 0, wp // 128,
        )

        @pl.when(even)
        def _():
            _dma_route_out(route, tile, merge, aux, ob, i, tile_h, pad, sems.at[0])

        @pl.when(jnp.logical_not(even))
        def _():
            _dma_route_out(route, tile, merge, aux, oa, i, tile_h, pad, sems.at[0])

    @pl.when((l == nlaunch - 1) & (i == grid - 1))
    def _():
        sk_ref[0] = acc[0]
        if remote:
            # The final launch's sends source the read buffer; they must
            # clear before the kernel (and the buffer's XLA lifetime) ends.
            @pl.when(even)
            def _():
                for k in range(4):
                    mk_exchange(oa, k).wait_send()

            @pl.when(jnp.logical_not(even))
            def _():
                for k in range(4):
                    mk_exchange(ob, k).wait_send()


@functools.lru_cache(maxsize=12)
def _build_dispatch_frontier_strip(
    strip: tuple[int, int],
    rule: LifeRule,
    turns: int,
    nlaunch: int,
    interpret: bool,
    tile_cap: int | None,
    remote: bool,
):
    """The in-kernel-exchange strip megakernel as ``(ids, board,
    scratch_board) -> (board_a, board_b, skipped, activity)`` —
    ``activity`` (int32[grid], ISSUE 11) counts per LOCAL stripe the
    launches where it measured a nonempty active interval (the sharded
    out-spec concatenates per-device vectors into the board-global
    bitmap ``Backend.activity_bitmap`` serves) — ``nlaunch`` launches
    of ``turns`` generations in ONE pallas_call per device, halos and
    interval state exchanged inside (``_kernel_frontier_mega_strip``).
    ``ids`` is int32[3]: north neighbour y, south neighbour y, own x mesh
    coordinate (ignored by the ``remote=False`` loopback build).  Board
    args alias the first two outputs (ping-pong pair); the final state is
    output ``nlaunch % 2``.  Callers pass only ``_NLAUNCH_CANON`` values
    for ``nlaunch`` (the bounded-compile-cache contract of
    ``_nlaunch_chunks``)."""
    h_loc, wp = strip
    _require_adaptive_eligible(turns)
    plan = _frontier_plan(strip, turns, tile_cap)
    if plan is None:
        raise ValueError(f"no frontier plan for {turns} turns on strip {strip}")
    pad, sub_rows, col_window = plan
    tile_h = _strip_plan_tile(strip, turns, tile_cap)
    grid = h_loc // tile_h
    kernel = partial(
        _kernel_frontier_mega_strip,
        tile_h=tile_h,
        pad=pad,
        grid=grid,
        nlaunch=nlaunch,
        turns=turns,
        rule=rule,
        sub_rows=sub_rows,
        col_window=col_window,
        remote=remote,
    )
    smem_i32 = lambda shp: pltpu.SMEM(shp, jnp.int32)  # noqa: E731
    params = _compiler_params(tile_h, pad, wp, True, sequential_grid=True)
    if remote:
        # The neighbour barrier uses the global barrier semaphore, which
        # Mosaic only allocates for kernels carrying a collective_id.
        params = dataclasses.replace(params, collective_id=7)
    return pl.pallas_call(
        kernel,
        grid=(nlaunch, grid),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h_loc, wp), jnp.uint32),
            jax.ShapeDtypeStruct((h_loc, wp), jnp.uint32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((grid,), jnp.int32),
        ],
        input_output_aliases={1: 0, 2: 1},
        scratch_shapes=[
            pltpu.VMEM((tile_h + 2 * pad, wp), jnp.uint32),
            pltpu.VMEM((tile_h + 2 * pad, wp), jnp.uint32),  # full buffer
            pltpu.VMEM((tile_h + 2 * pad, wp), jnp.uint32),  # merge buffer
            pltpu.VMEM(
                (sub_rows, col_window if col_window else _LANES), jnp.uint32
            ),  # column-tier window (minimal dummy when the tier is off)
            # Exchange slots: ping-pong (launch-parity) halo rows + the
            # four interval-state slabs (published top/bottom, received
            # north/south).
            pltpu.VMEM((2 * pad, wp), jnp.uint32),  # nhalo
            pltpu.VMEM((2 * pad, wp), jnp.uint32),  # shalo
            pltpu.VMEM((2 * _STATE_SLAB, _LANES), jnp.int32),  # tstate
            pltpu.VMEM((2 * _STATE_SLAB, _LANES), jnp.int32),  # bstate
            pltpu.VMEM((2 * _STATE_SLAB, _LANES), jnp.int32),  # nstate
            pltpu.VMEM((2 * _STATE_SLAB, _LANES), jnp.int32),  # sstate
            # Interval state (6) + change-rect state (4), (parity, stripe).
            smem_i32((2, grid)), smem_i32((2, grid)),
            smem_i32((2, grid)), smem_i32((2, grid)),
            smem_i32((2, grid)), smem_i32((2, grid)),
            smem_i32((2, grid)), smem_i32((2, grid)),
            smem_i32((2, grid)), smem_i32((2, grid)),
            smem_i32((1,)),  # skip accumulator
            pltpu.SemaphoreType.DMA((3,)),
            pltpu.SemaphoreType.DMA((8,)),  # exchange: 4 send + 4 recv
        ],
        compiler_params=params,
        interpret=interpret,
    )


# -- 2-D mesh tier (round 7) --------------------------------------------------
#
# The strip tier above ends at (ny, 1): row strips get needle-thin long
# before the device count runs out, which caps scale-out at ny devices and
# keeps the 262144²-class board out of reach (ROADMAP item 3).  The 2-D
# tier shards the packed board over a full (ny, nx) mesh — each device
# owns an (h/ny, wp/nx) word-aligned tile — and generalises the SAME
# kernel family:
#
# - Windows grow an ``xpad``-word column halo per side (one 128-lane
#   quantum on hardware — Mosaic lane slices are 128-quantized, the
#   ``halo_bytes_2d_model`` physics): the in-window lane rotate's wrap
#   error lands in the halo and penetrates ≤ 1 cell/generation, absorbed
#   by ``xpad·32 ≥ T + 6`` cells exactly as the pad rows absorb the
#   vertical dependency.  The shared window bodies (``_advance_window``,
#   ``_route_active``, ``_frontier_body``) are width-agnostic and run
#   unchanged; measures are restricted to the tile-local centre columns
#   (``_frontier_body(xpad=...)``).
# - The ppermute fallback tier pre-extends the tile in 2-D
#   (:func:`_extend_tile_2d` — corners ride the second exchange, the
#   ``parallel/halo.py`` trick at word granularity) and runs the plain or
#   probing-adaptive kernels; the probe-elision decision arrives
#   precomputed (three ppermutes of flag arithmetic), so corner flags
#   come along for free.
# - The IN-KERNEL exchange tier (``_kernel_frontier_mega_2d``) runs whole
#   launch chunks as ONE pallas_call per device: per launch it ships
#   north/south edge rows, east/west edge word-columns, the FOUR corner
#   blocks, and the per-stripe interval-state slabs of both x-neighbours
#   via ``pltpu.make_async_remote_copy`` with 2-D MESH addressing — ten
#   channels, send/recv semaphore pairs, launch-parity slot buffers, and
#   an 8-direction entry barrier (6 on (1, nx): the N/S self-wrap is a
#   local copy).  Edge stripes (i == 0, grid−1) always take the full
#   route, so N/S interval state never crosses the wire (only the
#   x-neighbour vectors do — every stripe's window spans the full local
#   width + x-halo, so E/W activity gates every stripe's skip).
# - Hermetic gating: the megakernel also builds in VIRTUAL mode — one
#   device owns the whole board, the grid grows a virtual-device axis,
#   and the exchange pulls each tile's halo blocks and neighbour slabs
#   from the shared ping-pong board through the same slot buffers,
#   parity discipline, and translation arithmetic.  The (1, 1) build is
#   the loopback torus; (2, 2)/(2, 4)/(4, 2) virtual builds run
#   hermetically in interpret mode, so everything except the literal
#   remote-DMA lowering is identity-gated on CPU before a TPU rig ever
#   sees the tier (the lowering is ``tools/hw_compile_gate.py``'s job,
#   as for the strip tier).


def _extend_tile_2d(local: jax.Array, pad: int, xpad: int) -> jax.Array:
    """(h_loc, wpl) tile -> (h_loc + 2·pad, wpl + 2·xpad) with pad
    boundary rows and xpad boundary word-columns from the torus
    neighbours; the four corner blocks ride along by exchanging columns
    OF the row-extended tile (the ``parallel/halo.py`` corner trick at
    word granularity; a 1-sized axis self-sends = the torus wrap)."""
    ny = axis_size("y")
    nx = axis_size("x")
    from_north = lax.ppermute(local[-pad:, :], "y", _shift_perm(ny, forward=True))
    from_south = lax.ppermute(local[:pad, :], "y", _shift_perm(ny, forward=False))
    ext = jnp.concatenate([from_north, local, from_south], axis=0)
    from_west = lax.ppermute(ext[:, -xpad:], "x", _shift_perm(nx, forward=True))
    from_east = lax.ppermute(ext[:, :xpad], "x", _shift_perm(nx, forward=False))
    return jnp.concatenate([from_west, ext, from_east], axis=1)


def _plan_tile_2d(
    strip: tuple[int, int], turns: int, tile_cap: int | None, xpad: int
) -> int:
    """The tile height a 2-D adaptive launch will use — the one plan call
    shared by the 2-D builders and ``make_superstep``'s grid arithmetic
    (the x-extended form of ``_strip_plan_tile``)."""
    tile_h = _tile_for_pad(
        strip[0], strip[1] + 2 * xpad, _round8(turns), tile_cap
    )
    if tile_h is None:
        raise ValueError(f"no VMEM tiling for {turns} turns on 2-D tile {strip}")
    return tile_h


def _exchange_scratch_bytes(
    h_loc: int, wpl: int, xpad: int, pad: int, grid: int
) -> int:
    """VMEM bytes of the 2-D megakernel's exchange scratch beyond the
    window working set ``_tile_for_pad`` already budgeted: the N/S row
    slots, the two FULL-HEIGHT (h_loc + 2·pad) × xpad column-halo slot
    pairs (the dominant term on tall tiles), and the three per-stripe
    interval-state slab buffers — kept in sync with
    ``_build_dispatch_frontier_2d``'s ``scratch_shapes``."""
    h2 = h_loc + 2 * pad
    return 4 * (
        2 * (2 * pad) * wpl  # nhalo + shalo
        + 2 * (2 * h2) * xpad  # whalo + ehalo
        + 3 * (2 * grid * _STATE_SLAB) * _LANES  # mystate + wstate + estate
    )


def _plan_2d(
    strip: tuple[int, int],
    turns: int,
    tile_cap: int | None,
    interpret: bool,
) -> tuple[int, int, int, int | None, int] | None:
    """(xpad, pad, sub_rows, col_window, tile_h) for the 2-D frontier
    megakernel on a per-device LOCAL (h_loc, wpl) tile, or None when the
    geometry can't host it.  Rides ``_frontier_plan`` at the x-EXTENDED
    width (the VMEM truth) but gates the column tier on the LOCAL width:
    the rectangle route reads the un-extended HBM tile directly, so its
    window must fit — and ``_col_placement``'s validity band keeps it t6
    cells clear of the tile seam (the same argument that kept it clear
    of the board edge, now per tile).  Also gates TOTAL VMEM: the
    exchange scratch (full-height column-halo slots dominate on tall
    tiles) rides on top of the window working set, and the plan declines
    — a policy fallback to the ppermute tiers — any geometry whose
    kernel could only fail at Mosaic allocation time on hardware (e.g.
    65536-row tiles — 262144² on (4, 8) — carry ~134 MB of column-halo
    slots alone; the 32768-row (8, 8) headline tile fits only at the
    default 512-row cap).  The policy records the reason either way, and
    the ppermute 2-D tiers carry the rest."""
    h_loc, wpl = strip
    xpad = _xpad_words(wpl, interpret)
    if turns + _SKIP_PERIOD > 32 * xpad:
        return None  # x-halo can't absorb the T+6 horizontal light cone
    ext = (h_loc, wpl + 2 * xpad)
    from distributed_gol_tpu.ops.pallas_packed import (
        _PLANES,
        _frontier_plan,
        _vmem_physical,
        plan_geometry,
    )

    fplan = _frontier_plan(ext, turns, tile_cap)
    if fplan is None:
        return None
    pad, sub_rows, _cw_ext = fplan
    cw = plan_geometry().col_window
    col_window = cw if cw and wpl >= 2 * cw else None
    tile_h = _tile_for_pad(h_loc, wpl + 2 * xpad, _round8(turns), tile_cap)
    if tile_h is None:
        return None
    # The limit _build_dispatch_frontier_2d will request (the adaptive
    # window factor of _compiler_params plus the exchange scratch) must
    # fit under the compiler ceiling _compiler_params caps at.
    ws = _PLANES * (tile_h + 2 * pad) * (wpl + 2 * xpad) * 4
    exch = _exchange_scratch_bytes(h_loc, wpl, xpad, pad, h_loc // tile_h)
    ceiling = _vmem_physical() - (8 << 20)
    if int(ws * 2.5) + (8 << 20) + exch > ceiling:
        return None
    return xpad, pad, sub_rows, col_window, tile_h


def _adaptive_plan_2d(
    strip: tuple[int, int],
    turns: int,
    raw_cap: int | None,
    interpret: bool,
) -> tuple[int, int, bool, tuple | None]:
    """(cap, t, adaptive, plan_2d) for a skip_stable dispatch on a 2-D
    tile — the 2-D analog of ``_adaptive_strip_plan``, with the depth
    decision made at the x-EXTENDED width (and clamped to the x-halo's
    depth capacity) so the plan the depth policy assumed is the plan
    that executes."""
    cap = raw_cap if raw_cap is not None else default_skip_cap(strip[0])
    xpad = _xpad_words(strip[1], interpret)
    ext = (strip[0], strip[1] + 2 * xpad)
    t, adaptive = adaptive_launch_depth(
        ext, min(turns, _x_depth_cap(xpad)), cap
    )
    plan2 = _plan_2d(strip, t, cap, interpret) if adaptive else None
    return cap, t, adaptive, plan2


def _ext_kernel_adaptive_2d(
    elig_ref, x_ext, dst_prev, o_hbm, st_ref, tile, aux, merge, sems,
    *, tile_h, pad, xpad, turns, rule
):
    """The probing adaptive launch on a 2-D mesh tile: the x-extended
    analog of ``_ext_kernel_adaptive`` whose probe-elision decision
    arrives PRECOMPUTED (``elig_ref``, SMEM int32[grid, 1]).  The 3×3
    tile-neighbourhood flag conjunction — own strip's extended flags AND
    both x-neighbours' (whose own N/S edge flags bring the corners) — is
    three ppermutes of host-side flag arithmetic in ``make_superstep``,
    so the kernel stays mesh-shape-agnostic.  The input is the
    pre-extended (h_loc + 2·pad, wpl + 2·xpad) tile: one contiguous
    window DMA per stripe (the 2-D fallback tier trades the round-4
    no-pre-extension optimisation for one exchange that covers rows,
    columns AND corners).  The probe window carries the x-halo columns;
    the stability compare therefore includes their in-window wrap
    garbage — exactly the conservative direction: a stripe only skips
    when its whole extended window, halos included, is period-6 stable,
    and the skip proof's shrinking-interior induction then pins the
    centre on both axes (both margins ≥ T).  Ping-pong write elision
    (``dst_prev`` aliased onto the output) is the strip kernel's
    contract unchanged."""
    del dst_prev  # same memory as o_hbm (aliased); contents ARE the output
    i = pl.program_id(0)
    elide = elig_ref[i, 0] == 1

    @pl.when(elide)
    def _():
        st_ref[i, 0] = 1

    @pl.when(jnp.logical_not(elide))
    def _():
        c = pltpu.make_async_copy(
            x_ext.at[pl.ds(i * tile_h, tile_h + 2 * pad), :],
            tile.at[:],
            sems.at[0],
        )
        c.start()
        c.wait()
        route, stable = _route_active(tile, aux, merge, tile_h, pad, turns, rule)
        st_ref[i, 0] = stable
        _dma_route_out(
            route, tile, merge, aux, o_hbm, i, tile_h, pad, sems.at[0],
            xpad=xpad,
        )


@functools.lru_cache(maxsize=None)
def _build_ext_launch_adaptive_2d(
    strip: tuple[int, int],
    rule: LifeRule,
    turns: int,
    interpret: bool,
    tile_cap: int | None,
    xpad: int,
):
    """The probing adaptive 2-D launch as ``(elig, ext_tile, dst_prev) ->
    (tile, bitmap)`` with ``elig`` int32[grid, 1] (the precomputed 3×3
    elision conjunction), ``ext_tile`` the 2-D pre-extended tile, and
    ``dst_prev`` ALIASED onto the tile output — the strip form's
    ping-pong write-elision contract.  Bitmap entries are (grid, 1) so
    the shard_map out-spec can concatenate them over BOTH mesh axes."""
    h_loc, wpl = strip
    _require_adaptive_eligible(turns)
    pad = _round8(turns)
    wpe = wpl + 2 * xpad
    tile_h = _plan_tile_2d(strip, turns, tile_cap, xpad)
    grid = h_loc // tile_h
    kernel = partial(
        _ext_kernel_adaptive_2d,
        tile_h=tile_h,
        pad=pad,
        xpad=xpad,
        turns=turns,
        rule=rule,
    )
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h_loc, wpl), jnp.uint32),
            jax.ShapeDtypeStruct((grid, 1), jnp.int32),
        ],
        input_output_aliases={2: 0},
        scratch_shapes=[
            pltpu.VMEM((tile_h + 2 * pad, wpe), jnp.uint32),
            pltpu.VMEM((tile_h + 2 * pad, wpe), jnp.uint32),  # probe buffer
            pltpu.VMEM((tile_h + 2 * pad, wpe), jnp.uint32),  # merge buffer
            pltpu.SemaphoreType.DMA((3,)),
        ],
        compiler_params=_compiler_params(tile_h, pad, wpe, True),
        interpret=interpret,
    )


def _kernel_frontier_mega_2d(
    *refs,
    tile_h, pad, xpad, grid, nlaunch, turns, rule, sub_rows, col_window,
    mesh_shape, remote,
):
    """The 2-D mesh dispatch as ONE kernel — the (ny, nx) form of
    ``_kernel_frontier_mega_strip`` (protocol in the section notes
    above).  Two builds share this body:

    - ``remote=True``: one instance per device over its LOCAL (h_loc,
      wpl) ping-pong boards; the launch prologue runs the ten-channel
      remote exchange (N/S rows, E/W columns, 4 corner blocks, 2
      interval-state slabs), with the N/S channels degenerating to local
      self-copies on a (1, nx) mesh.
    - ``remote=False`` (VIRTUAL): one instance owns the FULL board; the
      grid grows a virtual-device axis v (sequential, so launch l−1's
      writes — every tile's — complete before any launch-l read), and
      the prologue pulls the same ten transfers from the neighbour tile
      regions of the shared read board and the per-device state slabs,
      through the same slot buffers.  (1, 1) is the loopback torus; the
      hermetic interpret builds are how the whole 2-D protocol is
      identity-gated on CPU.

    Slot/parity discipline, forced launch-0 full unions, the rectangle/
    classic/skip routing, and the change-rect write protocol are the
    strip megakernel's verbatim; what is new is the x-halo window
    assembly (five DMAs: centre, N, S, and full-height W/E column
    blocks whose top/bottom pad rows ARE the corner blocks), the
    x-neighbour state fold (every stripe's skip decision consumes both
    x-neighbours' stripe intervals at i−1, i, i+1 — their row frames
    coincide, and their column entries translate by ∓wpl into the local
    word frame), and forced-full edge stripes (i == 0, grid−1), which
    keep every cross-row dependency exact without shipping N/S state."""
    ny, nx = mesh_shape
    nv = 1 if remote else ny * nx
    if remote:
        (ids_ref, xa, xb, oa, ob, sk_ref, act_ref,
         tile, aux, merge, colwin,
         nhalo, shalo, whalo, ehalo, mystate, wstate, estate,
         ilo0, ihi0, ilo1, ihi1, iclo, ichi,
         rr8, rn8, rc128, rn128,
         acc, sems, xsems) = refs
        l = pl.program_id(0)
        i = pl.program_id(1)
        v = 0
    else:
        (xa, xb, oa, ob, sk_ref, act_ref,
         tile, aux, merge, colwin,
         nhalo, shalo, whalo, ehalo, mystate, wstate, estate,
         ilo0, ihi0, ilo1, ihi1, iclo, ichi,
         rr8, rn8, rc128, rn128,
         acc, sems, xsems) = refs
        l = pl.program_id(0)
        v = pl.program_id(1)
        i = pl.program_id(2)
    del xa, xb  # same memory as oa/ob (aliased); contents ARE the boards
    t6 = turns + _SKIP_PERIOD
    h_loc = grid * tile_h
    wpe = tile.shape[1]
    wpl = wpe - 2 * xpad
    H2 = h_loc + 2 * pad  # E/W halo buffer rows per slot (corners included)
    nsb = grid * _STATE_SLAB  # state-slab rows per (parity, device) block
    w_lo = i * tile_h - pad
    w_hi = (i + 1) * tile_h + pad - 1
    c_lo = i * tile_h
    c_hi = (i + 1) * tile_h - 1
    wr = jax.lax.rem(l, 2)
    rd = 1 - wr
    even = wr == 0
    first = l == 0
    slot = wr
    if remote:
        dy = dx = 0
        row0 = 0
        col0 = 0
        gi = i
        my_sbase = 0  # mystate block index base (× nsb rows)
    else:
        dy = v // nx
        dx = jax.lax.rem(v, nx)
        row0 = dy * h_loc
        col0 = dx * wpl
        gi = v * grid + i
        my_sbase = v

    def bsl(ref, r, nr, c, nc):
        # Full-width column slices keep the literal `:` form the strip
        # kernels lower; offset forms only where the 2-D geometry needs
        # them (virtual mode is interpret-only, so dynamic column bases
        # never meet Mosaic).
        if isinstance(c, int) and c == 0 and nc == ref.shape[1]:
            return ref.at[pl.ds(r, nr), :]
        return ref.at[pl.ds(r, nr), pl.ds(c, nc)]

    first_step = first & (i == 0)
    if not remote:
        first_step = first_step & (v == 0)

    @pl.when(first_step)
    def _():
        acc[0] = 0

    @pl.when(first)
    def _():
        # Per-stripe activity accumulator (ISSUE 11), zeroed at launch 0.
        if remote:
            act_ref[i, 0] = 0
        else:
            act_ref[gi] = 0

    # -- launch prologue: the ten-channel exchange ----------------------------
    if remote:
        def dev(k):
            # Channel k's (y, x) MESH target; ids_ref = [y_n, y_s, x_w,
            # x_e, my_y, my_x].
            table = (
                (0, 5), (1, 5), (4, 2), (4, 3),
                (0, 2), (0, 3), (1, 2), (1, 3),
                (4, 2), (4, 3),
            )
            a, b = table[k]
            return (ids_ref[a], ids_ref[b])

        # (1, nx): the N/S "neighbour" is this device — the torus
        # self-wrap is a local copy through the same slot buffers.
        local_ch = (0, 1) if ny == 1 else ()
        remote_ch = tuple(k for k in range(10) if k not in local_ch)
        bar_dirs = tuple(k for k in range(8) if k not in local_ch)

        def mk_exchange(rd_board, k):
            state_src = mystate.at[pl.ds(rd * nsb, nsb), :]
            srcs = (
                rd_board.at[pl.ds(0, pad), :],
                rd_board.at[pl.ds(h_loc - pad, pad), :],
                rd_board.at[pl.ds(0, h_loc), pl.ds(0, xpad)],
                rd_board.at[pl.ds(0, h_loc), pl.ds(wpl - xpad, xpad)],
                rd_board.at[pl.ds(0, pad), pl.ds(0, xpad)],
                rd_board.at[pl.ds(0, pad), pl.ds(wpl - xpad, xpad)],
                rd_board.at[pl.ds(h_loc - pad, pad), pl.ds(0, xpad)],
                rd_board.at[pl.ds(h_loc - pad, pad), pl.ds(wpl - xpad, xpad)],
                state_src,
                state_src,
            )
            dsts = (
                shalo.at[pl.ds(slot * pad, pad), :],
                nhalo.at[pl.ds(slot * pad, pad), :],
                ehalo.at[pl.ds(slot * H2 + pad, h_loc), :],
                whalo.at[pl.ds(slot * H2 + pad, h_loc), :],
                ehalo.at[pl.ds(slot * H2 + pad + h_loc, pad), :],
                whalo.at[pl.ds(slot * H2 + pad + h_loc, pad), :],
                ehalo.at[pl.ds(slot * H2, pad), :],
                whalo.at[pl.ds(slot * H2, pad), :],
                estate.at[pl.ds(slot * nsb, nsb), :],
                wstate.at[pl.ds(slot * nsb, nsb), :],
            )
            if k in local_ch:
                return pltpu.make_async_copy(srcs[k], dsts[k], xsems.at[k])
            return pltpu.make_async_remote_copy(
                src_ref=srcs[k],
                dst_ref=dsts[k],
                send_sem=xsems.at[k],
                recv_sem=xsems.at[10 + k],
                device_id=dev(k),
                device_id_type=pltpu.DeviceIdType.MESH,
            )

        def prologue(rd_board):
            @pl.when(first)
            def _():
                # Rendezvous with every exchange partner before the first
                # remote write lands in their scratch (8 directions; 6 on
                # a (1, nx) mesh — the N/S self-slots neither signal nor
                # count).  Coincident neighbours on small meshes receive
                # one signal per DIRECTION, so the in-degree is always
                # len(bar_dirs) by torus symmetry.
                bar = pltpu.get_barrier_semaphore()
                for k in bar_dirs:
                    pltpu.semaphore_signal(
                        bar,
                        inc=1,
                        device_id=dev(k),
                        device_id_type=pltpu.DeviceIdType.MESH,
                    )
                pltpu.semaphore_wait(bar, len(bar_dirs))

            @pl.when(jnp.logical_not(first))
            def _():
                # Launch l overwrites the buffer launch l−1's sends read.
                for k in remote_ch:
                    mk_exchange(rd_board, k).wait_send()

            for k in remote_ch:
                mk_exchange(rd_board, k).start()
            for k in local_ch:
                op = mk_exchange(rd_board, k)
                op.start()
                op.wait()
            for k in remote_ch:
                mk_exchange(rd_board, k).wait_recv()
    else:
        yn = jax.lax.rem(dy + ny - 1, ny)
        ys = jax.lax.rem(dy + 1, ny)
        xw = jax.lax.rem(dx + nx - 1, nx)
        xe = jax.lax.rem(dx + 1, nx)

        def prologue(rd_board):
            # Virtual exchange: pull the ten transfers from the neighbour
            # tile regions of the shared read board (S_l everywhere — the
            # sequential grid finished launch l−1 for every tile) and the
            # per-device state slabs, into the same slot buffers the
            # remote build's messages land in.
            wv = dy * nx + xw
            ev = dy * nx + xe
            pulls = (
                # nhalo <- N tile's bottom rows; shalo <- S tile's top.
                (bsl(rd_board, yn * h_loc + (h_loc - pad), pad, col0, wpl),
                 nhalo.at[pl.ds(slot * pad, pad), :]),
                (bsl(rd_board, ys * h_loc, pad, col0, wpl),
                 shalo.at[pl.ds(slot * pad, pad), :]),
                # W/E mid columns.
                (bsl(rd_board, row0, h_loc, xw * wpl + (wpl - xpad), xpad),
                 whalo.at[pl.ds(slot * H2 + pad, h_loc), :]),
                (bsl(rd_board, row0, h_loc, xe * wpl, xpad),
                 ehalo.at[pl.ds(slot * H2 + pad, h_loc), :]),
                # Corner blocks: whalo top <- NW bottom-right, ehalo top
                # <- NE bottom-left, whalo bottom <- SW top-right, ehalo
                # bottom <- SE top-left.
                (bsl(rd_board, yn * h_loc + (h_loc - pad), pad,
                     xw * wpl + (wpl - xpad), xpad),
                 whalo.at[pl.ds(slot * H2, pad), :]),
                (bsl(rd_board, yn * h_loc + (h_loc - pad), pad,
                     xe * wpl, xpad),
                 ehalo.at[pl.ds(slot * H2, pad), :]),
                (bsl(rd_board, ys * h_loc, pad,
                     xw * wpl + (wpl - xpad), xpad),
                 whalo.at[pl.ds(slot * H2 + pad + h_loc, pad), :]),
                (bsl(rd_board, ys * h_loc, pad, xe * wpl, xpad),
                 ehalo.at[pl.ds(slot * H2 + pad + h_loc, pad), :]),
                # Both x-neighbours' interval-state vectors (published at
                # launch l−1, parity rd).
                (mystate.at[pl.ds((rd * nv + wv) * nsb, nsb), :],
                 wstate.at[pl.ds(slot * nsb, nsb), :]),
                (mystate.at[pl.ds((rd * nv + ev) * nsb, nsb), :],
                 estate.at[pl.ds(slot * nsb, nsb), :]),
            )
            ops = [
                pltpu.make_async_copy(src, dst, xsems.at[k])
                for k, (src, dst) in enumerate(pulls)
            ]
            for op in ops:
                op.start()
            for op in ops:
                op.wait()

    @pl.when(i == 0)
    def _():
        @pl.when(even)
        def _():
            prologue(oa)

        @pl.when(jnp.logical_not(even))
        def _():
            prologue(ob)

    # -- the skip decision: own + both x-neighbours' tracked intervals --------
    edge_n = i == 0
    edge_s = i == grid - 1
    iprev = jnp.maximum(i - 1, 0)
    inext = jnp.minimum(i + 1, grid - 1)
    gbase = 0 if remote else v * grid

    ivals = []
    cvals = []
    for j in (iprev, i, inext):
        gj = _off(gbase, j)
        ivals.append((ilo0[rd, gj], ihi0[rd, gj]))
        ivals.append((ilo1[rd, gj], ihi1[rd, gj]))
        cvals.append((iclo[rd, gj], ichi[rd, gj]))
    for buf, coff in ((wstate, -wpl), (estate, wpl)):
        for j in (iprev, i, inext):
            d = _decode_state6(
                buf[pl.ds(slot * nsb + j * _STATE_SLAB, _STATE_SLAB), :]
            )
            # Same row frame (same y); column entries translate into the
            # local word frame (empty intervals survive: lo > hi is
            # offset-invariant).
            ivals.append((d[0], d[1]))
            ivals.append((d[2], d[3]))
            cvals.append((d[4] + coff, d[5] + coff))
    hit, u_lo, u_hi, u_clo, u_chi = _hit_union(
        ivals, cvals, w_lo, w_hi, c_lo, c_hi, t6
    )
    # Forced-full stripes: launch 0 of a chunk (no tracked state yet) and
    # the N/S edge stripes of every launch — their windows reach into the
    # y-neighbours' tiles, whose interval state deliberately never
    # crosses the wire (the full route is exact regardless).
    forced = first | edge_n | edge_s
    hit = hit | forced
    u_lo = jnp.where(forced, c_lo - t6, u_lo)
    u_hi = jnp.where(forced, c_hi + t6, u_hi)
    p_r8 = rr8[rd, _off(gbase, i)]
    p_n8 = rn8[rd, _off(gbase, i)]
    p_c128 = rc128[rd, _off(gbase, i)]
    p_n128 = rn128[rd, _off(gbase, i)]

    def put_state(lo0, hi0, lo1, hi1, clo, chi, r8, n8, c128, n128):
        gI = _off(gbase, i)
        ilo0[wr, gI] = lo0
        ihi0[wr, gI] = hi0
        ilo1[wr, gI] = lo1
        ihi1[wr, gI] = hi1
        iclo[wr, gI] = clo
        ichi[wr, gI] = chi
        rr8[wr, gI] = r8
        rn8[wr, gI] = n8
        rc128[wr, gI] = c128
        rn128[wr, gI] = n128
        bump = (jnp.asarray(lo0) <= jnp.asarray(hi0)).astype(jnp.int32)
        if remote:
            act_ref[i, 0] = act_ref[i, 0] + bump
        else:
            act_ref[gi] = act_ref[gi] + bump
        # EVERY stripe publishes its slab: both x-neighbours consume the
        # full vector (unlike the strip form's edge-only tstate/bstate).
        vec = _encode_state6((lo0, hi0, lo1, hi1, clo, chi))
        sb = wr * nsb if remote else (wr * nv + my_sbase) * nsb
        mystate[pl.ds(sb + i * _STATE_SLAB, _STATE_SLAB), :] = vec

    def copy_rect(src, dst, r8, n8, c128, n128):
        _copy_rect(
            src, dst, tile, sems.at[0], r8, n8, c128, n128,
            tile_h=tile_h, wp=wpl, sub_rows=sub_rows, col_window=col_window,
            row_base=row0, col_base=col0,
        )

    @pl.when(jnp.logical_not(hit))
    def _():
        put_state(_EMPTY_LO, -1, _EMPTY_LO, -1, _EMPTY_LO, -1, 0, 0, 0, 0)
        acc[0] = acc[0] + 1

        @pl.when(p_n8 > 0)
        def _():
            @pl.when(even)
            def _():
                copy_rect(oa, ob, p_r8, p_n8, p_c128, p_n128)

            @pl.when(jnp.logical_not(even))
            def _():
                copy_rect(ob, oa, p_r8, p_n8, p_c128, p_n128)

    win_lo, m_lo, m_hi, windowed_ok = _frontier_placement(
        u_lo, u_hi, i, tile_h, pad, turns, sub_rows
    )
    # Window top in LOCAL tile rows, carried in 8-row chunk units so
    # Mosaic's divisibility proof survives (the recorded round-4 rule).
    g8 = i * (tile_h // 8) - pad // 8 + win_lo // 8
    g_lo = g8 * 8
    if col_window is not None:
        win_c, c_ok, cw = _col_placement(u_clo, u_chi, turns, col_window, wpl)
        # Tile-local seam bounds: the rectangle route reads the
        # UN-extended HBM tile directly, so the window must stay inside
        # it on BOTH axes (rows here; columns via _col_placement's
        # validity band, which keeps the reach t6 cells clear of the
        # tile seam exactly as it kept clear of the board edge).
        rect_ok = (
            hit
            & windowed_ok
            & c_ok
            & (g_lo >= 0)
            & (g_lo + sub_rows <= h_loc)
        )
    else:
        rect_ok = jnp.bool_(False)

    if col_window is not None:
        @pl.when(rect_ok)
        def _():
            def rect_in(board):
                c = pltpu.make_async_copy(
                    bsl(board, _off(row0, g_lo), sub_rows,
                        _off(col0, win_c), col_window),
                    colwin.at[:],
                    sems.at[0],
                )
                c.start()
                c.wait()

            @pl.when(even)
            def _():
                rect_in(oa)

            @pl.when(jnp.logical_not(even))
            def _():
                rect_in(ob)

            gT, g6, merged = _col_compute(
                colwin[:], turns, rule, cw, col_window, sub_rows
            )
            colwin[:] = merged
            lo0, hi0, lo1, hi1, clo, chi = _measure2(
                gT, g6, win_lo, m_lo, m_hi, w_lo,
                col_off=win_c, col_valid=(cw, col_window - cw),
            )
            r8 = jnp.maximum(g_lo, c_lo) // 8
            n8 = jnp.minimum(g_lo + sub_rows, c_lo + tile_h) // 8 - r8
            put_state(
                lo0, hi0, lo1, hi1, clo, chi,
                r8, n8, win_c // 128, col_window // 128,
            )

            def write_out(src_board, dst):
                @pl.when(p_n8 > 0)
                def _():
                    copy_rect(src_board, dst, p_r8, p_n8, p_c128, p_n128)

                full_span = n8 == sub_rows // 8

                @pl.when(full_span)
                def _():
                    c = pltpu.make_async_copy(
                        colwin.at[:],
                        bsl(dst, _off(row0, g_lo), sub_rows,
                            _off(col0, win_c), col_window),
                        sems.at[0],
                    )
                    c.start()
                    c.wait()

                @pl.when(jnp.logical_not(full_span))
                def _():
                    def chunk(kk, _):
                        c = pltpu.make_async_copy(
                            colwin.at[pl.ds((r8 + kk - g8) * 8, 8), :],
                            bsl(dst, _off(row0, (r8 + kk) * 8), 8,
                                _off(col0, win_c), col_window),
                            sems.at[0],
                        )
                        c.start()
                        c.wait()
                        return 0

                    jax.lax.fori_loop(0, n8, chunk, 0)

            @pl.when(even)
            def _():
                write_out(oa, ob)

            @pl.when(jnp.logical_not(even))
            def _():
                write_out(ob, oa)

    @pl.when(hit & jnp.logical_not(rect_ok))
    def _():
        def window_in(rd_board):
            # The five-DMA x-extended window assembly: centre, N/S rows
            # of the centre columns, and the full-height W/E column
            # blocks (whose outer pad rows ARE the corner blocks).
            center = pltpu.make_async_copy(
                bsl(rd_board, _off(row0, i * tile_h), tile_h, col0, wpl),
                tile.at[pl.ds(pad, tile_h), pl.ds(xpad, wpl)],
                sems.at[0],
            )
            center.start()

            n_dst = tile.at[pl.ds(0, pad), pl.ds(xpad, wpl)]
            s_dst = tile.at[pl.ds(pad + tile_h, pad), pl.ds(xpad, wpl)]

            @pl.when(edge_n)
            def _():
                pltpu.make_async_copy(
                    nhalo.at[pl.ds(slot * pad, pad), :], n_dst, sems.at[1]
                ).start()

            @pl.when(jnp.logical_not(edge_n))
            def _():
                pltpu.make_async_copy(
                    bsl(rd_board,
                        _off(row0, (i - 1) * tile_h + (tile_h - pad)),
                        pad, col0, wpl),
                    n_dst,
                    sems.at[1],
                ).start()

            @pl.when(edge_s)
            def _():
                pltpu.make_async_copy(
                    shalo.at[pl.ds(slot * pad, pad), :], s_dst, sems.at[2]
                ).start()

            @pl.when(jnp.logical_not(edge_s))
            def _():
                pltpu.make_async_copy(
                    bsl(rd_board, _off(row0, (i + 1) * tile_h), pad,
                        col0, wpl),
                    s_dst,
                    sems.at[2],
                ).start()

            wst = pltpu.make_async_copy(
                whalo.at[pl.ds(slot * H2 + i * tile_h, tile_h + 2 * pad), :],
                tile.at[:, pl.ds(0, xpad)],
                sems.at[3],
            )
            wst.start()
            est = pltpu.make_async_copy(
                ehalo.at[pl.ds(slot * H2 + i * tile_h, tile_h + 2 * pad), :],
                tile.at[:, pl.ds(xpad + wpl, xpad)],
                sems.at[4],
            )
            est.start()

            pltpu.make_async_copy(
                nhalo.at[pl.ds(slot * pad, pad), :], n_dst, sems.at[1]
            ).wait()
            pltpu.make_async_copy(
                shalo.at[pl.ds(slot * pad, pad), :], s_dst, sems.at[2]
            ).wait()
            wst.wait()
            est.wait()
            center.wait()

        @pl.when(even)
        def _():
            window_in(oa)

        @pl.when(jnp.logical_not(even))
        def _():
            window_in(ob)

        route, lo0, hi0, lo1, hi1, clo, chi = _frontier_body(
            tile, aux, merge, colwin, sems,
            u_lo, u_hi, u_clo, u_chi,
            i, tile_h, pad, turns, rule, sub_rows, None,
            xpad=xpad,
        )
        put_state(
            lo0, hi0, lo1, hi1, clo, chi,
            c_lo // 8, tile_h // 8, 0, wpl // 128,
        )

        @pl.when(even)
        def _():
            _dma_route_out(
                route, tile, merge, aux, ob, i, tile_h, pad, sems.at[0],
                xpad=xpad, row_base=row0, col_base=col0, wp_out=wpl,
            )

        @pl.when(jnp.logical_not(even))
        def _():
            _dma_route_out(
                route, tile, merge, aux, oa, i, tile_h, pad, sems.at[0],
                xpad=xpad, row_base=row0, col_base=col0, wp_out=wpl,
            )

    last = (l == nlaunch - 1) & (i == grid - 1)
    if not remote:
        last = last & (v == nv - 1)

    @pl.when(last)
    def _():
        if remote:
            sk_ref[0, 0] = acc[0]
            # The final launch's sends source the read buffer; they must
            # clear before the kernel (and the buffer's lifetime) ends.
            @pl.when(even)
            def _():
                for k in remote_ch:
                    mk_exchange(oa, k).wait_send()

            @pl.when(jnp.logical_not(even))
            def _():
                for k in remote_ch:
                    mk_exchange(ob, k).wait_send()
        else:
            sk_ref[0] = acc[0]


@functools.lru_cache(maxsize=12)
def _build_dispatch_frontier_2d(
    strip: tuple[int, int],
    mesh_shape: tuple[int, int],
    rule: LifeRule,
    turns: int,
    nlaunch: int,
    interpret: bool,
    tile_cap: int | None,
    remote: bool,
):
    """The 2-D in-kernel-exchange megakernel.  ``remote=True`` builds the
    per-device form: ``(ids, board, scratch_board) -> (board_a, board_b,
    skipped[1,1], activity[grid,1])`` over LOCAL (h_loc, wpl) tiles, with
    ``ids`` int32[6] = (north y, south y, west x, east x, own y, own x)
    mesh coordinates — an SMEM input so the hardware compile gate can AOT
    the remote lowering standalone.  ``remote=False`` builds the VIRTUAL
    form over the FULL (ny·h_loc, nx·wpl) board on one device:
    ``(board, scratch_board) -> (board_a, board_b, skipped[1],
    activity[ny·nx·grid])`` with activity in virtual-device-major order
    (the driver reshapes to the board-global (ny·grid, nx) bitmap).
    Board args alias the ping-pong outputs; the final state is output
    ``nlaunch % 2``.  Callers pass only ``_NLAUNCH_CANON`` values for
    ``nlaunch`` (the bounded-compile-cache contract)."""
    h_loc, wpl = strip
    ny, nx = mesh_shape
    _require_adaptive_eligible(turns)
    plan2 = _plan_2d(strip, turns, tile_cap, interpret)
    if plan2 is None:
        raise ValueError(
            f"no 2-D frontier plan for {turns} turns on tile {strip}"
        )
    xpad, pad, sub_rows, col_window, tile_h = plan2
    grid = h_loc // tile_h
    nv = 1 if remote else ny * nx
    wpe = wpl + 2 * xpad
    H2 = h_loc + 2 * pad
    kernel = partial(
        _kernel_frontier_mega_2d,
        tile_h=tile_h,
        pad=pad,
        xpad=xpad,
        grid=grid,
        nlaunch=nlaunch,
        turns=turns,
        rule=rule,
        sub_rows=sub_rows,
        col_window=col_window,
        mesh_shape=mesh_shape,
        remote=remote,
    )
    smem_i32 = lambda shp: pltpu.SMEM(shp, jnp.int32)  # noqa: E731
    scratch = [
        pltpu.VMEM((tile_h + 2 * pad, wpe), jnp.uint32),
        pltpu.VMEM((tile_h + 2 * pad, wpe), jnp.uint32),  # full buffer
        pltpu.VMEM((tile_h + 2 * pad, wpe), jnp.uint32),  # merge buffer
        pltpu.VMEM(
            (sub_rows, col_window if col_window else _LANES), jnp.uint32
        ),  # column-tier window (minimal dummy when the tier is off)
        # Exchange slots (launch parity): N/S rows, full-height W/E
        # column blocks (corner rows included), published + received
        # interval-state slab vectors.
        pltpu.VMEM((2 * pad, wpl), jnp.uint32),  # nhalo
        pltpu.VMEM((2 * pad, wpl), jnp.uint32),  # shalo
        pltpu.VMEM((2 * H2, xpad), jnp.uint32),  # whalo
        pltpu.VMEM((2 * H2, xpad), jnp.uint32),  # ehalo
        pltpu.VMEM(
            (2 * nv * grid * _STATE_SLAB, _LANES), jnp.int32
        ),  # mystate
        pltpu.VMEM((2 * grid * _STATE_SLAB, _LANES), jnp.int32),  # wstate
        pltpu.VMEM((2 * grid * _STATE_SLAB, _LANES), jnp.int32),  # estate
        # Interval state (6) + change-rect state (4), (parity, stripe).
        smem_i32((2, nv * grid)), smem_i32((2, nv * grid)),
        smem_i32((2, nv * grid)), smem_i32((2, nv * grid)),
        smem_i32((2, nv * grid)), smem_i32((2, nv * grid)),
        smem_i32((2, nv * grid)), smem_i32((2, nv * grid)),
        smem_i32((2, nv * grid)), smem_i32((2, nv * grid)),
        smem_i32((1,)),  # skip accumulator
        pltpu.SemaphoreType.DMA((5,)),
        pltpu.SemaphoreType.DMA((20,)),  # exchange: 10 send + 10 recv
    ]
    # The exchange scratch rides on top of the window working set the
    # shared helper budgets; raise the requested limit to match (capped
    # at the same physical-VMEM ceiling — _plan_2d already declined any
    # geometry that would overflow it).
    from distributed_gol_tpu.ops.pallas_packed import _vmem_physical

    exch = _exchange_scratch_bytes(h_loc, wpl, xpad, pad, grid)
    ceiling = _vmem_physical() - (8 << 20)

    def with_exchange(params):
        return dataclasses.replace(
            params,
            vmem_limit_bytes=min(ceiling, params.vmem_limit_bytes + exch),
        )

    if remote:
        params = with_exchange(
            _compiler_params(tile_h, pad, wpe, True, sequential_grid=True)
        )
        params = dataclasses.replace(params, collective_id=9)
        return pl.pallas_call(
            kernel,
            grid=(nlaunch, grid),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=[
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pl.ANY),
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.SMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((h_loc, wpl), jnp.uint32),
                jax.ShapeDtypeStruct((h_loc, wpl), jnp.uint32),
                jax.ShapeDtypeStruct((1, 1), jnp.int32),
                jax.ShapeDtypeStruct((grid, 1), jnp.int32),
            ],
            input_output_aliases={1: 0, 2: 1},
            scratch_shapes=scratch,
            compiler_params=params,
            interpret=interpret,
        )
    H, WP = ny * h_loc, nx * wpl
    params = with_exchange(
        _compiler_params(tile_h, pad, wpe, True, sequential_grid=True, grid_rank=3)
    )
    return pl.pallas_call(
        kernel,
        grid=(nlaunch, nv, grid),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((H, WP), jnp.uint32),
            jax.ShapeDtypeStruct((H, WP), jnp.uint32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((nv * grid,), jnp.int32),
        ],
        input_output_aliases={0: 0, 1: 1},
        scratch_shapes=scratch,
        compiler_params=params,
        interpret=interpret,
    )


def ici_tier_policy(
    mesh: Mesh,
    interpret: bool | None = None,
    in_kernel: bool | None = None,
    strip: tuple[int, int] | None = None,
    tile_cap: int | None = None,
) -> tuple[bool, str]:
    """Whether the sharded adaptive path runs the in-kernel ICI exchange
    tier, with the POLICY reason when it does not.  A False here is a
    deliberate policy outcome — recorded by the Backend
    (``sharded_tier_policy``) and printed by ``dryrun_multichip`` — NOT a
    capability downgrade: the ppermute strip form is bit-identical and
    remains the always-correct fallback, so no warning is ever emitted.

    ``in_kernel``: ``False`` forces the ppermute form (the documented
    escape hatch; env ``DGOL_ICI=0`` is the CLI-reachable spelling);
    ``True`` overrides the env switch but never capability — a mesh the
    tier cannot serve still falls back, with the reason recorded.

    ``strip`` (the per-device LOCAL (h_loc, wp_loc) packed tile, with
    ``tile_cap``): also checks the GEOMETRY can host the tier — the
    megakernel rides the frontier plan (the 2-D plan on nx > 1 meshes,
    which adds the x-halo VMEM and word-alignment requirements), probed
    here at the deep-dispatch depth (the hw-gate convention), so a
    Backend's recorded tier cannot claim in-kernel on a tile that has no
    plan.  A True verdict still describes deep dispatches only: a
    dispatch too shallow for even one adaptive launch runs the ppermute
    remainder forms regardless of tier."""
    ip = _use_interpret() if interpret is None else interpret
    ny = mesh.shape["y"]
    nx = mesh.shape["x"]
    if in_kernel is False:
        return False, "forced-ppermute (in_kernel=False)"
    if strip is not None:
        if nx == 1:
            _, _, adaptive, fplan = _adaptive_strip_plan(strip, 10**6, tile_cap)
        else:
            _, _, adaptive, fplan = _adaptive_plan_2d(strip, 10**6, tile_cap, ip)
        if not adaptive or fplan is None:
            return False, (
                f"no frontier plan for tile {strip} on ({ny}, {nx}): the "
                "in-kernel tier rides the frontier megakernel (ppermute "
                "probing/plain forms run instead)"
            )
    if in_kernel is not True and os.environ.get("DGOL_ICI", "").lower() in (
        "0", "off", "false",
    ):
        return False, "forced-ppermute (DGOL_ICI=0)"
    if ip and ny * nx > 1:
        return False, (
            "interpret-mode multi-device: no remote-DMA emulation "
            "(hermetic coverage runs the loopback/virtual builds — "
            "make_superstep_virtual_2d emulates (ny, nx) on one device; "
            "hardware lowering is gated by tools/hw_compile_gate.py)"
        )
    if ny * nx > 1 and len({d.process_index for d in mesh.devices.flat}) > 1:
        return False, (
            "multi-host mesh: the exchange crosses DCN, remote DMA is "
            "ICI-only (parallel/multihost.py keeps the ppermute form)"
        )
    return True, "in-kernel"


def _adaptive_strip_plan(
    strip: tuple[int, int], turns: int, raw_cap: int | None
) -> tuple[int, int, bool, tuple | None]:
    """(cap, t, adaptive, frontier_plan) for a skip_stable dispatch on a
    strip — THE one decision shared by ``make_superstep`` (execution)
    and ``launch_plan`` (the dryrun/BASELINE publication), so the
    published plan can never drift from the executing one (the same
    convention as ``_strip_plan_tile``).  A non-None plan means the
    frontier strip kernel runs; the depth policy only returns its
    shallow frontier depths when the plan exists, so the two cannot
    desync."""
    cap = raw_cap if raw_cap is not None else default_skip_cap(strip[0])
    t, adaptive = adaptive_launch_depth(strip, turns, cap)
    fplan = _frontier_plan(strip, t, cap) if adaptive else None
    return cap, t, adaptive, fplan


def _strip_plan_tile(
    strip: tuple[int, int], turns: int, tile_cap: int | None
) -> int:
    """The tile height an adaptive strip launch will use — the ONE plan
    call shared by the launch builder and the bitmap-shape computation in
    ``make_superstep``, so the SMEM bitmap length can never drift from the
    kernel grid (mirrors ``pallas_packed._plan_tile``)."""
    tile_h = _tile_for_pad(strip[0], strip[1], _round8(turns), tile_cap)
    if tile_h is None:
        raise ValueError(f"no VMEM tiling for {turns} turns on strip {strip}")
    return tile_h


@functools.lru_cache(maxsize=None)
def _build_ext_launch_adaptive(
    strip: tuple[int, int],
    rule: LifeRule,
    turns: int,
    interpret: bool,
    tile_cap: int | None,
):
    """The adaptive strip launch as ``(prev_ext, local, north, south,
    dst_prev) -> (strip, bitmap)`` with ``prev_ext`` int32[grid + 2]
    (neighbour edge flags prepended/appended by the caller) and
    ``dst_prev`` (the strip from two launches ago) ALIASED onto the strip
    output — the ping-pong write-elision contract (see
    ``_ext_kernel_adaptive``): callers alternate two buffers and zero the
    bitmap at dispatch start."""
    h_loc, wp = strip
    _require_adaptive_eligible(turns)
    pad = _round8(turns)
    tile_h = _strip_plan_tile(strip, turns, tile_cap)
    grid = h_loc // tile_h
    kernel = partial(
        _ext_kernel_adaptive,
        tile_h=tile_h,
        pad=pad,
        grid=grid,
        turns=turns,
        rule=rule,
    )
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h_loc, wp), jnp.uint32),
            jax.ShapeDtypeStruct((grid,), jnp.int32),
        ],
        input_output_aliases={4: 0},
        scratch_shapes=[
            pltpu.VMEM((tile_h + 2 * pad, wp), jnp.uint32),
            pltpu.VMEM((tile_h + 2 * pad, wp), jnp.uint32),  # probe buffer
            pltpu.VMEM((tile_h + 2 * pad, wp), jnp.uint32),  # merge buffer
            pltpu.SemaphoreType.DMA((3,)),
        ],
        compiler_params=_compiler_params(tile_h, pad, wp, True),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _build_ext_launch_frontier(
    strip: tuple[int, int],
    rule: LifeRule,
    turns: int,
    interpret: bool,
    tile_cap: int | None,
):
    """The frontier strip launch as ``(ps, lo0e, hi0e, lo1e, hi1e, cloe,
    chie, local, north, south, dst_prev) -> (strip, st, nlo0, nhi0,
    nlo1, nhi1, nclo, nchi)`` with the six interval arrays extended
    (int32[grid + 2], neighbour edge-tile entries pre-translated by the
    caller) and ``dst_prev`` ALIASED onto the strip output — the
    ping-pong write-elision contract (see ``_ext_kernel_frontier``):
    callers alternate two buffers and start each dispatch from full
    intervals + a zero bitmap."""
    h_loc, wp = strip
    _require_adaptive_eligible(turns)
    plan = _frontier_plan(strip, turns, tile_cap)
    if plan is None:
        raise ValueError(f"no frontier plan for {turns} turns on strip {strip}")
    pad, sub_rows, col_window = plan
    tile_h = _strip_plan_tile(strip, turns, tile_cap)
    grid = h_loc // tile_h
    kernel = partial(
        _ext_kernel_frontier,
        tile_h=tile_h,
        pad=pad,
        grid=grid,
        turns=turns,
        rule=rule,
        sub_rows=sub_rows,
        col_window=col_window,
    )
    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    any_ = pl.BlockSpec(memory_space=pl.ANY)
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[smem] * 7 + [any_] * 4,
        out_specs=[any_] + [smem] * 7,
        out_shape=[jax.ShapeDtypeStruct((h_loc, wp), jnp.uint32)]
        + [jax.ShapeDtypeStruct((grid,), jnp.int32)] * 7,
        input_output_aliases={10: 0},
        scratch_shapes=[
            pltpu.VMEM((tile_h + 2 * pad, wp), jnp.uint32),
            pltpu.VMEM((tile_h + 2 * pad, wp), jnp.uint32),  # full buffer
            pltpu.VMEM((tile_h + 2 * pad, wp), jnp.uint32),  # merge buffer
            pltpu.VMEM(
                (sub_rows, col_window if col_window else _LANES), jnp.uint32
            ),  # column-tier window (minimal dummy when the tier is off)
            pltpu.SemaphoreType.DMA((3,)),
        ],
        compiler_params=_compiler_params(tile_h, pad, wp, True),
        interpret=interpret,
    )


@functools.lru_cache(maxsize=None)
def _build_ext_launch(
    strip: tuple[int, int],
    rule: LifeRule,
    turns: int,
    interpret: bool,
    skip_stable: bool = False,
    tile_cap: int | None = None,
    xpad: int = 0,
):
    """pallas_call advancing a halo-extended (h_loc + 2·pad, wp + 2·xpad)
    strip by ``turns`` ≤ pad generations, returning the (h_loc, wp)
    centre.  ``xpad == 0`` is the classic full-board-width strip form;
    ``xpad > 0`` is the 2-D-mesh tile form (``strip`` is then the
    per-device LOCAL (h_loc, wp/nx) shape and the caller pre-extends with
    :func:`_extend_tile_2d`).  ``tile_cap`` must be passed whenever the
    caller's skip_stable request is active — even for
    non-adaptive-eligible launches — so planning and execution use the
    same tile set (round-2 advisor finding)."""
    h_loc, wp = strip
    wpe = wp + 2 * xpad
    if skip_stable:
        _require_adaptive_eligible(turns)
    pad = _round8(turns)
    tile_h = _tile_for_pad(h_loc, wpe, pad, tile_cap)
    if tile_h is None:
        raise ValueError(f"no VMEM tiling for {turns} turns on strip {strip}")
    grid = h_loc // tile_h
    kernel = partial(
        _ext_kernel,
        tile_h=tile_h,
        pad=pad,
        turns=turns,
        rule=rule,
        skip_stable=skip_stable,
        xpad=xpad,
    )
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec((tile_h, wp), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((h_loc, wp), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((tile_h + 2 * pad, wpe), jnp.uint32),
            pltpu.SemaphoreType.DMA,
        ],
        compiler_params=_compiler_params(tile_h, pad, wpe, skip_stable),
        interpret=interpret,
    )


def launch_plan(
    pshape: tuple[int, int],
    mesh_shape: tuple[int, int],
    turns: int = 128,
    skip_tile_cap: int | None = None,
) -> dict:
    """The static launch plan for a packed board on a row mesh, as data:
    ``{t, pad, tile_h, grid, halo_bytes}`` where ``halo_bytes`` is the ICI
    traffic per device per launch (pad rows each way).  This is what the
    driver's ``dryrun_multichip`` prints per mesh, and what BASELINE.md's
    multi-chip scaling model is computed from — one source of truth, so the
    published model is machine-checked against the executing planner every
    round."""
    h, wp = pshape
    ny, nx = mesh_shape
    if not supports(pshape, mesh_shape):
        raise ValueError(f"pallas_halo does not support {pshape} on {mesh_shape}")
    if nx > 1:
        return _launch_plan_2d(pshape, mesh_shape, turns, skip_tile_cap)
    strip = (h // ny, wp)
    t = launch_turns(strip, turns, skip_tile_cap)
    pad = _round8(t)
    tile_h = _tile_for_pad(strip[0], wp, pad, skip_tile_cap)
    # The adaptive tier this strip would run under skip_stable: the
    # round-5 frontier strip kernel when a plan exists at the adaptive
    # depth (its intervals add 6 int32 scalars per edge tile to the
    # exchange — noise next to the pad-row halo), else the probing form.
    _, t_a, adaptive, fplan = _adaptive_strip_plan(strip, turns, skip_tile_cap)
    return {
        "t": t,
        "pad": pad,
        "tile_h": tile_h,
        "grid": strip[0] // tile_h,
        # 2 directions x pad rows x wp words x 4 bytes, per device per launch
        "halo_bytes": 2 * pad * wp * 4,
        "adaptive_t": t_a if adaptive else None,
        "frontier": None
        if fplan is None
        else {
            "pad": fplan[0],
            "sub_rows": fplan[1],
            "col_window": fplan[2],
            "halo_bytes": 2 * fplan[0] * wp * 4,
        },
    }


def _launch_plan_2d(
    pshape: tuple[int, int],
    mesh_shape: tuple[int, int],
    turns: int,
    skip_tile_cap: int | None,
) -> dict:
    """The 2-D-mesh launch plan as data (round 7): per-device tile,
    depth, and PER-DIRECTION halo traffic — ``halo_bytes_y`` (N + S edge
    rows), ``halo_bytes_x`` (W + E edge word-columns INCLUDING the four
    corner blocks, which ride the full-height column buffers), and their
    total.  Same one-source-of-truth contract as the row plan: this is
    what ``bench.py --sharded-mesh NYxNX`` records and what the
    multi-chip scaling model reads."""
    h, wp = pshape
    ny, nx = mesh_shape
    ip = _use_interpret()
    strip = (h // ny, wp // nx)
    xpad = _xpad_words(strip[1], ip)
    ext = (strip[0], strip[1] + 2 * xpad)
    t = launch_turns(ext, min(turns, _x_depth_cap(xpad)), skip_tile_cap)
    pad = _round8(t)
    tile_h = _tile_for_pad(strip[0], ext[1], pad, skip_tile_cap)
    cap, t_a, adaptive, plan2 = _adaptive_plan_2d(strip, turns, skip_tile_cap, ip)

    def bytes_2d(p):
        return {
            "halo_bytes_y": 2 * p * strip[1] * 4,
            "halo_bytes_x": 2 * (strip[0] + 2 * p) * xpad * 4,
            "halo_bytes": 2 * p * strip[1] * 4
            + 2 * (strip[0] + 2 * p) * xpad * 4,
        }

    return {
        "t": t,
        "pad": pad,
        "xpad": xpad,
        "tile_h": tile_h,
        "grid": None if tile_h is None else strip[0] // tile_h,
        **bytes_2d(pad),
        "adaptive_t": t_a if adaptive else None,
        "frontier": None
        if plan2 is None
        else {
            "pad": plan2[1],
            "sub_rows": plan2[2],
            "col_window": plan2[3],
            **bytes_2d(plan2[1]),
        },
    }


def halo_bytes_2d_model(
    pshape: tuple[int, int], mesh_shape: tuple[int, int], turns: int = 128
) -> dict:
    """ICI bytes per device per launch the 2-D-mesh tier ships vs the row
    mesh with the same device count — the machine-checked byte model
    behind the tier policy's perf guidance.  Round 4 used this record to
    keep the flagship tier row-only; round 7 SHIPPED the 2-D tier (this
    ``two_d`` record now describes real traffic, see ``_launch_plan_2d``
    for the executing plan) because the row ceiling caps scale-out at ny
    devices — strips go needle-thin long before a pod runs out of chips,
    and the 262144²-class board needs the full (ny, nx) mesh.  The byte
    physics still holds and still matters:

    The y-halo is ``pad`` rows of the device's width.  The x-halo cannot
    be ``pad`` columns: the kernel's packed words live on the LANE axis,
    and Mosaic lane slices are 128-lane quantized (the measured
    column-blocking dead end in BASELINE.md is the same physics), so each
    x-halo ships ≥ 128 words = 4096 cells per side regardless of T ≤ 128.
    At 65536² on 8 devices the (2, 4) mesh ships ~40× the (8, 1) mesh's
    bytes — so the tier policy and ``mesh_shape_for`` still PREFER row
    meshes while strips stay tall enough, and the 2-D tier is the
    scale-out lever past that point, not a free lunch.  Row strips also
    keep the full-width lane rotate = the exact torus x-wrap; 2-D tiles
    pay the x-halo instead."""
    h, wp = pshape
    ny, nx = mesh_shape
    pad = _round8(min(turns, 128))
    row = {"mesh": (ny * nx, 1), "halo_bytes": 2 * pad * wp * 4}
    if nx == 1:
        return {"row": row, "mesh_2d": row, "ratio": 1.0}
    y_bytes = 2 * pad * (wp // nx) * 4
    # x-halo: pad CELLS = ceil(pad/32) packed words per side, rounded up
    # to the 128-word lane quantum (= 4096 cells; one quantum suffices
    # for any T ≤ 128 and dwarfs the actual need).
    pad_words = -(-pad // 32)
    x_words = -(-pad_words // _LANES) * _LANES
    x_bytes = 2 * x_words * (h // ny) * 4
    two_d = {"mesh": (ny, nx), "halo_bytes": y_bytes + x_bytes}
    return {
        "row": row,
        "mesh_2d": two_d,
        "ratio": two_d["halo_bytes"] / row["halo_bytes"],
    }


def _extend_rows(local: jax.Array, pad: int) -> jax.Array:
    """(h_loc, wp) strip -> (h_loc + 2·pad, wp) with pad boundary rows from
    the ring neighbours (self-send on a 1-sized axis = the torus wrap)."""
    ny = axis_size("y")
    from_north = lax.ppermute(local[-pad:, :], "y", _shift_perm(ny, forward=True))
    from_south = lax.ppermute(local[:pad, :], "y", _shift_perm(ny, forward=False))
    return jnp.concatenate([from_north, local, from_south], axis=0)


def adaptive_strip_launches(
    pshape: tuple[int, int],
    mesh_shape: tuple[int, int],
    turns: int,
    tile_cap: int | None,
) -> int:
    """How many tile-launches an adaptive sharded dispatch of ``turns``
    generations performs across ALL devices — the denominator for the
    skip fraction, from the same plan ``make_superstep`` executes (the
    remainder launch is excluded there and here; mirrors
    ``pallas_packed.adaptive_tile_launches``).  On 2-D meshes the
    denominator spans every (stripe, x-device) cell of the board-global
    activity grid — both the in-kernel 2-D tier and the probing 2-D
    fallback count in those units."""
    if not supports(pshape, mesh_shape):
        return 0
    ny, nx = mesh_shape
    if nx > 1:
        ip = _use_interpret()
        strip = (pshape[0] // ny, pshape[1] // nx)
        cap, t, adaptive, _plan = _adaptive_plan_2d(strip, turns, tile_cap, ip)
        full, _ = divmod(turns, t)
        if not adaptive or not full:
            return 0
        tile_h = _plan_tile_2d(strip, t, cap, _xpad_words(strip[1], ip))
        return full * ny * nx * (strip[0] // tile_h)
    strip = (pshape[0] // ny, pshape[1])
    # Resolve None exactly as make_superstep(skip_stable=True) does (from
    # the per-device STRIP height), so the "same plan" contract holds for
    # every caller, not just ones that pre-resolve the cap.
    if tile_cap is None:
        tile_cap = default_skip_cap(strip[0])
    t, adaptive = adaptive_launch_depth(strip, turns, tile_cap)
    full, _ = divmod(turns, t)
    if not adaptive or not full:
        return 0
    return full * ny * (strip[0] // _strip_plan_tile(strip, t, tile_cap))


def make_superstep(
    mesh: Mesh,
    rule: LifeRule = CONWAY,
    interpret: bool | None = None,
    skip_stable: bool = False,
    skip_tile_cap: int | None = None,
    with_stats: bool = False,
    in_kernel: bool | None = None,
):
    """``(packed, turns) -> packed`` on the mesh: turns split into launches
    of T = ``launch_turns(strip, turns)`` generations; each launch is one
    ppermute halo exchange + one pallas_call per device — except on the
    adaptive frontier path, where ``ici_tier_policy`` may select the
    round-6 IN-KERNEL exchange tier: whole canonical launch chunks run as
    ONE pallas_call per device with the halo rows and interval state
    exchanged by remote DMA inside the kernel
    (``_kernel_frontier_mega_strip``).  ``in_kernel`` forces the tier
    (``False`` = always ppermute; ``None`` = policy).

    ``skip_stable``: the exact period-6 activity skip of the single-device
    kernel, per strip tile, INCLUDING its frontier-aware probe elision
    (round 3): the per-tile skip bitmap's edge flags ride the same
    ``ppermute`` exchange as the halo rows, so a tile whose window sources
    — possibly in the neighbouring strip — all skipped last launch elides
    the probe (soundness: BASELINE.md; the bitmap is scoped to one
    dispatch's identical-geometry launches, zeroed at dispatch start).
    ``skip_tile_cap`` bounds the adaptive tile height (None = the default
    measured size-aware default from the strip height,
    ``pallas_packed.default_skip_cap``).  ``with_stats`` returns
    ``(board, skipped, activity)`` where ``skipped`` counts skip-branch
    tile-launches across all devices and full launches of the dispatch
    (the replicated result of one all-reduce per launch) and
    ``activity`` (int32[ny·grid], ISSUE 11) is the board-global
    per-stripe activity vector in top-to-bottom board order (empty when
    the dispatch carries no adaptive telemetry) — same live-telemetry
    contract as the single-device kernel.

    2-D meshes (round 7): ``nx > 1`` runs the x-extended tile family —
    the in-kernel 2-D megakernel when ``ici_tier_policy`` selects it,
    else the probing adaptive 2-D form (precomputed 3×3 elision flags),
    else the plain 2-D form; ``activity`` is then the (ny·grid, nx)
    board-global GRID (stripe × x-device) and ``skipped`` counts
    (stripe, x-device) cells, matching ``adaptive_strip_launches``'s 2-D
    denominator."""
    ny = mesh.shape["y"]
    nx = mesh.shape["x"]
    raw_cap = skip_tile_cap

    def _run_2d(board, turns, ip):
        h, wp = board.shape
        strip = (h // ny, wp // nx)
        xpad = _xpad_words(strip[1], ip)
        if skip_stable:
            cap, t, t_adaptive, plan2 = _adaptive_plan_2d(
                strip, turns, raw_cap, ip
            )
        else:
            cap = None
            t = launch_turns(
                (strip[0], strip[1] + 2 * xpad),
                min(turns, _x_depth_cap(xpad)),
                None,
            )
            t_adaptive = False
            plan2 = None
        full, rem = divmod(turns, t)

        def make_step(tt: int, adaptive_ok: bool = False):
            adaptive = skip_stable and adaptive_ok and _adaptive_eligible(tt)
            pad = _round8(tt)
            if not adaptive:
                call = _build_ext_launch(
                    strip,
                    rule,
                    tt,
                    ip,
                    skip_stable and _adaptive_eligible(tt),
                    cap if skip_stable else None,
                    xpad,
                )

                @partial(
                    shard_map,
                    mesh=mesh,
                    in_specs=BOARD_SPEC,
                    out_specs=BOARD_SPEC,
                    check_vma=False,
                )
                def step(local):
                    return call(_extend_tile_2d(local, pad, xpad))

                return step

            call = _build_ext_launch_adaptive_2d(strip, rule, tt, ip, cap, xpad)

            @partial(
                shard_map,
                mesh=mesh,
                in_specs=(BOARD_SPEC, BOARD_SPEC, BOARD_SPEC),
                out_specs=(BOARD_SPEC, BOARD_SPEC),
                check_vma=False,
            )
            def step(st, local, prev):
                # The 3×3 elision conjunction, computed in XLA so the
                # kernel stays mesh-shape-agnostic: extend own flags with
                # the y-neighbours' edge flags, conjoin vertically, then
                # conjoin with both x-neighbours' conjunctions — whose
                # own edge flags bring the corner tiles along (the same
                # two-phase trick as the halo exchange itself).
                nf = lax.ppermute(st[-1:, :], "y", _shift_perm(ny, forward=True))
                sf = lax.ppermute(st[:1, :], "y", _shift_perm(ny, forward=False))
                extf = jnp.concatenate([nf, st, sf])
                v3 = extf[:-2] * extf[1:-1] * extf[2:]
                vw = lax.ppermute(v3, "x", _shift_perm(nx, forward=True))
                ve = lax.ppermute(v3, "x", _shift_perm(nx, forward=False))
                elig = v3 * vw * ve
                return call(elig, _extend_tile_2d(local, pad, xpad), prev)

            return step

        def make_dispatch_ici(tt: int, nl: int):
            call = _build_dispatch_frontier_2d(
                strip, (ny, nx), rule, tt, nl, ip, cap, True
            )

            @partial(
                shard_map,
                mesh=mesh,
                in_specs=(BOARD_SPEC, BOARD_SPEC),
                out_specs=(BOARD_SPEC, BOARD_SPEC, BOARD_SPEC, BOARD_SPEC),
                check_vma=False,
            )
            def step(local, prev):
                my = lax.axis_index("y")
                mx = lax.axis_index("x")
                ids = jnp.stack(
                    [
                        lax.rem(my + ny - 1, ny),
                        lax.rem(my + 1, ny),
                        lax.rem(mx + nx - 1, nx),
                        lax.rem(mx + 1, nx),
                        my,
                        mx,
                    ]
                ).astype(jnp.int32)
                return call(ids, local, prev)

            return step

        adaptive_t = skip_stable and t_adaptive
        skipped = jnp.int32(0)
        act = jnp.zeros((0,), jnp.int32)
        use_ici = (
            adaptive_t
            and plan2 is not None
            and ici_tier_policy(mesh, ip, in_kernel)[0]
        )
        if full and use_ici:
            tile_h = plan2[4]
            grid = strip[0] // tile_h
            chunks, loose = _nlaunch_chunks(full)
            a = jnp.zeros_like(board)
            act = jnp.zeros((ny * grid, nx), jnp.int32)
            for c in chunks:
                step_c = make_dispatch_ici(t, c)
                na, nb, sk, act_c = step_c(board, a)
                board, a = (nb, na) if c % 2 else (na, nb)
                skipped = skipped + jnp.sum(sk)
                act = act + act_c
            if loose:
                step_l = make_step(t, adaptive_ok=True)
                st = jnp.zeros((ny * grid, nx), jnp.int32)
                prev = a
                for _ in range(loose):
                    nb, nst = step_l(st, board, prev)
                    board, prev, st = nb, board, nst
                    skipped = skipped + jnp.sum(nst)
                    act = act + (1 - nst)
        elif adaptive_t and full:
            tile_h = _plan_tile_2d(strip, t, cap, xpad)
            grid = strip[0] // tile_h
            step_t = make_step(t, adaptive_ok=True)
            st0 = jnp.zeros((ny * grid, nx), jnp.int32)
            act = jnp.zeros((ny * grid, nx), jnp.int32)

            def body(_, carry):
                a, b, st, sk, ac = carry
                nb1, nst1 = step_t(st, b, a)
                nb2, nst2 = step_t(nst1, nb1, b)
                return (
                    nb1,
                    nb2,
                    nst2,
                    sk + jnp.sum(nst1) + jnp.sum(nst2),
                    ac + (1 - nst1) + (1 - nst2),
                )

            a, board, st, skipped, act = jax.lax.fori_loop(
                0,
                full // 2,
                body,
                (jnp.zeros_like(board), board, st0, skipped, act),
            )
            if full % 2:
                board, nst = step_t(st, board, a)
                skipped = skipped + jnp.sum(nst)
                act = act + (1 - nst)
        elif full:
            step_t = make_step(t)
            board = jax.lax.fori_loop(0, full, lambda _, b: step_t(b), board)
        if rem and skip_stable:
            rem6 = rem - rem % _SKIP_PERIOD
            if rem6:
                board = make_step(rem6)(board)
                rem -= rem6
        if rem:
            board = make_step(rem)(board)
        if with_stats:
            return board, skipped, act
        return board

    @partial(jax.jit, static_argnames=("turns",))
    def run(board: jax.Array, turns: int):
        if turns == 0:
            if with_stats:
                return board, jnp.int32(0), jnp.zeros((0,), jnp.int32)
            return board
        ip = _use_interpret() if interpret is None else interpret
        if nx > 1:
            return _run_2d(board, turns, ip)
        h, wp = board.shape
        strip = (h // ny, wp)
        if skip_stable:
            cap, t, t_adaptive, fplan = _adaptive_strip_plan(
                strip, turns, raw_cap
            )
        else:
            cap = None
            t = launch_turns(strip, turns, None)  # clamps to _MAX_T internally
            t_adaptive = False
            fplan = None
        full, rem = divmod(turns, t)

        def make_step(tt: int, adaptive_ok: bool = False):
            adaptive = skip_stable and adaptive_ok and _adaptive_eligible(tt)
            pad = _round8(tt)
            # check_vma=False: pallas_call outputs carry no varying-mesh-axes
            # annotation, which the vma checker (rightly) refuses to guess;
            # the body is manifestly per-device (one kernel per strip).
            if not adaptive:
                call = _build_ext_launch(
                    strip,
                    rule,
                    tt,
                    ip,
                    skip_stable and _adaptive_eligible(tt),
                    cap if skip_stable else None,
                )

                @partial(
                    shard_map,
                    mesh=mesh,
                    in_specs=BOARD_SPEC,
                    out_specs=BOARD_SPEC,
                    check_vma=False,
                )
                def step(local):
                    return call(_extend_rows(local, pad))

                return step

            call = _build_ext_launch_adaptive(strip, rule, tt, ip, cap)

            @partial(
                shard_map,
                mesh=mesh,
                in_specs=(P("y"), BOARD_SPEC, BOARD_SPEC),
                out_specs=(BOARD_SPEC, P("y")),
                check_vma=False,
            )
            def step(st, local, prev):
                # Neighbour edge-tile flags, exchanged exactly like the
                # halo rows (self-send on a 1-sized axis = torus wrap).
                north_flag = lax.ppermute(
                    st[-1:], "y", _shift_perm(ny, forward=True)
                )
                south_flag = lax.ppermute(
                    st[:1], "y", _shift_perm(ny, forward=False)
                )
                prev_ext = jnp.concatenate([north_flag, st, south_flag])
                # Only the pad-row boundaries cross ICI; the kernel
                # assembles each tile's window itself, so the old
                # _extend_rows concatenate (a full strip copy per
                # launch) is gone.
                north = lax.ppermute(
                    local[-pad:, :], "y", _shift_perm(ny, forward=True)
                )
                south = lax.ppermute(
                    local[:pad, :], "y", _shift_perm(ny, forward=False)
                )
                return call(prev_ext, local, north, south, prev)

            return step

        def make_step_frontier(tt: int):
            # The frontier halo is DEEPER than the probing one:
            # round8(tt + 6), so gen tt+6 is valid on the whole centre
            # for the interval measure — the ppermute extent must match
            # the kernel's plan pad, not the probing round8(tt).
            pad = _frontier_plan(strip, tt, cap)[0]
            call = _build_ext_launch_frontier(strip, rule, tt, ip, cap)
            h_loc = strip[0]

            @partial(
                shard_map,
                mesh=mesh,
                in_specs=(P("y"),) * 7 + (BOARD_SPEC, BOARD_SPEC),
                out_specs=(BOARD_SPEC,) + (P("y"),) * 7,
                check_vma=False,
            )
            def step(ps, l0, h0, l1, h1, cl, ch, local, prev):
                # Edge-tile intervals ride the same ppermute as the halo
                # rows; row entries are translated into the receiving
                # strip's frame (the north neighbour's strip row r is
                # this strip's row r − h_loc), column entries are
                # board-global words and ship unshifted.  Empty
                # intervals survive translation: lo > hi is preserved
                # by adding the same offset to both.  The six edge
                # scalars ship STACKED — one (6,) ppermute per
                # direction, not twelve 4-byte collectives per launch.
                shift = jnp.array(
                    [h_loc] * 4 + [0, 0], dtype=jnp.int32
                )
                arrs = (l0, h0, l1, h1, cl, ch)
                edge_n = jnp.stack([a[-1] for a in arrs])
                edge_s = jnp.stack([a[0] for a in arrs])
                from_n = lax.ppermute(
                    edge_n, "y", _shift_perm(ny, forward=True)
                ) - shift
                from_s = lax.ppermute(
                    edge_s, "y", _shift_perm(ny, forward=False)
                ) + shift
                args = [
                    jnp.concatenate([from_n[k:k + 1], a, from_s[k:k + 1]])
                    for k, a in enumerate(arrs)
                ]
                north = lax.ppermute(
                    local[-pad:, :], "y", _shift_perm(ny, forward=True)
                )
                south = lax.ppermute(
                    local[:pad, :], "y", _shift_perm(ny, forward=False)
                )
                return call(ps, *args, local, north, south, prev)

            return step

        def make_dispatch_ici(tt: int, nl: int):
            # One in-kernel-exchange chunk: nl launches in one pallas_call
            # per device.  ny == 1 builds the loopback form (the torus
            # self-exchange — also the hermetic interpret-mode build).
            call = _build_dispatch_frontier_strip(
                strip, rule, tt, nl, ip, cap, ny > 1
            )

            @partial(
                shard_map,
                mesh=mesh,
                in_specs=(BOARD_SPEC, BOARD_SPEC),
                out_specs=(BOARD_SPEC, BOARD_SPEC, P("y"), P("y")),
                check_vma=False,
            )
            def step(local, prev):
                my = lax.axis_index("y")
                ids = jnp.stack(
                    [
                        lax.rem(my + ny - 1, ny),
                        lax.rem(my + 1, ny),
                        lax.axis_index("x"),
                    ]
                ).astype(jnp.int32)
                return call(ids, local, prev)

            return step

        # The helper's flag IS the decision (same-plan contract); only the
        # non-skip path, which never consulted the helper, derives none.
        adaptive_t = skip_stable and t_adaptive
        skipped = jnp.int32(0)
        # Board-global per-stripe activity (ISSUE 11): ny·grid entries in
        # device order == top-to-bottom board order; empty when the
        # dispatch carries no adaptive telemetry.
        act = jnp.zeros((0,), jnp.int32)
        # use_ici already conjoins the adaptive/frontier-plan capability
        # with the mesh policy; the dispatch branch below only adds the
        # "at least one full launch" requirement.
        use_ici = (
            adaptive_t
            and fplan is not None
            and ici_tier_policy(mesh, ip, in_kernel)[0]
        )
        if full and use_ici:
            # In-kernel ICI exchange tier (round 6): the dispatch runs as
            # canonical launch chunks (the bounded-compile-cache contract
            # shared with pallas_packed._run_tiled), each chunk one
            # pallas_call per device with halos + interval state exchanged
            # inside the kernel; the sub-chunk tail runs the per-launch
            # probing ppermute form, mirroring the single-device loose
            # tail.
            tile_h = _strip_plan_tile(strip, t, cap)
            grid = strip[0] // tile_h
            chunks, loose = _nlaunch_chunks(full)
            a = jnp.zeros_like(board)
            act = jnp.zeros((ny * grid,), jnp.int32)
            for c in chunks:
                step_c = make_dispatch_ici(t, c)
                na, nb, sk, act_c = step_c(board, a)
                board, a = (nb, na) if c % 2 else (na, nb)
                skipped = skipped + jnp.sum(sk)
                act = act + act_c
            if loose:
                step_l = make_step(t, adaptive_ok=True)
                st = jnp.zeros((ny * grid,), jnp.int32)
                prev = a
                for _ in range(loose):
                    nb, nst = step_l(st, board, prev)
                    board, prev, st = nb, board, nst
                    skipped = skipped + jnp.sum(nst)
                    act = act + (1 - nst)
        elif adaptive_t and full and fplan is not None:
            # Frontier strip kernel (round 5): tracked intervals replace
            # the probe + bitmap; state is carried across launches in the
            # XLA loop and exchanged at strip edges with the halo rows.
            # Launch 1 starts from FULL row intervals + full column
            # interval (everything computes, measuring exact state for
            # launch 2 on), mirroring the megakernel's forced launch 0.
            tile_h = _strip_plan_tile(strip, t, cap)
            grid = strip[0] // tile_h
            step_t = make_step_frontier(t)
            lo0 = jnp.tile(jnp.arange(grid, dtype=jnp.int32) * tile_h, ny)
            hi0 = lo0 + (tile_h - 1)
            e_lo = jnp.full((ny * grid,), _EMPTY_LO, jnp.int32)
            e_hi = jnp.full((ny * grid,), -1, jnp.int32)
            cl0 = jnp.zeros((ny * grid,), jnp.int32)
            ch0 = jnp.full((ny * grid,), wp - 1, jnp.int32)
            ps0 = jnp.zeros((ny * grid,), jnp.int32)

            def launch_activity(r):
                # A launch's measured activity per stripe: either tracked
                # row interval nonempty (lo <= hi) in the state it
                # publishes for the next launch.
                return ((r[2] <= r[3]) | (r[4] <= r[5])).astype(jnp.int32)

            def fbody(_, carry):
                a, b, ps, l0, h0, l1, h1, cl, ch, sk, ac = carry
                r1 = step_t(ps, l0, h0, l1, h1, cl, ch, b, a)
                nb1, st1 = r1[0], r1[1]
                r2 = step_t(st1, *r1[2:], nb1, b)
                nb2, st2 = r2[0], r2[1]
                return (nb1, nb2, st2) + tuple(r2[2:]) + (
                    sk + jnp.sum(st1) + jnp.sum(st2),
                    ac + launch_activity(r1) + launch_activity(r2),
                )

            act = jnp.zeros((ny * grid,), jnp.int32)
            out = jax.lax.fori_loop(
                0,
                full // 2,
                fbody,
                (jnp.zeros_like(board), board, ps0, lo0, hi0,
                 e_lo, e_hi, cl0, ch0, skipped, act),
            )
            a, board, ps = out[0], out[1], out[2]
            skipped, act = out[-2], out[-1]
            if full % 2:
                r = step_t(ps, *out[3:-2], board, a)
                board = r[0]
                skipped = skipped + jnp.sum(r[1])
                act = act + launch_activity(r)
        elif adaptive_t and full:
            grid = strip[0] // _strip_plan_tile(strip, t, cap)
            step_t = make_step(t, adaptive_ok=True)
            # Bitmap zeroed per dispatch: launch 1 probes every tile, so
            # the inheritance proof's same-plan requirement holds.
            # Ping-pong (mirrors pallas_packed._run_tiled): two launches
            # per loop iteration so each strip buffer keeps its carry
            # slot — a rotating carry would cost XLA a strip copy per
            # launch.  Post-launch bitmap accumulation by design: the
            # telemetry counts tiles PROVED stable at each launch
            # boundary, not executed skip branches
            # (Backend.skip_fraction documents the trade).
            st0 = jnp.zeros((ny * grid,), jnp.int32)
            act = jnp.zeros((ny * grid,), jnp.int32)

            def body(_, carry):
                a, b, st, sk, ac = carry
                nb1, nst1 = step_t(st, b, a)
                nb2, nst2 = step_t(nst1, nb1, b)
                return (
                    nb1,
                    nb2,
                    nst2,
                    sk + jnp.sum(nst1) + jnp.sum(nst2),
                    # Probing-form activity: tiles not proved stable this
                    # launch (conservative, like the single-device form).
                    ac + (1 - nst1) + (1 - nst2),
                )

            a, board, st, skipped, act = jax.lax.fori_loop(
                0,
                full // 2,
                body,
                (jnp.zeros_like(board), board, st0, skipped, act),
            )
            if full % 2:
                board, nst = step_t(st, board, a)
                skipped = skipped + jnp.sum(nst)
                act = act + (1 - nst)
        elif full:
            step_t = make_step(t)
            board = jax.lax.fori_loop(0, full, lambda _, b: step_t(b), board)
        if rem and skip_stable:
            # Remainder split (round 4, mirrors pallas_packed._run_tiled):
            # peel the period-multiple part into a probing skip launch so
            # only a ≤5-gen tail pays full compute.
            rem6 = rem - rem % _SKIP_PERIOD
            if rem6:
                board = make_step(rem6)(board)
                rem -= rem6
        if rem:
            board = make_step(rem)(board)
        if with_stats:
            return board, skipped, act
        return board

    return run


def make_superstep_bytes(
    mesh: Mesh,
    rule: LifeRule = CONWAY,
    interpret: bool | None = None,
    skip_stable: bool = False,
    skip_tile_cap: int | None = None,
    with_stats: bool = False,
    in_kernel: bool | None = None,
):
    """``(board_u8, turns) -> board_u8`` engine-layer drop-in: pack/unpack
    inside the jit, pinned to the mesh sharding so packing stays local.
    ``with_stats`` / ``in_kernel`` mirror :func:`make_superstep`."""
    from distributed_gol_tpu.ops.packed import pack, unpack
    from distributed_gol_tpu.parallel.packed_halo import packed_sharding

    inner = make_superstep(
        mesh, rule, interpret, skip_stable, skip_tile_cap, with_stats, in_kernel
    )

    @partial(jax.jit, static_argnames=("turns",))
    def run(board: jax.Array, turns: int):
        if turns == 0:
            if with_stats:
                return board, jnp.int32(0), jnp.zeros((0,), jnp.int32)
            return board
        p = jax.lax.with_sharding_constraint(pack(board), packed_sharding(mesh))
        if with_stats:
            out, skipped, act = inner(p, turns)
            return unpack(out), skipped, act
        return unpack(inner(p, turns))

    return run


def make_superstep_virtual_2d(
    mesh_shape: tuple[int, int],
    rule: LifeRule = CONWAY,
    interpret: bool | None = None,
    skip_tile_cap: int | None = None,
    with_stats: bool = False,
):
    """Single-device EMULATION of the 2-D in-kernel exchange tier — the
    hermetic gating harness: ``(packed_board, turns) -> packed_board``
    (or ``(board, skipped, activity)``) where the FULL packed board
    advances through the SAME megakernel body as the hardware tier
    (``_kernel_frontier_mega_2d``), built in VIRTUAL mode: the grid
    grows a virtual-device axis and the launch prologue pulls each
    tile's halo blocks (rows, columns, corners) and both x-neighbours'
    interval-state slabs from the shared ping-pong board and state
    scratch, through the same slot buffers, launch-parity discipline,
    and frame-translation arithmetic the remote build ships over ICI.
    ``(1, 1)`` is the production loopback torus; ``(2, 2)``-class builds
    are how the whole 2-D protocol is identity-gated on CPU before a TPU
    rig ever lowers the remote form.

    Chunks follow the same ``_nlaunch_chunks`` decomposition as the
    sharded tier; the sub-chunk tail and remainder run the XLA packed
    engine (bit-identical, itself oracle-gated), so ``skipped`` /
    ``activity`` cover the chunk launches exactly as the sharded
    dispatch's megakernel portion does.  ``activity`` is reshaped to the
    board-global (ny·grid, nx) bitmap the sharded tier emits."""
    ny, nx = mesh_shape
    raw_cap = skip_tile_cap

    @partial(jax.jit, static_argnames=("turns",))
    def run(pb: jax.Array, turns: int):
        from distributed_gol_tpu.ops.packed import superstep as xla_superstep

        ip = _use_interpret() if interpret is None else interpret
        h, wp = pb.shape
        if h % ny or wp % nx:
            raise ValueError(f"board {pb.shape} does not divide {mesh_shape}")
        strip = (h // ny, wp // nx)
        cap, t, adaptive, plan2 = _adaptive_plan_2d(strip, turns, raw_cap, ip)
        if not adaptive or plan2 is None:
            raise ValueError(
                f"no 2-D frontier plan for {pb.shape} on mesh {mesh_shape}"
            )
        tile_h = plan2[4]
        grid = strip[0] // tile_h
        full, rem = divmod(turns, t)
        chunks, loose = _nlaunch_chunks(full)
        skipped = jnp.int32(0)
        act = jnp.zeros((ny * grid, nx), jnp.int32)
        board = pb
        a = jnp.zeros_like(board)
        for c in chunks:
            call = _build_dispatch_frontier_2d(
                strip, mesh_shape, rule, t, c, ip, cap, False
            )
            na, nb, sk, act_c = call(board, a)
            board, a = (nb, na) if c % 2 else (na, nb)
            skipped = skipped + sk[0]
            # Virtual-device-major activity -> board-global (stripe, x).
            act = act + act_c.reshape(ny, nx, grid).transpose(0, 2, 1).reshape(
                ny * grid, nx
            )
        tail = loose * t + rem
        if tail:
            board = xla_superstep(board, rule, tail)
        if with_stats:
            return board, skipped, act
        return board

    return run
