"""Multi-host (multi-process) execution — the DCN tier of the backend.

The reference scales across machines with a broker dialling worker servers
over TCP (``broker/broker.go:86-108``); its data plane re-broadcasts the
whole board to every worker every turn.  The TPU-native equivalent is a
**process-spanning `jax.sharding.Mesh`**: each host owns a contiguous row
band of the board (its local devices subdivide the band), and the SAME
`shard_map` halo-exchange programs used within a chip mesh
(``parallel/halo.py``, ``parallel/packed_halo.py``, ``parallel/pallas_halo.py``)
run unchanged — XLA routes the `ppermute` edge exchanges over ICI between
local devices and over DCN (gloo/grpc on CPU test rigs) between hosts.
Only the two band-boundary rows per neighbouring host pair cross the
network per exchange, vs the reference's full board per worker per turn.

Control plane: process 0 is the controller (events, keypresses, PGM IO);
other processes run the same SPMD data plane and block in the collectives.
This module only owns the mesh/runtime plumbing — the engine programs are
deliberately unaware they span hosts.

Hermetic proof: ``tests/test_multihost.py`` launches two OS processes with
four virtual CPU devices each and checks (1) the data plane is
bit-identical to the single-process engine over the (8, 1) global mesh,
(2) a full ``run_distributed`` controller run — broadcast snapshot
keypress, file-write discipline, mid-run detach + negotiated resume —
lands exactly on the reference's golden board, and (3) the CLI multi-host
mode does the same.  The same oracle discipline as every other tier.
"""

from __future__ import annotations

import json
import threading
import time

import numpy as np

import jax

from distributed_gol_tpu.engine.controller import DispatchTimeout
from distributed_gol_tpu.obs import spans
from distributed_gol_tpu.parallel import mesh as mesh_lib


class PeerLost(DispatchTimeout):
    """A peer rank went silent past the heartbeat bound (ISSUE 7).  The
    survivors abort with the stream sentinel; the newest periodic
    checkpoint is the resumable state (the multi-host park policy never
    fetches collectively after a failure — a dead peer cannot join).

    Raised from two seams, both bounded by the HEARTBEAT timeout rather
    than the (necessarily compile-conservative) dispatch deadline: the
    turn-boundary poll (``_stop_now``), and the dispatch watchdog's
    mid-wait ``interrupt`` hook — a survivor already blocked in a
    collective its dead peer never joins must not sit out the full
    deadline.  At the controller seam it is re-classed as a
    :class:`~distributed_gol_tpu.engine.controller.DispatchTimeout`
    subtype (terminal, never retried): a collective whose peer is dead
    can never complete, so retrying is the one guaranteed-futile
    response."""


#: A peer is declared dead after this many missed heartbeat intervals —
#: one lost UDP datagram must not condemn a rank, three in a row is a
#: dead process on any sane network.
HEARTBEAT_MISS_FACTOR = 3.0


class PeerHeartbeat:
    """Lightweight peer liveness beside the collective stream (ISSUE 7).

    Every rank UDP-pings every other rank on ``interval`` seconds from a
    daemon thread and tracks when it last heard each peer; a rank silent
    for ``HEARTBEAT_MISS_FACTOR x interval`` is reported by
    :meth:`dead_peers`.  Deliberately OUTSIDE the collective transport:
    the existing keys/superstep broadcasts only detect a dead rank once a
    survivor blocks in a collective the corpse never joins (bounded by
    the dispatch watchdog), and the coordination service's own heartbeat
    hard-kills after minutes with no sentinel.  This detector works at
    turn boundaries even while no collective is in flight, names the
    dead rank, and costs one tiny datagram per peer per interval.

    ``start()`` exchanges addresses over ONE allgather (call on every
    rank together — arm uniformly, like ``stop``); tests inject
    ``peer_addrs`` directly and need no distributed runtime.  The
    advertised address is this host's name-resolved IP (loopback rigs:
    127.0.0.1); single-process runs have no peers and never report one
    dead."""

    def __init__(
        self,
        interval: float,
        process_id: int | None = None,
        num_processes: int | None = None,
    ):
        if interval <= 0:
            raise ValueError("heartbeat interval must be positive")
        self.interval = interval
        self.timeout = HEARTBEAT_MISS_FACTOR * interval
        self._pid = process_id if process_id is not None else jax.process_index()
        self._n = num_processes if num_processes is not None else jax.process_count()
        self._sock = None
        self._addr: tuple[str, int] | None = None
        self._peers: dict[int, tuple[str, int]] = {}
        self._last: dict[int, float] = {}
        self._stop_evt = threading.Event()
        self._thread: threading.Thread | None = None

    def _bind(self) -> tuple[str, int]:
        import socket

        if self._sock is not None:  # idempotent: tests bind early for the port
            return self._addr
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind(("0.0.0.0", 0))
        # Short poll so one loop both sends on cadence and drains receipts.
        self._sock.settimeout(self.interval / 4)
        port = self._sock.getsockname()[1]
        self._addr = (self._advertised_host(), port)
        return self._addr

    @staticmethod
    def _advertised_host() -> str:
        """The IP peers should ping.  A UDP connect() toward a routable
        address resolves the OUTBOUND interface without sending a packet
        — ``gethostbyname(gethostname())`` is wrong on Debian-style
        hosts, where /etc/hosts maps the hostname to 127.0.1.1 and every
        rank would advertise an unreachable loopback, spuriously
        declaring all peers dead on a real multi-machine rig.  Loopback
        fallbacks keep single-machine rigs working."""
        import socket

        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect(("8.8.8.8", 80))  # routing lookup only, no I/O
                return s.getsockname()[0]
            finally:
                s.close()
        except OSError:
            pass
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"

    def _exchange(self, host: str, port: int) -> dict[int, tuple[str, int]]:
        """One collective ``host:port`` allgather (64-byte padded rows,
        the ``gather_metrics_snapshots`` transport pattern)."""
        from jax.experimental import multihost_utils

        payload = f"{host}:{port}".encode()
        if len(payload) > 64:
            raise ValueError(f"heartbeat address too long: {payload!r}")
        buf = np.zeros(64, dtype=np.uint8)
        buf[: len(payload)] = np.frombuffer(payload, dtype=np.uint8)
        rows = np.atleast_2d(np.asarray(multihost_utils.process_allgather(buf)))
        out = {}
        for r in range(rows.shape[0]):
            text = bytes(rows[r]).rstrip(b"\x00").decode()
            h, _, p = text.rpartition(":")
            out[r] = (h, int(p))
        return out

    def start(self, peer_addrs: dict[int, tuple[str, int]] | None = None):
        """Bind, exchange addresses (collectively, unless injected), and
        start the ping/listen daemon.  Returns self."""
        host, port = self._bind()
        if peer_addrs is None:
            peer_addrs = self._exchange(host, port)
        self._peers = {r: a for r, a in peer_addrs.items() if r != self._pid}
        now = time.monotonic()
        self._last = {r: now for r in self._peers}  # grace: start = heard
        self._thread = threading.Thread(
            target=self._loop, name="gol-peer-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self):
        import socket

        msg = str(self._pid).encode()
        next_send = 0.0
        while not self._stop_evt.is_set():
            now = time.monotonic()
            if now >= next_send:
                for addr in self._peers.values():
                    try:
                        self._sock.sendto(msg, addr)
                    except OSError:
                        pass  # unreachable peer: its silence is the signal
                next_send = now + self.interval
            try:
                data, _ = self._sock.recvfrom(64)
                rank = int(data)
                if rank in self._last:
                    self._last[rank] = time.monotonic()
            except (socket.timeout, ValueError, OSError):
                continue

    def dead_peers(self) -> list[int]:
        """Ranks silent past the bound (empty = everyone alive)."""
        now = time.monotonic()
        return sorted(
            r for r, t in self._last.items() if now - t > self.timeout
        )

    def stop(self) -> None:
        self._stop_evt.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval)


def initialize(
    coordinator: str, num_processes: int, process_id: int
) -> None:
    """Join the process-spanning JAX runtime.

    On CPU rigs the cross-host collective transport is gloo; on TPU pods
    the TPU runtime owns transport and this reduces to
    ``jax.distributed.initialize()`` with cluster-provided defaults.
    """
    # Decide the CPU transport WITHOUT touching the backend:
    # jax.default_backend() would initialise XLA, which must not happen
    # before jax.distributed.initialize().
    import os

    platforms = getattr(jax.config, "jax_platforms", None) or ""
    platform = (platforms or os.environ.get("JAX_PLATFORMS", "")).split(",")[0]
    if platform == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # older jax spells it differently; non-fatal
            pass
    jax.distributed.initialize(
        coordinator, num_processes=num_processes, process_id=process_id
    )


def global_row_mesh() -> jax.sharding.Mesh:
    """A (n_global_devices, 1) mesh spanning every process.

    ``jax.devices()`` orders devices process-contiguously, so each host
    owns a contiguous row band — host boundaries cross DCN exactly once
    per halo exchange, interior boundaries stay on-host.
    """
    # Explicit device list: a cross-rank mesh must NOT be filtered by
    # the per-process blacklist (make_mesh's devices=None default) —
    # ranks would desynchronize, and the global shape is fixed anyway.
    return mesh_lib.make_mesh((len(jax.devices()), 1), jax.devices())


def put_global(board: np.ndarray, sharding) -> jax.Array:
    """Place a host-replicated board onto a process-spanning sharding.

    Every process passes the same full board (read from the shared
    filesystem, the standard multi-host pattern); each extracts and
    uploads only its addressable shards.
    """
    return jax.make_array_from_callback(
        board.shape, sharding, lambda idx: board[idx]
    )


def fetch_global(arr: jax.Array) -> np.ndarray:
    """Gather a process-spanning array to a full host copy on EVERY
    process (the final-board / snapshot path)."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def gather_metrics_snapshots(snapshot: dict) -> list[dict]:
    """Allgather every process's metrics snapshot (ISSUE 4): each process
    passes its own ``gol-metrics-v1`` dict; every process gets the full
    per-process list back, in process order.  Rides the existing
    collective transport — JSON bytes padded to the max length (two small
    collectives per RUN, not per dispatch, so the cost is noise).  All
    processes must call together, like every other collective."""
    from jax.experimental import multihost_utils

    payload = np.frombuffer(json.dumps(snapshot).encode(), dtype=np.uint8)
    sizes = np.atleast_1d(
        np.asarray(multihost_utils.process_allgather(np.int32(payload.size)))
    )
    width = int(sizes.max())
    padded = np.zeros(width, dtype=np.uint8)
    padded[: payload.size] = payload
    rows = np.atleast_2d(
        np.asarray(multihost_utils.process_allgather(padded))
    )
    return [
        json.loads(bytes(rows[i, : int(sizes[i])]).decode())
        for i in range(rows.shape[0])
    ]


# -- full controller runs across processes ------------------------------------
#
# The data plane above is enough for library users; ``run_distributed`` runs
# the ENTIRE reference controller contract (events, s/p/q/k keys, snapshots,
# checkpoints, final PGM) across processes.  The invariant that makes it
# work: every process executes the identical dispatch schedule, so every
# collective (superstep, count psum, snapshot allgather) lines up.  That
# requires (a) an explicit fixed superstep — the adaptive wall-clock sizing
# would diverge between hosts — and (b) identical control decisions, which
# ``_BroadcastKeys`` provides by broadcasting process 0's keypress stream to
# everyone at each poll (one tiny collective per poll; polls happen at
# superstep boundaries).  Process 0 is the controller (reference analog: the
# one machine running ``main.go``); followers feed a throwaway event queue
# and skip file writes.


class _BroadcastKeys:
    """Queue facade making every process see process 0's keypresses.

    Each ``get``/``empty`` call is one scalar broadcast, so ALL processes
    must call them in the same order — guaranteed because the controller's
    control flow is a pure function of what these calls return.

    ``watchdog`` (a ``controller._Watchdog``) bounds each broadcast: after
    a one-sided failure the surviving processes' next keys poll is a
    collective the dead peer never joins, and without the bound they hang
    there — outside the dispatch paths' own watchdog — until the
    coordination service's heartbeat timeout hard-kills them (observed as
    SIGABRT with no sentinel)."""

    def __init__(self, inner, watchdog=None):
        import queue as _queue

        self._inner = inner  # the real queue on process 0, else None
        self._queue_mod = _queue
        self._watchdog = watchdog

    def _bcast(self, value: int) -> int:
        from jax.experimental import multihost_utils

        def do():
            return int(multihost_utils.broadcast_one_to_all(np.int32(value)))

        # Annotated like every other blocking collective (ISSUE 4): a
        # trace shows WHERE a survivor sat when the peer died.
        with spans.span("gol.broadcast.keys"):
            return self._watchdog.call(do) if self._watchdog is not None else do()

    def get(self, block=False, timeout=None):
        code = 0
        if self._inner is not None:
            try:
                code = ord(self._inner.get(block=block, timeout=timeout))
            except self._queue_mod.Empty:
                code = 0
        code = self._bcast(code)
        if code == 0:
            raise self._queue_mod.Empty
        return chr(code)

    def empty(self) -> bool:
        mine = 1 if self._inner is None or self._inner.empty() else 0
        return bool(self._bcast(mine))


def make_backend(params):
    """A Backend whose board spans every process's devices (row bands) and
    whose host transfers are collective allgathers."""
    from distributed_gol_tpu.engine.backend import Backend

    ny = len(jax.devices())
    if params.mesh_shape not in ((1, 1), (ny, 1)):
        raise ValueError(
            f"multi-host runs shard rows over all {ny} global devices; "
            f"mesh_shape must be ({ny}, 1) (or left at (1, 1) to default). "
            "2-D (ny, nx) meshes are a SINGLE-host capability: the 2-D "
            "tier's halo exchange (in-kernel remote DMA or ppermute "
            "x-halos) rides ICI, while this tier's host boundary crosses "
            "DCN, where the row-banded layout keeps each host's halo one "
            "contiguous ppermute per direction — run 2-D meshes "
            "in-process (Params.mesh_shape) on one host's devices"
        )
    from dataclasses import replace

    params = replace(params, mesh_shape=(ny, 1))

    class MultihostBackend(Backend):
        def put(self, board):
            board = np.ascontiguousarray(board, dtype=np.uint8)
            return put_global(board, self._sharding)

        def fetch(self, board):
            return fetch_global(board)

    return MultihostBackend(params, devices=jax.devices())


def run_distributed(params, events=None, key_presses=None, session=None, stop=None):
    """The full controller contract over a process-spanning mesh.

    Call on EVERY process after :func:`initialize`.  Process 0 drives:
    its ``events`` queue receives the stream, its ``key_presses`` queue is
    broadcast to all processes, its filesystem gets the PGMs, and its
    ``session`` holds checkpoints.  Followers pass None everywhere: they
    get throwaway in-memory sessions (a 'q' detach must persist exactly
    one checkpoint, from process 0 — ``Session`` has no cross-process
    write locking), and the resume decision is negotiated by process 0
    and broadcast, because ``check_states`` is consume-once: letting every
    process ask would hand the checkpoint to whichever asked first and
    start the rest from turn 0, diverging the SPMD schedule.
    ``params.superstep`` may be 0 (adaptive): the sizing decision is
    wall-clock-driven, so process 0 decides and broadcasts the next size
    (one scalar broadcast per resolved dispatch — the same cadence as the
    keypress broadcast) and every process runs the identical dispatch
    schedule.  The auto ``skip_stable`` long-run policy rides on this: it
    resolves from Params alone, identically everywhere.

    ``stop`` (a ``supervisor.GracefulStop``, ISSUE 5): pass one on EVERY
    process (or none) to arm preemption handling — each process installs
    its own SIGTERM handler against its own latch, and the controller's
    turn-boundary stop poll becomes a tiny allgather
    (``MultihostController._stop_now``), so ONE signalled rank drains the
    whole collective together: every process forces the emergency
    checkpoint fetch in lockstep (process 0 persists it) and exits
    paused-and-resumable, instead of the signalled rank vanishing
    mid-allgather and wedging the survivors.  Arming must be uniform —
    the poll is a collective, so stop-armed and stop-less processes would
    diverge the schedule.

    ``params.peer_heartbeat_seconds > 0`` (ISSUE 7) additionally arms the
    :class:`PeerHeartbeat` membership monitor on every rank (uniformly —
    the setup address exchange is a collective): a rank that dies HARD
    (SIGKILL, machine loss) is detected locally by every survivor within
    ``HEARTBEAT_MISS_FACTOR`` intervals, and the next turn-boundary poll
    raises :class:`PeerLost` — sentinel-terminated abort, flight record
    ``peer_lost``, `multihost.peers_lost` counter, resumable from the
    newest periodic checkpoint — complementing the dispatch watchdog
    (which bounds waits INSIDE a collective) and pre-empting the
    coordination service's multi-minute no-sentinel hard-kill.
    """
    try:
        return _validate_and_run(params, events, key_presses, session, stop)
    except BaseException:
        # The controller guarantees the stream sentinel for failures inside
        # its run; failures BEFORE it starts — params validation, backend
        # construction, resume negotiation — must not leave a listener
        # blocked forever either.
        if events is not None:
            events.put(None)
        raise


def _validate_and_run(params, events, key_presses, session, stop):
    if not params.no_vis or params.wants_flips() or params.wants_frames():
        raise ValueError("multi-host runs are headless (no_vis=True)")
    if params.checkpoint_every_seconds:
        raise ValueError(
            "multi-host runs schedule periodic checkpoints by turn cadence "
            "only (checkpoint_every_turns): the wall-clock cadence would "
            "diverge the SPMD dispatch schedule between processes (the "
            "checkpoint fetch is a collective)"
        )
    if params.restart_limit:
        raise ValueError(
            "multi-host runs do not support the rollback-recovery "
            "supervisor yet (restart_limit must be 0): a restart tears "
            "down and rebuilds the backend, which on a process-spanning "
            "mesh is a collective act every process would have to "
            "coordinate through a failure the runtime may only have "
            "surfaced on one rank.  Refusing loudly beats silently "
            "running without the recovery the flag promised; preemption "
            "handling (stop=) and periodic checkpoints cover the "
            "resumability story across hosts."
        )
    return _run_distributed(params, events, key_presses, session, stop)


def _run_distributed(params, events, key_presses, session, stop=None):
    from jax.experimental import multihost_utils

    from distributed_gol_tpu.engine.controller import Controller, _Watchdog
    from distributed_gol_tpu.engine.session import Session, default_session

    main = jax.process_index() == 0
    # Peer heartbeat (ISSUE 7): armed uniformly via Params, so the setup
    # address allgather lines up on every rank like any other collective.
    heartbeat = None
    if params.peer_heartbeat_seconds > 0 and jax.process_count() > 1:
        heartbeat = PeerHeartbeat(params.peer_heartbeat_seconds).start()
    try:
        backend = make_backend(params)
        session = (session if session is not None else default_session()) if main else Session()

        # Resume negotiation: process 0 consumes the checkpoint (if any)
        # and broadcasts the outcome, so every process starts from the
        # same world and turn.  (With turns == 0 the reference skips
        # negotiation.)
        negotiated = None
        if params.turns > 0:
            ckpt = (
                session.check_states(
                    params.image_width, params.image_height, params.rule.notation
                )
                if main
                else None
            )
            found = int(
                multihost_utils.broadcast_one_to_all(
                    np.int32(0 if ckpt is None else 1)
                )
            )
            if found:
                shape = (params.image_height, params.image_width)
                world = np.asarray(
                    multihost_utils.broadcast_one_to_all(
                        ckpt.world if main else np.zeros(shape, np.uint8)
                    )
                )
                turn = int(
                    multihost_utils.broadcast_one_to_all(
                        np.int32(ckpt.turn if main else 0)
                    )
                )
                negotiated = (world, turn)
    except BaseException:
        # A failed backend build or negotiation must not leak the
        # heartbeat daemon + socket (a retrying caller would accumulate
        # one per attempt, with peers still seeing this rank alive).
        if heartbeat is not None:
            heartbeat.stop()
        raise

    class _DevNull:
        """Follower event sink: the stream only exists on process 0, and a
        real queue would grow unboundedly over a long run."""

        def put(self, _):
            pass

    ev = events if (main and events is not None) else _DevNull()
    keys = _BroadcastKeys(
        key_presses if main else None,
        _Watchdog(params.dispatch_deadline_seconds),
    )

    class MultihostController(Controller):
        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self._peer_loss_recorded = False
            if heartbeat is not None:
                # The watchdog's mid-wait hook (ISSUE 7): a survivor
                # blocked in a collective its dead peer never joins
                # aborts within the HEARTBEAT bound, naming the rank,
                # instead of sitting out the full dispatch deadline
                # (which must stay conservative enough for compiles).
                self._watchdog.interrupt = self._peer_lost_error
                keys._watchdog.interrupt = self._peer_lost_error

        def _write_pgm(self, path, board_np):
            if main:
                super()._write_pgm(path, board_np)

        def _park_checkpoint(self, board, turn, guard=None):
            # The base-class checkpoint fetch is a collective allgather; a
            # dispatch failure may be one-sided (one process's runtime
            # dies), and entering a collective alone hangs this process
            # instead of aborting with the sentinel.  Skip checkpointing:
            # the terminal DispatchError still reports checkpointed=False
            # and the stream still ends.  (PERIODIC checkpoints —
            # Controller._guard_boundary — do fetch collectively: their
            # turn cadence is deterministic in the dispatch schedule, so
            # every process enters that allgather together; they are the
            # resumable state a one-sided abort leaves behind.)
            #
            # The dispatch watchdog completes this divergence-safety
            # policy: a one-sided failure leaves the SURVIVING processes
            # blocked forcing a count whose collective the dead peer never
            # joined.  With Params.dispatch_deadline_seconds set, each
            # process's own watchdog raises DispatchTimeout (terminal:
            # never retried), the stream ends with the sentinel, and
            # run_distributed re-raises — every process aborts instead of
            # hanging alone in the collective.
            return False

        def _save_checkpoint(self, world, turn):
            # Followers' sessions are throwaway and never consulted for
            # resume; storing the allgathered board would pin a full-size
            # host copy per follower per cadence.  The collective fetch
            # itself already ran (SPMD lockstep) — only the session write
            # is main-only, like _write_pgm above.
            if main:
                super()._save_checkpoint(world, turn)

        def _initial_world(self):
            if negotiated is not None:
                # The negotiation CONSUMED process 0's pair — same
                # re-park-on-early-preempt semantics as the base class.
                self._resumed = True
                return negotiated
            return self._load_input(), 0

        def _force_probe(self, flag):
            # The base class swallows a probe-force failure (advisory
            # single-host semantics).  Here the cycle flag gates which
            # collectives every process issues next: its *value* is
            # all-reduced and identical everywhere, but a one-sided
            # failure while forcing it would make this process silently
            # read False while peers read True — divergent collective
            # schedules, a hang.  Abort with the stream sentinel instead
            # (same policy as _park_checkpoint above); the watchdog bounds
            # the force itself, like every other blocking collective wait.
            return self._watchdog.call(lambda: bool(flag))

        def _peer_lost_error(self):
            """The heartbeat verdict, as an exception or None: purely
            LOCAL — the dead rank cannot join a collective, so the
            detection must not be one.  Every survivor's own monitor
            trips within the same bound, so each aborts independently
            with the sentinel and the newest periodic checkpoint as the
            resumable state (the supervisor/resume path adopts it);
            detection is bounded by the heartbeat timeout instead of
            the coordination service's multi-minute hard-kill.  Records
            the loss (metrics + flight) exactly once."""
            if heartbeat is None:
                return None
            dead = heartbeat.dead_peers()
            if not dead:
                return None
            if not self._peer_loss_recorded:
                self._peer_loss_recorded = True
                self.metrics.counter("multihost.peers_lost").inc(len(dead))
                self.flight.record(
                    "peer_lost",
                    ranks=dead,
                    timeout_s=round(heartbeat.timeout, 3),
                )
            return PeerLost(
                f"peer rank(s) {dead} silent past the heartbeat "
                f"bound ({heartbeat.timeout:.1f}s); aborting — "
                "resume from the newest periodic checkpoint"
            )

        def _stop_now(self):
            # Peer-liveness check first (ISSUE 7); the same check also
            # rides the dispatch watchdog's mid-wait interrupt (wired in
            # __init__), because a survivor is usually BLOCKED in the
            # dead peer's collective when the loss bites — the boundary
            # poll alone would leave detection to the full dispatch
            # deadline.
            err = self._peer_lost_error()
            if err is not None:
                raise err
            # The preemption poll is COLLECTIVE (ISSUE 5): each process
            # contributes its own latch and everyone acts on the max, so
            # one signalled rank stops the whole mesh together — the
            # emergency-checkpoint fetch that follows is a collective and
            # must be entered by every process.  Called at schedule-
            # deterministic turn boundaries only (same cadence as the
            # keys broadcast), watchdog-bounded like every collective.
            # stop=None on every process keeps this a no-op (arming must
            # be uniform across processes — see run_distributed).
            if self._stop is None:
                return False
            if self._stop_seen:
                # Already observed collectively: every rank latched at the
                # same allgather, so the short-circuit is identical
                # everywhere and issues no further collective.
                return True
            mine = np.int32(1 if self._stop.requested else 0)
            with spans.span("gol.broadcast.stop"):
                flags = self._watchdog.call(
                    lambda: np.atleast_1d(
                        np.asarray(multihost_utils.process_allgather(mine))
                    )
                )
            if flags.max():
                self._stop_seen = True
            return self._stop_seen

        def _emergency_save_due(self, turn):
            # Process 0 owns the durable session, so ITS last-successful-
            # save state decides — broadcast, watchdog-bounded, reached by
            # every rank together (the stop decision above was collective).
            # Deciding locally would let a one-sided save failure (ENOSPC
            # on process 0, while followers' no-op saves "succeed") split
            # the ranks around _checkpoint_now's collective fetch.
            mine = super()._emergency_save_due(turn)
            with spans.span("gol.broadcast.emergency_due", turn=turn):
                return bool(
                    self._watchdog.call(
                        lambda: int(
                            multihost_utils.broadcast_one_to_all(
                                np.int32(1 if mine else 0)
                            )
                        )
                    )
                )

        def _gather_snapshots(self, snap):
            # The multihost half of the MetricsReport (ISSUE 4): every
            # process contributes its own snapshot through the broadcast
            # transport; the controller aggregates (counters sum, gauges
            # max).  Reached at the same schedule point everywhere
            # (_finalize emits the report before the final fetch), and
            # watchdog-bounded like every other collective.
            return self._watchdog.call(
                lambda: gather_metrics_snapshots(snap)
            )

        def _next_superstep(self, k, dt, superstep, warm_sizes, cap):
            # Deterministic adaptive sizing (round-3 verdict, missing-3):
            # dt is local wall-clock — the one input that differs between
            # processes — so process 0 makes the decision and broadcasts
            # it.  Every process reaches this call at the same point of
            # the dispatch schedule (the call sites are schedule-
            # deterministic), so the broadcast lines up like every other
            # collective.  Process 0's warm_sizes gating rides inside its
            # base-class call; followers' warm_sizes stay empty, which is
            # fine — they never decide.
            if main:
                superstep = super()._next_superstep(
                    k, dt, superstep, warm_sizes, cap
                )
            # Watchdog-bounded like the keys broadcast: this collective
            # runs once per resolved dispatch and must not become the
            # place a survivor hangs after a one-sided failure.
            with spans.span("gol.broadcast.superstep", k=k):
                return self._watchdog.call(
                    lambda: int(
                        multihost_utils.broadcast_one_to_all(np.int32(superstep))
                    )
                )

    try:
        MultihostController(params, ev, keys, session, backend, stop=stop).run()
    finally:
        if heartbeat is not None:
            heartbeat.stop()
