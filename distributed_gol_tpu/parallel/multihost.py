"""Multi-host (multi-process) execution — the DCN tier of the backend.

The reference scales across machines with a broker dialling worker servers
over TCP (``broker/broker.go:86-108``); its data plane re-broadcasts the
whole board to every worker every turn.  The TPU-native equivalent is a
**process-spanning `jax.sharding.Mesh`**: each host owns a contiguous row
band of the board (its local devices subdivide the band), and the SAME
`shard_map` halo-exchange programs used within a chip mesh
(``parallel/halo.py``, ``parallel/packed_halo.py``, ``parallel/pallas_halo.py``)
run unchanged — XLA routes the `ppermute` edge exchanges over ICI between
local devices and over DCN (gloo/grpc on CPU test rigs) between hosts.
Only the two band-boundary rows per neighbouring host pair cross the
network per exchange, vs the reference's full board per worker per turn.

Control plane: process 0 is the controller (events, keypresses, PGM IO);
other processes run the same SPMD data plane and block in the collectives.
This module only owns the mesh/runtime plumbing — the engine programs are
deliberately unaware they span hosts.

Hermetic proof: ``tests/test_multihost.py`` launches two OS processes with
four virtual CPU devices each, builds the (8, 1) global mesh, and checks
the sharded run is bit-identical to the single-process engine — the same
oracle discipline as every other tier.
"""

from __future__ import annotations

import numpy as np

import jax

from distributed_gol_tpu.parallel import mesh as mesh_lib


def initialize(
    coordinator: str, num_processes: int, process_id: int
) -> None:
    """Join the process-spanning JAX runtime.

    On CPU rigs the cross-host collective transport is gloo; on TPU pods
    the TPU runtime owns transport and this reduces to
    ``jax.distributed.initialize()`` with cluster-provided defaults.
    """
    # Decide the CPU transport WITHOUT touching the backend:
    # jax.default_backend() would initialise XLA, which must not happen
    # before jax.distributed.initialize().
    import os

    platforms = getattr(jax.config, "jax_platforms", None) or ""
    platform = (platforms or os.environ.get("JAX_PLATFORMS", "")).split(",")[0]
    if platform == "cpu":
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # older jax spells it differently; non-fatal
            pass
    jax.distributed.initialize(
        coordinator, num_processes=num_processes, process_id=process_id
    )


def global_row_mesh() -> jax.sharding.Mesh:
    """A (n_global_devices, 1) mesh spanning every process.

    ``jax.devices()`` orders devices process-contiguously, so each host
    owns a contiguous row band — host boundaries cross DCN exactly once
    per halo exchange, interior boundaries stay on-host.
    """
    return mesh_lib.make_mesh((len(jax.devices()), 1))


def put_global(board: np.ndarray, sharding) -> jax.Array:
    """Place a host-replicated board onto a process-spanning sharding.

    Every process passes the same full board (read from the shared
    filesystem, the standard multi-host pattern); each extracts and
    uploads only its addressable shards.
    """
    return jax.make_array_from_callback(
        board.shape, sharding, lambda idx: board[idx]
    )


def fetch_global(arr: jax.Array) -> np.ndarray:
    """Gather a process-spanning array to a full host copy on EVERY
    process (the final-board / snapshot path)."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))
