"""Run telemetry (ISSUE 4): the observability layer every serving stack has.

Three parts, wired through every hot and failure path of the engine:

- :mod:`~distributed_gol_tpu.obs.metrics` — process-wide named counters,
  gauges and fixed-bucket histograms with near-zero clean-path cost
  (plain attribute bumps, no locks on the dispatch path;
  snapshot-on-read), plus the snapshot schema lint that guards every
  artifact embedding.
- :mod:`~distributed_gol_tpu.obs.spans` — ``jax.profiler`` trace
  annotations naming WHICH dispatch each kernel launch belongs to, so a
  ``--trace`` capture is attributable instead of anonymous kernel soup.
- :mod:`~distributed_gol_tpu.obs.flight` — a bounded in-memory ring of
  structured records that every terminal path dumps as
  ``flight-<ts>.json`` before the run dies (the postmortem artifact).

Plus the continuous half (ISSUE 12), built on the registry:

- :mod:`~distributed_gol_tpu.obs.timeseries` — the ``TelemetrySampler``
  daemon: a bounded ring of timestamped registry snapshots with derived
  windowed rates and histogram-delta percentiles (the time axis the
  pull-on-demand artifacts lack).
- :mod:`~distributed_gol_tpu.obs.openmetrics` — render any
  ``gol-metrics-v1`` snapshot as OpenMetrics exposition text (and parse
  it back; the ``/metrics`` wire format).
- :mod:`~distributed_gol_tpu.obs.slo` — per-tenant SLO objectives,
  multi-window burn-rate alerts, and error budgets evaluated over the
  sampler ring.

And the request-scoped half (ISSUE 15):

- :mod:`~distributed_gol_tpu.obs.tracing` — the always-on, bounded,
  lock-cheap host span store: W3C ``traceparent`` in at the gateway,
  ``X-Gol-Trace-Id`` out on every traced response, spans from the
  admission ladder to the kernel launch (the ``obs.spans`` call sites
  feed both sinks), head-sampled with tail retention for error traces,
  exported via ``/traces`` and ``tools/trace_export.py`` (Chrome Trace
  Event JSON) — plus the per-request SLI histograms (queue wait,
  time-to-first-dispatch/-frame) the SLO machinery targets.

Everything degrades to a no-op: ``Params.metrics=False`` swaps in null
instruments, ``Params.flight_recorder_depth=0`` disables the ring, and
spans become ``nullcontext`` on profiler-less builds — exactly like
``utils.profiling.trace``.
"""
