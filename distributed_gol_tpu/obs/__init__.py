"""Run telemetry (ISSUE 4): the observability layer every serving stack has.

Three parts, wired through every hot and failure path of the engine:

- :mod:`~distributed_gol_tpu.obs.metrics` — process-wide named counters,
  gauges and fixed-bucket histograms with near-zero clean-path cost
  (plain attribute bumps, no locks on the dispatch path;
  snapshot-on-read), plus the snapshot schema lint that guards every
  artifact embedding.
- :mod:`~distributed_gol_tpu.obs.spans` — ``jax.profiler`` trace
  annotations naming WHICH dispatch each kernel launch belongs to, so a
  ``--trace`` capture is attributable instead of anonymous kernel soup.
- :mod:`~distributed_gol_tpu.obs.flight` — a bounded in-memory ring of
  structured records that every terminal path dumps as
  ``flight-<ts>.json`` before the run dies (the postmortem artifact).

Everything degrades to a no-op: ``Params.metrics=False`` swaps in null
instruments, ``Params.flight_recorder_depth=0`` disables the ring, and
spans become ``nullcontext`` on profiler-less builds — exactly like
``utils.profiling.trace``.
"""
