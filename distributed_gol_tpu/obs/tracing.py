"""Request-scoped tracing (ISSUE 15): trace IDs from the wire to the
kernel launch.

The telemetry plane (ISSUE 12) answers "how is the pod doing" with
aggregate p99s and burn rates; this module answers "why was THIS request
slow": an **always-on, bounded, lock-cheap host-side span store** that
follows one request across every layer it crosses — gateway HTTP
handling, the admission ladder (queue-wait as a span), ``ServePlane``
session start, the controller's dispatch issue/resolve (the existing
``obs.spans`` call sites feed BOTH the ``jax.profiler`` annotation and
this store), cohort-batched launches, supervisor restarts, checkpoint
saves, and FramePlane publish → WebSocket spectator send.

Design constraints:

- **Lock-cheap.**  The hot-path question "is a trace active here?" is
  one ``contextvars.ContextVar`` read; with no active trace every helper
  is a no-op returning a shared nullcontext.  Recording a span is a
  monotonic-ns read plus a bounded ``list.append`` — no locks on the
  dispatch path (span interleavings across threads are tolerated; each
  record is atomic under the GIL).
- **Bounded.**  A trace retains at most ``max_spans`` spans (the FIRST
  N — a request timeline's interesting part is its head: admission,
  first dispatch, first frame; later spans are counted in
  ``dropped_spans``), plus a small always-retained event ring for the
  records that must never be evicted (watchdog fires, restarts).  The
  store holds a bounded ring of finished traces and a bounded map of
  active ones.
- **Head-sampled, tail-retained.**  The retention decision is made at
  trace START (``sample_rate``, deterministic in the trace id, so tests
  and multi-process pods agree) — but ANY trace that was ``flag()``-ed
  (terminal failure, watchdog fire, supervisor restart) is retained at
  end regardless: error traces are never lost.  Unretained traces cost
  their bounded in-flight buffer and nothing else.

**Propagation** is W3C Trace Context: the gateway accepts an inbound
``traceparent`` header (an inbound sampled flag forces retention — the
caller asked), answers every traced response with ``X-Gol-Trace-Id`` +
``traceparent``, and stamps the id into flight records, the terminal
``MetricsReport``, and gateway receipts.  In-process, the active trace
rides a context variable (``activate``/``current``) so deep layers need
no plumbing — ``obs.spans.span`` call sites attach automatically.

**Export**: ``/traces`` on the telemetry server AND the gateway serves
:func:`http_traces` (recent retained traces, or one by id);
``tools/trace_export.py`` renders any trace to Chrome Trace Event JSON
loadable in Perfetto.  Schema ``gol-trace-v1``::

    {"schema": "gol-trace-v1", "trace_id": <32-hex>, "name": "gol.request",
     "tenant": ..., "sampled": bool, "flagged": <reason or None>,
     "status": "ok|completed|parked|failed|...", "error": ...,
     "t0_unix": <seconds>, "duration_ns": ...,
     "spans": [{"span_id", "parent_id", "name", "t0_ns", "dur_ns",
                "labels": {...}}, ...],            # t0_ns relative to trace start
     "events": [...],                              # always-retained instants
     "marks": {"first_dispatch": <ns>, ...},       # SLI first-occurrence marks
     "dropped_spans": 0}
"""

from __future__ import annotations

import contextlib
import itertools
import os
import re
import threading
import time
from collections import deque
from contextvars import ContextVar
from typing import Mapping, Sequence

from distributed_gol_tpu.obs import metrics as metrics_lib

SCHEMA = "gol-trace-v1"

#: The one nullcontext every inactive-path helper returns.
NULL_CM = contextlib.nullcontext()

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def parse_traceparent(header: str | None):
    """``(trace_id, parent_span_id, sampled)`` from a W3C ``traceparent``
    header, or None when absent/malformed (a bad header must never fail
    the request — the trace just starts fresh)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if not m or m.group(2) == "0" * 32 or m.group(3) == "0" * 16:
        return None
    return m.group(2), m.group(3), bool(int(m.group(4), 16) & 1)


def format_traceparent(trace_id: str, span_id: str, sampled: bool = True) -> str:
    return f"00-{trace_id}-{span_id}-{'01' if sampled else '00'}"


def new_trace_id() -> str:
    return os.urandom(16).hex()


def clock_ns() -> int:
    """The store's clock (monotonic ns) — callers building explicit
    spans (``record_span``) sample it so their timestamps share the
    traces' timeline."""
    return time.monotonic_ns()


def head_sampled(trace_id: str, rate: float) -> bool:
    """Deterministic head-sampling decision: a pure function of the
    trace id, so every process of a pod (and every test) agrees."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    return int(trace_id[:8], 16) / 0xFFFFFFFF < rate


class _Ctx:
    __slots__ = ("trace", "span_id")

    def __init__(self, trace: "Trace", span_id: str):
        self.trace = trace
        self.span_id = span_id


_ACTIVE: ContextVar[_Ctx | None] = ContextVar("gol_trace_ctx", default=None)


class _SpanCtx:
    """One in-flight span: parent resolved from the context at entry,
    children nest under it while it is open."""

    __slots__ = ("_trace", "_name", "_labels", "_t0", "_id", "_parent", "_token")

    def __init__(self, trace: "Trace", name: str, labels: dict):
        self._trace = trace
        self._name = name
        self._labels = labels

    def __enter__(self):
        trace = self._trace
        ctx = _ACTIVE.get()
        self._parent = (
            ctx.span_id if ctx is not None and ctx.trace is trace else trace.root_id
        )
        self._id = trace._next_id()
        self._t0 = time.monotonic_ns()
        self._token = _ACTIVE.set(_Ctx(trace, self._id))
        return self

    def __exit__(self, exc_type, exc, tb):
        _ACTIVE.reset(self._token)
        labels = self._labels
        if exc_type is not None:
            labels = dict(labels, error=exc_type.__name__)
        self._trace._append(
            self._name, self._id, self._parent, self._t0,
            time.monotonic_ns(), labels,
        )
        return False


class Trace:
    """One request's causal timeline.  Construct via
    :meth:`Tracer.start_trace`; record with :meth:`span` (context
    manager, nests via the active context), :meth:`record_span`
    (explicit timestamps — the queue-wait / cohort-launch spelling), and
    :meth:`add_event` (always-retained instants).  ``mark(name)``
    returns elapsed seconds on the FIRST call per name (None after) —
    the SLI first-occurrence hook (time-to-first-dispatch/-frame)."""

    _MAX_EVENTS = 32

    def __init__(
        self,
        trace_id: str,
        name: str = "gol.request",
        tenant: str | None = None,
        sampled: bool = True,
        parent_span_id: str | None = None,
        max_spans: int = 512,
    ):
        self.trace_id = trace_id
        self.name = name
        self.tenant = tenant
        self.sampled = sampled
        self.parent_span_id = parent_span_id  # the remote caller's span
        self.flagged: str | None = None
        self.status = "active"
        self.error: str | None = None
        self.ended = False
        self.t0_unix = time.time()
        self.t0_ns = time.monotonic_ns()
        self.duration_ns: int | None = None
        self.max_spans = max_spans
        self.dropped = 0
        self._seq = itertools.count(2)
        self.root_id = f"{1:016x}"
        self._spans: list[dict] = []
        self._events: deque[dict] = deque(maxlen=self._MAX_EVENTS)
        self._marks: dict[str, int] = {}
        self._marks_lock = threading.Lock()

    @property
    def short_id(self) -> str:
        """The 8-hex prefix stamped on flight-ring records (the full id
        rides the dump header)."""
        return self.trace_id[:8]

    def _next_id(self) -> str:
        return f"{next(self._seq):016x}"

    def _append(self, name, span_id, parent_id, t0_ns, t1_ns, labels) -> None:
        if self.ended:
            return
        if len(self._spans) >= self.max_spans:
            # Bounded by keeping the HEAD of the timeline (admission,
            # first dispatches, first frames — what a request postmortem
            # reads); the tail is counted, and always-retained events
            # (add_event) have their own ring.
            self.dropped += 1
            return
        self._spans.append(
            {
                "span_id": span_id,
                "parent_id": parent_id,
                "name": name,
                "t0_ns": t0_ns - self.t0_ns,
                "dur_ns": max(0, t1_ns - t0_ns),
                "labels": labels,
            }
        )

    # -- recording -------------------------------------------------------------
    def span(self, name: str, **labels) -> _SpanCtx:
        return _SpanCtx(self, name, labels)

    def record_span(
        self,
        name: str,
        t0_ns: int,
        t1_ns: int,
        parent_id: str | None = None,
        **labels,
    ) -> None:
        """A span with explicit :func:`clock_ns` timestamps — for spans
        whose start predates the code that records them (queue wait) or
        that are recorded into ANOTHER request's trace (the cohort
        batcher linking member traces)."""
        self._append(
            name,
            self._next_id(),
            parent_id or self.root_id,
            t0_ns,
            t1_ns,
            labels,
        )

    def add_event(self, name: str, **labels) -> None:
        """An always-retained instant (watchdog fire, restart, first
        spectator send): lands in the bounded event ring, never evicted
        by the span cap."""
        if self.ended:
            return
        self._events.append(
            {
                "name": name,
                "t_ns": time.monotonic_ns() - self.t0_ns,
                "labels": labels,
            }
        )

    def flag(self, reason: str) -> None:
        """Force tail retention: this trace is kept at end even when
        head sampling dropped it (failure/watchdog-fire/restart traces
        are never lost).  First reason wins."""
        if self.flagged is None:
            self.flagged = reason

    def mark(self, name: str) -> float | None:
        """First-occurrence mark: elapsed seconds since the request
        started, returned exactly once per name (None afterwards) — the
        SLI observation hook."""
        with self._marks_lock:
            if name in self._marks:
                return None
            dt = time.monotonic_ns() - self.t0_ns
            self._marks[name] = dt
            return dt / 1e9

    # -- export ----------------------------------------------------------------
    def to_dict(self) -> dict:
        dur = self.duration_ns
        if dur is None:
            dur = time.monotonic_ns() - self.t0_ns
        return {
            "schema": SCHEMA,
            "trace_id": self.trace_id,
            "name": self.name,
            "tenant": self.tenant,
            "sampled": self.sampled,
            "flagged": self.flagged,
            "status": self.status,
            "error": self.error,
            "parent_span_id": self.parent_span_id,
            "root_span_id": self.root_id,
            "t0_unix": round(self.t0_unix, 6),
            "duration_ns": int(dur),
            "spans": list(self._spans),
            "events": list(self._events),
            "marks": dict(self._marks),
            "dropped_spans": self.dropped,
        }

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.root_id, self.sampled)


class Tracer:
    """The process-wide store (:data:`TRACER`): a bounded map of active
    traces, a bounded ring of finished (retained) trace dicts, and the
    tenant binding the cohort batcher / gateway headers look up.
    ``configure`` is how ``ServeConfig`` applies its knobs."""

    _MAX_ACTIVE = 1024

    def __init__(
        self,
        sample_rate: float = 1.0,
        ring_depth: int = 256,
        max_spans: int = 512,
    ):
        self.sample_rate = sample_rate
        self.max_spans = max_spans
        self._lock = threading.Lock()
        self._active: dict[str, Trace] = {}
        self._by_tenant: dict[str, Trace] = {}
        self._finished: deque[dict] = deque(maxlen=max(1, ring_depth))
        reg = metrics_lib.REGISTRY
        self._c_started = reg.counter("traces.started")
        self._c_retained = reg.counter("traces.retained")
        self._c_dropped = reg.counter("traces.dropped")
        self._c_tail = reg.counter("traces.tail_retained")

    def configure(
        self,
        sample_rate: float | None = None,
        ring_depth: int | None = None,
        max_spans: int | None = None,
    ) -> "Tracer":
        with self._lock:
            if sample_rate is not None:
                self.sample_rate = sample_rate
            if max_spans is not None:
                self.max_spans = max_spans
            if ring_depth is not None and ring_depth != self._finished.maxlen:
                self._finished = deque(
                    self._finished, maxlen=max(1, ring_depth)
                )
        return self

    # -- lifecycle -------------------------------------------------------------
    def start_trace(
        self,
        name: str = "gol.request",
        traceparent: str | None = None,
        tenant: str | None = None,
        sampled: bool | None = None,
    ) -> Trace:
        parsed = parse_traceparent(traceparent)
        if parsed is not None:
            trace_id, parent_id, remote_sampled = parsed
        else:
            trace_id, parent_id, remote_sampled = new_trace_id(), None, False
        if sampled is None:
            # An inbound sampled flag forces retention (the caller asked
            # to see this trace); otherwise head-sample at the rate.
            sampled = remote_sampled or head_sampled(trace_id, self.sample_rate)
        trace = Trace(
            trace_id,
            name=name,
            tenant=tenant,
            sampled=sampled,
            parent_span_id=parent_id,
            max_spans=self.max_spans,
        )
        self._c_started.inc()
        with self._lock:
            self._active[trace_id] = trace
            while len(self._active) > self._MAX_ACTIVE:
                # A leaked/never-ended trace must not grow the store:
                # evict oldest-started as dropped.
                old_id = next(iter(self._active))
                old = self._active.pop(old_id)
                old.ended = True
                self._c_dropped.inc()
            if tenant is not None:
                self._by_tenant[tenant] = trace
        return trace

    def end_trace(
        self, trace: Trace, status: str = "ok", error: str | None = None
    ) -> None:
        """Finalize + apply the retention policy (idempotent).  The root
        span (the whole-request bar every export anchors on) is appended
        here, covering start→end."""
        with self._lock:
            self._active.pop(trace.trace_id, None)
            if trace.ended:
                return
            trace.ended = True
        trace.status = status
        if error is not None:
            trace.error = str(error)[:500]
        now = time.monotonic_ns()
        trace.duration_ns = now - trace.t0_ns
        trace._spans.append(
            {
                "span_id": trace.root_id,
                "parent_id": trace.parent_span_id,
                "name": trace.name,
                "t0_ns": 0,
                "dur_ns": trace.duration_ns,
                "labels": {"tenant": trace.tenant, "status": status},
            }
        )
        if trace.sampled or trace.flagged is not None:
            if trace.flagged is not None and not trace.sampled:
                self._c_tail.inc()
            self._c_retained.inc()
            with self._lock:
                self._finished.append(trace.to_dict())
        else:
            self._c_dropped.inc()

    # -- tenant binding (the batcher/gateway lookup) ---------------------------
    def bind_tenant(self, tenant: str, trace: Trace) -> None:
        """Latest submission wins — the lookup the cohort batcher and
        the gateway's response headers use."""
        with self._lock:
            self._by_tenant[tenant] = trace

    def for_tenant(self, tenant: str) -> Trace | None:
        """The tenant's CURRENT trace (latest submission wins); ended
        traces still resolve (the gateway's state/control responses
        stamp the id after the run finished)."""
        with self._lock:
            return self._by_tenant.get(tenant)

    def unbind_tenant(self, tenant: str) -> None:
        """The serving plane's eviction hook — rides beside
        ``MetricsRegistry.clear_tenant`` so a churning-tenant pod's
        binding map stays bounded."""
        with self._lock:
            self._by_tenant.pop(tenant, None)

    # -- queries (the /traces surface) -----------------------------------------
    def recent(self, limit: int = 32, tenant: str | None = None) -> list[dict]:
        """Retained traces, newest first."""
        with self._lock:
            docs = list(self._finished)
        if tenant is not None:
            docs = [d for d in docs if d.get("tenant") == tenant]
        return list(reversed(docs))[: max(0, limit)]

    def lookup(self, trace_id: str) -> dict | None:
        """One trace by id (or unique prefix): finished first, then a
        live snapshot of an active trace."""
        with self._lock:
            docs = list(self._finished)
            active = list(self._active.values())
        hits = [d for d in docs if d["trace_id"].startswith(trace_id)]
        if hits:
            return hits[-1]
        live = [t for t in active if t.trace_id.startswith(trace_id)]
        if live:
            return live[-1].to_dict()
        return None

    def lookup_all(self, trace_id: str) -> list[dict]:
        """EVERY leg this process retains for an id (or prefix):
        finished docs plus live snapshots.  One process can hold
        several legs of one trace — a broker's request leg already
        ended while a relay's subscribe leg on the same id is still
        open — and ``lookup`` returns only one of them (finished
        first, shadowing the live leg).  The fleet stitcher wants
        them all."""
        with self._lock:
            docs = list(self._finished)
            active = list(self._active.values())
        out = [d for d in docs if d["trace_id"].startswith(trace_id)]
        out.extend(
            t.to_dict() for t in active if t.trace_id.startswith(trace_id)
        )
        return out

    def clear(self) -> None:
        """Drop all state (tests)."""
        with self._lock:
            self._active.clear()
            self._by_tenant.clear()
            self._finished.clear()


#: The process-wide store every layer records into.
TRACER = Tracer()


# -- the context-variable face (zero-plumbing deep layers) ---------------------

def current() -> Trace | None:
    """The trace active on this thread's context, or None."""
    ctx = _ACTIVE.get()
    return ctx.trace if ctx is not None else None


class _ActivateCtx:
    __slots__ = ("_trace", "_token")

    def __init__(self, trace: Trace):
        self._trace = trace

    def __enter__(self):
        self._token = _ACTIVE.set(_Ctx(self._trace, self._trace.root_id))
        return self._trace

    def __exit__(self, *exc):
        _ACTIVE.reset(self._token)
        return False


def activate(trace: Trace | None):
    """Bind ``trace`` as this context's active trace (None = no-op
    nullcontext): everything the controller/supervisor records through
    ``obs.spans`` / the module helpers below attaches to it, with no
    parameter threading."""
    if trace is None:
        return NULL_CM
    return _ActivateCtx(trace)


def span(name: str, **labels):
    """A span on the ACTIVE trace (shared nullcontext when none — one
    ContextVar read on the inactive path)."""
    ctx = _ACTIVE.get()
    if ctx is None:
        return NULL_CM
    return ctx.trace.span(name, **labels)


def add_event(name: str, **labels) -> None:
    ctx = _ACTIVE.get()
    if ctx is not None:
        ctx.trace.add_event(name, **labels)


def flag(reason: str) -> None:
    """Tail-retain the active trace (no-op when none)."""
    ctx = _ACTIVE.get()
    if ctx is not None:
        ctx.trace.flag(reason)


def current_trace_id() -> str | None:
    ctx = _ACTIVE.get()
    return ctx.trace.trace_id if ctx is not None else None


# -- the /traces HTTP payload (shared by telemetry + gateway servers) ----------

def http_traces(query: dict) -> tuple[int, dict]:
    """``GET /traces`` handler body: ``?trace_id=`` (full or prefix) for
    one trace, else the recent retained ring (``?tenant=`` filter,
    ``?limit=``, default 32).  Pure in-memory reads — the bounded-time
    endpoint contract."""
    trace_id = query.get("trace_id")
    if trace_id:
        if query.get("all"):
            # Every retained leg of the id (the fleet stitcher's form):
            # a process serving both a finished request leg and a live
            # relay leg on one id returns BOTH.
            docs = TRACER.lookup_all(trace_id)
            if not docs:
                return 404, {"error": f"no retained trace {trace_id!r}"}
            return 200, {"schema": "gol-traces-v1", "traces": docs}
        doc = TRACER.lookup(trace_id)
        if doc is None:
            return 404, {"error": f"no retained trace {trace_id!r}"}
        return 200, doc
    try:
        limit = int(query.get("limit", 32))
    except ValueError:
        return 400, {"error": "bad limit"}
    return 200, {
        "schema": "gol-traces-v1",
        "traces": TRACER.recent(limit, tenant=query.get("tenant")),
    }


# -- cross-process stitching (the fleet plane, ISSUE 19) -----------------------

FLEET_SCHEMA = "gol-fleet-trace-v1"


def stitch_traces(node_docs: Mapping[str, Sequence[dict]]) -> dict | None:
    """Merge per-process ``gol-trace-v1`` docs sharing ONE trace id into
    a single ``gol-fleet-trace-v1`` timeline: ``{node: [docs]}`` (as the
    fleet collector's ``/traces?trace_id=`` fan-out returns them) →
    one span forest whose every span/event carries a ``node`` stamp and
    a ``t0_ns`` re-based onto the EARLIEST process's clock.

    Alignment is by wall clock: each doc's ``t0_unix`` is its monotonic
    origin's wall time, so ``offset_ns = (t0_unix - min_t0_unix)*1e9``
    places its relative span times on the shared axis (good to NTP skew
    — microseconds locally, the only cross-process clock there is).
    Span ids are process-local (every trace roots at span 1), so span
    ids and parent links are namespaced ``node:span_id`` in the merged
    forest.  Pure function; returns None when no node had the trace."""
    docs = [
        (node, doc)
        for node, ds in node_docs.items()
        for doc in (ds or ())
        if doc and doc.get("trace_id")
    ]
    if not docs:
        return None
    trace_id = docs[0][1]["trace_id"]
    base = min(float(d.get("t0_unix", 0.0)) for _, d in docs)
    spans: list[dict] = []
    events: list[dict] = []
    nodes: dict[str, dict] = {}
    tenant = None
    flagged = None
    end_ns = 0
    for node, d in sorted(docs, key=lambda nd: float(nd[1].get("t0_unix", 0.0))):
        off = round((float(d.get("t0_unix", 0.0)) - base) * 1e9)
        info = nodes.setdefault(
            node,
            {"traces": 0, "names": [], "t0_unix": d.get("t0_unix")},
        )
        info["traces"] += 1
        if d.get("name") not in info["names"]:
            info["names"].append(d.get("name"))
        if tenant is None:
            tenant = d.get("tenant")
        if flagged is None:
            flagged = d.get("flagged")
        for s in d.get("spans", ()):
            t0 = int(s.get("t0_ns", 0)) + off
            spans.append(
                {
                    **s,
                    "node": node,
                    "t0_ns": t0,
                    "span_id": f"{node}:{s.get('span_id')}",
                    "parent_id": (
                        f"{node}:{s['parent_id']}"
                        if s.get("parent_id") is not None
                        else None
                    ),
                }
            )
            end_ns = max(end_ns, t0 + int(s.get("dur_ns", 0)))
        for e in d.get("events", ()):
            t = int(e.get("t_ns", 0)) + off
            events.append({**e, "node": node, "t_ns": t})
            end_ns = max(end_ns, t)
    spans.sort(key=lambda s: s["t0_ns"])
    events.sort(key=lambda e: e["t_ns"])
    return {
        "schema": FLEET_SCHEMA,
        "trace_id": trace_id,
        "tenant": tenant,
        "flagged": flagged,
        "t0_unix": round(base, 6),
        "duration_ns": end_ns,
        "nodes": nodes,
        "spans": spans,
        "events": events,
    }


__all__ = [
    "FLEET_SCHEMA",
    "SCHEMA",
    "TRACER",
    "Trace",
    "Tracer",
    "activate",
    "add_event",
    "clock_ns",
    "current",
    "current_trace_id",
    "flag",
    "format_traceparent",
    "head_sampled",
    "http_traces",
    "new_trace_id",
    "parse_traceparent",
    "span",
    "stitch_traces",
]
