"""Annotated device-trace spans (ISSUE 4): name the dispatch, not the kernel.

A bare ``--trace`` capture shows every Pallas launch and remote DMA as
anonymous kernel soup; these helpers wrap each controller-level operation
— dispatch issue/resolve, checkpoint fetch, cycle probe, multihost
broadcast — in ``jax.profiler.TraceAnnotation`` /
``StepTraceAnnotation`` spans carrying turn/superstep/tier labels, so the
Perfetto timeline reads "gol.resolve turn=4096 k=512 tier=ici-megakernel"
above the kernels that dispatch produced.

Naming convention (documented in docs/API.md "Observability"):
``gol.<operation>`` with labels as TraceMe metadata — ``gol.issue``,
``gol.resolve``, ``gol.dispatch.sync``, ``gol.checkpoint.fetch``,
``gol.cycle_probe``, ``gol.park``, ``gol.broadcast.<what>``, and the
resilience layer's ``gol.supervisor.restore``, ``gol.sdc.check``,
``gol.preempt.checkpoint`` (ISSUE 5).

Since ISSUE 15 the same call sites feed TWO sinks: the ``jax.profiler``
annotation (a ``--trace`` Perfetto capture, unchanged) AND the
request-scoped host-side span store (``obs.tracing``) whenever a trace
is active on the calling context — so "why was this request slow" and
"what did the device do" are answered from one instrumentation point.

Degrades exactly like ``utils.profiling.trace`` — the profiler class is
resolved ONCE through the shared ``utils.profiling.profiler()`` seam
(ISSUE 15 satellite: one tested profiler-less path): on a stripped jax
build the device half is skipped; with no active trace the host half is
skipped; with neither, every helper returns ``contextlib.nullcontext``.
"""

from __future__ import annotations

from distributed_gol_tpu.obs import tracing

_UNRESOLVED = object()
_TRACE_CLS = _UNRESOLVED  # jax.profiler.TraceAnnotation, or None
_STEP_CLS = _UNRESOLVED  # jax.profiler.StepTraceAnnotation, or None


def _resolve():
    global _TRACE_CLS, _STEP_CLS
    if _TRACE_CLS is _UNRESOLVED:
        from distributed_gol_tpu.utils import profiling

        mod = profiling.profiler()  # the ONE resolution seam
        _TRACE_CLS = getattr(mod, "TraceAnnotation", None)
        _STEP_CLS = getattr(mod, "StepTraceAnnotation", None)
    return _TRACE_CLS, _STEP_CLS


def _reset() -> None:
    """Testing hook: re-resolve on next use (pairs with
    ``utils.profiling._reset_profiler_cache``)."""
    global _TRACE_CLS, _STEP_CLS
    _TRACE_CLS = _UNRESOLVED
    _STEP_CLS = _UNRESOLVED


class _Pair:
    """Enter/exit two context managers as one (device annotation +
    host-side trace span) without ExitStack overhead."""

    __slots__ = ("_a", "_b")

    def __init__(self, a, b):
        self._a = a
        self._b = b

    def __enter__(self):
        self._a.__enter__()
        self._b.__enter__()
        return self

    def __exit__(self, *exc):
        try:
            self._b.__exit__(*exc)
        finally:
            self._a.__exit__(*exc)
        return False


def _combine(dev, name, labels):
    """Device annotation (may be None) + host span (nullcontext when no
    trace is active on this context) → the cheapest CM that covers both."""
    host = tracing.span(name, **labels)
    if dev is None:
        return host  # host may itself be the shared nullcontext
    if host is tracing.NULL_CM:
        return dev
    return _Pair(dev, host)


def span(name: str, **labels):
    """A ``TraceAnnotation`` context manager for one host-side operation;
    ``labels`` ride as TraceMe metadata (Perfetto args) and, when a
    request trace is active (``obs.tracing``), as host-span labels.
    No-op without a profiler backend and without an active trace."""
    cls, _ = _resolve()
    dev = None
    if cls is not None:
        try:
            dev = cls(name, **labels)
        except Exception:  # an exotic label type must never take the run down
            dev = None
    return _combine(dev, name, labels)


def step_span(name: str, step: int, **labels):
    """A ``StepTraceAnnotation``: like :func:`span` but also marks a step
    boundary (``step_num``), so trace viewers group one dispatch's kernels
    under one step.  Falls back to a plain span when the build has no
    StepTraceAnnotation."""
    _, cls = _resolve()
    if cls is None:
        return span(name, step=step, **labels)
    try:
        dev = cls(name, step_num=step, **labels)
    except Exception:
        dev = None
    return _combine(dev, name, dict(labels, step=step))
