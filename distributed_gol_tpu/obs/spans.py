"""Annotated device-trace spans (ISSUE 4): name the dispatch, not the kernel.

A bare ``--trace`` capture shows every Pallas launch and remote DMA as
anonymous kernel soup; these helpers wrap each controller-level operation
— dispatch issue/resolve, checkpoint fetch, cycle probe, multihost
broadcast — in ``jax.profiler.TraceAnnotation`` /
``StepTraceAnnotation`` spans carrying turn/superstep/tier labels, so the
Perfetto timeline reads "gol.resolve turn=4096 k=512 tier=ici-megakernel"
above the kernels that dispatch produced.

Naming convention (documented in docs/API.md "Observability"):
``gol.<operation>`` with labels as TraceMe metadata — ``gol.issue``,
``gol.resolve``, ``gol.dispatch.sync``, ``gol.checkpoint.fetch``,
``gol.cycle_probe``, ``gol.park``, ``gol.broadcast.<what>``, and the
resilience layer's ``gol.supervisor.restore``, ``gol.sdc.check``,
``gol.preempt.checkpoint`` (ISSUE 5).

Degrades exactly like ``utils.profiling.trace``: on a stripped jax build
(no profiler backend) every helper returns ``contextlib.nullcontext`` —
resolved once, cached, zero per-call import cost afterwards.
"""

from __future__ import annotations

import contextlib

_UNRESOLVED = object()
_TRACE_CLS = _UNRESOLVED  # jax.profiler.TraceAnnotation, or None
_STEP_CLS = _UNRESOLVED  # jax.profiler.StepTraceAnnotation, or None


def _resolve():
    global _TRACE_CLS, _STEP_CLS
    if _TRACE_CLS is _UNRESOLVED:
        try:
            import jax

            _TRACE_CLS = jax.profiler.TraceAnnotation
            _STEP_CLS = getattr(jax.profiler, "StepTraceAnnotation", None)
        except Exception:  # stripped build: spans are no-ops, like trace()
            _TRACE_CLS = None
            _STEP_CLS = None
    return _TRACE_CLS, _STEP_CLS


def span(name: str, **labels):
    """A ``TraceAnnotation`` context manager for one host-side operation;
    ``labels`` ride as TraceMe metadata (Perfetto args).  No-op without a
    profiler backend."""
    cls, _ = _resolve()
    if cls is None:
        return contextlib.nullcontext()
    try:
        return cls(name, **labels)
    except Exception:  # an exotic label type must never take the run down
        return contextlib.nullcontext()


def step_span(name: str, step: int, **labels):
    """A ``StepTraceAnnotation``: like :func:`span` but also marks a step
    boundary (``step_num``), so trace viewers group one dispatch's kernels
    under one step.  Falls back to a plain span when the build has no
    StepTraceAnnotation."""
    _, cls = _resolve()
    if cls is None:
        return span(name, **labels)
    try:
        return cls(name, step_num=step, **labels)
    except Exception:
        return contextlib.nullcontext()
