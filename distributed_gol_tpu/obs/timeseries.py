"""Continuous telemetry sampling (ISSUE 12 tentpole, layer 1).

Everything PRs 4-8 planted is *pull-on-demand*: registry snapshots
materialize at terminal ``MetricsReport`` events, bench records, and
flight dumps — a live pod has no time axis.  :class:`TelemetrySampler`
adds it: a daemon thread snapshots the process-wide registry every
``interval`` seconds into a bounded ring of timestamped samples, and the
derived views — windowed rates (gens/s, dispatches/s, retries/s,
watchdog-fires/min) and histogram-delta percentiles (p50/p95/p99
issue/resolve latency) — are computed from *consecutive samples*, so
they describe what the pod is doing NOW, not since process start.

Contracts:

- **The sampling path never blocks on a device.**  Samples are taken
  with ``include_lazy=False`` (plain dict copies under the registry
  lock); the lazy callback gauges (skip fraction, compile-cache stats,
  live subscriber counts) — which may force device values — are
  evaluated only every ``lazy_every``-th tick and merged into that
  tick's sample.  A wedged device can therefore stall at most the lazy
  leg of one tick; the ring keeps serving the last good sample, and
  consumers read the growing :attr:`staleness` instead of hanging.
- **Bounded everything.**  The ring holds ``depth`` samples (oldest
  evicted), a sample is a plain ``gol-metrics-v1`` dict, and every read
  API is lock-bounded pure-Python — which is what lets the HTTP
  endpoints (``serve/telemetry.py``) promise bounded-time scrapes.
- **Staleness bound = one interval.**  Consumers serving from
  :meth:`latest` (the serving plane's ``health()``, the ``/metrics``
  endpoint) see data at most ``interval`` seconds old while the sampler
  is healthy; :attr:`staleness` exposes the actual age so a stalled
  sampler is itself observable.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Sequence

from distributed_gol_tpu.obs import metrics as metrics_lib


class Sample:
    """One timestamped registry snapshot (``snapshot`` is the plain
    ``gol-metrics-v1`` dict; ``lazy`` marks a tick that also evaluated
    the callback gauges)."""

    __slots__ = ("t", "snapshot", "lazy")

    def __init__(self, t: float, snapshot: dict, lazy: bool = False):
        self.t = t
        self.snapshot = snapshot
        self.lazy = lazy


def histogram_delta_percentiles(
    new: dict | None, old: dict | None, qs: Sequence[float] = (0.5, 0.95, 0.99)
) -> dict[str, float] | None:
    """Percentiles of the observations that landed BETWEEN two snapshots
    of one fixed-bucket histogram (``{"buckets", "counts", ...}`` dicts
    as snapshots carry them), linearly interpolated within a bucket.

    ``old=None`` treats ``new`` as the whole population (the since-start
    view).  Returns None when no observations landed in the window.
    Values past the last bound are pinned to it — an overflow quantile
    reads "at least the last bound", which is the conservative answer a
    latency SLO wants."""
    if not new:
        return None
    bounds = list(new.get("buckets", ()))
    counts = list(new.get("counts", ()))
    if old and old.get("buckets") == new.get("buckets"):
        counts = [a - b for a, b in zip(counts, old.get("counts", ()))]
    if len(counts) != len(bounds) + 1 or any(c < 0 for c in counts):
        return None
    total = sum(counts)
    if total <= 0:
        return None
    out: dict[str, float] = {}
    for q in qs:
        target = q * total
        cum = 0.0
        value = float(bounds[-1])
        for i, c in enumerate(counts):
            if cum + c >= target and c > 0:
                hi = float(bounds[i]) if i < len(bounds) else float(bounds[-1])
                lo = float(bounds[i - 1]) if i > 0 else 0.0
                frac = (target - cum) / c
                value = min(lo + frac * (hi - lo), float(bounds[-1]))
                break
            cum += c
        out[f"p{int(q * 100)}"] = value
    return out


def fraction_above(
    new: dict | None, old: dict | None, threshold: float
) -> float | None:
    """Fraction of the window's observations ABOVE ``threshold``, from
    the histogram delta between two snapshots.  The threshold is rounded
    DOWN to the nearest bucket bound (conservative: observations between
    the bound and the threshold count as "above"), so a latency SLO
    judged through this never under-reports violations.  None = no
    observations in the window."""
    if not new:
        return None
    bounds = list(new.get("buckets", ()))
    counts = list(new.get("counts", ()))
    if old and old.get("buckets") == new.get("buckets"):
        counts = [a - b for a, b in zip(counts, old.get("counts", ()))]
    if len(counts) != len(bounds) + 1 or any(c < 0 for c in counts):
        return None
    total = sum(counts)
    if total <= 0:
        return None
    # counts[i] covers values <= bounds[i]; everything in a bucket whose
    # UPPER bound exceeds the threshold is counted as a violation.
    good = sum(c for b, c in zip(bounds, counts) if b <= threshold)
    return (total - good) / total


class SnapshotRegistry:
    """A :class:`~distributed_gol_tpu.obs.metrics.MetricsRegistry`
    duck-type over an EXTERNAL snapshot source — what lets a
    :class:`TelemetrySampler` ring hold some OTHER process's metrics
    (the fleet collector's per-node rings over scraped ``/metrics``
    text, and the fleet-aggregate ring over their merge — ISSUE 19)
    while the sampler's own bookkeeping counters land on a real local
    ``registry``.  ``fn`` returns the newest ``gol-metrics-v1`` dict
    the source holds (None samples as empty); it should hand back a
    dict it will not mutate afterwards, since ring samples alias it."""

    def __init__(self, fn: Callable[[], dict | None], registry=None):
        self._fn = fn
        self._registry = (
            registry if registry is not None else metrics_lib.REGISTRY
        )

    def snapshot(self, include_lazy: bool = True) -> metrics_lib.MetricsSnapshot:
        snap = self._fn()
        if snap is None:
            snap = {
                "schema": metrics_lib.SCHEMA,
                "counters": {},
                "gauges": {},
                "histograms": {},
                "info": {},
            }
        return metrics_lib.MetricsSnapshot(snap)

    def counter(self, name: str):
        return self._registry.counter(name)


class TelemetrySampler:
    """The continuous-sampling daemon (module doc).  ``interval`` is the
    cadence in seconds; ``depth`` bounds the ring; every ``lazy_every``-th
    tick also evaluates the registry's callback gauges.  ``on_sample``
    (optional) is called with the sampler after each tick — the SLO
    tracker's hook — on the sampler thread, exceptions contained."""

    def __init__(
        self,
        registry=None,
        interval: float = 1.0,
        depth: int = 600,
        lazy_every: int = 10,
        on_sample: Callable[["TelemetrySampler"], None] | None = None,
    ):
        if interval <= 0:
            raise ValueError("sampler interval must be positive")
        if depth < 2:
            raise ValueError("sampler depth must be >= 2 (rates need a delta)")
        if lazy_every < 1:
            raise ValueError("lazy_every must be >= 1")
        self.registry = registry if registry is not None else metrics_lib.REGISTRY
        self.interval = interval
        self.lazy_every = lazy_every
        self.on_sample = on_sample
        self._ring: deque[Sample] = deque(maxlen=depth)
        self._lock = threading.Lock()
        # Two small locks, deliberately NOT one around the whole tick: a
        # lazy tick's snapshot may block on a wedged device's callback
        # gauge, and an event-driven fast tick (the serving plane's
        # terminal-session freshness tick, taken under the plane lock)
        # must never queue behind it — only the cadence bump and the
        # on_sample callback (alert edge-triggering) are serialized.
        self._cadence_lock = threading.Lock()
        self._cb_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._ticks = 0
        self._m_samples = self.registry.counter("telemetry.samples")
        self._m_lazy = self.registry.counter("telemetry.lazy_samples")

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> "TelemetrySampler":
        """Take one sample synchronously (so ``latest()`` is never None
        after start) and launch the daemon."""
        if self._thread is not None:
            return self
        self.sample_now()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="gol-telemetry-sampler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=timeout)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sample_now()
            except Exception:  # noqa: BLE001 — telemetry never kills a pod
                continue

    # -- the tick --------------------------------------------------------------
    def sample_now(self, lazy: bool | None = None) -> Sample:
        """One tick: snapshot, append, fire ``on_sample``.  Public so
        tests (and the synchronous start above) can drive the ring
        without wall-clock waits.  ``lazy=None`` follows the cadence;
        ``False`` forces a fast (never device-touching) tick — what
        event-driven callers like the serving plane's terminal-session
        freshness tick must pass, since they may hold locks a scrape
        path also needs; ``True`` forces a lazy tick.

        The cadence bump and the ``on_sample`` callback are serialized
        (concurrent ticks cannot skew the lazy schedule or race the SLO
        tracker's alert edge-trigger); the snapshot itself is NOT — a
        lazy tick blocked on a wedged device must not make a concurrent
        fast tick wait behind it (see the lock comment in __init__)."""
        with self._cadence_lock:
            self._ticks += 1
            if lazy is None:
                # Never-lazy on the first tick (even at lazy_every=1):
                # start() samples synchronously and must not block pod
                # startup on a device-forcing callback gauge.
                lazy = (
                    self._ticks > 1 and (self._ticks % self.lazy_every) == 0
                )
        snap = self.registry.snapshot(include_lazy=lazy).to_dict()
        sample = Sample(time.time(), snap, lazy=lazy)
        if lazy:
            self._m_lazy.inc()
        self._m_samples.inc()
        with self._lock:
            self._ring.append(sample)
        cb = self.on_sample
        if cb is not None:
            with self._cb_lock:
                try:
                    cb(self)
                except Exception:  # noqa: BLE001 — an SLO bug must not stop sampling
                    pass
        return sample

    # -- reads -----------------------------------------------------------------
    def latest(self) -> Sample | None:
        with self._lock:
            return self._ring[-1] if self._ring else None

    def samples(self) -> list[Sample]:
        with self._lock:
            return list(self._ring)

    @property
    def staleness(self) -> float:
        """Seconds since the last sample (inf before the first) — how a
        consumer of :meth:`latest` observes a stalled sampler."""
        s = self.latest()
        return time.time() - s.t if s is not None else float("inf")

    def window(self, seconds: float | None = None) -> tuple[Sample, Sample] | None:
        """(oldest-within-window, newest) pair, or None until two samples
        exist.  ``seconds=None`` spans the whole ring.  When the ring
        does not yet cover ``seconds``, the whole ring is used — the
        window grows to spec as samples accumulate (documented SLO
        warm-up behaviour)."""
        with self._lock:
            if len(self._ring) < 2:
                return None
            new = self._ring[-1]
            if seconds is None:
                return self._ring[0], new
            old = self._ring[0]
            for s in self._ring:
                if s.t >= new.t - seconds:
                    old = s
                    break
            if old is new:
                old = self._ring[-2]
            return old, new

    def counter_delta(self, name: str, seconds: float | None = None):
        """(delta, dt) for one counter over the window; None without two
        samples."""
        w = self.window(seconds)
        if w is None:
            return None
        old, new = w
        dt = max(new.t - old.t, 1e-9)
        d = new.snapshot.get("counters", {}).get(name, 0) - old.snapshot.get(
            "counters", {}
        ).get(name, 0)
        return d, dt

    def rate(self, name: str, seconds: float | None = None) -> float | None:
        d = self.counter_delta(name, seconds)
        return None if d is None else d[0] / d[1]

    def percentiles(
        self,
        name: str,
        seconds: float | None = None,
        qs: Sequence[float] = (0.5, 0.95, 0.99),
    ) -> dict[str, float] | None:
        """Windowed percentiles of one histogram instrument (see
        :func:`histogram_delta_percentiles`)."""
        w = self.window(seconds)
        if w is None:
            return None
        old, new = w
        return histogram_delta_percentiles(
            new.snapshot.get("histograms", {}).get(name),
            old.snapshot.get("histograms", {}).get(name),
            qs,
        )

    def derived(self, seconds: float | None = None) -> dict:
        """The dashboard rollup: pod-wide windowed rates + latency
        percentiles + per-tenant rows, all from ring deltas.  Shape::

            {"window_seconds", "gens_per_s", "dispatches_per_s",
             "retries_per_s", "watchdog_fires_per_min",
             "issue_latency": {p50, p95, p99} | None,
             "resolve_latency": {...} | None,
             "tenants": {tenant: {"gens_per_s", "dispatches_per_s",
                                  "resolve_latency": {...} | None}}}

        Pod-wide rates SUM the untenanted instruments and every
        ``tenant=`` variant (a serving pod's work lives under labels)."""
        w = self.window(seconds)
        if w is None:
            return {}
        old, new = w
        dt = max(new.t - old.t, 1e-9)
        oc = old.snapshot.get("counters", {})
        nc = new.snapshot.get("counters", {})

        def rate_all(base: str) -> float:
            total = 0.0
            for k, v in nc.items():
                if k == base or (
                    k.startswith(base + "{")
                    and metrics_lib.tenant_of(k) is not None
                ):
                    total += v - oc.get(k, 0)
            return total / dt

        oh = old.snapshot.get("histograms", {})
        nh = new.snapshot.get("histograms", {})
        tenants: dict[str, dict] = {}
        for k in nc:
            t = metrics_lib.tenant_of(k)
            if t is None or not k.startswith("controller."):
                continue
            row = tenants.setdefault(t, {})
            base = k[: k.index("{")]
            if base == "controller.turns":
                row["gens_per_s"] = (nc[k] - oc.get(k, 0)) / dt
            elif base == "controller.dispatches":
                row["dispatches_per_s"] = (nc[k] - oc.get(k, 0)) / dt
        for t, row in tenants.items():
            hname = metrics_lib.labelled("controller.dispatch_seconds", t)
            row["resolve_latency"] = histogram_delta_percentiles(
                nh.get(hname), oh.get(hname)
            )
        return {
            "window_seconds": round(dt, 3),
            "gens_per_s": rate_all("controller.turns"),
            "dispatches_per_s": rate_all("controller.dispatches"),
            "retries_per_s": rate_all("faults.retries"),
            "watchdog_fires_per_min": rate_all("faults.watchdog_fires") * 60.0,
            "issue_latency": histogram_delta_percentiles(
                nh.get("controller.issue_seconds"),
                oh.get("controller.issue_seconds"),
            ),
            "resolve_latency": histogram_delta_percentiles(
                nh.get("controller.dispatch_seconds"),
                oh.get("controller.dispatch_seconds"),
            ),
            "tenants": tenants,
        }
