"""Per-tenant SLO tracking over the telemetry ring (ISSUE 12, layer 3).

Two objective kinds, both judged from the sampler's ring — never from a
device — so evaluation is bounded-time and a wedged tenant cannot stall
its own (or anyone else's) verdict:

- **Latency**: "``latency_percentile`` of dispatches resolve within
  ``latency_seconds``".  The violating fraction over a window comes from
  the per-tenant ``controller.dispatch_seconds{tenant=}`` histogram
  delta, with the threshold rounded DOWN to a bucket bound
  (:func:`obs.timeseries.fraction_above` — conservative, never
  under-reports).
- **Error rate**: "at most ``error_rate`` of dispatch attempts fail",
  from the per-tenant ``controller.dispatch_failures{tenant=}`` vs
  ``controller.dispatches{tenant=}`` counter deltas.

**Burn rate** is the standard SRE quotient: the observed bad fraction
over a window divided by the fraction the objective allows (1.0 = the
error budget spends exactly at sustainable pace).  Alerts are
multi-window: a tenant pages only when BOTH the fast and the slow
window burn above ``burn_threshold`` — a one-sample blip can't page,
a sustained burn can't hide.  Until the ring spans a window, the whole
ring stands in for it (documented warm-up: a young pod alerts on
sustained early burn rather than staying blind for a slow-window).

**Error budget** is tracked over ``budget_window_seconds`` — clamped to
the sampler ring's span (``ServeConfig`` validates the slow window fits
the ring and ships defaults where the budget window equals the span;
an oversized budget window degrades to the ring, never silently to
less): ``remaining = 1 - bad_events / (allowed_fraction ·
total_events)``, clamped to [0, 1], published as the per-tenant
``slo.error_budget_remaining{tenant=}`` gauge — the WORST (minimum)
across armed objectives — with the per-objective fast burn rates
beside it (``slo.<objective>_burn_rate{tenant=}``).

Alert transitions are edge-triggered into the plane's flight ring
(``slo_alert`` records, rendered by ``tools/flight_report.py``) and the
``serve.slo_alerts`` counter; the full per-tenant table rides
``ServePlane.health()["slo"]`` and the ``/slo`` endpoint.
"""

from __future__ import annotations

from dataclasses import dataclass

from distributed_gol_tpu.obs import metrics as metrics_lib
from distributed_gol_tpu.obs.timeseries import (
    TelemetrySampler,
    fraction_above,
    histogram_delta_percentiles,
)


@dataclass(frozen=True)
class SLOObjectives:
    """The objective set one pod enforces (built by ``ServePlane`` from
    ``ServeConfig``'s ``slo_*`` fields).  An objective with its
    threshold at 0 is OFF."""

    latency_seconds: float = 0.0  # 0 = no latency objective
    latency_percentile: float = 0.99  # "p99 under latency_seconds"
    error_rate: float = 0.0  # 0 = no error objective
    fast_window_seconds: float = 60.0
    slow_window_seconds: float = 300.0
    burn_threshold: float = 2.0
    budget_window_seconds: float = 3600.0
    # Queue-wait objective (ISSUE 15; 0 = off): "latency_percentile of
    # admissions start within queue_wait_seconds", judged from the
    # per-tenant ``sli.queue_wait_seconds{tenant=}`` histogram the
    # request-tracing plane observes at session start — the admission
    # half of request latency the dispatch objective cannot see.
    queue_wait_seconds: float = 0.0

    def __post_init__(self):
        if (
            self.latency_seconds < 0
            or self.error_rate < 0
            or self.queue_wait_seconds < 0
        ):
            raise ValueError("SLO thresholds must be >= 0 (0 disables)")
        if not 0 < self.latency_percentile < 1:
            raise ValueError("latency_percentile must be in (0, 1)")
        if not 0 < self.fast_window_seconds <= self.slow_window_seconds:
            raise ValueError(
                "windows must satisfy 0 < fast <= slow"
            )
        if self.burn_threshold <= 0:
            raise ValueError("burn_threshold must be positive")
        if self.budget_window_seconds <= 0:
            raise ValueError("budget_window_seconds must be positive")

    @property
    def enabled(self) -> bool:
        return (
            self.latency_seconds > 0
            or self.error_rate > 0
            or self.queue_wait_seconds > 0
        )


def _tenants_of(snapshot: dict) -> set[str]:
    out = set()
    for name in snapshot.get("counters", {}):
        t = metrics_lib.tenant_of(name)
        if t is not None and name.startswith("controller."):
            out.add(t)
    # A queued tenant has SLI observations before its first dispatch
    # counter exists — the queue-wait objective must see it (ISSUE 15).
    for name in snapshot.get("histograms", {}):
        t = metrics_lib.tenant_of(name)
        if t is not None and name.startswith(("controller.", "sli.")):
            out.add(t)
    return out


class SLOTracker:
    """Evaluates :class:`SLOObjectives` for every tenant visible in the
    sampler ring; designed to run as the sampler's ``on_sample`` hook
    (one evaluation per sample, pure ring reads)."""

    def __init__(self, objectives: SLOObjectives, registry, flight=None):
        self.objectives = objectives
        self.registry = registry
        self.flight = flight  # the plane's ring; None = no records
        self._m_alerts = registry.counter("serve.slo_alerts")
        # (tenant, objective) pairs currently alerting — the edge trigger.
        self._alerting: set[tuple[str, str]] = set()
        self._summary: dict[str, dict] = {}

    # -- the window math -------------------------------------------------------
    def _hist_bad_fraction(
        self,
        sampler: TelemetrySampler,
        metric: str,
        tenant: str,
        window_seconds: float,
        threshold: float,
    ) -> float | None:
        """Fraction of ``metric``'s window observations above
        ``threshold`` (bucket-rounded-down, conservative) — shared by
        the dispatch-latency and queue-wait objectives."""
        w = sampler.window(window_seconds)
        if w is None:
            return None
        old, new = w
        name = metrics_lib.labelled(metric, tenant)
        return fraction_above(
            new.snapshot.get("histograms", {}).get(name),
            old.snapshot.get("histograms", {}).get(name),
            threshold,
        )

    def _latency_bad_fraction(
        self, sampler: TelemetrySampler, tenant: str, seconds: float
    ) -> float | None:
        return self._hist_bad_fraction(
            sampler,
            "controller.dispatch_seconds",
            tenant,
            seconds,
            self.objectives.latency_seconds,
        )

    def _error_fraction(
        self, sampler: TelemetrySampler, tenant: str, seconds: float
    ):
        """(bad, total) dispatch attempts over the window, or None."""
        ok = sampler.counter_delta(
            metrics_lib.labelled("controller.dispatches", tenant), seconds
        )
        bad = sampler.counter_delta(
            metrics_lib.labelled("controller.dispatch_failures", tenant),
            seconds,
        )
        if ok is None:
            return None
        n_ok = ok[0]
        n_bad = bad[0] if bad is not None else 0
        total = n_ok + n_bad
        return (n_bad, total) if total > 0 else None

    def _burn(self, bad_fraction: float | None, allowed: float) -> float | None:
        if bad_fraction is None:
            return None
        return bad_fraction / max(allowed, 1e-9)

    # -- evaluation (the sampler hook) -----------------------------------------
    def observe(self, sampler: TelemetrySampler) -> dict[str, dict]:
        """One evaluation pass; returns (and retains, for ``summary``)
        the per-tenant table."""
        obj = self.objectives
        latest = sampler.latest()
        if latest is None or not obj.enabled:
            return self._summary
        table: dict[str, dict] = {}
        for tenant in sorted(_tenants_of(latest.snapshot)):
            row: dict = {}
            # Live latency percentiles for the dashboard, objective or not.
            pcts = sampler.percentiles(
                metrics_lib.labelled("controller.dispatch_seconds", tenant),
                obj.fast_window_seconds,
            )
            if pcts is not None:
                row["resolve_latency"] = pcts
            if obj.latency_seconds > 0:
                allowed = 1.0 - obj.latency_percentile
                row["latency"] = self._objective_row(
                    tenant,
                    "latency",
                    allowed,
                    fast=self._latency_bad_fraction(
                        sampler, tenant, obj.fast_window_seconds
                    ),
                    slow=self._latency_bad_fraction(
                        sampler, tenant, obj.slow_window_seconds
                    ),
                    budget=self._latency_bad_fraction(
                        sampler, tenant, obj.budget_window_seconds
                    ),
                )
            if obj.queue_wait_seconds > 0:
                allowed = 1.0 - obj.latency_percentile
                qwait = lambda window: self._hist_bad_fraction(  # noqa: E731
                    sampler,
                    "sli.queue_wait_seconds",
                    tenant,
                    window,
                    obj.queue_wait_seconds,
                )
                row["queue_wait"] = self._objective_row(
                    tenant,
                    "queue_wait",
                    allowed,
                    fast=qwait(obj.fast_window_seconds),
                    slow=qwait(obj.slow_window_seconds),
                    budget=qwait(obj.budget_window_seconds),
                )
            if obj.error_rate > 0:
                fast = self._error_fraction(
                    sampler, tenant, obj.fast_window_seconds
                )
                slow = self._error_fraction(
                    sampler, tenant, obj.slow_window_seconds
                )
                budget = self._error_fraction(
                    sampler, tenant, obj.budget_window_seconds
                )
                row["errors"] = self._objective_row(
                    tenant,
                    "errors",
                    obj.error_rate,
                    fast=None if fast is None else fast[0] / fast[1],
                    slow=None if slow is None else slow[0] / slow[1],
                    budget=None if budget is None else budget[0] / budget[1],
                )
            table[tenant] = row
            # One budget gauge per tenant: the WORST (minimum) remaining
            # across armed objectives — the operationally meaningful
            # number (a dashboard must not show a full error budget
            # while the latency budget is burnt).
            budgets = [
                o["budget_remaining"]
                for o in (
                    row.get("latency"),
                    row.get("errors"),
                    row.get("queue_wait"),
                )
                if o is not None and o.get("budget_remaining") is not None
            ]
            if budgets:
                self.registry.gauge(
                    metrics_lib.labelled("slo.error_budget_remaining", tenant)
                ).set(round(min(budgets), 4))
        # Tenants that left the snapshot (terminal handle evicted,
        # labelled instruments cleared) must not haunt the alert set:
        # un-latch them so a REUSED tenant name can page again, and the
        # /slo 'alerting' list stops naming ghosts.
        for key in [k for k in self._alerting if k[0] not in table]:
            self._alerting.discard(key)
            if self.flight is not None:
                self.flight.record(
                    "slo_resolved",
                    tenant=key[0],
                    objective=key[1],
                    reason="tenant evicted",
                )
        self._summary = table
        return table

    def _objective_row(
        self,
        tenant: str,
        objective: str,
        allowed: float,
        fast: float | None,
        slow: float | None,
        budget: float | None,
    ) -> dict:
        obj = self.objectives
        burn_fast = self._burn(fast, allowed)
        burn_slow = self._burn(slow, allowed)
        alerting = (
            burn_fast is not None
            and burn_slow is not None
            and burn_fast > obj.burn_threshold
            and burn_slow > obj.burn_threshold
        )
        remaining = None
        if budget is not None:
            remaining = max(0.0, min(1.0, 1.0 - budget / max(allowed, 1e-9)))
        key = (tenant, objective)
        if alerting and key not in self._alerting:
            self._alerting.add(key)
            self._m_alerts.inc()
            if self.flight is not None:
                self.flight.record(
                    "slo_alert",
                    tenant=tenant,
                    objective=objective,
                    burn_fast=round(burn_fast, 3),
                    burn_slow=round(burn_slow, 3),
                    threshold=obj.burn_threshold,
                    budget_remaining=(
                        round(remaining, 4) if remaining is not None else None
                    ),
                )
        elif not alerting and key in self._alerting:
            self._alerting.discard(key)
            if self.flight is not None:
                self.flight.record(
                    "slo_resolved", tenant=tenant, objective=objective
                )
        # Per-objective burn gauges; the single budget gauge is set by
        # observe() as the minimum across objectives.
        self.registry.gauge(
            metrics_lib.labelled(f"slo.{objective}_burn_rate", tenant)
        ).set(round(burn_fast, 4) if burn_fast is not None else -1.0)
        return {
            "burn_fast": burn_fast,
            "burn_slow": burn_slow,
            "alerting": alerting,
            "budget_remaining": remaining,
        }

    def summary(self) -> dict:
        """The ``health()['slo']`` / ``/slo`` payload: objectives +
        latest per-tenant table."""
        obj = self.objectives
        return {
            "objectives": {
                "latency_seconds": obj.latency_seconds,
                "latency_percentile": obj.latency_percentile,
                "error_rate": obj.error_rate,
                "fast_window_seconds": obj.fast_window_seconds,
                "slow_window_seconds": obj.slow_window_seconds,
                "burn_threshold": obj.burn_threshold,
                "budget_window_seconds": obj.budget_window_seconds,
                "queue_wait_seconds": obj.queue_wait_seconds,
            },
            "alerting": sorted(
                f"{t}:{o}" for t, o in self._alerting
            ),
            "tenants": self._summary,
        }


__all__ = [
    "SLOObjectives",
    "SLOTracker",
    "histogram_delta_percentiles",
]
