"""Crash flight recorder (ISSUE 4): the postmortem artifact.

A bounded in-memory ring of structured records — the last N dispatches
with timings, retries, watchdog transitions, checkpoint commits, tier
decisions — that costs one ``deque.append`` per record while the run is
healthy and is dumped as ``flight-<ts>.json`` next to the checkpoint dir
by every terminal path (``DispatchTimeout``, ``DispatchError``
exhaustion, any sentinel abort) just before the run dies.  A clean run
writes nothing: the absence of a flight record IS the "nothing went
wrong" signal (asserted by the chaos matrix).

Schema (``gol-flight-v1``; linted by :func:`check_flight_record` the same
way ``measure.check_headline_stats`` lints bench records)::

    {"schema": "gol-flight-v1",
     "cause": "<exception class>",      # what killed the run
     "error": "<str(exception)>",
     "turn": <last completed turn>,
     "written_at": <unix seconds>,
     "records": [{"kind": ..., "t": <unix seconds>, ...}, ...],  # oldest first
     "metrics": {...}}                  # gol-metrics-v1 snapshot, optional

The ring's tail must explain the abort: the dumping path appends one
``{"kind": "abort", "cause": ...}`` record before writing, so
``records[-1]`` names the cause even when the ring wrapped.
``tools/flight_report.py`` renders one of these for humans.
"""

from __future__ import annotations

import collections
import json
import time
from pathlib import Path
from typing import Mapping

from distributed_gol_tpu.obs.metrics import check_metrics_snapshot

SCHEMA = "gol-flight-v1"


class MalformedFlightRecord(ValueError):
    """A flight record violated the ``gol-flight-v1`` schema."""


class FlightRecorder:
    """The bounded ring.  ``depth == 0`` disables recording entirely
    (``record`` and ``dump`` become no-ops) — the ``Params.
    flight_recorder_depth=0`` spelling."""

    def __init__(self, depth: int = 256):
        if depth < 0:
            raise ValueError("flight recorder depth must be >= 0")
        self.depth = depth
        self._ring: collections.deque = collections.deque(maxlen=depth or 1)

    @property
    def enabled(self) -> bool:
        return self.depth > 0

    def record(self, kind: str, **fields) -> None:
        """Append one structured record; a deque append under the GIL, no
        lock (records may interleave across threads — each is atomic)."""
        if not self.depth:
            return
        entry = {"kind": kind, "t": round(time.time(), 6)}
        entry.update(fields)
        self._ring.append(entry)

    def records(self) -> list[dict]:
        return list(self._ring)

    def dump(
        self,
        directory: str | Path,
        cause: str,
        error: str = "",
        turn: int = 0,
        metrics: dict | None = None,
        run_id: str | None = None,
        tenant: str | None = None,
        trace_id: str | None = None,
    ) -> Path | None:
        """Write the postmortem ``flight-<ts>.json`` into ``directory``
        (created if needed).  Appends the terminal ``abort`` record first
        so the tail always explains the abort.  ``run_id``/``tenant``
        (ISSUE 12) stamp the correlation id shared with the run's
        MetricsReport and checkpoint sidecars; ``trace_id`` (ISSUE 15)
        joins the dump to the request's ``/traces`` timeline.
        Best-effort by contract:
        a failing dump (ENOSPC, perms) returns None — the postmortem
        artifact must never mask the abort it is documenting."""
        if not self.depth:
            return None
        self.record("abort", cause=cause, error=error[:500], turn=turn)
        doc = {
            "schema": SCHEMA,
            "cause": cause,
            "error": error[:2000],
            "turn": turn,
            "written_at": round(time.time(), 6),
            "records": self.records(),
        }
        if run_id:
            doc["run_id"] = run_id
        if tenant is not None:
            doc["tenant"] = tenant
        if trace_id:
            doc["trace_id"] = trace_id
        if metrics is not None:
            doc["metrics"] = metrics
        try:
            directory = Path(directory)
            directory.mkdir(parents=True, exist_ok=True)
            path = directory / f"flight-{time.time_ns()}.json"
            path.write_text(json.dumps(doc, default=str))
            return path
        except OSError:
            return None


def check_flight_record(obj, path: str = "$") -> list[str]:
    """Lint one flight-record dict; returns violations (empty = clean)."""
    problems: list[str] = []
    if not isinstance(obj, Mapping):
        return [f"{path}: flight record is not a dict ({type(obj).__name__})"]
    if obj.get("schema") != SCHEMA:
        problems.append(f"{path}.schema: want {SCHEMA!r}, got {obj.get('schema')!r}")
    cause = obj.get("cause")
    if not isinstance(cause, str) or not cause:
        problems.append(f"{path}.cause: missing or empty ({cause!r})")
    if not isinstance(obj.get("turn"), int):
        problems.append(f"{path}.turn: not an int ({obj.get('turn')!r})")
    records = obj.get("records")
    if not isinstance(records, list) or not records:
        problems.append(f"{path}.records: missing or empty")
    else:
        for i, r in enumerate(records):
            if not isinstance(r, Mapping) or not isinstance(r.get("kind"), str):
                problems.append(f"{path}.records[{i}]: no 'kind' string")
            elif not isinstance(r.get("t"), (int, float)):
                problems.append(f"{path}.records[{i}]: no numeric 't'")
        tail = records[-1]
        if isinstance(tail, Mapping) and tail.get("kind") != "abort":
            problems.append(
                f"{path}.records[-1]: tail must be the 'abort' record, "
                f"got kind={tail.get('kind')!r}"
            )
    if "metrics" in obj:
        problems.extend(check_metrics_snapshot(obj["metrics"], f"{path}.metrics"))
    return problems


def require_flight_record(obj) -> None:
    problems = check_flight_record(obj)
    if problems:
        raise MalformedFlightRecord("; ".join(problems))


def load_flight_record(path: str | Path) -> dict:
    """Read + schema-check one ``flight-*.json`` (the test/tooling entry)."""
    doc = json.loads(Path(path).read_text())
    require_flight_record(doc)
    return doc


def latest_flight_record(directory: str | Path) -> Path | None:
    """The newest ``flight-*.json`` under ``directory``, or None."""
    paths = sorted(Path(directory).glob("flight-*.json"))
    return paths[-1] if paths else None
