"""Fleet observability plane (ISSUE 19): the first subsystem whose unit
of observation is the FLEET, not the process.

Everything PRs 4-15 built — flight rings, ``/metrics`` + SLOs,
``/traces`` — is per-process: a failover is three disjoint stories on
three ports.  :class:`FleetCollector` is the device-less federation of
those surfaces:

- **Federated scrape plane.**  Every node's ``/metrics`` (OpenMetrics
  text, re-parsed via :func:`obs.openmetrics.parse`) + ``/healthz`` is
  scraped on a cadence into bounded per-node
  :class:`~distributed_gol_tpu.obs.timeseries.TelemetrySampler` rings
  (over :class:`~distributed_gol_tpu.obs.timeseries.SnapshotRegistry`
  shims), and a fleet-AGGREGATE ring samples their merge
  (:func:`obs.metrics.aggregate_snapshots`: counters sum, gauges max,
  histogram buckets sum).  ``/fleet/metrics`` re-exports ONE OpenMetrics
  page: the aggregate families unlabelled beside every node's families
  under a ``node=`` label.  A dead node's last-good snapshot stays in
  the aggregate (its counters are history, not state), which is exactly
  what makes a migrated tenant's fleet SLO budget CONTINUOUS — the
  budget window sums ``tenant=`` counters across every pod that ever
  ran the tenant.
- **Trace stitching.**  ``/fleet/traces/<id>`` fans the prefix lookup
  to every node's ``/traces`` (plus the local tracer when the collector
  rides in-broker) and merges the span forests on the shared trace id
  via :func:`obs.tracing.stitch_traces` — broker ``gol.broker.*``, pod
  ``gol.request``→dispatch, relay subscribe/first-frame, one timeline.
- **Merged postmortems.**  ``/fleet/flight`` time-orders the local
  (broker) flight ring, every node's ``/flight`` ring, and the on-disk
  ``flight-*.json`` abort dumps under the shared checkpoint root into
  one node-stamped sequence: a SIGKILL failover reads
  ``pod_condemned → failover → rejoin_readopt`` in one report.

Never-block contract (the PR 10 sampler staleness contract, fleet-
sized): scrapes use bounded per-node HTTP timeouts
(:class:`~distributed_gol_tpu.serve.podclient.PodClient` with
``attempts=1``); a wedged or dead node costs one bounded miss
(``fleet.scrape_misses{node=}``) per round, its ring simply stops
advancing, and its growing ``sample_age_seconds`` is surfaced in
``/fleet/healthz`` beside the ``staleness_bound_seconds`` the cadence
promises.  Every ``/fleet/*`` read is served from the rings — pure
in-memory (plus one bounded directory glob for ``/fleet/flight``) —
so a scrape storm or a dying pod can never wedge the observers.

Zero device deps: importable and runnable without jax, like the broker
and relay tiers.
"""

from __future__ import annotations

import threading
import time
from pathlib import Path
from typing import Mapping, Sequence
from urllib.parse import urlsplit

from distributed_gol_tpu.obs import metrics as metrics_lib
from distributed_gol_tpu.obs import openmetrics, tracing
from distributed_gol_tpu.obs.flight import load_flight_record
from distributed_gol_tpu.obs.timeseries import (
    SnapshotRegistry,
    TelemetrySampler,
    fraction_above,
)
from distributed_gol_tpu.serve.podclient import (
    PodClient,
    PodHTTPError,
    PodUnreachable,
)

FLEET_FLIGHT_SCHEMA = "gol-fleet-flight-v1"
FLEET_SLO_SCHEMA = "gol-fleet-slo-v1"

#: Mangled (post-:func:`openmetrics.parse`) spellings of the SLI
#: instruments the fleet burn math reads from the AGGREGATE ring — the
#: per-process :class:`obs.slo.SLOTracker` reads the unmangled names.
_M_DISPATCHES = "gol_controller_dispatches"
_M_FAILURES = "gol_controller_dispatch_failures"
_M_LATENCY = "gol_controller_dispatch_seconds"


def node_name(url: str) -> str:
    """The default ``node=`` label value for one scrape target: its
    ``host:port`` (stable, unique per endpoint, safe in the registry's
    ``{node=...}`` spelling — no ``,``/``=``/braces)."""
    net = urlsplit(url).netloc
    return net or url


def _empty_snapshot() -> dict:
    return {
        "schema": metrics_lib.SCHEMA,
        "counters": {},
        "gauges": {},
        "histograms": {},
        "info": {},
    }


class _Node:
    """One scrape target's books: transport, last-good scrape results,
    and the per-node time-series ring."""

    def __init__(self, name: str, url: str, timeout: float, interval: float,
                 depth: int, registry):
        self.name = name
        self.url = url
        self.client = PodClient(url, timeout=timeout, attempts=1)
        self.metrics: dict | None = None  # last-good parsed gol-metrics-v1
        self.health: dict | None = None  # last-good /healthz body
        self.consecutive_misses = 0
        self.last_error: str | None = None
        self.sampler = TelemetrySampler(
            registry=SnapshotRegistry(lambda: self.metrics, registry),
            interval=interval,
            depth=depth,
        )


class FleetCollector:
    """The device-less collector (module doc).  ``nodes`` maps node name
    → base URL (a plain URL sequence auto-names via :func:`node_name`).
    Rides in-broker (the broker delegates ``/fleet/*`` to
    :meth:`handle_http` and passes its flight ring as ``local_flight``)
    or standalone behind :class:`CollectorServer`.

    ``objectives`` (an :class:`obs.slo.SLOObjectives` or None) arms the
    fleet-level burn math ``/fleet/slo`` computes over the aggregate
    ring; without it the endpoint still reports per-tenant fleet
    dispatch totals (the budget-continuity surface).
    """

    def __init__(
        self,
        nodes: Mapping[str, str] | Sequence[str],
        interval: float = 0.5,
        scrape_timeout: float = 2.0,
        depth: int = 240,
        checkpoint_root: str | Path | None = None,
        objectives=None,
        local_name: str | None = None,
        local_flight=None,
        registry=None,
        start: bool = True,
    ):
        if interval <= 0:
            raise ValueError("collector interval must be positive")
        if scrape_timeout <= 0:
            raise ValueError("collector scrape timeout must be positive")
        if not isinstance(nodes, Mapping):
            nodes = {node_name(u): u for u in nodes}
        if not nodes:
            raise ValueError("a collector needs at least one node")
        self.interval = interval
        self.scrape_timeout = scrape_timeout
        self.checkpoint_root = (
            Path(checkpoint_root) if checkpoint_root is not None else None
        )
        self.objectives = objectives
        self.local_name = local_name
        self.local_flight = local_flight
        self.registry = (
            registry if registry is not None else metrics_lib.REGISTRY
        )
        self._nodes = {
            name: _Node(
                name, url, scrape_timeout, interval, depth, self.registry
            )
            for name, url in nodes.items()
        }
        self._agg: dict = _empty_snapshot()
        self._agg_sampler = TelemetrySampler(
            registry=SnapshotRegistry(lambda: self._agg, self.registry),
            interval=interval,
            depth=depth,
        )
        self._m_rounds = self.registry.counter("fleet.scrape_rounds")
        self._m_misses = {
            name: self.registry.counter(
                f"fleet.scrape_misses{{node={name}}}"
            )
            for name in self._nodes
        }
        self.registry.gauge("fleet.nodes").set(len(self._nodes))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if start:
            self.start()

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> "FleetCollector":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="gol-fleet-collector", daemon=True
        )
        self._thread.start()
        return self

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=timeout)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — observers never kill the host
                continue

    # -- the scrape round ------------------------------------------------------
    def scrape_once(self) -> None:
        """One round: scrape every node (bounded per-node timeouts),
        advance the per-node rings that answered, then re-aggregate and
        advance the fleet ring.  Public so tests drive rounds without
        wall-clock waits (the ``probe_once`` idiom)."""
        for node in self._nodes.values():
            self._scrape_node(node)
        merged = metrics_lib.aggregate_snapshots(
            [n.metrics for n in self._nodes.values() if n.metrics is not None]
        )
        self._agg = merged
        self._agg_sampler.sample_now(lazy=False)
        self._m_rounds.inc()

    def _scrape_node(self, node: _Node) -> None:
        try:
            doc = node.client.request("GET", "/metrics")
            text = doc.get("raw") if isinstance(doc, dict) else None
            if text is None:
                raise ValueError("/metrics did not return exposition text")
            node.metrics = openmetrics.parse(text)
            node.health = node.client.health()
            node.consecutive_misses = 0
            node.last_error = None
            node.sampler.sample_now(lazy=False)
        except (PodUnreachable, PodHTTPError, ValueError, OSError) as e:
            node.consecutive_misses += 1
            node.last_error = f"{type(e).__name__}: {e}"
            self._m_misses[node.name].inc()

    # -- /fleet/metrics --------------------------------------------------------
    def merged_snapshot(self) -> dict:
        """The export snapshot: fleet-aggregate families (unlabelled) +
        every node's families re-keyed under ``node=`` + the collector's
        own local instruments (``fleet.*`` and, riding in-broker, the
        ``broker.*`` families).  Pure ring/registry reads."""
        out = _empty_snapshot()
        for section in ("counters", "gauges", "histograms", "info"):
            out[section].update(self._agg.get(section, {}))
        for node in self._nodes.values():
            snap = node.metrics
            if snap is None:
                continue
            for section in ("counters", "gauges", "histograms", "info"):
                for key, v in snap.get(section, {}).items():
                    base, labels = openmetrics.split_all(key)
                    labels["node"] = node.name
                    out[section][openmetrics.spell(base, labels)] = v
        local = self.registry.snapshot(include_lazy=False).to_dict()
        for section in ("counters", "gauges", "histograms", "info"):
            for key, v in local.get(section, {}).items():
                base, labels = openmetrics.split_all(key)
                mangled = openmetrics.spell(
                    openmetrics.metric_name(base), labels
                )
                # An in-process pod sharing the collector's registry
                # already rides the aggregate — exporting its local
                # spelling too would render duplicate sample lines.
                if mangled in out[section]:
                    continue
                out[section][key] = v
        return out

    def render_metrics(self) -> str:
        return openmetrics.render(self.merged_snapshot())

    # -- /fleet/healthz --------------------------------------------------------
    def fleet_health(self) -> dict:
        """Fleet readiness + the per-node staleness contract: each node
        row carries ``sample_age_seconds`` (its ring's actual age)
        beside the ``staleness_bound_seconds`` the cadence promises —
        the PR 10 sampler contract, per scrape target.  ``stale`` marks
        a node whose last-good sample has outlived twice the bound."""
        now = time.time()
        nodes = {}
        ready = True
        bound = self.interval + self.scrape_timeout
        for node in self._nodes.values():
            age = node.sampler.staleness
            stale = age > 2 * bound
            node_ready = bool((node.health or {}).get("ready")) and not stale
            latest = node.sampler.latest()
            nodes[node.name] = {
                "url": node.url,
                "ready": node_ready,
                "stale": stale,
                "sample_age_seconds": (
                    round(age, 3) if age != float("inf") else None
                ),
                "last_sample_t": round(latest.t, 3) if latest else None,
                "consecutive_misses": node.consecutive_misses,
                "last_error": node.last_error,
            }
            ready = ready and node_ready
        agg_age = self._agg_sampler.staleness
        return {
            "fleet": True,
            "ready": ready,
            "nodes": nodes,
            "scrape_interval_seconds": self.interval,
            "staleness_bound_seconds": bound,
            "aggregate_sample_age_seconds": (
                round(agg_age, 3) if agg_age != float("inf") else None
            ),
            "t": round(now, 3),
        }

    # -- /fleet/slo ------------------------------------------------------------
    def fleet_slo(self) -> dict:
        """Per-tenant SLI/SLO rollup over the AGGREGATE ring — the fleet
        keeps one continuous series per tenant across migrations because
        the aggregate sums every pod's ``tenant=`` counters (dead pods'
        last-good snapshots included).  Burn rates mirror
        ``obs.slo.SLOTracker`` (bad_fraction / allowed per window, both
        windows over threshold = alerting) but read the mangled
        post-``parse`` instrument names."""
        obj = self.objectives
        sampler = self._agg_sampler
        latest = sampler.latest()
        out: dict = {
            "schema": FLEET_SLO_SCHEMA,
            "aggregate": True,
            "tenants": {},
        }
        if obj is not None:
            out["objectives"] = {
                "latency_seconds": obj.latency_seconds,
                "latency_percentile": obj.latency_percentile,
                "error_rate": obj.error_rate,
                "fast_window_seconds": obj.fast_window_seconds,
                "slow_window_seconds": obj.slow_window_seconds,
                "burn_threshold": obj.burn_threshold,
                "budget_window_seconds": obj.budget_window_seconds,
            }
        if latest is None:
            return out
        tenants = set()
        for key in latest.snapshot.get("counters", {}):
            base, labels = openmetrics.split_all(key)
            if base == _M_DISPATCHES and "tenant" in labels:
                tenants.add(labels["tenant"])
        windows = [("budget", None if obj is None else obj.budget_window_seconds)]
        if obj is not None:
            windows = [
                ("fast", obj.fast_window_seconds),
                ("slow", obj.slow_window_seconds),
                ("budget", obj.budget_window_seconds),
            ]
        for tenant in sorted(tenants):
            d_key = openmetrics.spell(_M_DISPATCHES, {"tenant": tenant})
            f_key = openmetrics.spell(_M_FAILURES, {"tenant": tenant})
            h_key = openmetrics.spell(_M_LATENCY, {"tenant": tenant})
            row: dict = {
                "dispatches_total": latest.snapshot["counters"].get(d_key, 0),
                "failures_total": latest.snapshot["counters"].get(
                    f_key, 0
                ),
            }
            alerting = []
            for wname, seconds in windows:
                w = sampler.window(seconds)
                if w is None:
                    continue
                old, new = w
                oc = old.snapshot.get("counters", {})
                nc = new.snapshot.get("counters", {})
                dd = nc.get(d_key, 0) - oc.get(d_key, 0)
                fd = nc.get(f_key, 0) - oc.get(f_key, 0)
                wrow: dict = {
                    "window_seconds": round(new.t - old.t, 3),
                    "dispatches": dd,
                    "failures": fd,
                }
                if obj is not None and obj.latency_seconds > 0:
                    bad = fraction_above(
                        new.snapshot.get("histograms", {}).get(h_key),
                        old.snapshot.get("histograms", {}).get(h_key),
                        obj.latency_seconds,
                    )
                    allowed = 1.0 - obj.latency_percentile
                    if bad is not None:
                        wrow["latency_bad_fraction"] = round(bad, 6)
                        wrow["latency_burn"] = round(bad / allowed, 3)
                if obj is not None and obj.error_rate > 0 and dd > 0:
                    err = fd / dd
                    wrow["error_fraction"] = round(err, 6)
                    wrow["error_burn"] = round(err / obj.error_rate, 3)
                row[wname] = wrow
            if obj is not None and "fast" in row and "slow" in row:
                for kind in ("latency", "error"):
                    fast = row["fast"].get(f"{kind}_burn")
                    slow = row["slow"].get(f"{kind}_burn")
                    if (
                        fast is not None
                        and slow is not None
                        and fast > obj.burn_threshold
                        and slow > obj.burn_threshold
                    ):
                        alerting.append(kind)
            if obj is not None and "budget" in row:
                budget = row["budget"]
                remaining = 1.0
                if obj.latency_seconds > 0:
                    bad = budget.get("latency_bad_fraction")
                    if bad is not None:
                        allowed = 1.0 - obj.latency_percentile
                        remaining = min(
                            remaining, max(0.0, 1.0 - bad / allowed)
                        )
                if obj.error_rate > 0:
                    err = budget.get("error_fraction")
                    if err is not None:
                        remaining = min(
                            remaining,
                            max(0.0, 1.0 - err / obj.error_rate),
                        )
                row["budget_remaining"] = round(remaining, 6)
            row["alerting"] = alerting
            out["tenants"][tenant] = row
        return out

    # -- /fleet/traces ---------------------------------------------------------
    def stitched_trace(self, trace_id: str) -> dict | None:
        """Fan ``GET /traces?trace_id=&all=1`` to every node (bounded
        by the scrape timeout), include every leg the local tracer
        retains when riding in-broker, and merge on the shared id.
        The ``all`` form matters: one process can hold a finished
        request leg AND a live relay leg on the same id, and the
        stitch wants both lanes."""
        hits: dict[str, list[dict]] = {}
        if self.local_name is not None:
            docs = tracing.TRACER.lookup_all(trace_id)
            if docs:
                hits[self.local_name] = docs
        for node in self._nodes.values():
            try:
                doc = node.client.request(
                    "GET", f"/traces?trace_id={trace_id}&all=1"
                )
            except (PodUnreachable, PodHTTPError, OSError):
                continue
            if not isinstance(doc, dict):
                continue
            if isinstance(doc.get("traces"), list):
                hits.setdefault(node.name, []).extend(
                    d for d in doc["traces"]
                    if isinstance(d, dict) and d.get("trace_id")
                )
            elif doc.get("trace_id"):
                # A node that predates the ``all`` form answers with
                # its single best leg — still stitchable.
                hits.setdefault(node.name, []).append(doc)
        return tracing.stitch_traces(hits)

    # -- /fleet/flight ---------------------------------------------------------
    def merged_flight(self, limit: int = 512) -> dict:
        """One time-ordered, node-stamped postmortem sequence: the local
        (broker) ring, every node's ``/flight`` ring, and the abort
        dumps parked as ``flight-*.json`` under the shared checkpoint
        root.  Nodes without a ``/flight`` surface (or dead ones) are
        skipped — their on-disk dumps still tell their half."""
        records: list[dict] = []
        sources: list[str] = []
        if self.local_flight is not None and self.local_name is not None:
            sources.append(self.local_name)
            for r in self.local_flight.records():
                records.append({**r, "node": self.local_name})
        for node in self._nodes.values():
            try:
                doc = node.client.request("GET", "/flight")
            except (PodUnreachable, PodHTTPError, OSError):
                continue
            if isinstance(doc, dict) and isinstance(doc.get("records"), list):
                sources.append(node.name)
                for r in doc["records"]:
                    if isinstance(r, dict):
                        records.append({**r, "node": node.name})
        if self.checkpoint_root is not None and self.checkpoint_root.is_dir():
            for path in sorted(self.checkpoint_root.rglob("flight-*.json")):
                try:
                    doc = load_flight_record(path)
                except (OSError, ValueError):
                    continue
                src = str(path.relative_to(self.checkpoint_root))
                sources.append(f"dump:{src}")
                stamp = {
                    "node": f"dump:{src}",
                    "cause": doc.get("cause"),
                }
                for r in doc.get("records", []):
                    if isinstance(r, dict):
                        records.append({**r, **stamp})
        records.sort(key=lambda r: r.get("t", 0))
        if limit > 0:
            records = records[-limit:]
        return {
            "schema": FLEET_FLIGHT_SCHEMA,
            "records": records,
            "sources": sources,
        }

    # -- the shared HTTP face --------------------------------------------------
    def handle_http(self, request, method: str, path: str, query: dict) -> bool:
        """``/fleet/*`` routing, shared by the in-broker rider and the
        standalone :class:`CollectorServer` (same contract as
        ``StdlibHTTPServer.handle``: True = handled)."""
        if method != "GET" or not path.startswith("/fleet"):
            return False
        if path == "/fleet/metrics":
            request._send(
                200,
                self.render_metrics().encode(),
                openmetrics.CONTENT_TYPE,
            )
            return True
        if path == "/fleet/healthz":
            health = self.fleet_health()
            request._send_json(200 if health["ready"] else 503, health)
            return True
        if path == "/fleet/slo":
            request._send_json(200, self.fleet_slo())
            return True
        if path == "/fleet/flight":
            try:
                limit = int(query.get("limit", 512))
            except ValueError:
                request._send_json(400, {"error": "bad limit"})
                return True
            request._send_json(200, self.merged_flight(limit=limit))
            return True
        if path == "/fleet/traces" or path.startswith("/fleet/traces/"):
            trace_id = (
                path.rpartition("/")[2]
                if path.startswith("/fleet/traces/")
                else query.get("trace_id", "")
            )
            if not trace_id:
                request._send_json(
                    400, {"error": "need /fleet/traces/<id> or ?trace_id="}
                )
                return True
            doc = self.stitched_trace(trace_id)
            if doc is None:
                request._send_json(
                    404, {"error": f"no node retains trace {trace_id!r}"}
                )
                return True
            request._send_json(200, doc)
            return True
        return False


class CollectorServer:
    """The standalone surface: ``python -m distributed_gol_tpu collector
    --node URL...`` — a :class:`FleetCollector` behind its own HTTP
    port.  ``/healthz`` and ``/metrics`` alias the fleet forms so one
    ``tools/pod_top.py --fleet`` scrape (or any OpenMetrics scraper
    pointed at the collector) needs no ``/fleet`` prefix."""

    def __init__(self, collector: FleetCollector, port: int = 0,
                 host: str = "127.0.0.1"):
        # Local import: serve.httpd is stdlib-only, but keep obs/fleet
        # importable even if the serve package grows heavier imports.
        from distributed_gol_tpu.serve.httpd import StdlibHTTPServer

        self.collector = collector
        outer = self

        class _Server(StdlibHTTPServer):
            thread_name = "gol-collector-http"

            def handle(self, request, method, path, query):
                if path == "/healthz":
                    path = "/fleet/healthz"
                elif path == "/metrics":
                    path = "/fleet/metrics"
                elif path == "/traces" or path.startswith("/traces/"):
                    path = "/fleet" + path
                return outer.collector.handle_http(
                    request, method, path, query
                )

        self._server = _Server(port=port, host=host)
        self.collector.registry.info("fleet.endpoint", self._server.url)

    @property
    def url(self) -> str:
        return self._server.url

    def close(self) -> None:
        self._server.close()
        self.collector.close()


__all__ = [
    "FLEET_FLIGHT_SCHEMA",
    "FLEET_SLO_SCHEMA",
    "CollectorServer",
    "FleetCollector",
    "node_name",
]
