"""OpenMetrics rendering of ``gol-metrics-v1`` snapshots (ISSUE 12, layer 2).

The registry's snapshot dict is the internal truth; this module is the
wire adapter an external scraper (Prometheus & friends) understands.
Mapping rules (documented in docs/API.md "Telemetry export"):

- **Names** — ``gol_`` prefix, every char outside ``[a-zA-Z0-9_]``
  becomes ``_`` (``controller.dispatch_seconds`` →
  ``gol_controller_dispatch_seconds``; the engine tier in
  ``backend.dispatches.pallas-packed`` mangles the same way).
- **Tenant labels** — the flat registry spells a tenant-labelled
  instrument ``name{tenant=x}`` (:func:`obs.metrics.labelled`); the
  renderer parses that suffix back into a REAL OpenMetrics label
  (``gol_controller_turns_total{tenant="x"}``), so one scrape separates
  tenants the way the serving plane promised.
- **Counters** — ``# TYPE ... counter`` with the ``_total`` sample name.
- **Gauges** — ``# TYPE ... gauge``.
- **Histograms** — ``# TYPE ... histogram``: cumulative ``_bucket``
  samples with ``le`` labels (upper bounds rendered via ``repr`` so they
  re-parse to the identical float), the ``le="+Inf"`` bucket, ``_sum``
  and ``_count``.
- **Info** — each registry info label becomes its own info family:
  ``# TYPE gol_backend_engine info`` +
  ``gol_backend_engine_info{value="pallas-packed"} 1``.

:func:`parse` is the inverse (modulo the lossy name mangling: dots came
back as underscores), producing a schema-valid ``gol-metrics-v1`` dict —
:func:`check_roundtrip` renders + re-parses + lints + value-compares a
snapshot in one call, which is what the property tests run on every
snapshot the suite produces.
"""

from __future__ import annotations

import re
from typing import Mapping

from distributed_gol_tpu.obs.metrics import (
    SCHEMA,
    check_metrics_snapshot,
    tenant_of,
)

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_]")
_SAMPLE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)\s*$")
_LABEL = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')
#: Sample-scoped labels :func:`parse` must NOT fold back into the
#: registry key (``le`` belongs to a bucket, ``value`` to an info line).
_RESERVED_LABELS = frozenset({"le", "value"})

CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def metric_name(name: str) -> str:
    """The OpenMetrics family name for one registry instrument name
    (WITHOUT its ``{tenant=...}`` suffix — strip via :func:`split_name`
    first).  Idempotent: an already-mangled family name (as the fleet
    collector re-renders after :func:`parse`) passes through unchanged
    instead of growing a second ``gol_`` prefix."""
    if name.startswith("gol_") and not _NAME_BAD.search(name):
        return name
    return "gol_" + _NAME_BAD.sub("_", name)


def split_all(name: str) -> tuple[str, dict[str, str]]:
    """Registry name → (base name, labels dict).  The generalised form of
    :func:`split_name` for the fleet plane's multi-label spelling
    (``name{node=a,tenant=b}``): the trailing ``{k=v,...}`` suffix is
    parsed into a dict; a name whose brace suffix is not label-shaped
    (every comma-part carrying ``=``) comes back unlabelled."""
    if not name.endswith("}") or "{" not in name:
        return name, {}
    base, _, suffix = name.rpartition("{")
    labels: dict[str, str] = {}
    for part in suffix[:-1].split(","):
        k, eq, v = part.partition("=")
        if not eq or not k:
            return name, {}
        labels[k] = v
    return base, labels


def spell(base: str, labels: Mapping[str, str]) -> str:
    """Inverse of :func:`split_all`: the registry spelling of a labelled
    instrument, label keys sorted so one (base, labels) set always maps
    to one snapshot key (``{node=...}`` sorts before ``{tenant=...}``)."""
    if not labels:
        return base
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{base}{{{inner}}}"


def split_name(name: str) -> tuple[str, str | None]:
    """Registry name → (base name, tenant or None)."""
    t = tenant_of(name)
    if t is not None:
        return name[: name.rindex("{")], t
    base, labels = split_all(name)
    return (base, labels["tenant"]) if "tenant" in labels else (name, None)


def _esc(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels(labels: Mapping[str, str], extra: str | None = None) -> str:
    parts = []
    if extra:
        parts.append(extra)
    for k in sorted(labels):
        parts.append(f'{k}="{_esc(str(labels[k]))}"')
    return "{" + ",".join(parts) + "}" if parts else ""


def _num(v) -> str:
    # repr round-trips floats exactly; ints render without a dot.
    return repr(int(v)) if isinstance(v, int) or float(v).is_integer() else repr(
        float(v)
    )


def render(snapshot: Mapping) -> str:
    """One ``gol-metrics-v1`` snapshot dict → OpenMetrics exposition
    text (ends with ``# EOF``).  Pure function of the dict: bounded-time
    by construction, never touches a device."""
    families: dict[str, dict] = {}

    def family(base: str, kind: str) -> list:
        fam = families.setdefault(
            metric_name(base), {"kind": kind, "lines": []}
        )
        return fam["lines"]

    for name, v in snapshot.get("counters", {}).items():
        base, labels = split_all(name)
        family(base, "counter").append((labels, None, v))
    for name, v in snapshot.get("gauges", {}).items():
        base, labels = split_all(name)
        family(base, "gauge").append((labels, None, v))
    for name, h in snapshot.get("histograms", {}).items():
        base, labels = split_all(name)
        family(base, "histogram").append((labels, None, h))
    for name, v in snapshot.get("info", {}).items():
        base, labels = split_all(name)
        family(base, "info").append((labels, None, v))

    out: list[str] = []
    for fname in sorted(families):
        fam = families[fname]
        kind = fam["kind"]
        out.append(f"# TYPE {fname} {kind}")
        for labels, _, v in fam["lines"]:
            if kind == "counter":
                out.append(f"{fname}_total{_labels(labels)} {_num(v)}")
            elif kind == "gauge":
                out.append(f"{fname}{_labels(labels)} {_num(v)}")
            elif kind == "info":
                value_label = 'value="' + _esc(str(v)) + '"'
                out.append(f"{fname}_info{_labels(labels, value_label)} 1")
            else:  # histogram
                cum = 0
                for bound, count in zip(v["buckets"], v["counts"]):
                    cum += count
                    le = 'le="' + repr(float(bound)) + '"'
                    out.append(f"{fname}_bucket{_labels(labels, le)} {cum}")
                inf_le = 'le="+Inf"'
                out.append(
                    f"{fname}_bucket{_labels(labels, inf_le)} {v['count']}"
                )
                out.append(f"{fname}_sum{_labels(labels)} {_num(v['sum'])}")
                out.append(f"{fname}_count{_labels(labels)} {v['count']}")
    out.append("# EOF")
    return "\n".join(out) + "\n"


def parse(text: str) -> dict:
    """OpenMetrics exposition text (as :func:`render` produces) back into
    a ``gol-metrics-v1`` dict.  Names stay in their mangled form (the
    dot→underscore mapping is lossy by design); tenant — and, on the
    fleet plane, ``node=`` — labels are folded back into the registry's
    ``name{k=v,...}`` spelling via :func:`spell`, so the result
    round-trips through :func:`obs.metrics.check_metrics_snapshot`."""
    kinds: dict[str, str] = {}
    # family -> tenant -> accumulated state
    hists: dict[str, dict] = {}
    out = {
        "schema": SCHEMA,
        "counters": {},
        "gauges": {},
        "histograms": {},
        "info": {},
    }
    for line in text.splitlines():
        line = line.strip()
        if not line or line == "# EOF":
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            fam, _, kind = rest.partition(" ")
            kinds[fam] = kind.strip()
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if m is None:
            raise ValueError(f"unparseable OpenMetrics sample: {line!r}")
        sample, labelstr, value = m.group(1), m.group(2) or "", m.group(3)
        labels = {
            k: v.replace('\\"', '"').replace("\\n", "\n").replace("\\\\", "\\")
            for k, v in _LABEL.findall(labelstr)
        }
        key_labels = {
            k: v for k, v in labels.items() if k not in _RESERVED_LABELS
        }
        # Resolve the family by stripping the kind-specific suffix and
        # checking the TYPE line registered that family with the kind
        # the suffix implies; bare names resolve as gauges last, so a
        # histogram's `_sum` can never be read as a gauge named `.._sum`.
        resolved = None
        for suffix, want in (
            ("_bucket", "histogram"),
            ("_total", "counter"),
            ("_info", "info"),
            ("_sum", "histogram"),
            ("_count", "histogram"),
            ("", "gauge"),
        ):
            fam = sample[: -len(suffix)] if suffix else sample
            if (suffix == "" or sample.endswith(suffix)) and kinds.get(
                fam
            ) == want:
                resolved = (fam, want, suffix)
                break
        if resolved is None:
            raise ValueError(f"sample names no declared family: {line!r}")
        fam, kind, hit = resolved
        key = spell(fam, key_labels)
        if kind == "counter":
            out["counters"][key] = float(value)
        elif kind == "gauge":
            out["gauges"][key] = float(value)
        elif kind == "info":
            out["info"][key] = labels.get("value", "")
        else:
            h = hists.setdefault(key, {"buckets": [], "cum": [], "inf": 0})
            if hit == "_bucket":
                le = labels.get("le", "")
                if le == "+Inf":
                    h["inf"] = int(float(value))
                else:
                    h["buckets"].append(float(le))
                    h["cum"].append(int(float(value)))
            elif hit == "_sum":
                h["sum"] = float(value)
            else:
                h["cnt"] = int(float(value))
    for key, h in hists.items():
        pairs = sorted(zip(h["buckets"], h["cum"]))
        bounds = [b for b, _ in pairs]
        cum = [c for _, c in pairs]
        counts = [c - (cum[i - 1] if i else 0) for i, c in enumerate(cum)]
        counts.append(h["inf"] - (cum[-1] if cum else 0))
        out["histograms"][key] = {
            "buckets": bounds,
            "counts": counts,
            "sum": h.get("sum", 0.0),
            "count": h.get("cnt", h["inf"]),
        }
    # Counters that came back integral stay ints (histogram counts already
    # are): the schema allows floats, but value comparison in round-trip
    # tests is cleaner this way.
    out["counters"] = {
        k: int(v) if v.is_integer() else v for k, v in out["counters"].items()
    }
    return out


def check_roundtrip(snapshot: Mapping) -> list[str]:
    """Render ``snapshot``, re-parse the text, lint the result against
    the ``gol-metrics-v1`` schema, and compare every value through the
    name mangling.  Returns violations (empty = clean) — the property
    check the test suite runs on every snapshot it produces."""
    problems = []
    try:
        text = render(snapshot)
    except Exception as e:  # noqa: BLE001
        return [f"render failed: {type(e).__name__}: {e}"]
    try:
        parsed = parse(text)
    except Exception as e:  # noqa: BLE001
        return [f"parse failed: {type(e).__name__}: {e}"]
    problems.extend(check_metrics_snapshot(parsed, "$roundtrip"))

    def mangled(name: str) -> str:
        base, labels = split_all(name)
        return spell(metric_name(base), labels)

    for section in ("counters", "gauges"):
        for name, v in snapshot.get(section, {}).items():
            got = parsed[section].get(mangled(name))
            if got is None or abs(float(got) - float(v)) > 1e-9:
                problems.append(f"{section}.{name}: {v!r} came back as {got!r}")
    for name, h in snapshot.get("histograms", {}).items():
        got = parsed["histograms"].get(mangled(name))
        if got is None:
            problems.append(f"histograms.{name}: lost in round-trip")
            continue
        if list(got["buckets"]) != [float(b) for b in h["buckets"]]:
            problems.append(f"histograms.{name}: bucket bounds changed")
        if list(got["counts"]) != list(h["counts"]):
            problems.append(f"histograms.{name}: counts changed")
        if abs(got["sum"] - h["sum"]) > 1e-9 or got["count"] != h["count"]:
            problems.append(f"histograms.{name}: sum/count changed")
    for name, v in snapshot.get("info", {}).items():
        got = parsed["info"].get(mangled(name))
        if got != str(v):
            problems.append(f"info.{name}: {v!r} came back as {got!r}")
    return problems
