"""Metrics registry: process-wide counters, gauges, fixed-bucket histograms.

Design constraints (ISSUE 4 tentpole):

- **Near-zero clean-path cost.**  A bump is a plain attribute operation on
  a pre-resolved instrument object — no locks, no dict lookups, no string
  formatting on the dispatch path.  Instruments are resolved ONCE (at
  controller/backend construction, the cold path, under a lock) and held
  as attributes; concurrent bumps may lose the occasional increment under
  free-threading, which is the standard serving-stack trade (a metric is
  telemetry, not an invariant).
- **Snapshot-on-read.**  Nothing is aggregated until someone asks:
  :meth:`MetricsRegistry.snapshot` walks the instruments and copies their
  values into a plain-dict :class:`MetricsSnapshot`.  Expensive or lazy
  values (skip fraction, compile-cache hit counts) register as
  *callback gauges* (:meth:`MetricsRegistry.gauge_fn`) and are evaluated
  only at snapshot time.
- **Schema-linted artifacts.**  Every embedded snapshot — ``bench.py``
  records, ``Session`` checkpoint sidecars, flight records, the terminal
  :class:`~distributed_gol_tpu.engine.events.MetricsReport` — carries the
  ``gol-metrics-v1`` shape, and :func:`check_metrics_snapshot` /
  :func:`require_metrics_snapshot` lint it exactly the way
  ``measure.check_headline_stats`` lints bench records.

The process-wide default registry is :data:`REGISTRY`;
``Params.metrics=False`` swaps in :data:`NULL` (same interface, no-op
instruments, empty snapshots) via :func:`registry_for`, so instrumented
code never branches.
"""

from __future__ import annotations

import json
import math
import threading
from bisect import bisect_left
from typing import Callable, Mapping, Sequence

from distributed_gol_tpu.engine.events import TurnTiming

SCHEMA = "gol-metrics-v1"

# Dispatch/checkpoint latency buckets (seconds): sub-ms async issues up to
# the tens-of-seconds first-dispatch jit compile at 16384²-class boards.
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0,
)


class MalformedSnapshot(ValueError):
    """A metrics snapshot violated the ``gol-metrics-v1`` schema."""


class Counter:
    """Monotonic accumulator.  ``inc`` is one attribute add — the whole
    point; never put a lock here."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: float = 1) -> None:
        self.value += n


class Gauge:
    """Last-written value (None = never set, omitted from snapshots)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = None

    def set(self, v: float) -> None:
        self.value = v


class Histogram:
    """Fixed-bucket histogram: ``counts[i]`` covers values ≤ ``buckets[i]``
    (first bucket that fits), with one overflow slot past the last bound —
    so ``len(counts) == len(buckets) + 1`` and ``count == sum(counts)``,
    which is exactly what the schema lint checks."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS):
        bounds = tuple(float(b) for b in buckets)
        if not bounds or any(a >= b for a, b in zip(bounds, bounds[1:])):
            raise ValueError("histogram buckets must be strictly increasing")
        self.buckets = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(self.buckets, v)] += 1
        self.sum += v
        self.count += 1


class MetricsSnapshot:
    """A point-in-time copy of a registry, as a plain ``gol-metrics-v1``
    dict (:attr:`data`) ready for JSON embedding."""

    def __init__(self, data: dict):
        self.data = data

    def to_dict(self) -> dict:
        return self.data

    def to_json(self) -> str:
        return json.dumps(self.data, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "MetricsSnapshot":
        snap = cls(json.loads(text))
        require_metrics_snapshot(snap.data)
        return snap

    def delta(self, earlier: "MetricsSnapshot | dict") -> "MetricsSnapshot":
        """This snapshot minus ``earlier`` — the per-run view of a
        process-wide registry (counters and histogram counts subtract;
        gauges and info keep this snapshot's values, they are not
        cumulative)."""
        base = earlier.data if isinstance(earlier, MetricsSnapshot) else earlier
        bc = base.get("counters", {})
        # Untouched instruments are DROPPED from the delta (not emitted as
        # zeros): a run's report describes what that run did, not every
        # counter the process ever created.
        counters = {
            k: v - bc.get(k, 0)
            for k, v in self.data.get("counters", {}).items()
            if v - bc.get(k, 0)
        }
        bh = base.get("histograms", {})
        histograms = {}
        for k, h in self.data.get("histograms", {}).items():
            prev = bh.get(k)
            if prev and prev.get("buckets") == h["buckets"]:
                d = {
                    "buckets": list(h["buckets"]),
                    "counts": [a - b for a, b in zip(h["counts"], prev["counts"])],
                    "sum": h["sum"] - prev["sum"],
                    "count": h["count"] - prev["count"],
                }
            else:
                d = {
                    "buckets": list(h["buckets"]),
                    "counts": list(h["counts"]),
                    "sum": h["sum"],
                    "count": h["count"],
                }
            if d["count"]:
                histograms[k] = d
        return MetricsSnapshot(
            {
                "schema": SCHEMA,
                "counters": counters,
                "gauges": dict(self.data.get("gauges", {})),
                "histograms": histograms,
                "info": dict(self.data.get("info", {})),
            }
        )


def new_run_id(tenant: str | None = None) -> str:
    """A fresh correlation id (ISSUE 12): stamped once per logical run
    (stable across supervisor restarts) on the terminal
    ``MetricsReport``, every flight dump, and every checkpoint sidecar,
    so a scrape series, a postmortem, and a resumed session join
    offline.  Tenant-prefixed for human-greppable artifacts."""
    import uuid

    suffix = uuid.uuid4().hex[:12]
    return f"{tenant}-{suffix}" if tenant else suffix


def labelled(name: str, tenant: str | None = None) -> str:
    """Instrument name carrying a ``tenant=`` label (ISSUE 6): the flat
    registry stays flat — a labelled instrument is just a distinct name,
    ``name{tenant=x}`` — so one process-wide snapshot separates tenants
    multiplexed through the serving plane, and deltas/aggregation/lint
    need no label machinery.  ``tenant=None`` returns ``name`` unchanged:
    untenanted runs keep the exact pre-serving metric names."""
    return name if tenant is None else f"{name}{{tenant={tenant}}}"


def tenant_of(name: str) -> str | None:
    """Inverse of :func:`labelled`: the tenant a snapshot key belongs to
    (None = untenanted) — what per-tenant rollups key on."""
    if name.endswith("}") and "{tenant=" in name:
        return name[name.rindex("{tenant=") + 8 : -1]
    return None


class MetricsRegistry:
    """Named instruments; creation is locked (cold path), bumps are not
    (hot path).  ``snapshot()`` is the only aggregation point."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._gauge_fns: dict[str, Callable[[], float | None]] = {}
        self._info: dict[str, str] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter())

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge())

    def histogram(
        self, name: str, buckets: Sequence[float] = DEFAULT_BUCKETS
    ) -> Histogram:
        with self._lock:
            return self._histograms.setdefault(name, Histogram(buckets))

    def gauge_fn(self, name: str, fn: Callable[[], float | None]) -> None:
        """Register a snapshot-time callback gauge: ``fn`` is called only
        when a snapshot is taken; returning None omits the gauge.  Latest
        registration under a name wins (a new run's backend replaces the
        previous run's callbacks)."""
        with self._lock:
            self._gauge_fns[name] = fn

    def info(self, name: str, value: str) -> None:
        """A string-valued label (engine in use, exchange tier, ...)."""
        with self._lock:
            self._info[name] = str(value)

    def clear_tenant(self, tenant: str) -> None:
        """Drop every instrument carrying this ``tenant=`` label — the
        serving plane's eviction hook (ISSUE 6): a pod serving churning
        tenant names must not grow the registry without bound.  Unlike
        :meth:`clear_labels`, COUNTERS go too: an evicted tenant's
        series is over (its run is terminal, nothing bumps the orphaned
        instruments again), and snapshot deltas tolerate missing keys."""
        with self._lock:
            suffix = f"{{tenant={tenant}}}"
            for store in (
                self._counters,
                self._gauges,
                self._histograms,
                self._gauge_fns,
                self._info,
            ):
                for k in [k for k in store if k.endswith(suffix)]:
                    del store[k]

    def clear_labels(self, prefix: str) -> None:
        """Drop every gauge, callback gauge, and info label under
        ``prefix``.  The run-scoped reset: a new Backend clears
        ``backend.`` before registering its own, so a run's snapshot
        cannot carry a PREVIOUS run's tier label or skip fraction — and
        the old backend's bound-method callbacks stop pinning it alive.
        Counters are cumulative by design and stay (deltas subtract
        them correctly)."""
        with self._lock:
            for store in (self._gauges, self._gauge_fns, self._info):
                for k in [k for k in store if k.startswith(prefix)]:
                    del store[k]

    def snapshot(self, include_lazy: bool = True) -> MetricsSnapshot:
        """``include_lazy=False`` skips the callback gauges: abort-path
        snapshots (the flight dump) must not force device values — a
        wedged device would turn the postmortem into the very unbounded
        hang it documents."""
        with self._lock:
            counters = {k: c.value for k, c in self._counters.items()}
            gauges = {
                k: g.value for k, g in self._gauges.items() if g.value is not None
            }
            histograms = {
                k: {
                    "buckets": list(h.buckets),
                    "counts": list(h.counts),
                    "sum": h.sum,
                    "count": h.count,
                }
                for k, h in self._histograms.items()
            }
            fns = list(self._gauge_fns.items()) if include_lazy else []
            info = dict(self._info)
        for name, fn in fns:
            try:
                v = fn()
            except Exception:  # noqa: BLE001 — telemetry must not take a run down
                continue
            if v is not None:
                gauges[name] = float(v)
        return MetricsSnapshot(
            {
                "schema": SCHEMA,
                "counters": counters,
                "gauges": gauges,
                "histograms": histograms,
                "info": info,
            }
        )


class _NullInstrument:
    __slots__ = ()

    def inc(self, n: float = 1) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass


_NULL_INSTRUMENT = _NullInstrument()


class NullRegistry:
    """The ``Params.metrics=False`` registry: same interface, no state —
    instrumented code never branches on whether metrics are on."""

    def counter(self, name: str) -> _NullInstrument:
        return _NULL_INSTRUMENT

    def gauge(self, name: str) -> _NullInstrument:
        return _NULL_INSTRUMENT

    def histogram(self, name: str, buckets=DEFAULT_BUCKETS) -> _NullInstrument:
        return _NULL_INSTRUMENT

    def gauge_fn(self, name: str, fn) -> None:
        pass

    def info(self, name: str, value: str) -> None:
        pass

    def clear_labels(self, prefix: str) -> None:
        pass

    def clear_tenant(self, tenant: str) -> None:
        pass

    def snapshot(self, include_lazy: bool = True) -> MetricsSnapshot:
        return MetricsSnapshot(
            {
                "schema": SCHEMA,
                "counters": {},
                "gauges": {},
                "histograms": {},
                "info": {},
            }
        )


#: The process-wide registry every instrumented component resolves from.
REGISTRY = MetricsRegistry()
#: The no-op registry ``Params.metrics=False`` swaps in.
NULL = NullRegistry()


def registry_for(enabled: bool) -> MetricsRegistry | NullRegistry:
    return REGISTRY if enabled else NULL


class DispatchRecorder:
    """The one home of per-dispatch instrumentation — the unified form of
    the two hand-rolled ``TurnTiming`` emission sites the controller used
    to carry (sync viewer path and pipelined headless resolve): timing
    events, metrics bumps, and the flight-ring dispatch record can never
    drift between paths again (ISSUE 4 satellite)."""

    def __init__(
        self,
        registry,
        flight,
        emit: Callable[[object], None],
        emit_timing: bool = False,
        qsize: Callable[[], int] | None = None,
        tenant: str | None = None,
        trace=None,
    ):
        self._flight = flight
        self._emit = emit
        self._emit_timing = emit_timing
        self._qsize = qsize
        # Request trace (ISSUE 15): when the run serves a traced request
        # the recorder stamps the trace's short id on flight dispatch
        # records and observes the time-to-first-dispatch SLI off the
        # trace's first-occurrence mark.  None (the untraced default)
        # keeps the hot path at one attribute compare.
        self._trace = trace
        # ``tenant`` labels every instrument (ISSUE 6 satellite): N
        # sessions multiplexed onto one process-wide registry stay
        # separable in a single snapshot — and the labels ride the run's
        # delta into checkpoint sidecars and the terminal MetricsReport.
        self._c_dispatches = registry.counter(
            labelled("controller.dispatches", tenant)
        )
        self._c_turns = registry.counter(labelled("controller.turns", tenant))
        self._h_seconds = registry.histogram(
            labelled("controller.dispatch_seconds", tenant)
        )
        self._g_superstep = registry.gauge(
            labelled("controller.superstep", tenant)
        )
        self._g_qdepth = registry.gauge(
            labelled("controller.event_queue_depth", tenant)
        )
        # Failed dispatch ATTEMPTS, tenant-labelled (ISSUE 12): beside
        # the per-cause ``faults.failures.<Type>`` counters, this is the
        # per-tenant series the SLO tracker's error-rate objective reads
        # off the sampler ring.
        self._c_failures = registry.counter(
            labelled("controller.dispatch_failures", tenant)
        )
        # Time-to-first-dispatch SLI (ISSUE 15): request start (trace
        # t0) → first RESOLVED dispatch, per tenant — the "how long until
        # this request computed anything" histogram the SLO machinery
        # was missing.  Observed once per request, only for traced runs.
        self._h_ttfd = registry.histogram(
            labelled("sli.time_to_first_dispatch_seconds", tenant)
        )
        self.last_turn = 0  # the abort path's best known turn

    def record(self, turn: int, k: int, seconds: float) -> None:
        """One resolved dispatch: ``k`` generations ending at ``turn``
        took ``seconds`` of wall-clock (same dt semantics each caller
        already measured)."""
        self._c_dispatches.inc()
        self._c_turns.inc(k)
        self._h_seconds.observe(seconds)
        self._g_superstep.set(k)
        if self._qsize is not None:
            self._g_qdepth.set(self._qsize())
        if self._trace is None:
            self._flight.record(
                "dispatch", turn=turn, k=k, s=round(seconds, 6)
            )
        else:
            # The flight↔trace correlation (ISSUE 15): dispatch records
            # carry the trace's short id, so `flight_report` joins a
            # postmortem ring to the request timeline.
            self._flight.record(
                "dispatch",
                turn=turn,
                k=k,
                s=round(seconds, 6),
                trace=self._trace.short_id,
            )
            first = self._trace.mark("first_dispatch")
            if first is not None:
                self._h_ttfd.observe(first)
        self.last_turn = turn
        if self._emit_timing:
            self._emit(TurnTiming(turn, k, seconds))

    def record_failure(self) -> None:
        """One failed dispatch attempt (retried or terminal) — the
        error-rate half of the per-tenant SLO inputs."""
        self._c_failures.inc()


# -- aggregation (the multihost seam's pure half) ------------------------------

def aggregate_snapshots(snaps: Sequence[dict | MetricsSnapshot]) -> dict:
    """Merge per-process snapshots into one: counters and histogram counts
    sum (work is additive across processes), gauges take the max (each is
    a local last-observation; max keeps the worst queue depth / largest
    superstep visible), info keeps the first process's labels (identical
    everywhere by SPMD construction)."""
    out = {
        "schema": SCHEMA,
        "counters": {},
        "gauges": {},
        "histograms": {},
        "info": {},
    }
    for s in snaps:
        d = s.data if isinstance(s, MetricsSnapshot) else s
        for k, v in d.get("counters", {}).items():
            out["counters"][k] = out["counters"].get(k, 0) + v
        for k, v in d.get("gauges", {}).items():
            prev = out["gauges"].get(k)
            out["gauges"][k] = v if prev is None else max(prev, v)
        for k, h in d.get("histograms", {}).items():
            prev = out["histograms"].get(k)
            if prev is None or prev["buckets"] != h["buckets"]:
                out["histograms"][k] = {
                    "buckets": list(h["buckets"]),
                    "counts": list(h["counts"]),
                    "sum": h["sum"],
                    "count": h["count"],
                }
            else:
                prev["counts"] = [
                    a + b for a, b in zip(prev["counts"], h["counts"])
                ]
                prev["sum"] += h["sum"]
                prev["count"] += h["count"]
        for k, v in d.get("info", {}).items():
            out["info"].setdefault(k, v)
    return out


# -- the snapshot schema lint --------------------------------------------------

def _finite(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) and math.isfinite(v)


def check_metrics_snapshot(obj, path: str = "$") -> list[str]:
    """Lint one ``gol-metrics-v1`` snapshot dict; returns the violations
    (empty = clean) — the same contract shape as
    ``measure.check_headline_stats``."""
    problems: list[str] = []
    if not isinstance(obj, Mapping):
        return [f"{path}: snapshot is not a dict ({type(obj).__name__})"]
    if obj.get("schema") != SCHEMA:
        problems.append(f"{path}.schema: want {SCHEMA!r}, got {obj.get('schema')!r}")
    # Sections come from arbitrary on-disk JSON (flight records, sidecars):
    # a corrupted section must become a VIOLATION, never an AttributeError
    # out of the lint itself.
    for section in ("counters", "gauges", "histograms", "info"):
        if not isinstance(obj.get(section, {}), Mapping):
            problems.append(
                f"{path}.{section}: not a dict "
                f"({type(obj.get(section)).__name__})"
            )
    if problems:
        return problems
    for k, v in obj.get("counters", {}).items():
        if not _finite(v) or v < 0:
            problems.append(f"{path}.counters.{k}: not a finite non-negative number ({v!r})")
    for k, v in obj.get("gauges", {}).items():
        if not _finite(v):
            problems.append(f"{path}.gauges.{k}: not a finite number ({v!r})")
    for k, h in obj.get("histograms", {}).items():
        hp = f"{path}.histograms.{k}"
        if not isinstance(h, Mapping):
            problems.append(f"{hp}: not a dict")
            continue
        buckets = h.get("buckets")
        counts = h.get("counts")
        if not isinstance(buckets, (list, tuple)) or not all(
            _finite(b) for b in buckets
        ):
            problems.append(f"{hp}.buckets: not a list of finite numbers")
            continue
        if any(a >= b for a, b in zip(buckets, list(buckets)[1:])):
            problems.append(f"{hp}.buckets: not strictly increasing")
        if not isinstance(counts, (list, tuple)) or len(counts) != len(buckets) + 1:
            problems.append(
                f"{hp}.counts: want len(buckets)+1 slots, got "
                f"{len(counts) if isinstance(counts, (list, tuple)) else 'n/a'}"
            )
            continue
        if any(not isinstance(c, int) or c < 0 for c in counts):
            problems.append(f"{hp}.counts: not all non-negative ints")
        elif h.get("count") != sum(counts):
            problems.append(
                f"{hp}.count: {h.get('count')!r} != sum(counts) {sum(counts)}"
            )
        if not _finite(h.get("sum")):
            problems.append(f"{hp}.sum: not a finite number ({h.get('sum')!r})")
    for k, v in obj.get("info", {}).items():
        if not isinstance(v, str):
            problems.append(f"{path}.info.{k}: not a string ({v!r})")
    return problems


def require_metrics_snapshot(obj) -> None:
    """Raising form of :func:`check_metrics_snapshot` — artifact writers
    (bench.py, the flight dump) run this before publishing, same contract
    as ``measure.require_headline_stats``."""
    problems = check_metrics_snapshot(obj)
    if problems:
        raise MalformedSnapshot("; ".join(problems))


def check_embedded_metrics(record, path: str = "$") -> list[str]:
    """Walk an arbitrary artifact record; every ``"metrics"`` key holding
    a dict must be a schema-valid snapshot.  This is what ``bench.py``
    runs on its own record before printing (alongside
    ``require_headline_stats``)."""
    problems: list[str] = []
    if isinstance(record, Mapping):
        for k, v in record.items():
            if k == "metrics" and isinstance(v, Mapping):
                problems.extend(check_metrics_snapshot(v, f"{path}.metrics"))
            else:
                problems.extend(check_embedded_metrics(v, f"{path}.{k}"))
    elif isinstance(record, (list, tuple)):
        for i, v in enumerate(record):
            problems.extend(check_embedded_metrics(v, f"{path}[{i}]"))
    return problems


def require_embedded_metrics(record) -> None:
    problems = check_embedded_metrics(record)
    if problems:
        raise MalformedSnapshot("; ".join(problems))
