"""distributed_gol_tpu — a TPU-native distributed Game of Life framework.

A brand-new JAX / XLA / Pallas / pjit framework with the capabilities of the
reference system ``Oliver-Cairns/distributed-gol`` (a Go controller + broker +
4 worker servers over ``net/rpc``; see ``SURVEY.md``).  Instead of round-
tripping the full board over TCP every generation (reference
``gol/distributor.go:48-66``, ``broker/broker.go:37-56``), the board lives on
device as a ``jnp.uint8`` array; the per-generation update is a 9-point
stencil inside one jitted SPMD program, sharded over a ``jax.sharding.Mesh``
with ``lax.ppermute`` halo exchange and on-device alive counts.

Public API (mirrors the reference's ``gol`` package surface,
``gol/gol.go:6-14`` and ``gol/event.go:9-68``):

- :class:`Params` — run configuration (``gol/gol.go:6-11``).
- :func:`run` — the engine façade, equivalent of ``gol.Run``
  (``gol/gol.go:14``): drives a whole simulation, emitting events.
- Event types: :class:`AliveCellsCount`, :class:`ImageOutputComplete`,
  :class:`StateChange`, :class:`CellFlipped`, :class:`CellsFlipped`,
  :class:`TurnComplete`, :class:`FinalTurnComplete` and the :class:`State`
  enum (``gol/event.go:19-68``).
- :class:`Cell` — an (x, y) coordinate (``util/cell.go:4-6``).
"""

from distributed_gol_tpu.utils.cell import Cell
from distributed_gol_tpu.engine.params import Params
from distributed_gol_tpu.engine.events import (
    AliveCellsCount,
    CellFlipped,
    CellsFlipped,
    CheckpointSaved,
    CycleDetected,
    DispatchError,
    Event,
    EventQueue,
    FinalTurnComplete,
    FrameDelta,
    FrameReady,
    ImageOutputComplete,
    MetricsReport,
    State,
    StateChange,
    TurnComplete,
    TurnsCompleted,
    TurnTiming,
)
from distributed_gol_tpu.engine.controller import (
    CorruptionDetected,
    DispatchTimeout,
)
from distributed_gol_tpu.engine.gol import run, start
from distributed_gol_tpu.engine.supervisor import GracefulStop, supervise

__all__ = [
    "AliveCellsCount",
    "Cell",
    "CellFlipped",
    "CellsFlipped",
    "CheckpointSaved",
    "CorruptionDetected",
    "CycleDetected",
    "DispatchError",
    "DispatchTimeout",
    "Event",
    "EventQueue",
    "FinalTurnComplete",
    "FrameReady",
    "GracefulStop",
    "ImageOutputComplete",
    "MetricsReport",
    "Params",
    "State",
    "StateChange",
    "TurnComplete",
    "TurnsCompleted",
    "TurnTiming",
    "run",
    "start",
    "supervise",
]

__version__ = "0.4.0"
