"""Batched-board engine (ISSUE 8): one launch for N boards.

Three layers, each pinned bit-identical per slot to B independent runs:

- **Portable form** (``ops/packed.py``): ``vmap`` over the packed SWAR
  superstep — pure XLA, every backend.  Integer bitwise ops batch
  exactly, so identity here is structural; the tests make it explicit.
- **Fast forms** (``ops/pallas_packed.py``): an explicit leading-axis
  grid dimension in the Pallas kernels — the VMEM-resident vertical
  kernel for small boards and the frontier MEGAKERNEL for tiled ones
  (boards stacked along the row axis, per-board toroidal wrap, the
  (2, grid) SMEM interval state reused serially across boards).  The
  identity matrix runs in interpret mode across ``B ∈ {1, 2, 7}`` ×
  ``geometry_candidates()`` × both headline lane counts (wp = 512 and
  wp = 2048 boards); hardware lowering is gated by
  ``tools/hw_compile_gate.py``'s batched rows.
- **Engine seam** (``engine/backend.py``): :class:`BatchedBackend`
  resolves the batched form per the solo ranking and exposes
  ``run_turns_async`` over ``(B, H, W)`` stacks plus the fused
  ``run_boards`` the serving plane's coalescer launches through.

The serving-plane half of the tentpole (cohort rendezvous, eviction,
chaos) lives in ``tests/test_serve.py``.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_gol_tpu.engine.backend import Backend, BatchedBackend
from distributed_gol_tpu.engine.params import Params
from distributed_gol_tpu.models.life import CONWAY, HIGHLIFE
from distributed_gol_tpu.ops import packed, pallas_packed, stencil

rng = np.random.default_rng(8)


def soup_stack(b, h, w, density=0.3):
    return (rng.random((b, h, w)) < density).astype(np.uint8) * 255


def glider(board, y, x):
    for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
        board[y + dy, x + dx] = 255


# -- portable vmap form --------------------------------------------------------


class TestBatchedPackedOps:
    def test_slots_match_independent_runs(self):
        stack = soup_stack(3, 64, 128)
        p = jnp.asarray(np.stack([np.asarray(packed.pack(jnp.asarray(b))) for b in stack]))
        got = packed.batched_superstep(p, CONWAY, 17)
        for i in range(3):
            want = packed.superstep(packed.pack(jnp.asarray(stack[i])), CONWAY, 17)
            np.testing.assert_array_equal(np.asarray(got[i]), np.asarray(want))

    def test_per_board_counts(self):
        stack = soup_stack(4, 32, 64)
        p = jnp.stack([packed.pack(jnp.asarray(b)) for b in stack])
        counts = packed.batched_alive_counts(p)
        assert counts.shape == (4,)
        for i in range(4):
            assert int(counts[i]) == np.count_nonzero(stack[i])

    def test_byte_driver_roundtrip_and_rule(self):
        # A non-Conway rule through the batched driver: the rule is a
        # static compile-time parameter per cohort, not global state.
        stack = soup_stack(2, 32, 64)
        run = packed.make_batched_superstep(HIGHLIFE)
        out, counts = run(jnp.asarray(stack), 9)
        solo = packed.make_superstep(HIGHLIFE)
        for i in range(2):
            want = solo(jnp.asarray(stack[i]), 9)
            np.testing.assert_array_equal(np.asarray(out[i]), np.asarray(want))
            assert int(counts[i]) == np.count_nonzero(np.asarray(want))

    def test_zero_turns_counts_input(self):
        stack = soup_stack(2, 32, 64)
        out, counts = packed.make_batched_superstep(CONWAY)(jnp.asarray(stack), 0)
        np.testing.assert_array_equal(np.asarray(out), stack)
        assert [int(c) for c in counts] == [int(np.count_nonzero(b)) for b in stack]


# -- leading-axis Pallas fast forms (interpret mode) ---------------------------


class TestBatchedVmemResident:
    """Small boards — the serving plane's admission class — take the
    batched VMEM-resident vertical kernel: grid (B,), one pallas_call
    for B whole supersteps."""

    @pytest.mark.parametrize("b", [1, 3])
    def test_slots_match_solo(self, b):
        stack = soup_stack(b, 512, 512)
        assert pallas_packed.is_vmem_resident((512, 16))
        run = pallas_packed.make_batched_superstep_bytes(CONWAY)
        out, counts = run(jnp.asarray(stack), 9)
        solo = pallas_packed.make_superstep_bytes(CONWAY)
        for i in range(b):
            want = solo(jnp.asarray(stack[i]), 9)
            np.testing.assert_array_equal(np.asarray(out[i]), np.asarray(want))
            assert int(counts[i]) == np.count_nonzero(np.asarray(want))


def _identity_board(h, w, slot):
    """Per-slot content exercising distinct frontier tiers: a mid-board
    glider (column tier), a quantum-straddling cluster (C=128 fallback),
    a blinker fence (S-margin fallback), and ash — varied by slot so a
    cross-slot mixup cannot cancel out."""
    b = np.zeros((h, w), dtype=np.uint8)
    if slot % 3 == 0:
        glider(b, h // 3, min(w - 8, w // 2))
        b[h - 30 : h - 28, 200:202] = 255  # far ash
    elif slot % 3 == 1:
        # Straddles the 128-word (4096-cell) placement quantum when the
        # board is wide enough; plain mid-board residue otherwise.
        x = 4090 if w > 8192 else w // 4
        b[h // 2 : h // 2 + 2, x : x + 12 : 4] = 255
    else:
        y = min(h - 48, 2 * h // 3)
        b[y : y + 40 : 6, 100:103] = 255  # blinker fence (tall-ish cluster)
    return b


def _run_batched_matrix(boards, turns, cap=512):
    stack = jnp.stack([packed.pack(jnp.asarray(b)) for b in boards])
    got, _ = pallas_packed._run_tiled_batched(stack, CONWAY, turns, True, cap)
    for i, b in enumerate(boards):
        want = packed.superstep(packed.pack(jnp.asarray(b)), CONWAY, turns)
        np.testing.assert_array_equal(
            np.asarray(got[i]),
            np.asarray(want),
            err_msg=f"slot {i} diverged from its solo run",
        )


def _mega_turns(shape, cap=512):
    """A turn count whose decomposition holds a canonical megakernel
    chunk (full = 8 launches ≥ min(_NLAUNCH_CANON)) — sub-chunk counts
    would route to the vmapped tail and never run the megakernel."""
    t, adaptive = pallas_packed.adaptive_launch_depth(shape, 960, cap)
    assert adaptive
    return 8 * t


class TestBatchedMegakernel:
    """The leading-axis frontier megakernel identity matrix (interpret
    mode): B ∈ {1, 2, 7} × geometry candidates × the wp = 512 and
    wp = 2048 lane counts.  Short boards keep interpret affordable; the
    lane geometry (placement quanta, window widths, per-board seam
    bounds) is the headline one.  The expensive corners of the matrix
    are marked slow; tier-1 keeps every candidate at B = 2 plus the
    B-sweep at a narrow board."""

    H512, W512 = 1024, 16384  # wp = 512 — column tier engages
    H2048, W2048 = 512, 65536  # wp = 2048 — the 65536² lane count
    HN, WN = 1024, 4096  # wp = 128 — row tier only, cheap B sweep

    @pytest.mark.parametrize(
        "geom", pallas_packed.geometry_candidates(), ids=lambda g: g.label
    )
    def test_wp512_candidates_b2(self, geom):
        shape = (self.H512, self.W512 // 32)
        with pallas_packed.plan_geometry_override(geom):
            assert pallas_packed._frontier_plan(shape, 18, 512) is not None
            boards = [
                _identity_board(self.H512, self.W512, s) for s in range(2)
            ]
            _run_batched_matrix(boards, _mega_turns(shape))

    @pytest.mark.parametrize("b", [1, 7])
    def test_narrow_board_b_sweep(self, b):
        # B = 1 pins that the batched build IS the solo lowering (the
        # board-global arithmetic folds away); B = 7 an odd batch with
        # per-slot content variety and a soup slot.
        shape = (self.HN, self.WN // 32)
        boards = [_identity_board(self.HN, self.WN, s) for s in range(b)]
        if b > 1:
            boards[-1] = soup_stack(1, self.HN, self.WN)[0]
        _run_batched_matrix(boards, _mega_turns(shape))

    def test_wp2048_shipped_b2(self):
        shape = (self.H2048, self.W2048 // 32)
        boards = [_identity_board(self.H2048, self.W2048, s) for s in range(2)]
        _run_batched_matrix(boards, _mega_turns(shape))

    @pytest.mark.slow
    @pytest.mark.parametrize(
        "geom", pallas_packed.geometry_candidates(), ids=lambda g: g.label
    )
    def test_wp512_candidates_b7_slow(self, geom):
        shape = (self.H512, self.W512 // 32)
        with pallas_packed.plan_geometry_override(geom):
            boards = [
                _identity_board(self.H512, self.W512, s) for s in range(7)
            ]
            _run_batched_matrix(boards, _mega_turns(shape))

    @pytest.mark.slow
    @pytest.mark.parametrize(
        "geom",
        [g for g in pallas_packed.geometry_candidates()][1:],
        ids=lambda g: g.label,
    )
    def test_wp2048_candidates_b2_slow(self, geom):
        shape = (self.H2048, self.W2048 // 32)
        with pallas_packed.plan_geometry_override(geom):
            boards = [
                _identity_board(self.H2048, self.W2048, s) for s in range(2)
            ]
            _run_batched_matrix(boards, _mega_turns(shape))

    def test_per_board_skip_telemetry(self):
        # An all-ash slot skips; an active slot does not — the sk vector
        # separates them (per-board accumulator reset at each board's
        # launch 0).
        shape = (self.HN, self.WN // 32)
        turns = _mega_turns(shape)
        ash = np.zeros((self.HN, self.WN), dtype=np.uint8)
        ash[100:102, 200:202] = 255  # one block: pure still life
        active = _identity_board(self.HN, self.WN, 0)  # glider
        stack = jnp.stack(
            [packed.pack(jnp.asarray(b)) for b in (ash, active)]
        )
        out, sk = pallas_packed._run_tiled_batched(
            stack, CONWAY, turns, True, 512
        )
        sk = np.asarray(sk)
        assert sk.shape == (2,)
        assert sk[0] > sk[1], f"ash slot should out-skip the glider slot: {sk}"

    def test_batched_supports_gate(self):
        assert pallas_packed.batched_supports((512, 16))  # vmem-resident
        assert pallas_packed.batched_supports((self.H512, self.W512 // 32))
        assert not pallas_packed.batched_supports((64, 3))  # nobody's shape
        assert not pallas_packed.batched_supports((64, 0))


# -- the engine seam -----------------------------------------------------------


class TestBatchedBackend:
    def _solo(self, params, board, turns):
        be = Backend(params)
        out, count = be.run_turns(be.put(board), turns)
        return be.fetch(out), count

    def test_roll_stack_matches_solo(self):
        p = Params(image_width=16, image_height=16, engine="roll", superstep=4)
        bb = BatchedBackend(p)
        assert bb.engine_used == "roll"
        stack = soup_stack(3, 16, 16, 0.25)
        out, counts = bb.run_turns(bb.put(stack), 4)
        for i in range(3):
            want, wc = self._solo(p, stack[i], 4)
            np.testing.assert_array_equal(np.asarray(out[i]), want)
            assert int(counts[i]) == wc

    def test_packed_stack_matches_solo(self):
        p = Params(image_width=256, image_height=256, superstep=16)
        bb = BatchedBackend(p)
        assert bb.engine_used in ("packed", "pallas-packed")
        stack = soup_stack(2, 256, 256)
        out, counts = bb.run_turns(bb.put(stack), 16)
        for i in range(2):
            want, wc = self._solo(p, stack[i], 16)
            np.testing.assert_array_equal(np.asarray(out[i]), want)
            assert int(counts[i]) == wc

    def test_run_boards_fused_form(self):
        p = Params(image_width=64, image_height=64, superstep=8)
        bb = BatchedBackend(p)
        stack = soup_stack(4, 64, 64)
        outs, counts = bb.run_boards([jnp.asarray(b) for b in stack], 8)
        assert len(outs) == len(counts) == 4
        for i in range(4):
            want, wc = self._solo(p, stack[i], 8)
            np.testing.assert_array_equal(np.asarray(outs[i]), want)
            assert int(counts[i]) == wc

    def test_async_seam_counts_are_unresolved_devices_values(self):
        p = Params(image_width=32, image_height=32, superstep=4)
        bb = BatchedBackend(p)
        stack = bb.put(soup_stack(2, 32, 32))
        out, counts = bb.run_turns_async(stack, 4)
        # Per-board vector, forceable like any dispatch count.
        assert int(counts[0]) >= 0 and int(counts[1]) >= 0
        assert out.shape == stack.shape

    def test_zero_turns(self):
        p = Params(image_width=32, image_height=32)
        bb = BatchedBackend(p)
        stack = soup_stack(2, 32, 32)
        out, counts = bb.run_turns(bb.put(stack), 0)
        np.testing.assert_array_equal(np.asarray(out), stack)
        assert [int(c) for c in counts] == [
            int(np.count_nonzero(b)) for b in stack
        ]

    def test_mesh_is_rejected(self):
        with pytest.raises(NotImplementedError, match="single-device"):
            BatchedBackend(
                Params(image_width=64, image_height=64, mesh_shape=(2, 1))
            )

    def test_batched_dispatch_counter(self):
        from distributed_gol_tpu.obs import metrics as obs_metrics

        p = Params(image_width=16, image_height=16, engine="roll")
        bb = BatchedBackend(p)
        before = obs_metrics.REGISTRY.snapshot()
        bb.run_turns(bb.put(soup_stack(2, 16, 16)), 2)
        delta = (
            obs_metrics.REGISTRY.snapshot().delta(before).to_dict()["counters"]
        )
        assert delta.get("backend.batched_dispatches.roll") == 1
