"""Sharded temporally-blocked pallas-packed engine (parallel/pallas_halo.py).

The sharded flagship path VERDICT r1 flagged as missing: T-generation
ppermute halos + the VMEM-tiled kernel per strip.  Gated bit-identical
against the XLA packed engine (itself oracle-gated) on virtual CPU meshes,
including the 512²×100 golden-PGM configuration the reference tests use
(``gol_test.go:24-28``).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_gol_tpu.models.life import CONWAY, HIGHLIFE
from distributed_gol_tpu.ops import packed
from distributed_gol_tpu.parallel import pallas_halo
from distributed_gol_tpu.parallel.mesh import make_mesh
from distributed_gol_tpu.parallel.packed_halo import packed_sharding

from tests.conftest import random_board


def _run_sharded(board_np, mesh_shape, turns, rule=CONWAY):
    mesh = make_mesh(mesh_shape)
    p = packed.pack(jnp.asarray(board_np))
    pb = jax.device_put(np.asarray(p), packed_sharding(mesh))
    out = pallas_halo.make_superstep(mesh, rule)(pb, turns)
    return np.asarray(packed.unpack(out))


@pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 1), (4, 1), (8, 1)])
def test_bit_identity_vs_packed(rng, mesh_shape):
    board = random_board(rng, 128, 64)
    ref = np.asarray(
        packed.unpack(packed.superstep(packed.pack(jnp.asarray(board)), CONWAY, 30))
    )
    got = _run_sharded(board, mesh_shape, 30)
    assert np.array_equal(got, ref), f"diverged on mesh {mesh_shape}"


def test_remainder_launch(rng):
    # turns far below any launch depth exercises the remainder-only path;
    # a prime turn count exercises full + remainder.
    board = random_board(rng, 64, 64)
    pref = packed.pack(jnp.asarray(board))
    for turns in (1, 3, 37):
        ref = np.asarray(packed.unpack(packed.superstep(pref, CONWAY, turns)))
        got = _run_sharded(board, (4, 1), turns)
        assert np.array_equal(got, ref), f"diverged at turns={turns}"


def test_highlife_rule(rng):
    board = random_board(rng, 64, 64)
    ref = np.asarray(
        packed.unpack(packed.superstep(packed.pack(jnp.asarray(board)), HIGHLIFE, 16))
    )
    got = _run_sharded(board, (2, 1), 16, rule=HIGHLIFE)
    assert np.array_equal(got, ref)


def test_golden_512_on_8_device_mesh(input_images, golden_images):
    """512²×100 on an (8,1) mesh matches the reference's golden board —
    the sharded fast path against the same oracle as ``gol_test.go``."""
    from distributed_gol_tpu.engine.pgm import read_pgm

    board = read_pgm(input_images / "512x512.pgm")
    golden = read_pgm(golden_images / "512x512x100.pgm")
    got = _run_sharded(board, (8, 1), 100)
    assert np.array_equal(got, golden)


def test_supports_gates():
    # Row meshes: strips must tile.
    assert pallas_halo.supports((512, 16), (8, 1))
    assert not pallas_halo.supports((512, 16), (3, 1))  # does not divide
    assert not pallas_halo.supports((32, 16), (8, 1))  # 4-row strips
    # The v5e-4 north-star shape: 65536² over 4 chips, packed wp = 2048.
    assert pallas_halo.supports((65536, 2048), (4, 1))
    # 2-D meshes (round 7): word-aligned per-device tiles qualify...
    assert pallas_halo.supports((512, 16), (2, 4))
    assert pallas_halo.supports((65536, 2048), (2, 4))
    assert pallas_halo.supports((262144, 8192), (8, 8))  # the scale-out target
    # ...word-misaligned column splits do not (wp % nx != 0), nor tiles
    # whose strips are too short to tile.
    assert not pallas_halo.supports((512, 6), (2, 4))
    assert not pallas_halo.supports((32, 16), (8, 2))


def test_backend_selects_sharded_pallas(rng):
    """engine='pallas-packed' on a row mesh runs the sharded kernel (no more
    silent downgrade, VERDICT r1 missing #1); 'auto' on CPU stays packed
    (kernel upgrades are TPU-only); round 7: 2-D meshes run the x-extended
    tile family instead of falling back, and word-misaligned column splits
    still degrade (loudly) to packed."""
    from distributed_gol_tpu.engine.backend import Backend
    from distributed_gol_tpu.engine.params import Params

    common = dict(turns=16, image_width=64, image_height=64)
    b = Backend(Params(**common, mesh_shape=(2, 1), engine="pallas-packed"))
    assert b.engine_used == "pallas-packed"
    assert Backend(Params(**common, mesh_shape=(2, 1), engine="auto")).engine_used == "packed"
    b22 = Backend(Params(**common, mesh_shape=(2, 2), engine="pallas-packed"))
    assert b22.engine_used == "pallas-packed"
    # A column split off word granularity (64 / 2 = 32 cells = 1 word per
    # device... 64-wide on (1, 4): 16 cells/device) cannot take ANY
    # packed-family engine; the explicit request degrades with a warning.
    with pytest.warns(RuntimeWarning, match="falling back to 'roll'"):
        assert (
            Backend(
                Params(**common, mesh_shape=(1, 4), engine="pallas-packed")
            ).engine_used
            == "roll"
        )

    # And the selected sharded engines agree with the single-device result.
    board = random_board(rng, 64, 64)
    single = Backend(Params(**common, engine="packed"))
    ref, ref_count = single.run_turns(single.put(board), 16)
    for be in (b, b22):
        out, count = be.run_turns(be.put(board), 16)
        assert count == ref_count
        assert np.array_equal(be.fetch(out), single.fetch(ref))


def test_2d_halo_byte_model_still_prefers_rows_at_small_scale():
    """The x-halo is 128-lane quantized (the measured column-blocking
    physics, BASELINE.md), so at device counts where row strips stay
    tall the row mesh ships strictly fewer ICI bytes — the model that
    made round 4 keep the tier row-only, pinned so the perf guidance
    cannot rot.  Round 7 SHIPPED the 2-D tier anyway (the row ceiling
    caps scale-out at ny devices; 262144² needs the full mesh), so
    supports() now accepts both and the model is guidance, not a gate;
    the executing 2-D plan's per-direction bytes are published by
    ``launch_plan``."""
    from distributed_gol_tpu.parallel.pallas_halo import (
        halo_bytes_2d_model,
        launch_plan,
    )

    for n, shapes in [
        (4, [(2, 2), (4, 1)]),
        (8, [(2, 4), (4, 2), (8, 1)]),
        (64, [(8, 8), (16, 4), (64, 1)]),
        (256, [(16, 16), (256, 1)]),
    ]:
        for ny, nx in shapes:
            m = halo_bytes_2d_model((65536, 2048), (ny, nx), 48)
            assert m["ratio"] >= 1.0, (ny, nx, m)
            if nx > 1:
                assert m["ratio"] > 3, (ny, nx, m)  # not close: lane quantum
    # Both mesh families are supported; the 2-D plan records its halo
    # traffic per direction (y: edge rows, x: edge columns + corners).
    from distributed_gol_tpu.parallel import pallas_halo

    assert pallas_halo.supports((65536, 2048), (2, 4))
    assert pallas_halo.supports((65536, 2048), (8, 1))
    plan = launch_plan((65536, 2048), (2, 4))
    assert plan["halo_bytes"] == plan["halo_bytes_y"] + plan["halo_bytes_x"]
    assert plan["halo_bytes_y"] > 0 and plan["halo_bytes_x"] > 0
    assert plan["frontier"] is not None


@pytest.mark.parametrize("mesh_shape", [(2, 1), (4, 1)])
def test_sharded_ping_pong_multi_launch_elision(rng, mesh_shape):
    """Round-4 sharded ping-pong: dispatches spanning ≥4 launches on a
    mesh, with ash strips (elided — write-skipped from both buffers) and
    one active strip; bit-identity vs the XLA packed engine catches any
    stale-buffer row, and the skip telemetry still counts every launch."""
    H, W = 512, 4096
    b = np.zeros((H, W), dtype=np.uint8)
    b[10:12, 100:102] = 255
    b[300:302, 3000:3002] = 255
    for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
        b[150 + dy, 2000 + dx] = 255
    mesh = make_mesh(mesh_shape)
    p = packed.pack(jnp.asarray(b))
    pb = jax.device_put(np.asarray(p), packed_sharding(mesh))
    strip = (H // mesh_shape[0], W // 32)
    from distributed_gol_tpu.ops import pallas_packed

    t, adaptive = pallas_packed.adaptive_launch_depth(strip, 960, 64)
    assert adaptive
    run = pallas_halo.make_superstep(
        mesh, CONWAY, skip_stable=True, skip_tile_cap=64, with_stats=True
    )
    for turns in (4 * t, 5 * t, 4 * t + 20):  # both parities + remainder split
        out, skipped, _act = run(pb, turns)
        ref = packed.superstep(p, CONWAY, turns)
        assert np.array_equal(np.asarray(out), np.asarray(ref)), turns
        total = pallas_halo.adaptive_strip_launches(
            p.shape, mesh_shape, turns, 64
        )
        assert total > 0 and 0 < int(skipped) <= total


class TestShardedFrontier:
    """Frontier strip kernel (round 5): tracked row/column intervals ride
    the same ``ppermute`` as the halo rows (edge-tile entries translated
    into the receiving strip's frame), replacing the probe + bitmap on
    sharded meshes too.  Bit-identity vs the XLA packed engine across
    meshes, both launch parities, and the remainder split — the VERDICT
    round-4 'next' item 1 done-criteria."""

    H, W = 4096, 128  # (2,1)-mesh strips host the frontier plan

    def _run(self, board_np, mesh_shape, turns):
        mesh = make_mesh(mesh_shape)
        p = packed.pack(jnp.asarray(board_np))
        pb = jax.device_put(np.asarray(p), packed_sharding(mesh))
        out, sk, _act = pallas_halo.make_superstep(
            mesh, CONWAY, skip_stable=True, with_stats=True
        )(pb, turns)
        return np.asarray(packed.unpack(out)), int(sk)

    def _board(self):
        b = np.zeros((self.H, self.W), dtype=np.uint8)
        # Glider heading for the strip seam at H/2, ash elsewhere, and a
        # pulsar (period 3) that must still be skip-proved; most stripes
        # stay empty so skips + elisions actually exercise.
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[2030 + dy, 60 + dx] = 255
        b[100:102, 20:22] = 255
        seg = [2, 3, 4, 8, 9, 10]
        for c in seg:
            for r in (0, 5, 7, 12):
                b[3000 + r, 40 + c] = 255
                b[3000 + c, 40 + r] = 255
        return b

    def _check(self, mesh_shape, turns):
        b = self._board()
        ref = np.asarray(
            packed.unpack(
                packed.superstep(packed.pack(jnp.asarray(b)), CONWAY, turns)
            )
        )
        got, sk = self._run(b, mesh_shape, turns)
        assert np.array_equal(got, ref), (
            f"diverged on mesh {mesh_shape} at turns={turns}"
        )
        return sk

    def test_plan_engages(self):
        from distributed_gol_tpu.ops import pallas_packed as pp

        strip = (self.H // 2, self.W // 32)
        t, adaptive = pp.adaptive_launch_depth(strip, 960, 1024)
        assert adaptive and t == pp._FRONTIER_T
        assert pp._frontier_plan(strip, t, 1024) is not None

    def test_even_and_odd_launch_parity_2dev(self):
        sk = self._check((2, 1), 4 * 18)  # final board in the launch-2 buffer
        assert sk > 0  # empty stripes skipped
        self._check((2, 1), 5 * 18)  # ...and in the other one

    def test_remainder_split_and_tail(self):
        self._check((2, 1), 4 * 18 + 12)  # period-multiple remainder launch
        self._check((2, 1), 4 * 18 + 7)  # + 1-gen full-compute tail

    def test_4dev_single_tile_strips(self):
        # 1024-row strips at the default cap: grid == 1 per device, so a
        # tile's left AND right window sources are its own neighbours'
        # edge entries — the pure cross-strip adjacency case.
        self._check((4, 1), 4 * 18)
        self._check((4, 1), 5 * 18)

    def test_seam_glider_long_run(self):
        # Enough launches for the glider to cross the strip seam and for
        # settled stripes to reach write-elision on both buffers.
        self._check((2, 1), 10 * 18)

    def test_shallow_depths_need_deeper_halo(self):
        # t=6/t=12 dispatches: round8(t) != round8(t+6), so the ppermute
        # extent must follow the frontier plan's deeper pad — at t=18
        # the two coincide (24), which once masked exactly this bug.
        b = self._board()
        for turns in (6, 8, 12):
            ref = np.asarray(
                packed.unpack(
                    packed.superstep(packed.pack(jnp.asarray(b)), CONWAY, turns)
                )
            )
            got, _ = self._run(b, (2, 1), turns)
            assert np.array_equal(got, ref), f"diverged at turns={turns}"


class TestInKernelICI:
    """Round-6 in-kernel ICI exchange tier: whole launch chunks run as ONE
    pallas_call per device, halo rows + the (6,) interval state exchanged
    inside the kernel (``_kernel_frontier_mega_strip``).  Hermetic
    coverage is the ny == 1 LOOPBACK build — the torus self-exchange runs
    the full launch/slot/state sequencing with local copies, so interpret
    mode exercises everything except the literal remote-DMA lowering
    (gated on hardware by ``tools/hw_compile_gate.py``).  Bit-identity
    oracle: the single-device megakernel path of the XLA-gated packed
    engine."""

    H, W = 4096, 128

    def _board(self):
        b = np.zeros((self.H, self.W), dtype=np.uint8)
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[2030 + dy, 60 + dx] = 255
        b[100:102, 20:22] = 255
        seg = [2, 3, 4, 8, 9, 10]
        for c in seg:
            for r in (0, 5, 7, 12):
                b[3000 + r, 40 + c] = 255
                b[3000 + c, 40 + r] = 255
        return b

    def _run11(self, board_np, turns, **kw):
        mesh = make_mesh((1, 1))
        p = packed.pack(jnp.asarray(board_np))
        pb = jax.device_put(np.asarray(p), packed_sharding(mesh))
        out, sk, _act = pallas_halo.make_superstep(
            mesh, CONWAY, skip_stable=True, with_stats=True, **kw
        )(pb, turns)
        return np.asarray(packed.unpack(out)), int(sk)

    def test_policy_loopback_always_available(self):
        assert pallas_halo.ici_tier_policy(make_mesh((1, 1))) == (
            True,
            "in-kernel",
        )

    def test_policy_interpret_multidevice_falls_back(self):
        # POLICY-classed (non-warning) downgrade: interpret mode has no
        # remote-DMA emulation, the ppermute strip form stays selected.
        # interpret=True pins the branch under test so the assertion also
        # holds on a real multi-device TPU rig (where the tier would
        # legitimately engage).
        use, reason = pallas_halo.ici_tier_policy(
            make_mesh((2, 1)), interpret=True
        )
        assert not use and "interpret" in reason

    def test_policy_forced_ppermute(self):
        use, reason = pallas_halo.ici_tier_policy(
            make_mesh((1, 1)), in_kernel=False
        )
        assert not use and "forced" in reason

    def test_policy_env_escape_hatch(self, monkeypatch):
        monkeypatch.setenv("DGOL_ICI", "0")
        use, reason = pallas_halo.ici_tier_policy(make_mesh((1, 1)))
        assert not use and "DGOL_ICI" in reason
        # An explicit in_kernel=True outranks the env switch.
        assert pallas_halo.ici_tier_policy(make_mesh((1, 1)), in_kernel=True)[0]

    @pytest.mark.parametrize("turns", [4 * 18, 5 * 18, 4 * 18 + 12, 4 * 18 + 7])
    def test_loopback_bit_identity_parities_and_remainders(self, turns):
        b = self._board()
        ref = np.asarray(
            packed.unpack(
                packed.superstep(packed.pack(jnp.asarray(b)), CONWAY, turns)
            )
        )
        got, _ = self._run11(b, turns)
        assert np.array_equal(got, ref), f"diverged at turns={turns}"

    def test_loopback_megakernel_chunks_long_run(self):
        # full = 12 launches -> one 8-launch megakernel chunk + 4 loose
        # probing launches + no remainder: the chunk seam (state restarts,
        # buffer threading) and the mixed-tier dispatch both covered.
        from distributed_gol_tpu.ops.pallas_packed import _nlaunch_chunks

        assert _nlaunch_chunks(12) == ([8], 4)
        b = self._board()
        turns = 12 * 18
        ref = np.asarray(
            packed.unpack(
                packed.superstep(packed.pack(jnp.asarray(b)), CONWAY, turns)
            )
        )
        got, sk = self._run11(b, turns)
        assert np.array_equal(got, ref)
        assert sk > 0  # ash stripes skipped inside the megakernel

    def test_loopback_equals_forced_ppermute(self):
        b = self._board()
        got_ici, _ = self._run11(b, 6 * 18)
        got_pp, _ = self._run11(b, 6 * 18, in_kernel=False)
        assert np.array_equal(got_ici, got_pp)

    # The 256-row board is dual-eligible (VMEM-resident fast path) and
    # the test forces skip_stable anyway: the advisory UserWarning is the
    # documented trade, not the subject here.
    @pytest.mark.filterwarnings("ignore:skip_stable forces:UserWarning")
    def test_backend_records_tier_policy(self):
        from distributed_gol_tpu.engine.backend import Backend
        from distributed_gol_tpu.engine.params import Params

        common = dict(
            turns=64,
            image_width=4096,
            skip_stable=True,
            superstep=64,
        )
        b = Backend(
            Params(
                **common,
                image_height=256,
                mesh_shape=(1, 1),
                engine="pallas-packed",
            )
        )
        # (1, 1) runs the single-device engine; the sharded tier record
        # only exists on real meshes.
        assert b.sharded_tier is None
        # Strips tall enough for a frontier plan: on interpret rigs the
        # multi-device policy reason is the fallback — classed, recorded,
        # never warned; on a real multi-device TPU the tier legitimately
        # engages and the record must say so (Backend has no interpret
        # knob, so the expectation follows the backend).
        from distributed_gol_tpu.ops.pallas_packed import _use_interpret

        b2 = Backend(
            Params(
                **common,
                image_height=4096,
                mesh_shape=(2, 1),
                engine="pallas-packed",
            )
        )
        assert b2.engine_used == "pallas-packed"
        if _use_interpret():
            assert b2.sharded_tier == "ppermute"
            assert "interpret" in b2.sharded_tier_policy
        else:
            assert b2.sharded_tier == "ici-megakernel"
        # Strips too short to host the frontier plan: the record must NOT
        # claim the in-kernel tier (review finding, round 6) — geometry
        # outranks mesh policy.
        b3 = Backend(
            Params(
                **common,
                image_height=256,
                mesh_shape=(2, 1),
                engine="pallas-packed",
            )
        )
        assert b3.sharded_tier == "ppermute"
        assert "no frontier plan" in b3.sharded_tier_policy

    def test_remote_build_traces_hermetically(self):
        # The remote-DMA form cannot RUN off-TPU, but its whole kernel
        # body abstract-evals during pallas_call tracing — remote-copy
        # descriptors, send/recv semaphore plumbing, the barrier signals —
        # so Python-level regressions in the remote branch are caught
        # hermetically; the Mosaic-lowering half is tools/hw_compile_gate.
        call = pallas_halo._build_dispatch_frontier_strip(
            (2048, 512), CONWAY, 18, 8, False, 1024, True
        )
        ids = jax.ShapeDtypeStruct((3,), jnp.int32)
        b = jax.ShapeDtypeStruct((2048, 512), jnp.uint32)
        jax.make_jaxpr(call)(ids, b, b)

    def test_golden_512_in_kernel_tier(self, input_images, golden_images):
        """512²×100 through the in-kernel tier matches the reference's
        golden board — the same oracle as ``gol_test.go``, on the (1,1)
        loopback build (the hermetic form of the tier)."""
        from distributed_gol_tpu.engine.pgm import read_pgm

        board = read_pgm(input_images / "512x512.pgm")
        golden = read_pgm(golden_images / "512x512x100.pgm")
        mesh = make_mesh((1, 1))
        p = packed.pack(jnp.asarray(board))
        pb = jax.device_put(np.asarray(p), packed_sharding(mesh))
        out, _, _act = pallas_halo.make_superstep(
            mesh, CONWAY, skip_stable=True, with_stats=True, in_kernel=True
        )(pb, 100)
        assert np.array_equal(np.asarray(packed.unpack(out)), golden)


MESHES_2D = [(2, 2), (2, 4), (4, 2)]


class TestMesh2D:
    """Round-7 2-D mesh tier (ISSUE 13): the x-extended tile kernel
    family on full (ny, nx) meshes.  Three independent formulations are
    cross-gated per mesh shape:

    - the ppermute 2-D tier (plain + probing-adaptive x-extended tile
      kernels, real 8-device CPU meshes under shard_map) vs the XLA
      packed oracle;
    - the IN-KERNEL 2-D exchange megakernel in VIRTUAL mode (one device
      emulates the (ny, nx) pod through the same kernel body, slot
      buffers, parity, and frame translation as the hardware remote
      build) vs the same oracle AND vs the ppermute tier;
    - per-stripe skip/activity telemetry against the solo megakernel's
      per board region.

    The only thing NOT exercised here is the literal remote-DMA
    lowering — ``tools/hw_compile_gate.py`` compiles it on a real chip,
    exactly the strip tier's hermetic-coverage split."""

    H, W = 4096, 256

    def _board(self):
        b = np.zeros((self.H, self.W), dtype=np.uint8)
        # Glider aimed at the row seam at H/2, near a column seam; ash
        # (still life + pulsar) elsewhere; most tiles empty so skips and
        # write-elisions exercise on every mesh shape.
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[2030 + dy, 124 + dx] = 255
        b[100:102, 20:22] = 255
        seg = [2, 3, 4, 8, 9, 10]
        for c in seg:
            for r in (0, 5, 7, 12):
                b[3000 + r, 40 + c] = 255
                b[3000 + c, 40 + r] = 255
        return b

    def _oracle(self, b, turns):
        return np.asarray(
            packed.unpack(
                packed.superstep(packed.pack(jnp.asarray(b)), CONWAY, turns)
            )
        )

    def _run_ppermute(self, b, mesh_shape, turns, **kw):
        mesh = make_mesh(mesh_shape)
        p = packed.pack(jnp.asarray(b))
        pb = jax.device_put(np.asarray(p), packed_sharding(mesh))
        out, sk, act = pallas_halo.make_superstep(
            mesh, CONWAY, skip_stable=True, with_stats=True, **kw
        )(pb, turns)
        return np.asarray(packed.unpack(out)), int(sk), np.asarray(act)

    @pytest.mark.parametrize("mesh_shape", MESHES_2D)
    def test_ppermute_2d_bit_identity_and_telemetry(self, mesh_shape):
        b = self._board()
        for turns in (4 * 18, 5 * 18, 4 * 18 + 20):  # parities + remainder
            got, sk, act = self._run_ppermute(b, mesh_shape, turns)
            assert np.array_equal(got, self._oracle(b, turns)), (
                mesh_shape, turns,
            )
            total = pallas_halo.adaptive_strip_launches(
                (self.H, self.W // 32), mesh_shape, turns, None
            )
            assert total > 0 and 0 < sk <= total
            assert act.shape[1] == mesh_shape[1]

    def test_plain_2d_and_highlife(self):
        from distributed_gol_tpu.models.life import HIGHLIFE

        b = np.asarray(
            random_board(np.random.default_rng(3), 128, 256)
        )
        pref = packed.pack(jnp.asarray(b))
        for rule in (CONWAY, HIGHLIFE):
            ref = np.asarray(packed.unpack(packed.superstep(pref, rule, 23)))
            mesh = make_mesh((2, 2))
            pb = jax.device_put(np.asarray(pref), packed_sharding(mesh))
            out = pallas_halo.make_superstep(mesh, rule)(pb, 23)
            assert np.array_equal(np.asarray(packed.unpack(out)), ref), rule

    @pytest.mark.parametrize("mesh_shape", [(1, 1)] + MESHES_2D)
    def test_virtual_in_kernel_bit_identity(self, mesh_shape):
        """The in-kernel 2-D megakernel (virtual build) across chunk
        parities, the chunk/tail seam, and the remainder split."""
        b = self._board()
        p = jnp.asarray(np.asarray(packed.pack(jnp.asarray(b))))
        run = pallas_halo.make_superstep_virtual_2d(
            (int(mesh_shape[0]), int(mesh_shape[1])), CONWAY, with_stats=True
        )
        for turns in (8 * 18, 8 * 18 + 2 * 18 + 7):
            out, _sk, _act = run(p, turns)
            assert np.array_equal(
                np.asarray(packed.unpack(out)), self._oracle(b, turns)
            ), (mesh_shape, turns)

    @pytest.mark.parametrize("mesh_shape", MESHES_2D)
    def test_virtual_equals_ppermute_tier(self, mesh_shape):
        """The two independent 2-D formulations — in-kernel virtual
        emulation vs the real-mesh ppermute tier — agree bit-for-bit
        (each is separately oracle-gated; this pins them to each
        other the way the strip tier pinned loopback to ppermute)."""
        b = self._board()
        turns = 8 * 18
        got_pp, _, _ = self._run_ppermute(b, mesh_shape, turns)
        p = jnp.asarray(np.asarray(packed.pack(jnp.asarray(b))))
        out = pallas_halo.make_superstep_virtual_2d(mesh_shape, CONWAY)(p, turns)
        assert np.array_equal(np.asarray(packed.unpack(out)), got_pp)

    def test_virtual_geometry_candidates(self):
        from distributed_gol_tpu.ops import pallas_packed as pp

        b = self._board()
        turns = 8 * 18
        ref = self._oracle(b, turns)
        p = jnp.asarray(np.asarray(packed.pack(jnp.asarray(b))))
        for geom in pp.geometry_candidates():
            with pp.plan_geometry_override(geom):
                out = pallas_halo.make_superstep_virtual_2d((2, 2), CONWAY)(
                    p, turns
                )
            assert np.array_equal(
                np.asarray(packed.unpack(out)), ref
            ), geom.label

    def test_virtual_skip_and_activity_match_solo_regions(self):
        """Telemetry acceptance: the in-kernel 2-D tier's per-stripe
        activity, OR-reduced over the x axis, equals the solo
        megakernel's per-stripe activity bitmap at the same cap (both
        measure the same exact gen-T vs gen-(T+6) diff per board
        region), and ash tiles skip.  The board is wide enough (wp a
        lane multiple) that the SOLO megakernel runs the tiled adaptive
        path and emits telemetry at all."""
        from distributed_gol_tpu.ops import pallas_packed as pp

        H, W = 4096, 4096
        b = np.zeros((H, W), dtype=np.uint8)
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[2000 + dy, 2040 + dx] = 255  # glider near the column seam
        b[40:42, 20:22] = 255  # still life (measures inactive)
        turns = 8 * 18
        # 16 board stripes at every mesh shape below — and ≥ 4 stripes
        # per device even on (4, 2), so INTERIOR (skippable) stripes
        # exist everywhere (edge stripes are forced-full by design).
        cap = 256
        p = packed.pack(jnp.asarray(b))
        _, _, act_solo = pp.make_superstep(
            CONWAY, skip_stable=True, skip_tile_cap=cap, with_stats=True
        )(jnp.asarray(np.asarray(p)), turns)
        act_solo = np.asarray(act_solo)
        assert act_solo.size == 16 and (act_solo > 0).any()
        for mesh_shape in [(2, 2), (4, 2)]:
            run = pallas_halo.make_superstep_virtual_2d(
                mesh_shape, CONWAY, skip_tile_cap=cap, with_stats=True
            )
            out, sk, act = run(jnp.asarray(np.asarray(p)), turns)
            assert int(sk) > 0, mesh_shape  # ash tiles skipped in-kernel
            act = np.asarray(act)
            assert act.shape == (act_solo.shape[0], mesh_shape[1])
            got = (act > 0).any(axis=1)
            assert np.array_equal(got, act_solo > 0), mesh_shape

    def test_policy_2d_interpret_falls_back_and_plan_gates(self):
        mesh = make_mesh((2, 2))
        use, reason = pallas_halo.ici_tier_policy(mesh, interpret=True)
        assert not use and "interpret" in reason
        # Geometry outranks mesh policy: a tile with no 2-D frontier
        # plan must never record in-kernel.
        use, reason = pallas_halo.ici_tier_policy(
            mesh, interpret=False, strip=(16, 2), tile_cap=None
        )
        assert not use and "no frontier plan" in reason

    def test_plan_2d_gates_exchange_scratch_vmem(self):
        """The in-kernel tier's full-height column-halo slots ride on top
        of the window working set; a tile tall enough that the SUM would
        overflow the compiler's VMEM ceiling must be DECLINED by the plan
        (→ policy fallback) instead of failing at Mosaic allocation time
        on hardware.  Evaluable hermetically: off-TPU, ``_vmem_physical``
        reports the v5e baseline and ``interpret=False`` picks the
        hardware 128-lane xpad — this is exactly the plan a v5e rig
        would compute.  The 262144²/(8, 8) headline tile (32768, 1024)
        sits just UNDER the ceiling at the default 512-row cap (~69 MB
        of halo slots + the capped window request) but overflows with an
        uncapped 1024-row tile; a 65536-row tile (262144² on (4, 8) —
        ~134 MB of halo slots alone) overflows at ANY cap and the policy
        must decline it."""
        from distributed_gol_tpu.ops.pallas_packed import default_skip_cap

        assert pallas_halo._plan_2d((32768, 1024), 18, None, False) is None
        assert (
            pallas_halo._plan_2d(
                (32768, 1024), 18, default_skip_cap(32768), False
            )
            is not None
        )
        assert pallas_halo._plan_2d((65536, 1024), 18, None, False) is None
        use, reason = pallas_halo.ici_tier_policy(
            make_mesh((2, 2)), interpret=False,
            strip=(65536, 1024), tile_cap=None,
        )
        assert not use and "no frontier plan" in reason

    def test_remote_2d_build_traces_hermetically(self):
        """The remote 2-D form cannot RUN off-TPU, but its whole kernel
        body abstract-evals — ten-channel remote descriptors, corner
        routing, the 8-direction barrier, x-neighbour slab decode — so
        Python-level regressions in the remote branch are caught
        hermetically (Mosaic lowering: tools/hw_compile_gate.py)."""
        import jax.numpy as jnp2

        for mesh_shape, shape in (((4, 2), (2048, 512)), ((1, 2), (4096, 512))):
            call = pallas_halo._build_dispatch_frontier_2d(
                shape, mesh_shape, CONWAY, 18, 8, False, 1024, True
            )
            ids = jax.ShapeDtypeStruct((6,), jnp2.int32)
            bb = jax.ShapeDtypeStruct(shape, jnp2.uint32)
            jax.make_jaxpr(call)(ids, bb, bb)

    def test_backend_2d_records_tier_and_matches_solo(self):
        from distributed_gol_tpu.engine.backend import Backend
        from distributed_gol_tpu.engine.params import Params
        from distributed_gol_tpu.ops.pallas_packed import _use_interpret

        common = dict(
            turns=64,
            image_width=8192,
            image_height=4096,
            skip_stable=True,
            superstep=64,
            engine="pallas-packed",
        )
        be = Backend(Params(**common, mesh_shape=(2, 2)))
        assert be.engine_used == "pallas-packed"
        if _use_interpret():
            assert be.sharded_tier == "ppermute"
            assert "interpret" in be.sharded_tier_policy
        else:
            assert be.sharded_tier == "ici-megakernel"
        # ...and the 2-D backend's dispatched boards match a solo run
        # through the Backend seam itself (put/superstep/count/fetch).
        b = np.zeros((4096, 8192), np.uint8)
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[2000 + dy, 4090 + dx] = 255  # glider astride the column seam
        b[10:12, 50:52] = 255
        out, count = be.run_turns(be.put(b), 36)
        solo = Backend(
            Params(
                turns=64, image_width=8192, image_height=4096,
                superstep=64, engine="packed",
            )
        )
        ref, ref_count = solo.run_turns(solo.put(b), 36)
        assert count == ref_count
        assert np.array_equal(be.fetch(out), solo.fetch(ref))
