"""Seeded random-soup input (framework extension, ``Params.soup_density``).

The reference ships its soups as PGM files (``images/WxH.pgm``,
``gol/distributor.go:205``) — fine at 512², impractical at 16384²+ where the
input file alone is hundreds of MB.  A soup run generates the board from a
seeded RNG instead; determinism matters because multi-host followers load
input independently and must agree bit-for-bit.
"""

import queue

import numpy as np
import pytest

import distributed_gol_tpu as gol


def run_final(tmp_path, **kw):
    defaults = dict(image_width=64, image_height=64, engine="roll")
    defaults.update(kw)
    params = gol.Params(
        turns=30,
        out_dir=tmp_path,
        images_dir=tmp_path / "no-images-dir-needed",
        **defaults,
    )
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    seen = []
    while (e := events.get(timeout=60)) is not None:
        seen.append(e)
    return [e for e in seen if isinstance(e, gol.FinalTurnComplete)][0]


def test_soup_is_deterministic_and_seed_sensitive(tmp_path):
    a = run_final(tmp_path, soup_density=0.3, soup_seed=7)
    b = run_final(tmp_path, soup_density=0.3, soup_seed=7)
    c = run_final(tmp_path, soup_density=0.3, soup_seed=8)
    assert sorted(a.alive) == sorted(b.alive)
    assert sorted(a.alive) != sorted(c.alive)
    assert a.completed_turns == 30
    # No input PGM was ever needed.
    assert not (tmp_path / "no-images-dir-needed").exists()


def test_soup_density_validated():
    with pytest.raises(ValueError, match="soup_density"):
        gol.Params(soup_density=1.5)
    with pytest.raises(ValueError, match="soup_density"):
        gol.Params(soup_density=0.0)


def test_cli_soup_flag(tmp_path):
    from distributed_gol_tpu.__main__ import build_parser, params_from_args

    args = build_parser().parse_args(
        ["-w", "64", "-h", "64", "--soup", "0.25", "--soup-seed", "3"]
    )
    p = params_from_args(args)
    assert p.soup_density == 0.25 and p.soup_seed == 3


def test_soup_generator_chunking_is_transparent():
    """The chunked generator equals an unchunked run of the same stream
    (PCG64 fills row-major), and memory stays bounded by construction."""
    from distributed_gol_tpu.utils import soup as soup_mod

    full = soup_mod.random_soup(64, 128, 0.3, seed=5)
    # Same board when the chunk boundary lands mid-array.
    old = soup_mod._CHUNK_ROWS
    try:
        soup_mod._CHUNK_ROWS = 16
        chunked = soup_mod.random_soup(64, 128, 0.3, seed=5)
    finally:
        soup_mod._CHUNK_ROWS = old
    np.testing.assert_array_equal(full, chunked)
    density = np.count_nonzero(full) / full.size
    assert 0.25 < density < 0.35


def test_rectangular_board_cross_engine(tmp_path):
    """Non-square boards through the full run path: engines agree (the
    oracle set is square-only, so this is the cross-engine gate)."""
    finals = {}
    for engine in ("roll", "packed"):
        f = run_final(
            tmp_path,
            soup_density=0.3,
            soup_seed=11,
            image_width=96,
            image_height=40,
            engine=engine,
        )
        finals[engine] = sorted(f.alive)
    assert finals["roll"] == finals["packed"]
    assert finals["roll"]  # something survived 30 turns
