"""Worker process for the multi-host proof (see test_multihost.py).

Each OS process joins the distributed runtime with 4 virtual CPU devices,
builds the process-spanning (8, 1) row mesh, runs the packed word-halo
engine 100 turns at 64² over it, and (process 0) checks bit-identity
against the single-device engine plus the psum'd per-turn counts.

Run: python tests/multihost_worker.py <coordinator> <nprocs> <pid> <okfile>
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Drop any inherited device-count flag (the pytest parent sets 8) before
# pinning this process to 4 — flag parsers don't reliably take the last.
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if not f.startswith("--xla_force_host_platform_device_count")
]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=4"]
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    coordinator, nprocs, pid, okfile = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],
    )
    mode = sys.argv[5] if len(sys.argv) > 5 else "dataplane"
    if mode == "controller":
        return controller_main(coordinator, nprocs, pid, okfile, sys.argv[6])
    if mode == "cycle":
        return cycle_main(coordinator, nprocs, pid, okfile, sys.argv[6])
    if mode == "adaptive":
        return adaptive_main(coordinator, nprocs, pid, okfile, sys.argv[6])
    if mode == "frontier":
        return frontier_main(coordinator, nprocs, pid, okfile, sys.argv[6])
    if mode == "faults":
        return faults_main(coordinator, nprocs, pid, okfile, sys.argv[6])
    if mode == "preempt":
        return preempt_main(coordinator, nprocs, pid, okfile, sys.argv[6])
    if mode == "peerloss":
        return peerloss_main(coordinator, nprocs, pid, okfile, sys.argv[6])
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from distributed_gol_tpu.models.life import CONWAY
    from distributed_gol_tpu.ops import packed
    from distributed_gol_tpu.parallel import multihost, packed_halo

    multihost.initialize(coordinator, nprocs, pid)
    assert len(jax.devices()) == 4 * nprocs, jax.devices()
    assert len(jax.local_devices()) == 4

    mesh = multihost.global_row_mesh()
    rng = np.random.default_rng(42)  # same seed everywhere: shared "PGM"
    board = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
    turns = 100

    pboard_np = np.asarray(packed.pack(jnp.asarray(board)))
    pb = multihost.put_global(pboard_np, packed_halo.packed_sharding(mesh))
    final, counts = packed_halo.sharded_steps_with_counts(mesh, CONWAY)(pb, turns)
    jax.block_until_ready(final)

    final_np = multihost.fetch_global(final)
    counts_np = multihost.fetch_global(counts)[:turns]  # replicated

    # Single-process oracle (local device 0 only).
    want_final, want_counts = packed._steps_with_counts(
        jnp.asarray(pboard_np), CONWAY, turns
    )
    if not np.array_equal(final_np, np.asarray(want_final)):
        print(f"[{pid}] FINAL MISMATCH", flush=True)
        sys.exit(1)
    if not np.array_equal(counts_np, np.asarray(want_counts)):
        print(f"[{pid}] COUNTS MISMATCH", flush=True)
        sys.exit(1)
    with open(okfile, "w") as f:
        f.write("ok")
    print(f"[{pid}] multihost 64x64x{turns} bit-identical over "
          f"{nprocs}-process (8,1) mesh", flush=True)


def controller_main(coordinator, nprocs, pid, okfile, out_dir):
    """Full ``run_distributed`` contract: 64²×100 with a snapshot keypress,
    process 0 checks the stream + files against the reference goldens."""
    import queue
    import threading

    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import distributed_gol_tpu as gol
    from distributed_gol_tpu.parallel import multihost

    multihost.initialize(coordinator, nprocs, pid)
    # Per-process out dirs prove the file-write discipline: only process
    # 0's directory may gain files.
    my_out = os.path.join(out_dir, f"p{pid}")
    os.makedirs(my_out, exist_ok=True)
    params = gol.Params(
        turns=100,
        image_width=64,
        image_height=64,
        images_dir="/root/reference/images",
        out_dir=my_out,
        superstep=10,
        ticker_period=60.0,
    )
    if pid == 0:
        events: queue.Queue = queue.Queue()
        keys: queue.Queue = queue.Queue()
        keys.put("s")  # snapshot via the broadcast keypress path
        seen = []

        def pump():
            while (e := events.get(timeout=120)) is not None:
                seen.append(e)

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        multihost.run_distributed(params, events, keys)
        t.join(timeout=30)

        finals = [e for e in seen if isinstance(e, gol.FinalTurnComplete)]
        assert len(finals) == 1 and finals[0].completed_turns == 100, finals
        snaps = [e for e in seen if isinstance(e, gol.ImageOutputComplete)]
        assert snaps, "snapshot keypress never produced a file event"
        assert os.path.exists(f"{my_out}/{snaps[0].filename}.pgm")
        got = open(f"{my_out}/64x64x100.pgm", "rb").read()
        want = open(
            "/root/reference/check/images/64x64x100.pgm", "rb"
        ).read()
        assert got == want, "multi-host final PGM differs from golden"
        tcs = [
            e.completed_turns for e in seen if isinstance(e, gol.TurnComplete)
        ]
        assert tcs == list(range(1, 101))
    else:
        multihost.run_distributed(params)
        assert not os.listdir(my_out), "follower wrote files"

    # Phase 2+3: 'q'-detach mid-run (broadcast key), checkpoint on process
    # 0's session only, then a fresh multi-host run resumes from the
    # negotiated checkpoint and still lands exactly on the golden board.
    from dataclasses import replace

    from distributed_gol_tpu.engine.session import Session

    # Phase 2 runs at most 90 turns, so phase 3's STATIC turns=100 always
    # finishes on the golden board whether or not the 'q' lands before the
    # run completes (the keypress is sent from an event-consumer thread,
    # which can lag the engine; the detach branch is the overwhelmingly
    # likely one, the race-lost branch still exercises a fresh run).
    long_params = replace(params, turns=90)
    if pid == 0:
        ses = Session(os.path.join(out_dir, "ckpt"))
        events2: queue.Queue = queue.Queue()
        keys2: queue.Queue = queue.Queue()
        seen2 = []

        def pump2():
            sent = False
            while (e := events2.get(timeout=120)) is not None:
                seen2.append(e)
                if (
                    not sent
                    and isinstance(e, gol.TurnComplete)
                    and e.completed_turns >= 20
                ):
                    keys2.put("q")
                    sent = True

        t2 = threading.Thread(target=pump2, daemon=True)
        t2.start()
        multihost.run_distributed(long_params, events2, keys2, ses)
        t2.join(timeout=30)
        final2 = [e for e in seen2 if isinstance(e, gol.FinalTurnComplete)][0]
        detached = final2.alive == ()
        detach_turn = final2.completed_turns
        assert detach_turn >= 20, detach_turn

        events3: queue.Queue = queue.Queue()
        seen3 = []

        def pump3():
            while (e := events3.get(timeout=120)) is not None:
                seen3.append(e)

        t3 = threading.Thread(target=pump3, daemon=True)
        t3.start()
        multihost.run_distributed(replace(params, turns=100), events3, session=ses)
        t3.join(timeout=30)
        final3 = [e for e in seen3 if isinstance(e, gol.FinalTurnComplete)][0]
        assert final3.completed_turns == 100
        got = open(f"{my_out}/64x64x100.pgm", "rb").read()
        assert got == want, "resumed multi-host final PGM differs from golden"
        first_tc = [
            e.completed_turns for e in seen3 if isinstance(e, gol.TurnComplete)
        ][0]
        if detached:
            # Resume really started mid-run: TurnComplete events pick up
            # right after the negotiated detach point.
            assert first_tc == detach_turn + 1, (first_tc, detach_turn)
        else:
            assert first_tc == 1, first_tc
    else:
        multihost.run_distributed(long_params)
        multihost.run_distributed(replace(params, turns=100))

    with open(okfile, "w") as f:
        f.write("ok")
    print(f"[{pid}] controller-mode multihost run ok (incl. detach+resume)",
          flush=True)


def cycle_main(coordinator, nprocs, pid, okfile, out_dir):
    """Multi-host cycle fast-forward: the 64² board settles near turn 1.6k;
    the collective probe (scheduled by dispatch count, so every process
    issues it at the same point) proves period-6 stability, and all
    processes fast-forward the remaining ~10^6 turns in lockstep.  Process
    0 checks the stream and compares the final PGM byte-for-byte against a
    single-device run of the same parameters."""
    import queue
    import threading

    import jax

    jax.config.update("jax_platforms", "cpu")

    import distributed_gol_tpu as gol
    from distributed_gol_tpu.parallel import multihost

    multihost.initialize(coordinator, nprocs, pid)
    my_out = os.path.join(out_dir, f"p{pid}")
    os.makedirs(my_out, exist_ok=True)
    turns = 10**6
    # Hermetic (round 6): a seeded soup — every process generates the
    # identical board, and the proof below is parity against a
    # single-device run of the SAME params, so no reference mount is
    # needed.  Seed 7 settles to period-<=6 ash by ~turn 600.
    params = gol.Params(
        turns=turns,
        image_width=64,
        image_height=64,
        soup_density=0.3,
        soup_seed=7,
        out_dir=my_out,
        superstep=10,
        turn_events="batch",
        ticker_period=60.0,
    )
    if pid == 0:
        events: queue.Queue = queue.Queue()
        seen = []

        def pump():
            while (e := events.get(timeout=120)) is not None:
                seen.append(e)

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        multihost.run_distributed(params, events)
        t.join(timeout=30)

        cycles = [e for e in seen if isinstance(e, gol.CycleDetected)]
        assert len(cycles) == 1, cycles
        final = [e for e in seen if isinstance(e, gol.FinalTurnComplete)][0]
        assert final.completed_turns == turns
        assert len(final.alive) > 0  # settled ash, not an empty board

        # Single-device comparison run (same process, default backend).
        single_out = os.path.join(out_dir, "single")
        os.makedirs(single_out, exist_ok=True)
        from dataclasses import replace

        ev2: queue.Queue = queue.Queue()
        seen2 = []
        gol.run(replace(params, out_dir=single_out), ev2)
        while (e := ev2.get(timeout=120)) is not None:
            seen2.append(e)

        # Multi-host metrics aggregation (ISSUE 4): every process's
        # snapshot travels the broadcast seam and the terminal report
        # merges them — counters SUM across processes, so the aggregated
        # dispatch count is exactly nprocs x the single-device run's (the
        # dispatch schedule is deterministic and identical by SPMD
        # construction).
        from distributed_gol_tpu.obs.metrics import check_metrics_snapshot

        reports = [e for e in seen if isinstance(e, gol.MetricsReport)]
        assert len(reports) == 1, reports
        assert reports[0].processes == nprocs
        snap = reports[0].snapshot
        assert check_metrics_snapshot(snap) == []
        single_snap = [
            e for e in seen2 if isinstance(e, gol.MetricsReport)
        ][0].snapshot
        want = nprocs * single_snap["counters"]["controller.dispatches"]
        assert snap["counters"]["controller.dispatches"] == want, (
            snap["counters"],
            single_snap["counters"],
        )
        got = open(f"{my_out}/64x64x{turns}.pgm", "rb").read()
        want = open(f"{single_out}/64x64x{turns}.pgm", "rb").read()
        assert got == want, "multi-host fast-forward differs from single-device"
    else:
        multihost.run_distributed(params)
        assert not os.listdir(my_out), "follower wrote files"

    with open(okfile, "w") as f:
        f.write("ok")
    print(f"[{pid}] multi-host cycle fast-forward ok ({turns} turns)", flush=True)


def adaptive_main(coordinator, nprocs, pid, okfile, out_dir):
    """Adaptive superstep (superstep=0) + the auto skip_stable long-run
    policy across processes (round-3 verdict, missing-3): the dispatch
    size is wall-clock-driven, so process 0's doubling/halving decisions
    are broadcast and every process runs the identical schedule — proved
    by the run completing (a divergent schedule wedges a collective and
    times the test out) and by the final PGM being byte-identical to a
    single-device run.  turns=10^6 makes ``skip_stable=None`` resolve to
    the auto long-run policy on every process; the 64² board settles near
    turn 1.6k, so the (collective, dispatch-count-scheduled) cycle probe
    bounds the wall-clock."""
    import queue
    import threading

    import jax

    jax.config.update("jax_platforms", "cpu")

    import distributed_gol_tpu as gol
    from distributed_gol_tpu.parallel import multihost

    multihost.initialize(coordinator, nprocs, pid)
    my_out = os.path.join(out_dir, f"p{pid}")
    os.makedirs(my_out, exist_ok=True)
    turns = 10**6
    # Hermetic seeded soup (round 6) — see cycle_main; the proof is
    # parity against a single-device run of the same params.
    params = gol.Params(
        turns=turns,
        image_width=64,
        image_height=64,
        soup_density=0.3,
        soup_seed=7,
        out_dir=my_out,
        superstep=0,  # adaptive: the thing under test
        skip_stable=None,  # auto: resolves to the long-run policy
        max_dispatch_seconds=0.02,  # exercise growth AND the 1.5x shrink guard
        turn_events="batch",
        ticker_period=60.0,
    )
    assert params.skip_stable_requested(), "auto policy should engage here"
    if pid == 0:
        events: queue.Queue = queue.Queue()
        seen = []

        def pump():
            while (e := events.get(timeout=120)) is not None:
                seen.append(e)

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        multihost.run_distributed(params, events)
        t.join(timeout=30)

        final = [e for e in seen if isinstance(e, gol.FinalTurnComplete)][0]
        assert final.completed_turns == turns
        assert len(final.alive) > 0  # settled ash, not an empty board

        # Single-device comparison run, same adaptive params: dispatch
        # partitioning never changes results, so byte-identity holds even
        # though the schedules differ.
        single_out = os.path.join(out_dir, "single")
        os.makedirs(single_out, exist_ok=True)
        from dataclasses import replace

        ev2: queue.Queue = queue.Queue()
        gol.run(replace(params, out_dir=single_out), ev2)
        while ev2.get(timeout=120) is not None:
            pass
        got = open(f"{my_out}/64x64x{turns}.pgm", "rb").read()
        want = open(f"{single_out}/64x64x{turns}.pgm", "rb").read()
        assert got == want, "adaptive multi-host differs from single-device"
    else:
        multihost.run_distributed(params)
        assert not os.listdir(my_out), "follower wrote files"

    with open(okfile, "w") as f:
        f.write("ok")
    print(f"[{pid}] adaptive multi-host run ok ({turns} turns, superstep=0)",
          flush=True)


def frontier_main(coordinator, nprocs, pid, okfile, out_dir):
    """Frontier strip kernel across processes (round 5, VERDICT item 6):
    skip_stable + superstep=0 on a board whose (8,1)-mesh strips host a
    frontier plan (512-row strips), over a multi-dispatch adaptive run —
    the tracked intervals cross the PROCESS boundary on the same
    ppermute as the halo rows.  Bit-identity to a single-device run of
    the same soup proves the whole chain; completing at all proves the
    broadcast dispatch schedule (a divergent schedule wedges a
    collective)."""
    import queue
    import threading

    import jax

    jax.config.update("jax_platforms", "cpu")

    import distributed_gol_tpu as gol
    from distributed_gol_tpu.ops import pallas_packed as pp
    from distributed_gol_tpu.parallel import multihost

    multihost.initialize(coordinator, nprocs, pid)
    my_out = os.path.join(out_dir, f"p{pid}")
    os.makedirs(my_out, exist_ok=True)
    # 600 turns keeps the 0.3 soup far from settled on this geometry, so
    # the frontier plan stays engaged across a long adaptive multi-
    # dispatch chain — the same chain as 2000 turns at a fraction of the
    # suite cost (the soup needs thousands of turns to settle at this
    # size, so the frontier never disengages within the run).
    turns = 600
    params = gol.Params(
        turns=turns,
        image_width=128,
        image_height=4096,
        soup_density=0.3,
        engine="pallas-packed",
        skip_stable=True,
        superstep=0,  # adaptive sizing, broadcast from process 0
        max_dispatch_seconds=0.05,
        out_dir=my_out,
        turn_events="batch",
        ticker_period=60.0,
    )
    # The geometry under test: 512-row strips host a frontier plan.
    assert (
        pp._frontier_plan((512, 4), pp._FRONTIER_T, pp.default_skip_cap(512))
        is not None
    )
    if pid == 0:
        events: queue.Queue = queue.Queue()

        def pump():
            while events.get(timeout=240) is not None:
                pass

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        multihost.run_distributed(params, events)
        t.join(timeout=30)

        # Single-device reference: the packed XLA engine (every engine is
        # bit-identical by contract; packed avoids the interpret-mode
        # single-device lane gate on this narrow board).
        from dataclasses import replace

        single_out = os.path.join(out_dir, "single")
        os.makedirs(single_out, exist_ok=True)
        ev2: queue.Queue = queue.Queue()
        gol.run(
            replace(params, out_dir=single_out, engine="packed", superstep=500),
            ev2,
        )
        while ev2.get(timeout=240) is not None:
            pass
        got = open(f"{my_out}/128x4096x{turns}.pgm", "rb").read()
        want = open(f"{single_out}/128x4096x{turns}.pgm", "rb").read()
        assert got == want, "sharded frontier multihost differs from single"
    else:
        events2: queue.Queue = queue.Queue()

        def pump2():
            while events2.get(timeout=240) is not None:
                pass

        t2 = threading.Thread(target=pump2, daemon=True)
        t2.start()
        multihost.run_distributed(params, events2)
        t2.join(timeout=30)
    open(okfile, "w").write("ok")


def faults_main(coordinator, nprocs, pid, okfile, out_dir):
    """One-sided dispatch failure across processes (ISSUE 2 satellite):
    process 1's backend injects an issue-time fault (retry_limit=0 keeps
    its dispatch schedule short) and aborts; process 0 stays healthy, so
    its next count force blocks in a collective its peer never joins — the
    divergence mode that used to hang forever.  With a dispatch watchdog
    armed, EVERY process must end its stream with the sentinel and abort
    within the deadline: process 1 via the terminal DispatchError path,
    process 0 via DispatchTimeout (or the transport surfacing the dead
    collective, whichever gloo delivers first — both are bounded aborts).

    The faulted peer stays alive (parked on its okfile wait) until the
    survivor has also aborted, so the survivor genuinely exercises the
    hung-collective wait rather than a torn-down transport."""
    import queue
    import threading
    import time
    import traceback

    import jax

    jax.config.update("jax_platforms", "cpu")

    import distributed_gol_tpu as gol
    from distributed_gol_tpu.parallel import multihost
    from distributed_gol_tpu.testing.faults import (
        Fault,
        FaultInjectionBackend,
        FaultPlan,
    )

    try:
        multihost.initialize(coordinator, nprocs, pid)
        my_out = os.path.join(out_dir, f"p{pid}")
        os.makedirs(my_out, exist_ok=True)
        params = gol.Params(
            turns=400,
            image_width=64,
            image_height=64,
            soup_density=0.3,
            out_dir=my_out,
            superstep=10,
            retry_limit=0,
            dispatch_deadline_seconds=3.0,
            cycle_check=0,
            turn_events="batch",
            ticker_period=60.0,
        )
        # The injection seam: only process 1's backend is wrapped — the
        # fault is genuinely one-sided.
        real_make = multihost.make_backend

        def make_faulty(p):
            backend = real_make(p)
            if pid == 1:
                backend = FaultInjectionBackend(
                    backend, FaultPlan([Fault(4, "issue")])
                )
            return backend

        multihost.make_backend = make_faulty

        events: queue.Queue = queue.Queue()
        sentinel = threading.Event()
        seen = []

        def pump():
            while True:
                e = events.get(timeout=120)
                if e is None:
                    sentinel.set()
                    return
                seen.append(e)

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        t0 = time.monotonic()
        err = None
        try:
            multihost.run_distributed(params, events)
        except BaseException as e:  # noqa: BLE001 — the abort under test
            err = e
        elapsed = time.monotonic() - t0
        assert err is not None, "one-sided failure must abort the run"
        assert sentinel.wait(10), "stream did not end with the sentinel"
        assert elapsed < 90, f"abort took {elapsed:.0f}s — watchdog must bound it"
        if pid == 0:
            # The survivor aborts by whichever bounded exit trips first —
            # all three are clean sentinel aborts and which one wins is a
            # race between the peer's teardown and the next collective:
            #   (a) the watchdog on a control-plane broadcast
            #       (DispatchTimeout, no dispatch failed → no DispatchError),
            #   (b) a failed or timed-out dispatch (terminal DispatchError,
            #       checkpoint skipped by the multi-host park policy),
            #   (c) the transport noticing the dead peer first (a gloo
            #       "connection closed" runtime error from a collective).
            errors = [e for e in seen if isinstance(e, gol.DispatchError)]
            if errors:
                assert not errors[-1].will_retry, errors
                assert not errors[-1].checkpointed
            else:
                assert isinstance(err, gol.DispatchTimeout) or (
                    "closed" in str(err).lower()
                    or "gloo" in str(err).lower()
                    or "unavailable" in str(err).lower()
                ), err
        with open(okfile, "w") as f:
            f.write("ok")
        print(
            f"[{pid}] one-sided failure: sentinel + abort in {elapsed:.1f}s "
            f"({type(err).__name__}: {err})",
            flush=True,
        )
    except BaseException:
        traceback.print_exc()
        os._exit(1)
    # Wait for the peer's okfile so the transport stays up while IT aborts;
    # then exit hard — abandoned watchdog waits and the distributed
    # runtime's service threads must not wedge interpreter shutdown.  The
    # peer's okfile is THIS process's okfile with the rank digit swapped
    # (the launcher suffixes okfiles per attempt, so rebuilding the name
    # from scratch would wait on a file that never appears and burn the
    # whole deadline on both ranks).  The cap only binds when the peer
    # cannot abort until this process's transport dies — keep it well
    # clear of dispatch_deadline_seconds without parking for a minute.
    assert str(okfile).endswith(str(pid))
    peer = str(okfile)[: -len(str(pid))] + str(1 - pid)
    deadline = time.time() + 20
    while not os.path.exists(peer) and time.time() < deadline:
        time.sleep(0.5)
    os._exit(0)


def preempt_main(coordinator, nprocs, pid, okfile, out_dir):
    """One-sided SIGTERM mid-run (ISSUE 5 tentpole leg 2, multi-host):
    process 1 — a FOLLOWER, not the controller — receives a real SIGTERM
    while the collective is mid-flight.  Its GracefulStop latch is polled
    collectively (MultihostController._stop_now allgathers the flags), so
    BOTH ranks observe the stop at the same turn boundary, enter the
    emergency-checkpoint fetch together, and exit paused-and-resumable
    within a bound — instead of the signalled rank vanishing and wedging
    the survivor in a dead collective.  A resumed multi-host run then
    completes and lands byte-identically on a single-device run of the
    same parameters."""
    import queue
    import signal
    import threading
    import time
    import traceback

    import jax

    jax.config.update("jax_platforms", "cpu")

    import distributed_gol_tpu as gol
    from distributed_gol_tpu.engine.session import Session
    from distributed_gol_tpu.engine.supervisor import GracefulStop
    from distributed_gol_tpu.parallel import multihost

    try:
        multihost.initialize(coordinator, nprocs, pid)
        my_out = os.path.join(out_dir, f"p{pid}")
        os.makedirs(my_out, exist_ok=True)
        # turns is effectively unbounded for phase 1 (the stop ends it);
        # cycle_check=0 keeps the run dispatching until then.  Phase 2
        # (resume) re-enables the cycle probe, so the settled 64² soup
        # fast-forwards the tail and the whole test stays bounded.
        params = gol.Params(
            turns=10**6,
            image_width=64,
            image_height=64,
            soup_density=0.3,
            soup_seed=7,
            out_dir=my_out,
            superstep=10,
            cycle_check=0,
            turn_events="batch",
            ticker_period=60.0,
        )
        stop = GracefulStop()
        stop.install((signal.SIGTERM,))
        ckpt_dir = os.path.join(out_dir, "ckpt")
        started_marker = os.path.join(out_dir, "started")

        if pid == 1:
            # The one-sided signal: SIGTERM to SELF once process 0 has
            # seen real progress (the marker), i.e. genuinely mid-run.
            def send_sigterm():
                deadline = time.time() + 120
                while not os.path.exists(started_marker) and time.time() < deadline:
                    time.sleep(0.05)
                time.sleep(0.3)  # land between turn boundaries
                os.kill(os.getpid(), signal.SIGTERM)

            threading.Thread(target=send_sigterm, daemon=True).start()

        t0 = time.monotonic()
        if pid == 0:
            ses = Session(ckpt_dir)
            events: queue.Queue = queue.Queue()
            seen = []

            def pump():
                while (e := events.get(timeout=180)) is not None:
                    seen.append(e)
                    if isinstance(
                        e, (gol.TurnComplete, gol.TurnsCompleted)
                    ) and not os.path.exists(started_marker):
                        open(started_marker, "w").write("go")

            t = threading.Thread(target=pump, daemon=True)
            t.start()
            multihost.run_distributed(params, events, None, ses, stop=stop)
            t.join(timeout=30)
            elapsed = time.monotonic() - t0
            assert elapsed < 120, f"preempt drain took {elapsed:.0f}s"
            final = [e for e in seen if isinstance(e, gol.FinalTurnComplete)][0]
            assert final.alive == (), "preempt must exit paused, not complete"
            preempt_turn = final.completed_turns
            assert 0 < preempt_turn < params.turns, preempt_turn
            saved = [e for e in seen if isinstance(e, gol.CheckpointSaved)]
            assert saved and saved[-1].completed_turns == preempt_turn
            report = [e for e in seen if isinstance(e, gol.MetricsReport)][0]
            # The signal landed on rank 1 only; the aggregated report
            # (counters sum across processes) must show exactly one latch
            # observed by the collective.
            assert report.snapshot["counters"]["preempt.signals"] == nprocs
        else:
            multihost.run_distributed(params, stop=stop)
            elapsed = time.monotonic() - t0
            assert elapsed < 120, f"preempt drain took {elapsed:.0f}s"
            assert stop.requested and stop.signum == signal.SIGTERM

        # Phase 2: the resumed multi-host run completes from the emergency
        # checkpoint and lands byte-identically on a single-device run.
        from dataclasses import replace

        resumed = replace(params, cycle_check=8)
        if pid == 0:
            events2: queue.Queue = queue.Queue()
            seen2 = []

            def pump2():
                while (e := events2.get(timeout=180)) is not None:
                    seen2.append(e)

            t2 = threading.Thread(target=pump2, daemon=True)
            t2.start()
            multihost.run_distributed(resumed, events2, None, Session(ckpt_dir))
            t2.join(timeout=30)
            final2 = [e for e in seen2 if isinstance(e, gol.FinalTurnComplete)][0]
            assert final2.completed_turns == params.turns
            first_turns = [
                e
                for e in seen2
                if isinstance(e, (gol.TurnComplete, gol.TurnsCompleted))
            ][0]
            first = (
                first_turns.first_turn
                if isinstance(first_turns, gol.TurnsCompleted)
                else first_turns.completed_turns
            )
            assert first == preempt_turn + 1, (first, preempt_turn)

            single_out = os.path.join(out_dir, "single")
            os.makedirs(single_out, exist_ok=True)
            ev3: queue.Queue = queue.Queue()
            gol.run(replace(resumed, out_dir=single_out), ev3)
            while ev3.get(timeout=180) is not None:
                pass
            got = open(f"{my_out}/64x64x{params.turns}.pgm", "rb").read()
            want = open(f"{single_out}/64x64x{params.turns}.pgm", "rb").read()
            assert got == want, "preempted+resumed run differs from single-device"
        else:
            multihost.run_distributed(resumed)

        with open(okfile, "w") as f:
            f.write("ok")
        print(f"[{pid}] one-sided SIGTERM: collective drain + resume ok", flush=True)
    except BaseException:
        traceback.print_exc()
        os._exit(1)
    os._exit(0)


def peerloss_main(coordinator, nprocs, pid, okfile, out_dir):
    """Hard peer death mid-run (ISSUE 7 multihost leg): process 1 — a
    FOLLOWER — SIGKILLs itself once the survivor has committed a periodic
    checkpoint.  No drain, no teardown: the corpse never joins another
    collective.  With the peer heartbeat armed (``Params.
    peer_heartbeat_seconds``), the survivor must exit within a bound —
    via :class:`multihost.PeerLost` from its own liveness monitor when
    the turn boundary gets there first, or via the dispatch watchdog /
    the transport surfacing the closed connection when the kill lands
    mid-collective; all are clean sentinel aborts, never the
    coordination service's multi-minute no-sentinel hard-kill.  Symmetric
    injected dispatch latency paces the run so boundaries (where only the
    heartbeat can detect) dominate the cycle.  The newest periodic
    checkpoint then resumes on a single device and lands byte-identically
    on a never-killed single-device run — device loss shrank the
    topology; it did not cost committed progress."""
    import queue
    import signal
    import threading
    import time
    import traceback

    import jax

    jax.config.update("jax_platforms", "cpu")

    import distributed_gol_tpu as gol
    from distributed_gol_tpu.engine.session import Session
    from distributed_gol_tpu.parallel import multihost
    from distributed_gol_tpu.testing.faults import (
        Fault,
        FaultInjectionBackend,
        FaultPlan,
    )

    try:
        multihost.initialize(coordinator, nprocs, pid)
        my_out = os.path.join(out_dir, f"p{pid}")
        os.makedirs(my_out, exist_ok=True)
        params = gol.Params(
            turns=10**6,  # effectively unbounded: the kill ends phase 1
            image_width=64,
            image_height=64,
            soup_density=0.3,
            soup_seed=7,
            out_dir=my_out,
            superstep=10,
            cycle_check=0,
            checkpoint_every_turns=10,
            peer_heartbeat_seconds=0.1,  # dead-peer bound: 0.3 s
            dispatch_deadline_seconds=10.0,  # backstop, not the detector
            turn_events="batch",
            ticker_period=60.0,
        )
        # Symmetric pacing: every dispatch from 1 on sleeps 0.5 s on BOTH
        # ranks (deterministic, identical schedules), so the kill almost
        # always lands while both ranks are OUTSIDE a collective and the
        # heartbeat — not the transport — is what notices.
        real_make = multihost.make_backend
        plan = FaultPlan(
            [Fault(i, "latency", seconds=0.5) for i in range(1, 400)]
        )
        multihost.make_backend = lambda p: FaultInjectionBackend(
            real_make(p), plan
        )

        ckpt_dir = os.path.join(out_dir, "ckpt")
        started_marker = os.path.join(out_dir, "started")

        if pid == 1:
            # The hard death: SIGKILL to SELF once the survivor has a
            # durable checkpoint — no handlers run, no socket linger.
            def die():
                deadline = time.time() + 120
                while not os.path.exists(started_marker) and time.time() < deadline:
                    time.sleep(0.05)
                time.sleep(0.25)  # land mid-latency-sleep, between boundaries
                os.kill(os.getpid(), signal.SIGKILL)

            threading.Thread(target=die, daemon=True).start()

        t0 = time.monotonic()
        if pid == 0:
            ses = Session(ckpt_dir)
            events: queue.Queue = queue.Queue()
            sentinel = threading.Event()
            seen = []

            def pump():
                while True:
                    e = events.get(timeout=180)
                    if e is None:
                        sentinel.set()
                        return
                    seen.append(e)
                    if isinstance(e, gol.CheckpointSaved) and not os.path.exists(
                        started_marker
                    ):
                        open(started_marker, "w").write("go")

            t = threading.Thread(target=pump, daemon=True)
            t.start()
            err = None
            try:
                multihost.run_distributed(params, events, None, ses)
            except BaseException as e:  # noqa: BLE001 — the abort under test
                err = e
            elapsed = time.monotonic() - t0
            assert err is not None, "peer SIGKILL must abort the survivor"
            assert sentinel.wait(10), "stream did not end with the sentinel"
            # Bounded: heartbeat (0.3 s) or watchdog (10 s) plus slack —
            # never the coordination service's multi-minute hard-kill.
            assert elapsed < 90, f"survivor exit took {elapsed:.0f}s"
            if isinstance(err, multihost.PeerLost):
                assert "1" in str(err), err
                # The liveness monitor documented the loss in telemetry.
                reports = [e for e in seen if isinstance(e, gol.MetricsReport)]
                if reports:
                    counters = reports[0].snapshot["counters"]
                    assert counters.get("multihost.peers_lost", 0) >= 1
            else:
                # The kill landed inside a collective: the transport or
                # the watchdog got there first — equally bounded.
                print(f"[0] transport beat the heartbeat: {type(err).__name__}",
                      flush=True)
            saved = [e for e in seen if isinstance(e, gol.CheckpointSaved)]
            assert saved, "no periodic checkpoint before the kill"

            # Phase 2: the survivor resumes SINGLE-DEVICE from the newest
            # periodic checkpoint (the dead rank cannot come back) and
            # must land byte-identically on a never-killed run.
            from dataclasses import replace

            resumed = replace(
                params,
                cycle_check=8,  # settles + fast-forwards: bounded turns
                peer_heartbeat_seconds=0.0,
                dispatch_deadline_seconds=0.0,
                checkpoint_every_turns=0,
            )
            multihost.make_backend = real_make  # plan stays off phase 2
            ev2: queue.Queue = queue.Queue()
            seen2 = []
            gol.run(resumed, ev2, session=Session(ckpt_dir))
            while (e := ev2.get(timeout=180)) is not None:
                seen2.append(e)
            final2 = [e for e in seen2 if isinstance(e, gol.FinalTurnComplete)][0]
            assert final2.completed_turns == params.turns

            single_out = os.path.join(out_dir, "single")
            os.makedirs(single_out, exist_ok=True)
            ev3: queue.Queue = queue.Queue()
            gol.run(replace(resumed, out_dir=single_out), ev3)
            while ev3.get(timeout=180) is not None:
                pass
            got = open(f"{my_out}/64x64x{params.turns}.pgm", "rb").read()
            want = open(f"{single_out}/64x64x{params.turns}.pgm", "rb").read()
            assert got == want, "post-peerloss resume differs from oracle"

            with open(okfile, "w") as f:
                f.write("ok")
            print(
                f"[0] peer loss: bounded exit in {elapsed:.1f}s "
                f"({type(err).__name__}) + resumed to oracle",
                flush=True,
            )
        else:
            # The victim: runs until the SIGKILL takes it.  Nothing below
            # should be reached; if the kill never lands, time out hard so
            # the launcher sees the failure.
            try:
                multihost.run_distributed(params)
            except BaseException:  # noqa: BLE001 — teardown races are fine
                pass
            time.sleep(180)
            os._exit(1)
    except BaseException:
        traceback.print_exc()
        os._exit(1)
    os._exit(0)


if __name__ == "__main__":
    main()
