"""Worker process for the multi-host proof (see test_multihost.py).

Each OS process joins the distributed runtime with 4 virtual CPU devices,
builds the process-spanning (8, 1) row mesh, runs the packed word-halo
engine 100 turns at 64² over it, and (process 0) checks bit-identity
against the single-device engine plus the psum'd per-turn counts.

Run: python tests/multihost_worker.py <coordinator> <nprocs> <pid> <okfile>
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# Drop any inherited device-count flag (the pytest parent sets 8) before
# pinning this process to 4 — flag parsers don't reliably take the last.
_flags = [
    f
    for f in os.environ.get("XLA_FLAGS", "").split()
    if not f.startswith("--xla_force_host_platform_device_count")
]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=4"]
)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    coordinator, nprocs, pid, okfile = (
        sys.argv[1],
        int(sys.argv[2]),
        int(sys.argv[3]),
        sys.argv[4],
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from distributed_gol_tpu.models.life import CONWAY
    from distributed_gol_tpu.ops import packed
    from distributed_gol_tpu.parallel import multihost, packed_halo

    multihost.initialize(coordinator, nprocs, pid)
    assert len(jax.devices()) == 4 * nprocs, jax.devices()
    assert len(jax.local_devices()) == 4

    mesh = multihost.global_row_mesh()
    rng = np.random.default_rng(42)  # same seed everywhere: shared "PGM"
    board = np.where(rng.random((64, 64)) < 0.3, 255, 0).astype(np.uint8)
    turns = 100

    pboard_np = np.asarray(packed.pack(jnp.asarray(board)))
    pb = multihost.put_global(pboard_np, packed_halo.packed_sharding(mesh))
    final, counts = packed_halo.sharded_steps_with_counts(mesh, CONWAY)(pb, turns)
    jax.block_until_ready(final)

    final_np = multihost.fetch_global(final)
    counts_np = multihost.fetch_global(counts)[:turns]  # replicated

    # Single-process oracle (local device 0 only).
    want_final, want_counts = packed._steps_with_counts(
        jnp.asarray(pboard_np), CONWAY, turns
    )
    if not np.array_equal(final_np, np.asarray(want_final)):
        print(f"[{pid}] FINAL MISMATCH", flush=True)
        sys.exit(1)
    if not np.array_equal(counts_np, np.asarray(want_counts)):
        print(f"[{pid}] COUNTS MISMATCH", flush=True)
        sys.exit(1)
    with open(okfile, "w") as f:
        f.write("ok")
    print(f"[{pid}] multihost 64x64x{turns} bit-identical over "
          f"{nprocs}-process (8,1) mesh", flush=True)


if __name__ == "__main__":
    main()
