"""Multi-host distributed backend proof (the reference's multi-machine tier).

The reference needs 1 broker + 4 worker EC2 machines and re-broadcasts the
whole board to every worker every turn (``broker/broker.go:37-56``).  Here
the same capability is a process-spanning mesh: two OS processes × four
virtual CPU devices join one JAX distributed runtime, the packed word-halo
engine runs over the global (8, 1) mesh with `ppermute` crossing the
process boundary (gloo — the DCN stand-in), and the result is bit-identical
to the single-device engine.  See ``parallel/multihost.py``.
"""

import socket
import subprocess
import sys
from pathlib import Path

import pytest

from tests.conftest import needs_reference

WORKER = Path(__file__).parent / "multihost_worker.py"


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# The known multihost flake class under full-suite rig load (CHANGES.md
# PR-10 note): the gloo DCN stand-in's transport tears down mid-collective
# in a worker subprocess, or a collective wedges until the watchdog —
# plus the TOCTOU between ``free_port()`` closing its probe socket and the
# coordinator binding it (another suite process can grab the port in
# between).  ``_launch_workers`` therefore isolates the coordination port
# PER ATTEMPT (a fresh ``free_port()`` each time, ok-files suffixed so a
# half-failed attempt can't satisfy the next) and retries ONCE when the
# failure carries the transport-crash signature or timed out; a second
# failure — or any failure without the signature — is a real regression
# and fails the test.
# Deliberately NARROW: gloo/socket/port strings plus the two gRPC status
# codes the distributed runtime surfaces for transport loss.  The
# wedged-collective half of the flake class rarely prints anything — it
# manifests as the 240 s communicate() timeout, which retries via the
# separate ``timed_out`` flag.  A deterministic failure (wrong board,
# assertion, crash in the code under test) matches neither and fails on
# the first attempt.
_TRANSPORT_FLAKE_SIGNS = (
    "gloo",
    "Gloo",
    "transport",
    "Connection reset",
    "Connection closed",
    "Socket closed",
    "connection refused",
    "Address already in use",
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
)


def _launch_workers_once(tmp_path, mode, extra, attempt):
    """One cohort launch on a fresh coordinator port; returns
    (outs, returncodes, okfiles, timed_out)."""
    nprocs = 2
    coordinator = f"127.0.0.1:{free_port()}"
    okfiles = [tmp_path / f"ok{attempt}_{i}" for i in range(nprocs)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), coordinator, str(nprocs), str(i),
             str(okfiles[i]), mode, *extra],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(nprocs)
    ]
    outs = []
    timed_out = False
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            timed_out = True
            for q in procs:
                q.kill()
            out, _ = p.communicate()
        outs.append(out or "")
    return outs, [p.returncode for p in procs], okfiles, timed_out


def _launch_workers(tmp_path, mode, extra=(), retries=1):
    for attempt in range(retries + 1):
        outs, rcs, okfiles, timed_out = _launch_workers_once(
            tmp_path, mode, extra, attempt
        )
        if all(rc == 0 for rc in rcs) and all(f.exists() for f in okfiles):
            return
        blob = "\n".join(outs)
        flaky = timed_out or any(s in blob for s in _TRANSPORT_FLAKE_SIGNS)
        if attempt < retries and flaky:
            # Bounded retry on the known-flake signature only — and leave
            # the first attempt's tail on stdout so a recurring flake
            # records what it actually printed (pytest -rA / CI logs).
            print(
                f"[multihost {mode}] attempt {attempt} hit the transport-"
                f"flake signature (timed_out={timed_out}); retrying on a "
                f"fresh port. Tail:\n{blob[-2000:]}"
            )
            continue
        if timed_out:
            pytest.fail(
                "multihost worker timed out (collectives wedged?):\n"
                + blob[-3000:]
            )
        for i, rc in enumerate(rcs):
            assert rc == 0, f"worker {i} failed:\n{outs[i][-3000:]}"
            assert okfiles[i].exists(), f"worker {i} produced no ok-file"
        return


def test_two_process_mesh_bit_identical(tmp_path):
    _launch_workers(tmp_path, "dataplane")


@needs_reference
def test_two_process_full_controller_run(tmp_path):
    """The whole reference contract across processes: run_distributed on a
    2-process mesh — event stream, broadcast snapshot keypress, file-write
    discipline, golden final PGM (see multihost_worker.controller_main).
    Golden-gated: needs the reference mount (the hermetic cross-process
    proofs are the cycle/adaptive/frontier tests below)."""
    out = tmp_path / "out"
    out.mkdir()
    _launch_workers(tmp_path, "controller", extra=(str(out),))


def test_two_process_cycle_fast_forward(tmp_path):
    """The whole-board cycle probe across processes: the probe is a
    collective scheduled by dispatch count, every process proves the
    cycle at the same point and fast-forwards ~10^6 turns in lockstep;
    final PGM byte-identical to a single-device run (see
    multihost_worker.cycle_main)."""
    out = tmp_path / "out"
    out.mkdir()
    _launch_workers(tmp_path, "cycle", extra=(str(out),))


def test_two_process_adaptive_superstep(tmp_path):
    """superstep=0 (adaptive) + auto skip_stable policy across processes:
    process 0's wall-clock-driven sizing decisions are broadcast so the
    dispatch schedule stays identical everywhere; final PGM byte-identical
    to a single-device adaptive run (see multihost_worker.adaptive_main)."""
    out = tmp_path / "out"
    out.mkdir()
    _launch_workers(tmp_path, "adaptive", extra=(str(out),))


@needs_reference
def test_cli_multihost_run(tmp_path):
    """The CLI's multi-host mode: the same command on two 'hosts'
    (--process-id 0/1), golden-checked output from process 0."""
    coordinator = f"127.0.0.1:{free_port()}"
    outs = [tmp_path / f"out{i}" for i in range(2)]
    for o in outs:
        o.mkdir()
    env = dict(__import__("os").environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "distributed_gol_tpu",
             "-w", "64", "-h", "64", "-turns", "100", "-noVis",
             "--superstep", "10",
             "--images-dir", "/root/reference/images",
             "--out-dir", str(outs[i]),
             "--coordinator", coordinator,
             "--num-processes", "2", "--process-id", str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
            cwd="/root/repo",
        )
        for i in range(2)
    ]
    outs_txt = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("CLI multihost run timed out")
        outs_txt.append(out)
    for i, p in enumerate(procs):
        assert p.returncode == 0, f"process {i} failed:\n{outs_txt[i][-3000:]}"
    assert "Final turn 100" in outs_txt[0]
    got = (outs[0] / "64x64x100.pgm").read_bytes()
    want = open("/root/reference/check/images/64x64x100.pgm", "rb").read()
    assert got == want
    assert not list(outs[1].iterdir()), "follower wrote files"


def test_one_sided_failure_aborts_every_process(tmp_path):
    """ISSUE 2 satellite: an injected one-sided dispatch failure (process
    1's backend faults; process 0 stays healthy) must end in a bounded
    abort with the stream sentinel on EVERY process — the survivor's
    dispatch watchdog (Params.dispatch_deadline_seconds) breaks it out of
    the collective its dead peer never joins, instead of the pre-watchdog
    behaviour of hanging there forever (see multihost_worker.faults_main
    for the per-process assertions, including the abort-time bound)."""
    out = tmp_path / "out"
    out.mkdir()
    _launch_workers(tmp_path, "faults", extra=(str(out),))


def test_one_sided_sigterm_drains_the_collective(tmp_path):
    """ISSUE 5 tentpole leg 2, multi-host: a real SIGTERM lands on a
    FOLLOWER rank mid-run; the collective stop poll makes every rank
    observe it at the same turn boundary, force the emergency checkpoint
    together (process 0 persists it), and exit paused-and-resumable,
    bounded — then a resumed multi-host run completes byte-identically to
    a single-device run (see multihost_worker.preempt_main)."""
    out = tmp_path / "out"
    out.mkdir()
    _launch_workers(tmp_path, "preempt", extra=(str(out),))


def test_peer_sigkill_bounded_abort_and_resume(tmp_path):
    """ISSUE 7 multihost leg: a FOLLOWER rank dies HARD (SIGKILL — no
    drain, no teardown) mid-run with the peer heartbeat armed
    (``Params.peer_heartbeat_seconds``).  The survivor must exit with the
    stream sentinel within a bound — PeerLost from its own liveness
    monitor, or the watchdog/transport when the kill lands mid-collective
    (the same bounded-abort race ``faults_main`` documents) — and then
    resume the newest periodic checkpoint single-device, byte-identical
    to a never-killed run (see multihost_worker.peerloss_main).  The
    victim's exit code IS the SIGKILL; only the survivor writes an
    ok-file."""
    out = tmp_path / "out"
    out.mkdir()
    nprocs = 2
    coordinator = f"127.0.0.1:{free_port()}"
    okfiles = [tmp_path / f"ok{i}" for i in range(nprocs)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), coordinator, str(nprocs), str(i),
             str(okfiles[i]), "peerloss", str(out)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(nprocs)
    ]
    outs = []
    for p in procs:
        try:
            o, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("peerloss worker timed out (survivor wedged?)")
        outs.append(o)
    assert procs[1].returncode == -9, (
        f"victim should die by SIGKILL, got {procs[1].returncode}:\n"
        f"{outs[1][-3000:]}"
    )
    assert not okfiles[1].exists(), "the corpse wrote an ok-file"
    assert procs[0].returncode == 0, f"survivor failed:\n{outs[0][-3000:]}"
    assert okfiles[0].exists(), "survivor produced no ok-file"


def test_two_process_frontier_parity(tmp_path):
    """Round-5 frontier strip kernel across a process-spanning mesh:
    skip_stable + superstep=0 on 512-row strips (frontier plan engaged),
    multi-dispatch, bit-identical to a single-device run (see
    multihost_worker.frontier_main) — VERDICT round-4 'next' item 6."""
    out = tmp_path / "out"
    out.mkdir()
    _launch_workers(tmp_path, "frontier", extra=(str(out),))
