"""Port of the reference's TestAlive (count_test.go): the AliveCellsCount
telemetry stream.

Contract: events carry (completed_turns, count) pairs where count is exactly
the alive count at that turn (our engine reports exact pairs; the reference
latched one behind, quirk Q7, which its own test tolerated only because it
indexes by the event's turn).  Golden series: check/alive/WxH.csv turns
1..10000; beyond 10000 the 512² board is a period-2 oscillator (5565 even /
5567 odd, count_test.go:45-51).
"""

import csv
import queue
import threading
import time

import pytest

import distributed_gol_tpu as gol


def read_alive_csv(path):
    with open(path) as f:
        rows = list(csv.reader(f))
    return {int(t): int(c) for t, c in rows[1:]}


def expected_count(expected: dict, turn: int, size: int) -> int | None:
    if turn == 0:
        return None  # pre-first-turn tick; CSV starts at turn 1
    if turn <= 10_000:
        return expected[turn]
    if size == 512:
        return 5567 if turn % 2 else 5565
    return None


def test_alive_counts_cadence_and_values(tmp_path, input_images, golden_alive):
    """The reference's shape: long run (Turns=1e8), 2s default ticker, first
    count event within a 5s watchdog, first events checked against the CSV,
    then a 'q' graceful quit (count_test.go:19-68)."""
    expected = read_alive_csv(golden_alive / "512x512.csv")
    params = gol.Params(
        turns=10**8,
        image_width=512,
        image_height=512,
        images_dir=input_images,
        out_dir=tmp_path,
    )
    events: queue.Queue = queue.Queue()
    keys: queue.Queue = queue.Queue()
    t = gol.start(params, events, keys)

    deadline = time.monotonic() + 5.0  # the 5-second watchdog
    counts_seen = 0
    while counts_seen < 3:
        timeout = (
            deadline - time.monotonic() if counts_seen == 0 else 30.0
        )
        assert timeout > 0, "no AliveCellsCount within 5s of start"
        e = events.get(timeout=timeout)
        assert e is not None, "stream ended before any count event"
        if isinstance(e, gol.AliveCellsCount):
            counts_seen += 1
            exp = expected_count(expected, e.completed_turns, 512)
            if exp is not None:
                assert e.cells_count == exp, f"turn {e.completed_turns}"
    keys.put("q")  # graceful quit, also exercises the detach path
    t.join(timeout=60)
    assert not t.is_alive()
    # Drain to the sentinel; a FinalTurnComplete must be present.
    finals = []
    while (e := events.get(timeout=30)) is not None:
        if isinstance(e, gol.FinalTurnComplete):
            finals.append(e)
    assert len(finals) == 1


def test_fast_ticker_exact_pairs(tmp_path, input_images, golden_alive):
    """Every (turn, count) pair the ticker ever emits matches the golden
    series — run bounded so all turns stay within the CSV."""
    expected = read_alive_csv(golden_alive / "64x64.csv")
    params = gol.Params(
        turns=3000,
        image_width=64,
        image_height=64,
        images_dir=input_images,
        out_dir=tmp_path,
        ticker_period=0.02,
        superstep=2,
    )
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    checked = 0
    while (e := events.get(timeout=30)) is not None:
        if isinstance(e, gol.AliveCellsCount):
            exp = expected_count(expected, e.completed_turns, 64)
            if exp is not None:
                assert e.cells_count == exp, f"turn {e.completed_turns}"
                checked += 1
    assert checked >= 3, "ticker produced too few checkable events"


def test_turn_complete_stream_is_dense(tmp_path, input_images):
    """TurnComplete events are emitted for every turn in order, regardless
    of superstep batching."""
    params = gol.Params(
        turns=137,
        image_width=16,
        image_height=16,
        images_dir=input_images,
        out_dir=tmp_path,
        superstep=10,
    )
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    turns = []
    while (e := events.get(timeout=30)) is not None:
        if isinstance(e, gol.TurnComplete):
            turns.append(e.completed_turns)
    assert turns == list(range(1, 138))
