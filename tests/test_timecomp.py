"""Time-compression tier (ISSUE 16): fast-forward settled boards through
TIME, not just space — exactly.

The contract under test: with ``Params.time_compression`` on, a run that
settles into ash is delivered in ``p·2^k``-generation zero-launch chunks
(rung 1), its per-phase counts memoized process-wide (rung 2, the
:class:`AshCache`), with every fast-forwarded interval entered and exited
through the independent SDC roll-stencil guard — and the result is
BIT-IDENTICAL to the dense oracle across engines, meshes, checkpoint/
resume, and supervisor restarts.  With the knob off (the default), the
tier must be byte-for-byte absent: no counters, no sidecar fields.
"""

import json
import queue
import warnings
from pathlib import Path

import numpy as np
import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine import pgm
from distributed_gol_tpu.engine import timecomp as timecomp_lib
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.events import (
    CycleDetected,
    DispatchError,
    FinalTurnComplete,
    TurnComplete,
    TurnsCompleted,
)
from distributed_gol_tpu.engine.session import Session
from distributed_gol_tpu.models.life import CONWAY, parse_rule
from distributed_gol_tpu.obs import flight as flight_lib
from distributed_gol_tpu.obs import metrics as obs_metrics
from distributed_gol_tpu.testing.faults import Fault, FaultInjectionBackend, FaultPlan

from tests.oracle import oracle_run

REPO = Path(__file__).resolve().parent.parent

#: The ash board's methuselah (a T-tetromino) burns to a traffic light by
#: generation ~10; 36 is the first multiple of 6 safely past settling, so
#: board(t) == board(36 + (t - 36) % 6) for every t >= 36.
SETTLE = 36


def ash_board(size: int) -> np.ndarray:
    """A lattice of blocks and blinkers with one T-tetromino in a cleared
    centre: genuinely active at t=0 (the probe must NOT pass early),
    settled into whole-board period-<=6 ash well before ``SETTLE``, and
    glider-free (an escaping glider on the torus would never settle)."""
    b = np.zeros((size, size), np.uint8)
    for y in range(2, size - 8, 16):
        for x in range(2, size - 8, 16):
            b[y : y + 2, x : x + 2] = 255  # block
    for y in range(10, size - 8, 16):
        for x in range(8, size - 8, 16):
            b[y, x : x + 3] = 255  # blinker
    c = size // 2
    b[c - 16 : c + 16, c - 16 : c + 16] = 0
    b[c, c - 1 : c + 2] = 255  # T-tetromino
    b[c + 1, c] = 255
    return b


def expected_final(board: np.ndarray, turns: int) -> np.ndarray:
    """The dense oracle's board at ``turns``, computed through the settled
    board's periodicity (NumPy cannot run 10^9 generations directly; it
    can prove the period and land on the same phase)."""
    assert turns >= SETTLE
    out = oracle_run(board, SETTLE + (turns - SETTLE) % 6)
    # The reduction is only valid if the board really is periodic by
    # SETTLE — assert it rather than assume it.
    assert np.array_equal(out, oracle_run(out, 6))
    return out


def write_board(images_dir, board):
    images_dir.mkdir(parents=True, exist_ok=True)
    h, w = board.shape
    pgm.write_pgm(images_dir / f"{w}x{h}.pgm", board)


def make_params(tmp_path, size, **kw):
    defaults = dict(
        turns=10**9,
        image_width=size,
        image_height=size,
        images_dir=tmp_path / "images",
        out_dir=tmp_path,
        engine="roll",
        superstep=4,
        cycle_check=2,
        time_compression=True,
    )
    defaults.update(kw)
    return gol.Params(**defaults)


def drain(events, keep_turn_completes=True):
    out = []
    while (e := events.get(timeout=120)) is not None:
        if keep_turn_completes or not isinstance(e, TurnComplete):
            out.append(e)
    return out


def alive_set(board):
    ys, xs = np.nonzero(board)
    return {(int(x), int(y)) for y, x in zip(ys, xs)}


def run_compressed(params, *, session=None, keys=None, backend=None):
    """One compressed run; returns (event stream, timecomp counter delta)."""
    events: queue.Queue = queue.Queue()
    before = obs_metrics.REGISTRY.snapshot()
    gol.run(params, events, keys, session=session, backend=backend)
    delta = obs_metrics.REGISTRY.snapshot().delta(before).to_dict()["counters"]
    return drain(events), {
        k: v for k, v in delta.items() if k.startswith("timecomp.")
    }


# -- the oracle matrix (tentpole acceptance) -----------------------------------

# Full engine x mesh cross at 256^2 plus one 512^2 row; the +0..+6 turn
# offsets land every residue mod 6, so all six cycle phases are exit-
# guarded somewhere in the matrix.  pallas-packed on (1,1) at 256^2 is
# below the kernel's tile floor and records its packed fallback — same
# controller seam, same exactness contract; (2,1) runs the real sharded
# kernel (interpret-mode on this CPU rig).
MATRIX = [
    (256, "roll", (1, 1), 10**9 + 0),
    (256, "roll", (2, 1), 10**9 + 1),
    (256, "packed", (1, 1), 10**9 + 2),
    (256, "packed", (2, 1), 10**9 + 3),
    (256, "pallas-packed", (1, 1), 10**9 + 4),
    (256, "pallas-packed", (2, 1), 10**9 + 5),
    (512, "pallas-packed", (2, 1), 10**9 + 6),
]


@pytest.mark.parametrize(
    "size,engine,mesh,turns",
    MATRIX,
    ids=[f"{s}-{e}-{m[0]}x{m[1]}" for s, e, m, _ in MATRIX],
)
def test_compressed_matches_dense_oracle(tmp_path, size, engine, mesh, turns):
    board = ash_board(size)
    write_board(tmp_path / "images", board)
    params = make_params(
        tmp_path,
        size,
        turns=turns,
        engine=engine,
        mesh_shape=mesh,
        turn_events="batch",
    )
    stream, tc = run_compressed(params)

    cycles = [e for e in stream if isinstance(e, CycleDetected)]
    assert len(cycles) == 1 and cycles[0].period == 6
    # The skip did the work: billions of turns, a handful of dispatches.
    assert tc["timecomp.skipped_turns"] > turns - 10_000
    assert tc["timecomp.skips"] >= 1
    # Entry + exit guard both ran, neither mismatched.
    assert tc["timecomp.guard_checks"] >= 1
    assert tc.get("timecomp.guard_mismatches", 0) == 0

    # Batch stream is contiguous 1..turns.
    ranges = [
        (e.first_turn, e.completed_turns)
        for e in stream
        if isinstance(e, TurnsCompleted)
    ]
    assert ranges[0][0] == 1 and ranges[-1][1] == turns
    for (_, l0), (f1, _) in zip(ranges, ranges[1:]):
        assert f1 == l0 + 1

    expected = expected_final(board, turns)
    final = [e for e in stream if isinstance(e, FinalTurnComplete)][0]
    assert final.completed_turns == turns
    assert set(final.alive) == alive_set(expected)
    out = pgm.read_pgm(tmp_path / f"{size}x{size}x{turns}.pgm")
    assert np.array_equal(out, expected), (
        f"{engine} {mesh}: compressed final board differs from dense oracle"
    )


def test_per_turn_stream_stays_dense(tmp_path):
    """Per-turn mode under compression still emits every TurnComplete
    1..turns — compression changes launches, never the event contract."""
    size, turns = 256, 60_000
    board = ash_board(size)
    write_board(tmp_path / "images", board)
    params = make_params(tmp_path, size, turns=turns)
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    stream = drain(events)
    assert any(isinstance(e, CycleDetected) for e in stream)
    tcs = [e.completed_turns for e in stream if isinstance(e, TurnComplete)]
    assert tcs == list(range(1, turns + 1))
    final = [e for e in stream if isinstance(e, FinalTurnComplete)][0]
    expected = expected_final(board, turns)
    assert final.completed_turns == turns
    assert set(final.alive) == alive_set(expected)


# -- checkpoint/resume truthfulness --------------------------------------------

def test_detach_sidecar_splits_computed_from_effective(tmp_path):
    """'q' during per-turn fast-forward parks a checkpoint whose sidecar
    distinguishes dispatched work (computed_turns) from delivered turns
    (effective_turns); the resumed run restores the split and lands on
    the dense oracle."""
    # 10**6 keeps ~15 fast-forward chunk boundaries (key polls) after
    # detection while the per-turn queue traffic stays tier-1-cheap.
    size, turns = 256, 10**6
    board = ash_board(size)
    write_board(tmp_path / "images", board)
    ckpt_dir = tmp_path / "ckpts"
    session = Session(ckpt_dir)
    params = make_params(tmp_path, size, turns=turns)
    events: queue.Queue = queue.Queue()
    keys: queue.Queue = queue.Queue()
    t = gol.start(params, events, keys, session)
    saw_cycle = False
    while (e := events.get(timeout=120)) is not None:
        if isinstance(e, CycleDetected) and not saw_cycle:
            saw_cycle = True
            keys.put("q")
    t.join(timeout=120)
    assert saw_cycle

    meta = json.loads((ckpt_dir / "checkpoint.json").read_text())
    assert meta["paused"] is True
    assert meta["effective_turns"] == meta["turn"]
    assert 0 < meta["computed_turns"] < meta["effective_turns"]
    # The dispatched side is bounded by the settle horizon (plus probe
    # cadence slack), not by the billions delivered.
    assert meta["computed_turns"] < 10_000
    # The parked world is the exact phase board for the detach turn.
    world = pgm.read_pgm(ckpt_dir / "checkpoint.pgm")
    assert np.array_equal(world, expected_final(board, meta["turn"]))

    # Resume from disk: the rest of the run compresses and the final
    # board is the oracle's.
    events2: queue.Queue = queue.Queue()
    resumed = Session(ckpt_dir)
    before = obs_metrics.REGISTRY.snapshot()
    gol.run(params, events2, session=resumed)
    delta = obs_metrics.REGISTRY.snapshot().delta(before).to_dict()["counters"]
    stream = drain(events2, keep_turn_completes=False)
    final = [e for e in stream if isinstance(e, FinalTurnComplete)][0]
    assert final.completed_turns == turns
    assert set(final.alive) == alive_set(expected_final(board, turns))
    assert delta["timecomp.skipped_turns"] > 0


def test_default_off_runs_dense_with_no_tier_footprint(tmp_path):
    """The byte-identity pin: with time_compression off (the default) the
    tier must leave NO trace — no timecomp counters registered against
    the run, no sidecar fields in the detach checkpoint, and the legacy
    cycle fast-forward still delivers the exact board."""
    size, turns = 256, 10**7
    board = ash_board(size)
    write_board(tmp_path / "images", board)
    ckpt_dir = tmp_path / "ckpts"
    params = make_params(tmp_path, size, turns=turns, time_compression=False)
    assert timecomp_lib.maybe_create(params, None, None) is None
    session = Session(ckpt_dir)
    events: queue.Queue = queue.Queue()
    keys: queue.Queue = queue.Queue()
    before = obs_metrics.REGISTRY.snapshot()
    t = gol.start(params, events, keys, session)
    saw_cycle = False
    while (e := events.get(timeout=120)) is not None:
        # The pre-existing whole-board fast-forward still runs — detach
        # mid-emission exactly like the compressed twin of this test.
        if isinstance(e, CycleDetected) and not saw_cycle:
            saw_cycle = True
            keys.put("q")
    t.join(timeout=120)
    assert saw_cycle
    delta = obs_metrics.REGISTRY.snapshot().delta(before).to_dict()["counters"]
    assert not any(k.startswith("timecomp.") for k in delta), delta
    # The 'q' sidecar of a dense run is byte-for-byte the pre-PR-16
    # shape: no effective-vs-computed split fields.
    meta = json.loads((ckpt_dir / "checkpoint.json").read_text())
    assert meta["paused"] is True and meta["turn"] > 0
    assert "computed_turns" not in meta
    assert "effective_turns" not in meta
    world = pgm.read_pgm(ckpt_dir / "checkpoint.pgm")
    assert np.array_equal(world, expected_final(board, meta["turn"]))


# -- supervisor restart --------------------------------------------------------

def test_supervisor_restart_preserves_exactness(tmp_path):
    """A terminal fault burst during the dense phase forces a supervisor
    rollback + backend rebuild; the fresh controller re-proves the ash
    through its own guard and the compressed run still lands
    bit-identically on the dense oracle."""
    size, turns = 256, 10**9 + 1
    board = ash_board(size)
    write_board(tmp_path / "images", board)
    params = make_params(
        tmp_path,
        size,
        turns=turns,
        engine="packed",
        turn_events="batch",
        checkpoint_every_turns=4,
        restart_limit=2,
    )
    plan = FaultPlan([Fault(2, "issue"), Fault(3, "issue")])

    def factory(p, attempt):
        backend = Backend(p)
        return FaultInjectionBackend(backend, plan) if attempt == 0 else backend

    events: queue.Queue = queue.Queue()
    before = obs_metrics.REGISTRY.snapshot()
    gol.run(params, events, backend_factory=factory)
    delta = obs_metrics.REGISTRY.snapshot().delta(before).to_dict()["counters"]
    stream = drain(events)
    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [True, False]
    assert delta["supervisor.restarts"] == 1
    assert delta["timecomp.skipped_turns"] > turns - 10_000
    final = [e for e in stream if isinstance(e, FinalTurnComplete)][0]
    assert final.completed_turns == turns
    assert set(final.alive) == alive_set(expected_final(board, turns))


# -- rung 2: the ash cache -----------------------------------------------------

class TestAshCache:
    def test_lru_eviction_and_counters(self):
        cache = timecomp_lib.AshCache(slots=2)
        e = timecomp_lib.AshEntry(1, (7,))
        cache.put(("a",), e)
        cache.put(("b",), e)
        assert cache.get(("a",)) is e  # refreshes 'a': 'b' is now LRU
        cache.put(("c",), e)  # evicts 'b'
        assert len(cache) == 2 and cache.evictions == 1
        assert cache.get(("b",)) is None
        assert cache.get(("a",)) is e and cache.get(("c",)) is e
        assert cache.hits == 3 and cache.misses == 1

    def test_put_honours_smallest_requested_bound(self):
        cache = timecomp_lib.AshCache(slots=8)
        e = timecomp_lib.AshEntry(1, (0,))
        for i in range(5):
            cache.put((i,), e)
        assert len(cache) == 5
        cache.put((5,), e, slots=2)  # a stricter caller shrinks the bound
        assert len(cache) == 2 and cache.evictions == 4

    def test_entry_validates_phase_count_length(self):
        with pytest.raises(ValueError):
            timecomp_lib.AshEntry(6, (1, 2, 3))

    def test_collision_cross_check_recaptures(self, tmp_path):
        """A cache hit whose stored counts disagree with the board's own
        popcount is a fingerprint collision: dropped, recaptured, counted
        as a miss — never trusted into output."""
        params = make_params(tmp_path, 16)
        tc = timecomp_lib.maybe_create(
            params,
            obs_metrics.registry_for(True),
            flight_lib.FlightRecorder(16),
        )
        assert tc is not None and tc.period == 6
        key = tc.cache_key(fingerprint=0xDEAD, popcount=7)
        # Poison the cache: right key, wrong counts (counts[p-1] != pop).
        timecomp_lib.CACHE.put(key, timecomp_lib.AshEntry(6, (9,) * 6))
        captured = tc.resolve_counts(key, popcount=7, capture=lambda: [7] * 6)
        assert captured == [7] * 6
        # The poisoned entry was replaced by the fresh capture...
        entry = timecomp_lib.CACHE.get(key)
        assert entry is not None and entry.counts == (7,) * 6
        # ...and a subsequent agreeing hit is served from cache.
        assert tc.resolve_counts(
            key, popcount=7, capture=lambda: pytest.fail("must not recapture")
        ) == [7] * 6


def test_cache_recognizes_ash_across_runs(tmp_path):
    """Rung 2 end-to-end: the SECOND run of the same settled board is
    recognized from the process-wide cache by its device-computed
    identity — a hit, zero misses — without refetching board bytes."""
    size = 256
    board = ash_board(size)
    write_board(tmp_path / "images", board)
    timecomp_lib.CACHE.clear()
    params = make_params(tmp_path, size, turns=10**6, turn_events="batch")
    _, tc1 = run_compressed(params)
    assert tc1["timecomp.cache_misses"] >= 1
    _, tc2 = run_compressed(params)
    assert tc2["timecomp.cache_hits"] >= 1
    assert tc2.get("timecomp.cache_misses", 0) == 0


# -- satellites ----------------------------------------------------------------

def test_ash_period_is_rule_data_not_an_assumption():
    assert CONWAY.ash_period == 6
    assert parse_rule("B36/S23").ash_period == 6  # highlife, by contents
    assert parse_rule("B2/S23").ash_period is None
    # Backend probe depth comes from the rule (legacy 6 when unknown).
    p = gol.Params(turns=8, image_width=16, image_height=16)
    assert Backend(p).cycle_period == 6


def test_unknown_rule_warns_once_and_runs_dense(tmp_path):
    rule = parse_rule("B2/S23")
    params = make_params(tmp_path, 16, rule=rule)
    with timecomp_lib._warned_lock:
        timecomp_lib._warned_rules.discard(rule.notation)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert timecomp_lib.maybe_create(params, None, None) is None
        assert timecomp_lib.maybe_create(params, None, None) is None
    scoped = [x for x in w if issubclass(x.category, RuntimeWarning)]
    assert len(scoped) == 1
    assert "no known ash period" in str(scoped[0].message)
    assert rule.notation in str(scoped[0].message)


def test_committed_timecomp_artifact_parses_and_self_gates():
    """The recorded BENCH_TIMECOMP_PR16.json is lint-clean, carries the
    effective-vs-computed split the stats lint demands of any
    'effective'-unit row, clears the 10x acceptance floor, and survives
    the bench gate against itself."""
    from distributed_gol_tpu.utils import measure
    from tools import bench_gate

    record = json.loads((REPO / "BENCH_TIMECOMP_PR16.json").read_text())
    assert measure.check_headline_stats(record) == []
    assert obs_metrics.check_embedded_metrics(record) == []
    assert "effective" in record["unit"]
    assert record["computed_turns"] < record["effective_turns"]
    assert record["speedup"] >= 10
    assert record["dense"]["median"] > 0
    regressions, _ = bench_gate.compare(record, record)
    assert regressions == []
    # Both headline rows (effective + dense) are gateable.
    assert len(bench_gate.headline_rows(record)) >= 2
