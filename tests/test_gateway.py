"""The network gateway suite (ISSUE 14).

Contracts, asserted hermetically on CPU over REAL loopback sockets:

- **Codecs**: the RFC 6455 frame codec (mask involution, the RFC
  handshake vector, fragmentation), the binary frame-event codec
  (keyframe/delta round-trip, truncation refused), wire-message
  mapping, and session-spec parsing (board upload vs soup, whitelists,
  SpecError on garbage).
- **Broker contract on the wire**: submit + pause/resume + quit for
  two tenants driven via ``tools/gol_client.py`` against a live pod,
  each completed session's final board bit-identical to its
  in-process ``ServePlane.submit`` oracle; shed submissions answer
  429 + Retry-After.
- **Detach/resume**: client disconnect is the reference's controller
  detach — the run keeps going; a reconnected controller (``?since=``)
  observes the same event-stream tail (seq-contiguous, turn ranges
  tiling the run with no gaps).
- **Spectators**: N wire spectators on one session cost 1.00 device
  fetches/frame (the FramePlane superset-fetch preserved over the
  wire), each reconstructing bit-identically to the final-board crop
  oracle; a stalled spectator never wedges the producer and re-anchors
  via drop-oldest + re-keyframe observed on the wire.
- **Chaos**: every gateway response stays bounded-time while a
  hang-faulted tenant is resident (the PR-10 2 s scrape bound); drain
  over the wire returns the parked-resumable receipt and a fresh pod
  re-adopts from it.
"""

import contextlib
import io
import json
import queue
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from distributed_gol_tpu.engine import frames as frames_lib
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.events import (
    CellFlipped,
    FrameDelta,
    FrameReady,
    TurnComplete,
    TurnsCompleted,
)
from distributed_gol_tpu.engine.params import Params
from distributed_gol_tpu.obs import metrics as obs_metrics
from distributed_gol_tpu.serve import (
    GatewayServer,
    ServeConfig,
    ServePlane,
)
from distributed_gol_tpu.serve import wire
from distributed_gol_tpu.serve import ws as ws_lib
from distributed_gol_tpu.testing.faults import (
    Fault,
    FaultInjectionBackend,
    FaultPlan,
)
from tools.gol_client import GatewayError, GolClient

W = H = 16
SUPERSTEP = 4
TURNS = 24


def base_spec(**kw):
    """A small fast wire session spec (soup-seeded, cycle probe off so
    control tests race nothing)."""
    spec = {
        "params": {
            "width": W,
            "height": H,
            "turns": TURNS,
            "engine": "roll",
            "superstep": SUPERSTEP,
            "cycle_check": 0,
            "ticker_period": 60.0,
        },
        "soup": {"density": 0.25, "seed": 7},
    }
    params = kw.pop("params", {})
    spec["params"].update(params)
    spec.update(kw)
    return spec


@pytest.fixture
def pod(tmp_path):
    plane = ServePlane(
        ServeConfig(max_sessions=4, telemetry_sample_seconds=0.1),
        checkpoint_root=tmp_path / "ckpt",
    )
    gateway = GatewayServer(plane, port=0)
    client = GolClient(gateway.url)
    yield plane, gateway, client
    gateway.close()
    plane.close()


def submit_spec(client: GolClient, tenant: str, spec: dict) -> dict:
    """POST a raw spec dict through the client's request machinery."""
    return client._request(
        "POST", "/v1/sessions", {"tenant": tenant, **spec}
    )


def wait_status(client, tenant, statuses, timeout=60.0) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = client.state(tenant)
        if st["status"] in statuses:
            return st
        time.sleep(0.05)
    raise AssertionError(
        f"{tenant} never reached {statuses}: {client.state(tenant)}"
    )


def oracle_final(tmp_path, tenant: str, spec: dict):
    """The in-process ServePlane.submit oracle for one wire spec: the
    same Params through the same plane machinery, no sockets."""
    params, _ = wire.params_from_spec(
        tenant, json.loads(json.dumps(spec)), root=tmp_path / "oracle-up"
    )
    with ServePlane(
        ServeConfig(max_sessions=1),
        checkpoint_root=tmp_path / "oracle-ckpt",
    ) as plane:
        handle = plane.submit(tenant, params)
        assert handle.wait(timeout=120)
        assert handle.status == "completed"
        return handle.final


# -- codec units ---------------------------------------------------------------


class TestWsCodec:
    def test_accept_key_rfc_vector(self):
        # RFC 6455 §1.3's worked example.
        assert (
            ws_lib.accept_key("dGhlIHNhbXBsZSBub25jZQ==")
            == "s3pPLMBiTxaQ9kYGzzhZRbK+xOo="
        )

    def test_mask_is_involutive(self):
        data = bytes(range(251))
        key = b"\x12\x34\x56\x78"
        masked = ws_lib._mask(data, key)
        assert masked != data
        assert ws_lib._mask(masked, key) == data
        assert ws_lib._mask(b"", key) == b""

    def test_frame_roundtrip_over_a_socket_pair(self):
        import socket

        a, b = socket.socketpair()
        try:
            end_a = ws_lib.WebSocket(
                a.makefile("rb"), a.makefile("wb"), mask=True, sock=a
            )
            end_b = ws_lib.WebSocket(
                b.makefile("rb"), b.makefile("wb"), mask=False, sock=b
            )
            end_a.send_text("hello")
            opcode, payload = end_b.recv()
            assert (opcode, payload) == (ws_lib.OP_TEXT, b"hello")
            blob = bytes(range(256)) * 300  # > 64 KiB: 8-byte length path
            end_b.send_binary(blob)
            opcode, payload = end_a.recv()
            assert opcode == ws_lib.OP_BINARY and payload == blob
            # Ping is answered transparently under a recv.
            end_a.ping(b"x")
            end_a.send_text("after")
            assert end_b.recv() == (ws_lib.OP_TEXT, b"after")
            end_a.close()
            with pytest.raises(ws_lib.WsClosed):
                end_b.recv()
        finally:
            a.close()
            b.close()


class TestFrameWireCodec:
    def test_keyframe_roundtrip(self):
        frame = np.arange(12 * 7, dtype=np.uint8).reshape(12, 7)
        blob = wire.encode_frame_event(FrameReady(5, frame, rect=(1, 2, 12, 7)))
        out = wire.decode_frame_event(blob)
        assert isinstance(out, FrameReady)
        assert out.completed_turns == 5 and out.rect == (1, 2, 12, 7)
        assert np.array_equal(np.asarray(out.frame), frame)

    def test_delta_roundtrip_applies_bit_identically(self):
        prev = np.zeros((32, 16), np.uint8)
        new = prev.copy()
        new[3, 4] = 255
        new[25, :] = 7
        bands = frames_lib.delta_bands(prev, new)
        blob = wire.encode_frame_event(
            FrameDelta(9, bands=bands, rect=(0, 0, 32, 16))
        )
        out = wire.decode_frame_event(blob)
        assert isinstance(out, FrameDelta)
        buf = prev.copy()
        frames_lib.apply_bands(buf, out.bands)
        assert np.array_equal(buf, new)

    def test_truncated_payload_refused(self):
        frame = np.ones((8, 8), np.uint8)
        blob = wire.encode_frame_event(FrameReady(1, frame))
        with pytest.raises(ValueError):
            wire.decode_frame_event(blob[:-3])
        with pytest.raises(ValueError):
            wire.decode_frame_event(b"\x00\x01")

    def test_pack_bands_mismatch_refused(self):
        meta, payload = frames_lib.pack_bands(
            ((0, np.ones((2, 4), np.uint8)),)
        )
        with pytest.raises(ValueError, match="truncated"):
            frames_lib.unpack_bands(meta, payload[:-1])
        with pytest.raises(ValueError, match="trailing"):
            frames_lib.unpack_bands(meta, payload + b"x")


class TestWireMessages:
    def test_event_mapping(self):
        assert wire.event_to_wire(TurnComplete(3)) == {
            "type": "turns", "first": 3, "turn": 3,
        }
        assert wire.event_to_wire(
            TurnsCompleted(completed_turns=8, first_turn=5)
        ) == {"type": "turns", "first": 5, "turn": 8}
        # Chatty per-cell forms are elided from the controller leg.
        from distributed_gol_tpu.utils.cell import Cell

        assert wire.event_to_wire(CellFlipped(1, Cell(0, 0))) is None
        assert wire.event_to_wire(FrameReady(1, np.zeros((2, 2)))) is None

    def test_parse_control(self):
        assert wire.parse_control('{"type": "pause"}') == {"type": "pause"}
        assert wire.parse_control(
            '{"type": "set_viewport", "rect": [1, 2, 3, 4]}'
        ) == {"type": "set_viewport", "rect": (1, 2, 3, 4)}
        assert wire.parse_control('{"type": "key", "key": "s"}') == {
            "type": "key", "key": "s",
        }
        for bad in (
            "not json",
            "[1]",
            '{"type": "reboot"}',
            '{"type": "key", "key": "Z"}',
            '{"type": "set_viewport", "rect": [1, 2]}',
        ):
            with pytest.raises(wire.SpecError):
                wire.parse_control(bad)


class TestSessionSpecs:
    def test_soup_spec(self, tmp_path):
        params, options = wire.params_from_spec(
            "alice", base_spec(), root=tmp_path
        )
        assert params.image_width == W and params.turns == TURNS
        assert params.soup_density == 0.25 and params.soup_seed == 7
        assert params.turn_events == "batch"
        assert not options["spectate"]

    def test_board_upload_roundtrip(self, tmp_path):
        import base64

        from distributed_gol_tpu.engine import pgm

        board = (np.random.default_rng(3).random((24, 16)) < 0.3).astype(
            np.uint8
        ) * 255
        spec = {
            "params": {"turns": 10},
            "board_b64": base64.b64encode(pgm.encode_pgm(board)).decode(),
        }
        params, _ = wire.params_from_spec("bob", spec, root=tmp_path)
        assert (params.image_width, params.image_height) == (16, 24)
        stored = pgm.read_pgm(Path(params.images_dir) / "16x24.pgm")
        assert np.array_equal(stored, board)

    def test_spectate_defaults(self, tmp_path):
        params, options = wire.params_from_spec(
            "carol", base_spec(spectate=True), root=tmp_path
        )
        assert options["spectate"]
        assert params.no_vis is False and params.view_mode == "frame"
        assert params.viewport == (0, 0, W, H)  # clamped to the board
        assert params.frame_stride == 1

    @pytest.mark.parametrize(
        "mutate",
        [
            {"params": {"width": "x"}},
            {"params": {"mesh_shape": [2, 1]}},
            {"nonsense": True},
            {"soup": {"density": "thick"}},
            {"viewport": [0, 0, 8, 8]},  # needs spectate
            {"spectate": True, "frame_stride": "fast"},
        ],
        ids=lambda m: str(sorted(m)[0]),
    )
    def test_bad_specs_refused(self, tmp_path, mutate):
        spec = base_spec()
        for key, val in mutate.items():
            if key == "params":
                spec["params"].update(val)
            else:
                spec[key] = val
        with pytest.raises(wire.SpecError):
            wire.params_from_spec("eve", spec, root=tmp_path)

    def test_board_and_soup_conflict(self, tmp_path):
        spec = base_spec(board_b64="aGk=")
        with pytest.raises(wire.SpecError, match="not both"):
            wire.params_from_spec("eve", spec, root=tmp_path)

    def test_missing_board_refused(self, tmp_path):
        with pytest.raises(wire.SpecError, match="needs a board"):
            wire.params_from_spec(
                "eve", {"params": {"turns": 5}}, root=tmp_path
            )


# -- the broker contract over a real socket ------------------------------------


class TestEndToEnd:
    def test_two_tenants_submit_control_quit_bit_identical(
        self, pod, tmp_path
    ):
        """THE acceptance row: two tenants driven entirely through
        tools/gol_client.py — alice runs to completion and her final
        board is bit-identical to the in-process ServePlane.submit
        oracle; bob is paused, resumed, then quit — the reference
        detach — leaving a parked resumable checkpoint."""
        plane, gateway, client = pod
        alice_spec = base_spec()
        doc = submit_spec(client, "alice", alice_spec)
        assert doc["status"] in ("queued", "running")
        bob_spec = base_spec(
            params={"turns": 500_000, "ticker_period": 0.2},
            soup={"density": 0.3, "seed": 11},
        )
        submit_spec(client, "bob", bob_spec)

        #

        # Bob: pause freezes the turn counter, resume advances it.
        assert client.pause("bob")["ok"]
        st1 = wait_status(client, "bob", ("running",), timeout=30)
        time.sleep(0.5)
        st1 = client.state("bob")
        time.sleep(0.5)
        st2 = client.state("bob")
        assert st2["paused"] and st2["turn"] == st1["turn"]
        client.resume("bob")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if client.state("bob")["turn"] > st2["turn"]:
                break
            time.sleep(0.05)
        assert client.state("bob")["turn"] > st2["turn"]
        # Quit = the reference detach: parked and resumable.
        client.quit("bob")
        st = wait_status(client, "bob", ("parked",), timeout=30)
        assert st["resumable"]

        # Alice: completed; the wire-observed final board equals the
        # in-process oracle bit for bit.
        st = wait_status(client, "alice", ("completed",), timeout=60)
        with client.controller("alice") as ctrl:
            final = None
            while True:
                msg = ctrl.recv(timeout=30)
                if msg["type"] == "final":
                    final = msg
                if msg["type"] == "end":
                    assert msg["status"] == "completed"
                    break
        assert final is not None and final["turn"] == TURNS
        oracle = oracle_final(tmp_path, "alice", alice_spec)
        assert oracle.completed_turns == TURNS
        assert set(map(tuple, final["alive"])) == {
            (c.x, c.y) for c in oracle.alive
        }

    def test_shed_submission_is_429_with_retry_after(self, tmp_path):
        plane = ServePlane(
            ServeConfig(max_sessions=1, max_queued=0),
            checkpoint_root=tmp_path / "ckpt",
        )
        gateway = GatewayServer(plane, port=0)
        client = GolClient(gateway.url)
        try:
            submit_spec(
                client, "a", base_spec(params={"turns": 500_000})
            )
            with pytest.raises(GatewayError) as ei:
                submit_spec(client, "b", base_spec())
            assert ei.value.status == 429
            assert ei.value.retry_after is not None
            # A permanent rejection (board over budget) is 409, no hint.
            with pytest.raises(GatewayError) as ei:
                submit_spec(
                    client,
                    "c",
                    base_spec(params={"width": 1 << 14, "height": 1 << 14}),
                )
            assert ei.value.status == 409
            client.quit("a")
        finally:
            gateway.close()
            plane.close()

    def test_errors_are_json_not_tracebacks(self, pod, tmp_path):
        plane, gateway, client = pod
        with pytest.raises(GatewayError) as ei:
            client.state("nobody")
        assert ei.value.status == 404
        with pytest.raises(GatewayError) as ei:
            submit_spec(client, "bad name!", base_spec())
        assert ei.value.status == 400
        with pytest.raises(GatewayError) as ei:
            submit_spec(client, "x", {"params": {"warp_factor": 9}})
        assert ei.value.status == 400
        # A plane-submitted tenant has state but no control channel.
        plane.submit("direct", Params(
            image_width=W, image_height=H, turns=SUPERSTEP,
            engine="roll", superstep=SUPERSTEP, soup_density=0.2,
            turn_events="batch", cycle_check=0, out_dir=tmp_path / "direct",
        ))
        assert wait_status(client, "direct", ("completed",), timeout=60)
        with pytest.raises(GatewayError) as ei:
            client.pause("direct")
        assert ei.value.status == 409


class TestWireBooksBounded:
    def test_ended_sessions_are_pruned_with_the_plane_eviction_ring(
        self, tmp_path
    ):
        """A churning-tenant gateway pod stays bounded-memory: wire
        books (replay rings, key queues) for ended tenants the plane
        evicted are pruned at the next submission."""
        plane = ServePlane(
            ServeConfig(max_sessions=1, max_retained_handles=2),
            checkpoint_root=tmp_path / "ckpt",
        )
        gateway = GatewayServer(plane, port=0)
        client = GolClient(gateway.url)
        try:
            for i in range(6):
                submit_spec(
                    client,
                    f"churn-{i}",
                    base_spec(params={"turns": SUPERSTEP}),
                )
                wait_status(
                    client, f"churn-{i}", ("completed",), timeout=60
                )
            with gateway._lock:
                books = len(gateway._sessions)
            # The current tenant plus at most the plane's retained ring.
            assert books <= 1 + plane.config.max_retained_handles
        finally:
            gateway.close()
            plane.close()


class TestDetachReconnect:
    def test_disconnect_is_detach_and_reconnect_reads_the_same_tail(
        self, pod
    ):
        """Controller disconnect must not touch the run; a reconnect
        with ?since= replays the ring tail seq-contiguously, and the
        union of both attachments tiles the whole turn range — the
        'same event stream as an attached oracle' acceptance bar."""
        plane, gateway, client = pod
        # 400 turns / superstep 4 = 100 turn-ranges: comfortably inside
        # the RING_DEPTH replay window, so the reconnect tail is exact.
        submit_spec(client, "alice", base_spec(params={"turns": 400}))
        seen: list[dict] = []
        with client.controller("alice") as ctrl:
            hello = ctrl.recv(timeout=30)
            assert hello["type"] == "hello"
            while len(seen) < 2:
                msg = ctrl.recv(timeout=30)
                if msg["type"] == "turns":
                    seen.append(msg)
        last_seq = seen[-1]["seq"]
        # Detached: the run keeps advancing without any controller.
        turn0 = client.state("alice")["turn"]
        wait_status(client, "alice", ("completed",), timeout=60)
        assert client.state("alice")["turn"] == 400 >= turn0
        # Reconnect after the end: the ring replays the tail.
        with client.controller("alice", since=last_seq) as ctrl:
            hello = ctrl.recv(timeout=30)
            assert hello["type"] == "hello" and hello["replay"] > 0
            while True:
                msg = ctrl.recv(timeout=30)
                if msg["type"] == "end":
                    assert msg["status"] == "completed"
                    break
                seen.append(msg)
        seqs = [m["seq"] for m in seen]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        turns = [m for m in seen if m["type"] == "turns"]
        # The ranges tile 1..400 with no gaps or overlaps — the union
        # of both attachments IS the attached oracle's stream.
        expect = 1
        for msg in turns:
            assert msg["first"] == expect
            expect = msg["turn"] + 1
        assert expect == 401


# -- spectators ----------------------------------------------------------------


def crop(board: np.ndarray, rect) -> np.ndarray:
    y0, x0, vh, vw = rect
    h, w = board.shape
    rows = (np.arange(vh) + y0) % h
    cols = (np.arange(vw) + x0) % w
    return board[rows[:, None], cols[None, :]]


class TestSpectators:
    SIZE = 64
    TURNS = 20

    def _spectate_spec(self, turns=None):
        return base_spec(
            params={
                "width": self.SIZE,
                "height": self.SIZE,
                "turns": turns or self.TURNS,
            },
            soup={"density": 0.3, "seed": 17},
            spectate=True,
            viewport=[0, 0, 32, 32],
        )

    def test_n_spectators_cost_one_fetch_per_frame_and_reconstruct(
        self, pod
    ):
        plane, gateway, client = pod
        reg = obs_metrics.REGISTRY
        fetches0 = reg.counter("frames.fetches").value
        publishes0 = reg.counter("frames.publishes").value
        submit_spec(client, "alice", self._spectate_spec())
        rng = np.random.default_rng(5)
        rects = [
            (
                int(rng.integers(0, self.SIZE)),
                int(rng.integers(0, self.SIZE)),
                24,
                24,
            )
            for _ in range(3)
        ]
        streams = [
            client.spectate("alice", rect=r, queue_depth=self.TURNS + 2)
            for r in rects
        ]
        try:
            finals = []
            for stream in streams:
                while not stream.ended:
                    event = stream.recv(timeout=60)
                    if not isinstance(event, dict):
                        stream.feed(event)
                finals.append((stream.buf, stream.turn))
        finally:
            for stream in streams:
                stream.close()
        st = wait_status(client, "alice", ("completed",), timeout=30)
        # Superset-fetch economics preserved over the wire: however
        # many wire spectators, fetches/frame == 1.
        fetches = reg.counter("frames.fetches").value - fetches0
        publishes = reg.counter("frames.publishes").value - publishes0
        assert publishes == self.TURNS
        assert fetches == publishes, "fetches/frame != 1 over the wire"
        # Every spectator's reconstruction equals the final-board crop.
        final_board = self._final_board(client, "alice")
        for (buf, turn), rect in zip(finals, rects):
            assert turn == self.TURNS
            want = (crop(final_board, rect) != 0) * np.uint8(255)
            assert np.array_equal(buf, want)

    def _final_board(self, client, tenant) -> np.ndarray:
        with client.controller(tenant) as ctrl:
            while True:
                msg = ctrl.recv(timeout=30)
                if msg["type"] == "final":
                    board = np.zeros((self.SIZE, self.SIZE), np.uint8)
                    for x, y in msg["alive"]:
                        board[y, x] = 255
                    return board
                if msg["type"] == "end":
                    raise AssertionError("stream ended without a final")

    def test_stalled_spectator_never_wedges_the_producer(self, pod):
        """A spectator that attaches and then reads NOTHING while the
        run completes: the producer finishes every turn on schedule
        (drop-oldest, bounded queues); when the client finally drains,
        it observes dropped turns and a re-anchoring keyframe, and
        still converges to the final board."""
        plane, gateway, client = pod
        turns = 150
        submit_spec(client, "alice", self._spectate_spec(turns=turns))
        # Slow consumer, deterministically: a pinned 4 KiB receive
        # buffer (+ the gateway's bounded spectator SO_SNDBUF) wedges
        # the SOCKET after a handful of full-board frames, so the
        # subscriber queue (depth 2) must drop-oldest long before the
        # run ends.
        stream = client.spectate(
            "alice",
            rect=(0, 0, self.SIZE, self.SIZE),
            queue_depth=2,
            recv_buffer=4096,
        )
        try:
            # Stall: no reads while the whole run executes.
            st = wait_status(client, "alice", ("completed",), timeout=120)
            assert st["turn"] == turns, "stalled spectator wedged the run"
            keyframes, frame_turns = 0, []
            while not stream.ended:
                event = stream.recv(timeout=30)
                if isinstance(event, dict):
                    continue
                if isinstance(event, FrameReady):
                    keyframes += 1
                frame_turns.append(event.completed_turns)
                stream.feed(event)
            # Drop-oldest on the wire: the stalled client cannot have
            # received every turn, and the post-drop re-keyframe is
            # what re-anchored the survivors.
            assert len(frame_turns) < turns
            assert keyframes >= 2, "no re-keyframe observed on the wire"
            assert stream.turn == turns
            final_board = self._final_board(client, "alice")
            want = (final_board != 0) * np.uint8(255)
            assert np.array_equal(stream.buf, want)
        finally:
            stream.close()

    def test_set_viewport_rekeyframes_midstream(self, pod):
        plane, gateway, client = pod
        submit_spec(client, "alice", self._spectate_spec(turns=200))
        with client.spectate("alice", rect=(0, 0, 16, 16)) as stream:
            first = stream.recv(timeout=30)
            while isinstance(first, dict):
                first = stream.recv(timeout=30)
            assert isinstance(first, FrameReady)
            assert first.rect == (0, 0, 16, 16)
            stream.set_viewport((8, 8, 24, 24))
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                event = stream.recv(timeout=30)
                if (
                    not isinstance(event, dict)
                    and event.rect == (8, 8, 24, 24)
                ):
                    assert isinstance(event, FrameReady), (
                        "viewport change must re-keyframe"
                    )
                    break
            else:
                raise AssertionError("new viewport never arrived")
        client.quit("alice")
        wait_status(client, "alice", ("parked",), timeout=30)


# -- chaos ---------------------------------------------------------------------


@pytest.mark.chaos
class TestGatewayChaos:
    BOUND_S = 2.0

    def test_bounded_time_with_a_hang_faulted_tenant_resident(
        self, pod, tmp_path
    ):
        """The PR-10 scrape bound, on the gateway: while one tenant's
        dispatch is wedged (hang fault, bounded by its own watchdog),
        every list/state/healthz answer lands within 2 s."""
        plane, gateway, client = pod
        hang_params = Params(
            image_width=W, image_height=H, turns=500_000,
            engine="roll", superstep=SUPERSTEP, soup_density=0.25,
            soup_seed=31, turn_events="batch", cycle_check=0,
            dispatch_deadline_seconds=3.0, out_dir=tmp_path / "hang",
        )
        hang_backend = FaultInjectionBackend(
            Backend(hang_params), FaultPlan([Fault(1, "hang", seconds=60.0)])
        )
        try:
            plane.submit("hang", hang_params, backend=hang_backend)
            submit_spec(client, "healthy", base_spec())
            worst = 0.0
            deadline = time.monotonic() + 60
            done = False
            while time.monotonic() < deadline and not done:
                for fn in (
                    lambda: client.sessions(),
                    lambda: client.state("hang"),
                    lambda: client.health(),
                ):
                    t0 = time.monotonic()
                    fn()
                    worst = max(worst, time.monotonic() - t0)
                hang_h = plane.handle("hang")
                done = (
                    client.state("healthy")["status"] == "completed"
                    and hang_h is not None
                    and hang_h.done
                )
                time.sleep(0.1)
            assert done, "storm never settled"
            assert worst < self.BOUND_S, (
                f"gateway took {worst:.2f}s with a wedged tenant resident"
            )
            st = client.state("hang")
            assert st["status"] == "parked"
            assert "DispatchTimeout" in (st["error"] or "")
        finally:
            hang_backend.release_hangs()

    def test_drain_over_the_wire_and_readopt(self, tmp_path):
        """POST /v1/drain: the parked-resumable receipt comes back over
        the socket, the gateway refuses new submissions before the
        plane sheds, and a restarted pod re-adopts every tenant — the
        serve --readopt contract end to end."""
        root = tmp_path / "ckpt"
        plane = ServePlane(
            ServeConfig(max_sessions=4, telemetry_sample_seconds=0.1),
            checkpoint_root=root,
        )
        gateway = GatewayServer(plane, port=0)
        client = GolClient(gateway.url)
        try:
            for name, seed in (("alice", 1), ("bob", 2)):
                submit_spec(
                    client,
                    name,
                    base_spec(
                        params={"turns": 500_000},
                        soup={"density": 0.3, "seed": seed},
                    ),
                )
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if all(
                    client.state(t)["turn"] > 0 for t in ("alice", "bob")
                ):
                    break
                time.sleep(0.05)
            receipt = client.drain(timeout=60)
            assert receipt["draining"]
            for name in ("alice", "bob"):
                row = receipt["sessions"][name]
                assert row["status"] == "drained"
                assert row["resumable"] and row["turn"] > 0
            with pytest.raises(GatewayError) as ei:
                submit_spec(client, "late", base_spec())
            assert ei.value.status == 503
        finally:
            gateway.close()
            plane.close()
        # The restarted pod: re-adopt and run each tenant forward.
        with ServePlane(
            ServeConfig(max_sessions=4), checkpoint_root=root
        ) as fresh:
            adoptable = fresh.resumable_tenants()
            assert set(adoptable) == {"alice", "bob"}
            parked_turn = adoptable["alice"]["turn"]
            target = parked_turn + 2 * SUPERSTEP
            handle = fresh.submit(
                "alice",
                Params(
                    image_width=W, image_height=H, turns=target,
                    engine="roll", superstep=SUPERSTEP,
                    turn_events="batch", cycle_check=0,
                    out_dir=root / "alice",
                ),
            )
            assert handle.wait(timeout=60)
            assert handle.status == "completed"
            assert handle.last_turn == target


# -- the serve CLI with a gateway ----------------------------------------------


class TestServeCliGateway:
    def test_gateway_pod_serves_until_drained_and_prints_endpoints(
        self, tmp_path
    ):
        """serve --gateway-port 0: the banner and the JSON receipt both
        carry the RESOLVED endpoint (never a placeholder), scripted
        tenants are wire-controllable, and drain-over-the-wire ends
        the pod."""
        from distributed_gol_tpu.__main__ import serve_main

        before = (
            obs_metrics.REGISTRY.snapshot()
            .to_dict()["info"]
            .get("gateway.endpoint")
        )
        out, err = io.StringIO(), io.StringIO()
        rc: list[int] = []

        def run():
            with contextlib.redirect_stdout(out), contextlib.redirect_stderr(
                err
            ):
                rc.append(
                    serve_main(
                        [
                            "--tenant", f"scripted:{W}x{H}x500000",
                            "--checkpoint-root", str(tmp_path / "ckpt"),
                            "--superstep", str(SUPERSTEP),
                            "--engine", "roll",
                            "--gateway-port", "0",
                        ]
                    )
                )

        thread = threading.Thread(target=run, daemon=True)
        thread.start()
        url = None
        deadline = time.monotonic() + 60
        while url is None and time.monotonic() < deadline:
            info = obs_metrics.REGISTRY.snapshot().to_dict()["info"]
            got = info.get("gateway.endpoint")
            if got and got != before:
                url = got
            else:
                time.sleep(0.05)
        assert url is not None, "pod never published its gateway endpoint"
        client = GolClient(url)
        st = wait_status(client, "scripted", ("running",), timeout=60)
        assert st["controllable"], "scripted tenant must be wire-controllable"
        receipt = client.drain(timeout=60)
        assert receipt["sessions"]["scripted"]["resumable"]
        thread.join(timeout=60)
        assert not thread.is_alive(), "pod did not exit after the drain"
        assert rc == [0]
        doc = json.loads(out.getvalue().strip().splitlines()[-1])
        assert doc["gateway"]["endpoint"] == url
        assert "<ephemeral>" not in out.getvalue() + err.getvalue()
        banner = err.getvalue()
        assert f"gateway: {url}/v1/sessions" in banner
