"""Activity-adaptive tiled kernel: exact stability skipping.

The skip criterion is a proof, not a heuristic (see ``_kernel`` in
``ops/pallas_packed.py``): a tile whose halo-extended window repeats after
p = 6 generations provably returns to its initial state at every multiple
of p up to pad, so a launch of T (a multiple of p) generations may return
the input tile unchanged.
These tests pin bit-exactness of the adaptive engine against the XLA
packed engine on boards spanning the interesting regimes: all-dead,
still-life ash, period-2 oscillators, a moving glider over ash, and a
random soup (nothing stable).  Interpret mode — hardware evidence comes
from ``bench.py --engine pallas-packed --skip-stable --verify``.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_gol_tpu.models.life import CONWAY
from distributed_gol_tpu.ops import packed, pallas_packed

H, W = 64, 4096  # tiled-path shape (wp = 128 lanes), multiple tiles


def run_both(board_np: np.ndarray, turns: int):
    p = packed.pack(jnp.asarray(board_np))
    got = pallas_packed.make_superstep(CONWAY, interpret=True, skip_stable=True)(
        p, turns
    )
    want = packed.superstep(p, CONWAY, turns)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def blank() -> np.ndarray:
    return np.zeros((H, W), dtype=np.uint8)


def test_all_dead_board_skips_to_itself():
    run_both(blank(), 24)


def test_still_life_ash():
    b = blank()
    for y, x in [(10, 100), (30, 2000), (50, 4000)]:  # blocks
        b[y : y + 2, x : x + 2] = 255
    run_both(b, 24)


def test_period_2_oscillators():
    b = blank()
    for y, x in [(8, 64), (40, 1024), (20, 3000)]:  # blinkers
        b[y, x : x + 3] = 255
    run_both(b, 24)
    run_both(b, 26)  # non-multiple remainder handling: launches + rem


def test_period_3_pulsar():
    """Pulsars dominate residual ash activity in settled soups (measured:
    period-2 skipping stabilises 0/16 stripes of a 400k-gen board, period-6
    stabilises 14/16) — the reason _SKIP_PERIOD is 6."""
    b = blank()
    # Pulsar: quadrant-symmetric period-3 oscillator in a 13x13 box.
    seg = [2, 3, 4, 8, 9, 10]
    for y, x in [(20, 200), (40, 2000)]:
        for c in seg:
            for r in (0, 5, 7, 12):
                b[y + r, x + c] = 255
                b[y + c, x + r] = 255
    run_both(b, 24)
    run_both(b, 30)


def test_glider_over_ash():
    b = blank()
    # glider (active region) ...
    g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], dtype=np.uint8) * 255
    b[4:7, 4:7] = g
    # ... plus stable furniture far away
    b[50:52, 3000:3002] = 255
    b[30, 2000:2003] = 255
    for turns in (8, 22, 40):
        run_both(b, turns)


def test_random_soup_never_stable():
    rng = np.random.default_rng(9)
    b = np.where(rng.random((H, W)) < 0.3, 255, 0).astype(np.uint8)
    run_both(b, 30)


def test_wrap_activity_crosses_tile_seam():
    """Activity at the torus seam: the top tile's halo sees the bottom
    rows; a skip decision there must account for it."""
    b = blank()
    g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], dtype=np.uint8) * 255
    b[H - 3 :, 100:103] = g  # glider about to wrap
    run_both(b, 16)


def test_odd_turns_and_tiny_remainders():
    b = blank()
    b[8, 64:67] = 255  # blinker
    for turns in (1, 3, 7, 9, 25):
        run_both(b, turns)


@pytest.mark.parametrize("bad_turns", [1, 3, 4, 8])
def test_non_period_multiple_launch_rejected(bad_turns):
    with pytest.raises(ValueError, match="multiple of the skip period"):
        pallas_packed._build_launch((H, W // 32), CONWAY, bad_turns, True, True)


def test_sharded_elision_multi_launch():
    """Sharded frontier elision: multi-launch dispatches on row meshes
    with a small cap (multi-tile strips), a glider crossing a STRIP
    boundary while the rest elides, and ash near the mesh seam — the
    edge-tile flags must travel with the ppermute or a stale elision
    would corrupt the neighbour strip's first/last tile."""
    import jax

    from distributed_gol_tpu.parallel import packed_halo, pallas_halo
    from distributed_gol_tpu.parallel.mesh import make_mesh

    b = blank()
    g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], dtype=np.uint8) * 255
    b[28:31, 50:53] = g  # glider heading down-right across the H/2 seam
    b[10:12, 3000:3002] = 255  # ash in strip 0
    b[50, 1000:1003] = 255  # blinker in strip 1 (for ny=2)
    b[H - 2 :, 200:202] = 255  # ash at the wrap seam
    p = packed.pack(jnp.asarray(b))
    for ny in (2, 4):
        for turns in (48, 96):
            want = np.asarray(packed.superstep(p, CONWAY, turns))
            mesh = make_mesh((ny, 1))
            pb = jax.device_put(
                np.asarray(p), packed_halo.packed_sharding(mesh)
            )
            got = pallas_halo.make_superstep(
                mesh, CONWAY, interpret=True, skip_stable=True,
                skip_tile_cap=16,
            )(pb, turns)
            np.testing.assert_array_equal(np.asarray(got), want)


def test_sharded_adaptive_bit_identity():
    """The sharded form (pallas_halo + skip_stable) on a virtual row mesh:
    T-deep ppermute halos feed the same per-tile skip proof."""
    import jax

    from distributed_gol_tpu.parallel import packed_halo, pallas_halo
    from distributed_gol_tpu.parallel.mesh import make_mesh

    b = blank()
    g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], dtype=np.uint8) * 255
    b[4:7, 4:7] = g  # active
    b[50:52, 3000:3002] = 255  # ash
    b[30, 2000:2003] = 255
    p = packed.pack(jnp.asarray(b))
    want = np.asarray(packed.superstep(p, CONWAY, 24))
    for ny in (2, 4):
        mesh = make_mesh((ny, 1))
        pb = jax.device_put(np.asarray(p), packed_halo.packed_sharding(mesh))
        got = pallas_halo.make_superstep(
            mesh, CONWAY, interpret=True, skip_stable=True
        )(pb, 24)
        np.testing.assert_array_equal(np.asarray(got), want)


def test_backend_level_skip_stable():
    """Params.skip_stable reaches the kernel through the Backend and
    changes nothing about results (run vs the roll backend)."""
    from distributed_gol_tpu.engine.backend import Backend
    from distributed_gol_tpu.engine.params import Params

    common = dict(image_width=W, image_height=H, turns=20, superstep=20)
    b = blank()
    b[8, 64:67] = 255
    b[20:23, 300:303] = (
        np.array([[0, 255, 0], [0, 0, 255], [255, 255, 255]], dtype=np.uint8)
    )
    skip = Backend(Params(engine="pallas-packed", skip_stable=True, **common))
    assert skip.engine_used == "pallas-packed"
    roll = Backend(Params(engine="roll", **common))
    got, count = skip.run_turns(skip.put(b), 20)
    want, want_count = roll.run_turns(roll.put(b), 20)
    assert count == want_count
    np.testing.assert_array_equal(skip.fetch(got), roll.fetch(want))


def run_both_capped(board_np: np.ndarray, turns: int, cap: int):
    """Bit-identity with a small tile cap: forces a multi-tile grid at the
    hermetic board size, so the frontier-aware probe elision actually has
    neighbours to consult (the default plan would give one 64-row tile)."""
    p = packed.pack(jnp.asarray(board_np))
    got = pallas_packed.make_superstep(
        CONWAY, interpret=True, skip_stable=True, skip_tile_cap=cap
    )(p, turns)
    want = packed.superstep(p, CONWAY, turns)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestFrontierElision:
    """Multi-launch dispatches where later launches elide the probe for
    all-stable neighbourhoods (BASELINE.md soundness argument).  cap=16
    gives a 4-tile grid at H=64 with t = 6 per launch, so turns=36-96 run
    6-16 identical-geometry launches with the bitmap carried between."""

    def test_ash_multi_launch(self):
        b = blank()
        b[10:12, 100:102] = 255  # block in tile 0
        b[40, 2000:2003] = 255  # blinker in tile 2
        run_both_capped(b, 48, cap=16)

    def test_glider_invades_elided_tiles(self):
        """The adversarial case for elision: a glider starts in one tile
        and crosses into tiles that were skipping (and eliding) — the
        neighbour flag must un-elide them the launch the frontier
        arrives."""
        b = blank()
        g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], dtype=np.uint8) * 255
        b[0:3, 50:53] = g  # glider headed down-right from tile 0
        b[30:32, 60:62] = 255  # ash in its path (tile 1)
        b[50, 64:67] = 255  # blinker further along (tile 3)
        for turns in (36, 48, 96):
            run_both_capped(b, turns, cap=16)

    def test_seam_wrap_with_elision(self):
        """Glider wrapping the torus seam while the interior tiles elide:
        the cyclic neighbour indexing of the bitmap must wrap too."""
        b = blank()
        g = np.array([[0, 1, 0], [0, 0, 1], [1, 1, 1]], dtype=np.uint8) * 255
        b[H - 3 :, 100:103] = g
        b[24:26, 3000:3002] = 255  # mid-board ash, elidable
        run_both_capped(b, 48, cap=16)

    def test_soup_then_ash_transition(self):
        """A band of soup that collapses while the rest is ash — skip
        fractions move over launches, exercising both cond branches and
        the elide/probe boundary repeatedly."""
        rng = np.random.default_rng(4)
        b = blank()
        b[16:32] = np.where(rng.random((16, W)) < 0.3, 255, 0).astype(np.uint8)
        b[50:52, 1000:1002] = 255
        run_both_capped(b, 96, cap=16)


class TestSkipTileCapKnob:
    def test_params_validation(self):
        from distributed_gol_tpu.engine.params import Params

        with pytest.raises(ValueError, match="skip_tile_cap"):
            Params(skip_tile_cap=12)
        with pytest.raises(ValueError, match="skip_tile_cap"):
            Params(skip_tile_cap=-8)
        Params(skip_tile_cap=512)  # ok
        Params(skip_tile_cap=0)  # ok: auto

    def test_explicit_cap_changes_plan(self):
        shape = (H, W // 32)
        assert pallas_packed._plan_tile(shape, 12, 16) == 16
        assert pallas_packed._plan_tile(shape, 12, None) == 64

    def test_adaptive_tile_launches_matches_plan(self):
        shape = (H, W // 32)
        # cap=16: the cost model picks t=8, skip_plan rounds to t=6 ->
        # turns=48 is 8 full launches over a 4-tile grid.
        t, adaptive = pallas_packed.skip_plan(
            pallas_packed.launch_turns(shape, 48, 16)
        )
        assert adaptive
        grid = H // pallas_packed._plan_tile(shape, t, 16)
        assert (
            pallas_packed.adaptive_tile_launches(shape, 48, 16)
            == (48 // t) * grid
            == 32
        )
        # Non-tileable shape -> 0.
        assert pallas_packed.adaptive_tile_launches((H, 100), 48, 16) == 0

    def test_backend_auto_cap_and_skip_fraction(self):
        """Auto cap (0) uses the measured-optimal default, and the live
        skip fraction becomes observable (≈1.0 on an all-ash board) once
        safely-resolved dispatches exist — never forcing in-flight work.
        Results stay bit-identical throughout."""
        from distributed_gol_tpu.engine.backend import Backend
        from distributed_gol_tpu.engine.params import Params

        params = Params(
            engine="pallas-packed",
            skip_stable=True,
            image_width=W,
            image_height=H,
            turns=120,
            superstep=24,
        )
        backend = Backend(params)
        assert backend.engine_used == "pallas-packed"
        assert backend._skip_cap == pallas_packed.default_skip_cap(H)
        assert backend.skip_fraction() is None
        b = blank()
        b[10:12, 100:102] = 255
        board = backend.put(b)
        want = Backend(Params(engine="roll", image_width=W, image_height=H,
                              turns=120, superstep=24))
        wboard = want.put(b)
        for _ in range(5):
            board, count = backend.run_turns(board, 24)
            wboard, wcount = want.run_turns(wboard, 24)
            assert count == wcount
        assert backend._skip_cap == pallas_packed.default_skip_cap(H)  # no tuning
        assert backend.skip_fraction() == 1.0  # all-ash: everything skips
        np.testing.assert_array_equal(backend.fetch(board), want.fetch(wboard))

    def test_sharded_backend_skip_fraction(self):
        """Live skip telemetry on a device mesh (round-3 parity with the
        single-device engine): the per-launch bitmap is summed on device,
        the denominator comes from the strip plan, and results stay
        bit-identical to the roll engine."""
        from distributed_gol_tpu.engine.backend import Backend
        from distributed_gol_tpu.engine.params import Params
        from distributed_gol_tpu.parallel import pallas_halo

        params = Params(
            engine="pallas-packed",
            skip_stable=True,
            image_width=W,
            image_height=H,
            turns=120,
            superstep=24,
            mesh_shape=(2, 1),
        )
        backend = Backend(params)
        assert backend.engine_used == "pallas-packed"
        assert backend.skip_fraction() is None
        assert (
            pallas_halo.adaptive_strip_launches(
                (H, W // 32), (2, 1), 24, backend._skip_cap
            )
            > 0
        )
        b = blank()
        b[10:12, 100:102] = 255  # one block: all-ash board
        board = backend.put(b)
        want = Backend(Params(engine="roll", image_width=W, image_height=H,
                              turns=120, superstep=24))
        wboard = want.put(b)
        for _ in range(5):
            board, count = backend.run_turns(board, 24)
            wboard, wcount = want.run_turns(wboard, 24)
            assert count == wcount
        assert backend.skip_fraction() == 1.0  # all-ash: everything skips
        np.testing.assert_array_equal(backend.fetch(board), want.fetch(wboard))

    def test_viewer_dispatch_does_not_poison_skip_stats(self):
        """The fused viewer dispatches jit-close over the DEVICE superstep,
        not the stats-keeping wrapper: tracing the impure wrapper would
        leak a tracer into _skip_stats and make skip_fraction() raise
        (round-3 review finding, reproduced before the fix)."""
        from distributed_gol_tpu.engine.backend import Backend
        from distributed_gol_tpu.engine.params import Params

        p = Params(
            engine="pallas-packed",
            skip_stable=True,
            image_width=W,
            image_height=H,
            turns=96,
            superstep=24,
            no_vis=False,
            view_mode="frame",
            frame_stride=24,
            frame_max=(16, 16),
        )
        backend = Backend(p)
        board = backend.put(blank())
        fy, fx = p.frame_factors()
        board, _, _ = backend.run_turn_with_frame(board, fy, fx, 24)
        for _ in range(3):
            board, _ = backend.run_turns(board, 24)
        assert backend.skip_fraction() == 1.0  # all-ash, no tracers

    def test_backend_explicit_cap(self):
        from distributed_gol_tpu.engine.backend import Backend
        from distributed_gol_tpu.engine.params import Params

        backend = Backend(
            Params(
                engine="pallas-packed",
                skip_stable=True,
                skip_tile_cap=16,
                image_width=W,
                image_height=H,
                turns=48,
                superstep=48,
            )
        )
        assert backend._skip_cap == 16
        b = blank()
        b[8, 64:67] = 255
        board, _ = backend.run_turns(backend.put(b), 48)
        assert backend._skip_cap == 16  # unchanged
        p = packed.pack(jnp.asarray(b))
        want = packed.superstep(p, CONWAY, 48)
        np.testing.assert_array_equal(
            backend.fetch(board), np.asarray(packed.unpack(want))
        )


def test_gosper_gun_unbounded_growth():
    """A glider gun (unbounded growth) — the adversarial case for any
    skipping scheme: the active region expands every generation and newly
    reached tiles must never be treated as stable."""
    b = blank()
    gun = [
        (5, 1), (5, 2), (6, 1), (6, 2),
        (5, 11), (6, 11), (7, 11), (4, 12), (8, 12), (3, 13), (9, 13),
        (3, 14), (9, 14), (6, 15), (4, 16), (8, 16), (5, 17), (6, 17),
        (7, 17), (6, 18),
        (3, 21), (4, 21), (5, 21), (3, 22), (4, 22), (5, 22), (2, 23),
        (6, 23), (1, 25), (2, 25), (6, 25), (7, 25),
        (3, 35), (4, 35), (3, 36), (4, 36),
    ]
    for y, x in gun:
        b[y + 8, x + 60] = 255
    for turns in (30, 62):
        run_both(b, turns)


def test_skip_stable_auto_policy():
    """skip_stable=None (the default) auto-enables for long headless
    multi-generation runs on tiled boards, never steals the
    VMEM-resident fast path, and explicit True/False always wins."""
    from distributed_gol_tpu.engine.backend import Backend
    from distributed_gol_tpu.engine.params import Params

    base = dict(engine="pallas-packed", image_width=W, image_height=H)
    auto_long = Params(**base, turns=200_000)
    assert auto_long.skip_stable_requested()
    assert Backend(auto_long)._skip_fn is not None  # engaged

    assert not Params(**base, turns=100).skip_stable_requested()
    assert not Params(
        **base, turns=200_000, no_vis=False, flip_events="cell"
    ).skip_stable_requested()  # per-turn visible: can't amortise
    assert not Params(
        **base, turns=200_000, skip_stable=False
    ).skip_stable_requested()  # explicit off wins
    assert Params(turns=10, skip_stable=True, image_width=W,
                  image_height=H).skip_stable_requested()

    # Dual-eligible board (VMEM-resident AND tiled): auto declines,
    # keeping the fast path; explicit True takes it (with a warning).
    dual = Params(engine="pallas-packed", image_width=4096,
                  image_height=2048, turns=200_000)
    assert dual.skip_stable_requested()
    b = Backend(dual)
    assert getattr(b, "_skip_fn", None) is None
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        with pytest.raises(UserWarning):
            Backend(Params(engine="pallas-packed", image_width=4096,
                           image_height=2048, turns=200_000,
                           skip_stable=True))


class TestActiveRowWindow:
    """The active-row windowed compute tier (round-4 frontier-overhead
    attack, ``_route_active``): a probe-failing stripe whose
    activity is confined to a narrow row interval recomputes only a
    static sub-window at a dynamic 8-aligned offset; every other centre
    row is proved pinned and copies through.  Geometry: tall stripes so
    ``_window_rows`` engages (S + 64 <= tile_h + 2 pad)."""

    HT, WT = 1024, 4096  # one 1024-row stripe at the default cap

    def _run_both(self, board_np, turns, cap=None):
        p = packed.pack(jnp.asarray(board_np))
        got = pallas_packed.make_superstep(
            CONWAY, interpret=True, skip_stable=True, skip_tile_cap=cap
        )(p, turns)
        want = packed.superstep(p, CONWAY, turns)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def _board(self):
        return np.zeros((self.HT, self.WT), dtype=np.uint8)

    @staticmethod
    def _glider(b, y, x):
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[y + dy, x + dx] = 255

    def test_window_engages_for_this_geometry(self):
        t = pallas_packed.launch_turns((self.HT, self.WT // 32), 48, 1024)
        t, adaptive = pallas_packed.skip_plan(t)
        assert adaptive
        tile = pallas_packed._plan_tile((self.HT, self.WT // 32), t, 1024)
        assert pallas_packed._window_rows(
            tile, pallas_packed._round8(t), t
        ) is not None

    def test_narrow_activity_mid_stripe(self):
        b = self._board()
        self._glider(b, 500, 2000)  # one glider mid-stripe: narrow interval
        b[100:102, 64:66] = 255  # plus far-away ash that must stay pinned
        b[900:902, 3000:3002] = 255
        self._run_both(b, 48)

    def test_activity_near_stripe_top_clamps_window(self):
        b = self._board()
        self._glider(b, 2, 100)  # interval near row 0: win_lo clamps at 0
        self._run_both(b, 48)

    def test_activity_near_stripe_bottom_clamps_window(self):
        b = self._board()
        self._glider(b, self.HT - 8, 3500)  # clamps at h_ext - S
        self._run_both(b, 48)

    def test_wide_activity_falls_back_to_full_compute(self):
        b = self._board()
        self._glider(b, 100, 1000)  # two clusters ~800 rows apart:
        self._glider(b, 900, 1000)  # interval exceeds S -> full branch
        self._run_both(b, 48)

    def test_soup_stripe(self):
        rng = np.random.default_rng(7)
        b = np.where(rng.random((self.HT, self.WT)) < 0.3, 255, 0).astype(
            np.uint8
        )
        self._run_both(b, 24)

    def test_multi_stripe_mixed(self):
        # Two stripes via cap 512: one stable, one windowed-active.
        b = self._board()
        b[100:102, 64:66] = 255  # stripe 0: ash only
        self._glider(b, 700, 2000)  # stripe 1: narrow activity
        self._run_both(b, 48, cap=512)


def test_adaptive_launch_depth_policy():
    """Round-5 measured policy: frontier-eligible plans use the shallow
    megakernel depths (_FRONTIER_T = 18, _FRONTIER_T_TALL = 24 — the
    hardware sweep in ops/pallas_packed.py), because per-launch fixed
    cost is tiny and active-window compute ∝ (T+6)·S(T)/T favours small
    T.  Geometries with no frontier plan keep the round-4 behaviour:
    cost-model depth floored at _SETTLED_T (48) on ≥32768-row boards
    (probing kernel measured 2,780 gens/s at T=24 vs 3,831 at T=48).
    The skip-fraction denominator uses the same depth (one home)."""
    tall = (65536, 2048)
    t, adaptive = pallas_packed.adaptive_launch_depth(tall, 960, 512)
    assert adaptive and t == pallas_packed._FRONTIER_T_TALL
    grid = 65536 // pallas_packed._plan_tile(tall, t, 512)
    assert pallas_packed.adaptive_tile_launches(tall, 960, 512) == (960 // t) * grid
    short = (16384, 512)
    t_s, ad_s = pallas_packed.adaptive_launch_depth(short, 960, 1024)
    assert ad_s and t_s == pallas_packed._FRONTIER_T
    # Dispatches shorter than the frontier depth can't be deepened past
    # the work.
    t_tiny, _ = pallas_packed.adaptive_launch_depth(tall, 12, 512)
    assert t_tiny <= 12
    # No frontier plan (narrow stripes would host one, so force the
    # structural fallback): the _SETTLED_T floor for tall boards stands.
    import unittest.mock as mock

    with mock.patch.object(pallas_packed, "_frontier_plan", lambda *a: None):
        t_fb, ad_fb = pallas_packed.adaptive_launch_depth(tall, 960, 512)
        assert ad_fb and t_fb == pallas_packed._SETTLED_T


class TestPingPongWriteElision:
    """Ping-pong write elision (round 4): elided stripes skip their write
    because the aliased output buffer (two launches back) already holds
    S_{k-2} == S_k.  These dispatches span ≥4 launches so stripes are
    written from BOTH buffers and elided in between; bit-identity vs the
    XLA packed engine catches any stale-buffer row."""

    HT, WT = 2048, 4096

    def _run_both(self, b, turns):
        p = packed.pack(jnp.asarray(b))
        got = pallas_packed.make_superstep(
            CONWAY, interpret=True, skip_stable=True, skip_tile_cap=512
        )(p, turns)
        want = packed.superstep(p, CONWAY, turns)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_all_ash_even_and_odd_launch_counts(self):
        b = np.zeros((self.HT, self.WT), dtype=np.uint8)
        for y in (100, 700, 1200, 1900):
            b[y : y + 2, 200:202] = 255  # a block per stripe
        t, _ = pallas_packed.adaptive_launch_depth((self.HT, self.WT // 32), 960, 512)
        self._run_both(b, 4 * t)  # final board lands in the launch-2 buffer
        self._run_both(b, 5 * t)  # ...and in the other one

    def test_mixed_glider_and_ash_stripes(self):
        b = np.zeros((self.HT, self.WT), dtype=np.uint8)
        b[100:102, 200:202] = 255
        b[1900:1902, 3000:3002] = 255
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[1000 + dy, 2000 + dx] = 255  # keeps its stripe un-elided
        t, _ = pallas_packed.adaptive_launch_depth((self.HT, self.WT // 32), 960, 512)
        self._run_both(b, 4 * t)
        self._run_both(b, 4 * t + 20)  # + remainder split path

    def test_probing_kernel_still_covered_when_frontier_declines(self, monkeypatch):
        # The static cost model routes this geometry to the frontier
        # kernel; force the probing ping-pong kernel so its write-elision
        # path keeps interpret coverage (it remains the fallback for
        # short-tile geometries like 65536² cap 512, where it measures
        # faster — see _frontier_plan).
        monkeypatch.setattr(pallas_packed, "_frontier_plan", lambda *a: None)
        pallas_packed._build_launch_adaptive.cache_clear()
        b = np.zeros((self.HT, self.WT), dtype=np.uint8)
        b[100:102, 200:202] = 255
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[1000 + dy, 2000 + dx] = 255
        t, _ = pallas_packed.adaptive_launch_depth((self.HT, self.WT // 32), 960, 512)
        self._run_both(b, 4 * t)
        self._run_both(b, 5 * t)


class TestColumnWindow:
    """The column-confined compute tier (round 5): a stripe whose active
    cells + T+6-cell reach fit a 256-word window at a 128-word-quantized
    lane offset computes only that window.  Geometry: wp = 512 (the
    16384² lane count) so the tier is a strict subset of the row
    (``_frontier_plan`` gates it off for wp < 512).  Bit-identity vs the
    XLA packed engine covers the fallback decisions implicitly — a wrong
    ``col_ok`` either way still has to produce the exact board."""

    HC, WC = 2048, 16384  # wp = 512, cap-512 stripes -> frontier + col tier

    def _run_both(self, b, turns):
        p = packed.pack(jnp.asarray(b))
        got = pallas_packed.make_superstep(
            CONWAY, interpret=True, skip_stable=True, skip_tile_cap=512
        )(p, turns)
        want = packed.superstep(p, CONWAY, turns)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def _board(self):
        return np.zeros((self.HC, self.WC), dtype=np.uint8)

    @staticmethod
    def _glider(b, y, x):
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[y + dy, x + dx] = 255

    def _t(self):
        t, adaptive = pallas_packed.adaptive_launch_depth(
            (self.HC, self.WC // 32), 960, 512
        )
        assert adaptive
        return t

    def test_tier_engages_for_this_geometry(self):
        plan = pallas_packed._frontier_plan((self.HC, self.WC // 32), self._t(), 512)
        assert plan is not None and plan[2] == 256
        # ...and stays off on the narrow hermetic boards.
        plan_narrow = pallas_packed._frontier_plan((2048, 128), self._t(), 512)
        assert plan_narrow is not None and plan_narrow[2] is None

    def test_mid_board_cluster_multi_launch(self):
        b = self._board()
        self._glider(b, 700, 8000)  # mid-stripe, mid-width
        b[1500:1502, 2000:2002] = 255  # far ash in another stripe
        self._run_both(b, 4 * self._t())

    def test_cluster_straddles_column_quantum(self):
        b = self._board()
        # Active cells right on the 128-word (4096-cell) boundary: the
        # 256-word window must cover both sides via floor placement.
        self._glider(b, 600, 4090)
        b[604:606, 4100:4102] = 255
        self._run_both(b, 4 * self._t())

    def test_cluster_at_board_edge_wrap_falls_back(self):
        b = self._board()
        # Activity within T+6 cells of the x-edge: col_ok must reject
        # (the window can't see the torus wrap) and the row tier take it.
        self._glider(b, 300, 2)
        b[900:902, self.WC - 3 : self.WC - 1] = 255  # right edge too
        self._run_both(b, 4 * self._t())

    def test_two_clusters_same_stripe_distant_columns(self):
        b = self._board()
        # Two clusters ~300 words apart in ONE stripe: the column union
        # exceeds the window validity band, so the tier must fall back
        # (row tier) while neighbours still skip.
        b[200:202, 1000:1002] = 255
        self._glider(b, 260, 12000)
        self._run_both(b, 4 * self._t())

    def test_glider_walks_across_quantum_boundary(self):
        b = self._board()
        # A glider heading +x from just left of the 8192-cell boundary:
        # successive launches re-place the column window as the tracked
        # column interval drifts across the quantum edge.
        self._glider(b, 1000, 8150)
        self._run_both(b, 8 * self._t())


class TestPlanGeometryCandidates:
    """Round-6 compute levers: every candidate ``PlanGeometry`` — the
    S-margin 96→64 sweep and the C 256→128 column-window A/B — must be
    bit-identical to the XLA packed engine in interpret mode at the
    headline lane counts (wp = 512, the 16384² lane count; wp = 2048,
    the 65536² one).  The boards place clusters where the NARROWED
    windows differ from the shipped ones: a 128-word-quantum straddle
    (C=128 must fall back where C=256 fits), a tall-ish cluster (the
    64-margin row window must fall back where 96 fits), and plain
    mid-board residue (the narrow windows engage).  Bit-identity makes
    every fallback decision self-checking — a wrong eligibility either
    way still has to produce the exact board."""

    H, W = 2048, 16384  # wp = 512

    def _t(self, shape=None):
        t, adaptive = pallas_packed.adaptive_launch_depth(
            shape or (self.H, self.W // 32), 960, 512
        )
        assert adaptive
        return t

    def _board(self):
        b = np.zeros((self.H, self.W), dtype=np.uint8)
        # Mid-board glider: the narrow windows engage.
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[700 + dy, 8000 + dx] = 255
        # Straddles the 4096-cell (128-word) placement quantum: C=128
        # cannot host it at floor placement, C=256 can.
        b[600:602, 4090:4102:4] = 255
        # A ~40-row vertical blinker fence: within the margin-96 row
        # window's c_max (~53 rows), beyond margin-64's (~21) — the
        # S-margin candidates must fall back here, shipped must not.
        b[1500:1540:6, 2000:2003] = 255
        return b

    def _run_both(self, geom, b, turns):
        p = packed.pack(jnp.asarray(b))
        with pallas_packed.plan_geometry_override(geom):
            got = pallas_packed.make_superstep(
                CONWAY, interpret=True, skip_stable=True, skip_tile_cap=512
            )(p, turns)
            want = packed.superstep(p, CONWAY, turns)
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    @pytest.mark.parametrize(
        "geom", pallas_packed.geometry_candidates(), ids=lambda g: g.label
    )
    def test_wp512_candidate_bit_identical(self, geom):
        t = self._t()
        shape = (self.H, self.W // 32)
        plan = pallas_packed._frontier_plan(shape, t, 512, geometry=geom)
        assert plan is not None
        assert plan[1] == pallas_packed._round8(4 * t + geom.sub_margin)
        assert plan[2] == geom.col_window
        self._run_both(geom, self._board(), 4 * t)

    def test_wp2048_combined_levers_bit_identical(self):
        # The 65536² lane count (wp = 2048) with both levers at once —
        # the short board keeps interpret mode affordable; the lane
        # geometry (placement quanta, window widths) is the headline one.
        H, W = 1024, 65536
        shape = (H, W // 32)
        t = self._t(shape)
        geom = pallas_packed.PlanGeometry(64, 128)
        assert pallas_packed._frontier_plan(shape, t, 512, geometry=geom)[2] == 128
        b = np.zeros((H, W), dtype=np.uint8)
        for dy, dx in [(0, 1), (1, 2), (2, 0), (2, 1), (2, 2)]:
            b[300 + dy, 30000 + dx] = 255
        b[800:802, 4094:4100] = 255  # quantum straddle
        p = packed.pack(jnp.asarray(b))
        with pallas_packed.plan_geometry_override(geom):
            got = pallas_packed.make_superstep(
                CONWAY, interpret=True, skip_stable=True, skip_tile_cap=512
            )(p, 2 * t)
            want = packed.superstep(p, CONWAY, 2 * t)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_override_scoping_and_validation(self):
        shipped = pallas_packed.plan_geometry()
        with pallas_packed.plan_geometry_override((64, 128)) as g:
            assert pallas_packed.plan_geometry() == g == (64, 128)
            assert g.label == "m64c128"
        assert pallas_packed.plan_geometry() == shipped
        with pytest.raises(ValueError):
            pallas_packed.PlanGeometry(40, 128)  # margin below the floor
        with pytest.raises(ValueError):
            pallas_packed.PlanGeometry(96, 100)  # not a placement quantum


def test_vmem_budget_platform_derivation(monkeypatch):
    """Round-4 verdict weak-4: the tuned VMEM budget must resolve per
    platform instead of silently running v5e capacity numbers.  CPU
    (hermetic) pins the measured v5e value so these plans match the
    hardware plans they stand in for; a device kind with more VMEM
    scales the budget in proportion."""
    pp = pallas_packed
    assert pp._vmem_budget() == pp._VMEM_BUDGET == 50 << 20

    class Kind:
        device_kind = "TPU v99 test"

    pp._vmem_physical.cache_clear()
    try:
        monkeypatch.setattr(pp.jax, "default_backend", lambda: "tpu")
        monkeypatch.setattr(pp.jax, "devices", lambda: [Kind()])
        monkeypatch.setitem(pp._VMEM_BY_KIND, "TPU v99 test", 256 << 20)
        assert pp._vmem_budget() == 100 << 20
        pp._vmem_physical.cache_clear()
        monkeypatch.delitem(pp._VMEM_BY_KIND, "TPU v99 test")
        # Unknown generation: the 128 MB baseline (= v5e values) — and
        # the one-time un-swept-hardware warning, asserted here (an
        # uncaptured escape is an error per pytest.ini).
        with pytest.warns(RuntimeWarning, match="not in the VMEM table"):
            assert pp._vmem_budget() == 50 << 20
    finally:
        pp._vmem_physical.cache_clear()


class TestRectRoute:
    """Rectangle-I/O route edge cases (round 5): clusters at stripe
    boundaries force CLIPPED change-rects (the 8-row chunk write path),
    and clusters whose window would cross the torus seam must fall back
    to the classic whole-window path (rect_ok gates on board rows).
    Shares TestColumnWindow's geometry via its helpers (not subclassing,
    which would re-run the parent's cases)."""

    HC, WC = TestColumnWindow.HC, TestColumnWindow.WC
    _run_both = TestColumnWindow._run_both
    _board = TestColumnWindow._board
    _glider = staticmethod(TestColumnWindow._glider)
    _t = TestColumnWindow._t

    def test_cluster_at_stripe_boundary_clips_rect(self):
        b = self._board()
        # Stripe boundary at row 1024 (cap 512 -> 512-row stripes... the
        # cap-512 grid puts boundaries every 512 rows): activity at
        # 1020-1030 spans one, so each stripe's window clips to its
        # centre and the chunked write path runs.
        self._glider(b, 1018, 7000)
        b[1030:1032, 7010:7012] = 255
        self._run_both(b, 4 * self._t())

    def test_cluster_near_board_top_falls_back(self):
        b = self._board()
        # Window would start above row 0: rect_ok false, classic path.
        self._glider(b, 2, 9000)
        b[self.HC - 4 : self.HC - 2, 11000:11002] = 255  # and bottom
        self._run_both(b, 4 * self._t())

    def test_settledish_multidispatch(self):
        b = self._board()
        b[700:702, 8000:8002] = 255  # block (stripe 1)
        self._glider(b, 1500, 3000)  # glider (stripe 2)
        for turns in (2 * self._t(), 5 * self._t()):
            self._run_both(b, turns)


def test_megakernel_nondefault_depths(monkeypatch):
    """The megakernel at forced launch depths either side of the shipped
    _FRONTIER_T: pad/validity margins and the t6 measure depth are all
    T-derived, so a depth-dependent arithmetic slip (cf. the sharded
    halo-depth bug the T=18 coincidence masked) must fail here.  Reuses
    TestColumnWindow's geometry/helpers (the suite this scenario
    belongs to)."""
    tc = TestColumnWindow()
    b = tc._board()
    tc._glider(b, 700, 8000)
    b[1500:1502, 2000:2002] = 255
    for t in (12, 24):
        monkeypatch.setattr(
            pallas_packed, "adaptive_launch_depth",
            lambda s, turns, c, frontier=True, _t=t: (_t, True),
        )
        tc._run_both(b, 4 * t)
