"""Fleet observability suite (ISSUE 19).

The collector tier's contracts, asserted hermetically on CPU:

- **Federated scrape plane**: every node's ``/metrics`` + ``/healthz``
  lands in bounded per-node rings; ``/fleet/metrics`` re-exports ONE
  OpenMetrics page (aggregate families + ``node=``-labelled per-node
  families) that round-trips through ``obs.openmetrics.parse``; the
  aggregation semantics are pinned (counters sum, gauges max, histogram
  buckets sum).
- **Never-block**: a wedged or dead node costs one bounded miss
  (``fleet.scrape_misses{node=}``) per round — the scrape loop's wall
  time stays bounded, the node's last-good snapshot is retained, and
  its growing staleness is surfaced in ``/fleet/healthz`` beside the
  cadence's ``staleness_bound_seconds`` (the PR 10 contract, per
  target).
- **Budget continuity**: the fleet SLO table reads the AGGREGATE ring,
  which keeps a dead pod's last-good ``tenant=`` counters — so a tenant
  that migrates mid-window keeps ONE monotone dispatch series and one
  error budget, not a reset.
- **Trace stitching**: ``/fleet/traces/<id>`` fans the prefix lookup to
  every node and merges span forests on the shared id into one
  node-stamped ``gol-fleet-trace-v1`` timeline.
- **Chaos**: a REAL subprocess pod is SIGKILLed mid-run under a broker
  + second subprocess pod + relay fleet; the stitched failover trace
  spans >= 2 processes on one id (Chrome-exportable), the merged
  ``/fleet/flight`` reads ``pod_condemned -> failover`` in order, the
  tenant's fleet dispatch series never resets across the failover, and
  every ``/fleet/*`` endpoint answers in under 2 s with one pod dead.
- **Tool purity pins**: ``pod_top`` collector frames,
  ``flight_report --fleet`` timelines, and ``trace_export`` fleet lanes
  are pure functions of their inputs, pinned exactly.
"""

import json
import signal
import time
import urllib.error
import urllib.request

import pytest

from distributed_gol_tpu.obs import metrics as metrics_lib
from distributed_gol_tpu.obs import openmetrics, tracing
from distributed_gol_tpu.obs.fleet import (
    FLEET_FLIGHT_SCHEMA,
    CollectorServer,
    FleetCollector,
    node_name,
)
from distributed_gol_tpu.obs.slo import SLOObjectives
from distributed_gol_tpu.serve.broker import Broker, BrokerConfig
from distributed_gol_tpu.serve.httpd import StdlibHTTPServer
from distributed_gol_tpu.serve.relay import RelayServer
from test_federation import (
    broker_state,
    counter,
    spec_doc,
    start_subprocess_pod,
    submit_via,
    wait_for,
)
from tools import flight_report, pod_top, trace_export
from tools.gol_client import GolClient


def http_get(url: str, timeout: float = 10.0) -> tuple[int, bytes]:
    """One bounded GET; the body comes back on error codes too (a 503
    ``/fleet/healthz`` still reports)."""
    try:
        with urllib.request.urlopen(
            urllib.request.Request(url), timeout=timeout
        ) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def node_snapshot(
    dispatches: float = 0,
    tenant: str = "alice",
    queue_depth: float = 0.0,
    latency: dict | None = None,
) -> dict:
    """One pod-shaped ``gol-metrics-v1`` snapshot a stub node exposes."""
    snap = {
        "schema": metrics_lib.SCHEMA,
        "counters": {f"controller.dispatches{{tenant={tenant}}}": dispatches},
        "gauges": {"frames.queue_depth": queue_depth},
        "histograms": {},
        "info": {"run.backend": "stub"},
    }
    if latency is not None:
        snap["histograms"][
            f"controller.dispatch_seconds{{tenant={tenant}}}"
        ] = latency
    return snap


class StubNode(StdlibHTTPServer):
    """One scrape-target-shaped server: ``/metrics`` renders a settable
    snapshot, ``/healthz``/``/flight``/``/traces`` answer from fields,
    ``delay`` wedges every response (the never-block row's victim), and
    the pod surfaces a broker's prober + discovery need are stubbed so
    the same class rides the ``broker --collector`` test."""

    thread_name = "gol-stub-node"

    def __init__(self, snapshot: dict | None = None):
        self.snapshot = snapshot or node_snapshot()
        self.healthz: dict = {"ready": True, "live": True, "tenants": {}}
        self.flight_records: list[dict] = []
        self.traces: dict[str, dict] = {}
        self.delay = 0.0
        super().__init__(port=0)

    def handle(self, request, method, path, query):
        if self.delay:
            time.sleep(self.delay)
        if path == "/metrics" and method == "GET":
            text = openmetrics.render(self.snapshot)
            request._send(200, text.encode(), openmetrics.CONTENT_TYPE)
            return True
        if path == "/healthz" and method == "GET":
            request._send_json(200, dict(self.healthz))
            return True
        if path == "/flight" and method == "GET":
            request._send_json(200, {"records": list(self.flight_records)})
            return True
        if path == "/traces" and method == "GET":
            prefix = query.get("trace_id", "")
            hit = next(
                (
                    doc
                    for tid, doc in self.traces.items()
                    if prefix and tid.startswith(prefix)
                ),
                None,
            )
            if hit is None:
                request._send_json(404, {"error": "no retained trace"})
            else:
                request._send_json(200, hit)
            return True
        if path == "/v1/sessions" and method == "GET":
            request._send_json(200, {"sessions": {}})
            return True
        return False


def trace_doc(trace_id: str, name: str, t0_unix: float, spans: list) -> dict:
    """One per-process ``gol-trace-v1`` doc for the stitcher."""
    return {
        "schema": "gol-trace-v1",
        "trace_id": trace_id,
        "name": name,
        "tenant": "alice",
        "status": "ok",
        "flagged": None,
        "t0_unix": t0_unix,
        "spans": spans,
        "events": [],
        "marks": {},
    }


# -- satellite units -----------------------------------------------------------


class TestNodeName:
    def test_host_port(self):
        assert node_name("http://127.0.0.1:9500") == "127.0.0.1:9500"

    def test_bare_fallback(self):
        assert node_name("not-a-url") == "not-a-url"


class TestFleetOpenMetrics:
    def test_node_labelled_snapshot_roundtrips(self):
        """The acceptance pin: a ``node=``-labelled page survives
        render -> parse with every label and value intact."""
        snap = {
            "schema": metrics_lib.SCHEMA,
            "counters": {
                "gol_controller_dispatches{node=pod-a,tenant=alice}": 7,
                "gol_controller_dispatches{tenant=alice}": 7,
            },
            "gauges": {"gol_fleet_nodes": 2.0},
            "histograms": {
                "gol_relay_frame_staleness_seconds{node=relay-1}": {
                    "buckets": [0.01, 0.1],
                    "counts": [3, 1, 0],
                    "sum": 0.09,
                    "count": 4,
                }
            },
            "info": {},
        }
        assert openmetrics.check_roundtrip(snap) == []
        parsed = openmetrics.parse(openmetrics.render(snap))
        assert (
            parsed["counters"][
                "gol_controller_dispatches{node=pod-a,tenant=alice}"
            ]
            == 7
        )
        hist = parsed["histograms"][
            "gol_relay_frame_staleness_seconds{node=relay-1}"
        ]
        assert hist["counts"] == [3, 1, 0] and hist["count"] == 4

    def test_spell_inverts_split_all(self):
        key = "gol_x{node=a,tenant=b}"
        base, labels = openmetrics.split_all(key)
        assert base == "gol_x" and labels == {"node": "a", "tenant": "b"}
        assert openmetrics.spell(base, labels) == key


class TestStitchTraces:
    def test_two_processes_one_axis(self):
        tid = "ab" * 16
        broker = trace_doc(
            tid, "gol.broker.failover", t0_unix=100.0,
            spans=[{"name": "gol.broker.place", "span_id": "1",
                    "parent_id": None, "t0_ns": 1000, "dur_ns": 500}],
        )
        pod = trace_doc(
            tid, "gol.request", t0_unix=100.5,
            spans=[{"name": "gol.admission", "span_id": "1",
                    "parent_id": None, "t0_ns": 2000, "dur_ns": 100}],
        )
        doc = tracing.stitch_traces({"broker": [broker], "pod-b": [pod]})
        assert doc["schema"] == tracing.FLEET_SCHEMA
        assert doc["trace_id"] == tid
        assert set(doc["nodes"]) == {"broker", "pod-b"}
        by_name = {s["name"]: s for s in doc["spans"]}
        # pod-b's clock is 0.5 s later: its span re-bases onto broker's.
        assert by_name["gol.broker.place"]["t0_ns"] == 1000
        assert by_name["gol.admission"]["t0_ns"] == 500_000_000 + 2000
        # Span ids are namespaced per process (both root at "1").
        assert by_name["gol.broker.place"]["span_id"] == "broker:1"
        assert by_name["gol.admission"]["span_id"] == "pod-b:1"
        assert doc["spans"] == sorted(
            doc["spans"], key=lambda s: s["t0_ns"]
        )

    def test_empty_is_none(self):
        assert tracing.stitch_traces({}) is None
        assert tracing.stitch_traces({"a": []}) is None


class TestScrapePlane:
    def test_aggregate_semantics_and_node_labels(self):
        """Counters sum, gauges max, histogram buckets sum — and the
        exported page carries both forms (aggregate + ``node=``)."""
        h = {"buckets": [0.1, 1.0], "counts": [2, 1, 0], "sum": 0.4,
             "count": 3}
        n1 = StubNode(node_snapshot(dispatches=10, queue_depth=3.0,
                                    latency=h))
        n2 = StubNode(node_snapshot(dispatches=5, queue_depth=7.0,
                                    latency=h))
        collector = None
        try:
            collector = FleetCollector(
                {"n1": n1.url, "n2": n2.url},
                interval=0.05, scrape_timeout=2.0, start=False,
            )
            collector.scrape_once()
            text = collector.render_metrics()
            parsed = openmetrics.parse(text)
            agg_key = "gol_controller_dispatches{tenant=alice}"
            assert parsed["counters"][agg_key] == 15  # counters SUM
            assert parsed["counters"][
                "gol_controller_dispatches{node=n1,tenant=alice}"
            ] == 10
            assert parsed["gauges"][
                "gol_frames_queue_depth"
            ] == 7.0  # gauges MAX
            agg_h = parsed["histograms"][
                "gol_controller_dispatch_seconds{tenant=alice}"
            ]
            assert agg_h["counts"] == [4, 2, 0]  # buckets SUM
            assert agg_h["count"] == 6
        finally:
            if collector is not None:
                collector.close()
            n1.close()
            n2.close()

    def test_wedged_node_is_one_bounded_miss(self):
        """The never-block bugfix row: a node that stops answering
        inside the timeout costs one bounded miss per round; its
        last-good snapshot stays aggregated and its staleness is
        surfaced (and eventually flagged) in ``/fleet/healthz``."""
        victim = StubNode(node_snapshot(dispatches=100))
        healthy = StubNode(node_snapshot(dispatches=1))
        collector = None
        try:
            collector = FleetCollector(
                {"victim": victim.url, "healthy": healthy.url},
                interval=0.05, scrape_timeout=0.25, start=False,
            )
            collector.scrape_once()
            assert collector.fleet_health()["ready"]
            base_miss = counter("fleet.scrape_misses{node=victim}")

            victim.delay = 5.0  # wedged: answers WAY past the timeout
            t0 = time.monotonic()
            collector.scrape_once()
            elapsed = time.monotonic() - t0
            assert elapsed < 2.0, f"scrape blocked {elapsed:.2f}s on a wedge"
            assert (
                counter("fleet.scrape_misses{node=victim}") == base_miss + 1
            )
            health = collector.fleet_health()
            row = health["nodes"]["victim"]
            assert row["consecutive_misses"] == 1
            assert row["last_error"]
            assert health["staleness_bound_seconds"] == pytest.approx(0.3)
            # Last-good retention: the wedged node's counters still ride
            # the aggregate (its history is history).
            parsed = openmetrics.parse(collector.render_metrics())
            assert parsed["counters"][
                "gol_controller_dispatches{tenant=alice}"
            ] == 101
            # Past 2x the bound the node is flagged stale and the fleet
            # goes not-ready.
            time.sleep(0.7)
            collector.scrape_once()
            health = collector.fleet_health()
            assert health["nodes"]["victim"]["stale"]
            assert not health["ready"]
        finally:
            if collector is not None:
                collector.close()
            victim.close()
            healthy.close()

    def test_dead_pod_keeps_tenant_budget_continuous(self):
        """The fleet SLO continuity row, unit-sized: a tenant's fleet
        dispatch series is MONOTONE across its pod dying and the work
        moving elsewhere — no reset, one budget."""
        first = StubNode(node_snapshot(dispatches=100))
        second = StubNode(node_snapshot(dispatches=40))
        collector = None
        try:
            collector = FleetCollector(
                {"first": first.url, "second": second.url},
                interval=0.05, scrape_timeout=0.25, start=False,
            )
            collector.scrape_once()
            slo = collector.fleet_slo()
            assert slo["schema"] == "gol-fleet-slo-v1"
            assert slo["tenants"]["alice"]["dispatches_total"] == 140

            first.close()  # the pod dies; alice "migrates" to second
            second.snapshot = node_snapshot(dispatches=90)
            collector.scrape_once()
            total = collector.fleet_slo()["tenants"]["alice"][
                "dispatches_total"
            ]
            assert total == 190, "dead pod's last-good must stay summed"
            assert total >= 140, "the budget series must never reset"
        finally:
            if collector is not None:
                collector.close()
            second.close()


class TestStitchedTraceFanout:
    def test_fans_to_every_node_and_merges(self):
        tid = "cd" * 16
        n1 = StubNode()
        n2 = StubNode()
        n1.traces[tid] = trace_doc(
            tid, "gol.request", 50.0,
            [{"name": "gol.admission", "span_id": "1", "parent_id": None,
              "t0_ns": 10, "dur_ns": 5}],
        )
        n2.traces[tid] = trace_doc(
            tid, "gol.relay.subscribe", 50.1,
            [{"name": "gol.relay.subscribe", "span_id": "1",
              "parent_id": None, "t0_ns": 20, "dur_ns": 5}],
        )
        collector = None
        try:
            collector = FleetCollector(
                {"n1": n1.url, "n2": n2.url},
                interval=0.05, scrape_timeout=2.0, start=False,
            )
            doc = collector.stitched_trace(tid[:6])  # prefix lookup
            assert doc is not None
            assert set(doc["nodes"]) == {"n1", "n2"}
            assert {s["name"] for s in doc["spans"]} == {
                "gol.admission", "gol.relay.subscribe",
            }
            assert collector.stitched_trace("ffff" * 8) is None
        finally:
            if collector is not None:
                collector.close()
            n1.close()
            n2.close()


class TestCollectorServerHTTP:
    def test_endpoints_and_aliases(self, tmp_path):
        node = StubNode()
        node.flight_records.append(
            {"t": 5.0, "kind": "dispatch", "turn": 3}
        )
        server = None
        try:
            collector = FleetCollector(
                {"n1": node.url}, interval=0.05, scrape_timeout=2.0,
                checkpoint_root=tmp_path, start=False,
            )
            collector.scrape_once()
            server = CollectorServer(collector, port=0)
            code, body = http_get(server.url + "/fleet/metrics")
            assert code == 200
            parsed = openmetrics.parse(body.decode())
            assert any("node=n1" in k for k in parsed["counters"])
            # /metrics and /healthz alias the fleet forms.
            code2, body2 = http_get(server.url + "/metrics")
            assert (code2, body2) == (code, body)
            code, body = http_get(server.url + "/healthz")
            assert code == 200
            health = json.loads(body)
            assert health["fleet"] is True and "n1" in health["nodes"]
            code, body = http_get(server.url + "/fleet/slo")
            assert code == 200
            assert json.loads(body)["schema"] == "gol-fleet-slo-v1"
            code, body = http_get(server.url + "/fleet/flight")
            assert code == 200
            doc = json.loads(body)
            assert doc["schema"] == FLEET_FLIGHT_SCHEMA
            assert doc["records"][0]["node"] == "n1"
            code, _ = http_get(server.url + "/fleet/flight?limit=zap")
            assert code == 400
            code, _ = http_get(server.url + "/fleet/traces")
            assert code == 400  # no id
            code, _ = http_get(server.url + "/fleet/traces/feedface")
            assert code == 404  # nobody retains it
        finally:
            if server is not None:
                server.close()  # closes the collector too
            node.close()


class TestBrokerCollectorRider:
    def test_config_validation(self):
        with pytest.raises(ValueError):
            BrokerConfig(collector_interval_seconds=0.0)
        with pytest.raises(ValueError):
            BrokerConfig(collector_scrape_timeout_seconds=-1.0)

    def test_broker_serves_fleet_surface(self, tmp_path):
        """``broker --collector``: the /fleet/* plane rides the broker's
        own port, scraping the broker's pods, with the broker's flight
        ring as the local postmortem source."""
        pod = StubNode()
        broker = None
        try:
            broker = Broker(
                [pod.url],
                BrokerConfig(
                    probe_interval_seconds=60.0,
                    checkpoint_root=tmp_path,
                    collector=True,
                    collector_interval_seconds=0.05,
                ),
            )
            assert broker.collector is not None
            broker.collector.scrape_once()
            code, body = http_get(broker.url + "/fleet/metrics")
            assert code == 200
            parsed = openmetrics.parse(body.decode())
            name = node_name(pod.url)
            assert any(f"node={name}" in k for k in parsed["counters"])
            code, body = http_get(broker.url + "/fleet/healthz")
            health = json.loads(body)
            assert health["fleet"] is True and name in health["nodes"]
            # The broker's own /metrics (its registry) works beside it.
            code, body = http_get(broker.url + "/metrics")
            assert code == 200
            own = openmetrics.parse(body.decode())
            assert "gol_broker_pods_ready" in own["gauges"]
            # The broker ring is the merged postmortem's local source.
            broker.flight.record("discover", tenants=0)
            code, body = http_get(broker.url + "/fleet/flight")
            doc = json.loads(body)
            assert any(
                r["node"] == "broker" and r["kind"] == "discover"
                for r in doc["records"]
            )
        finally:
            if broker is not None:
                broker.close()
            pod.close()


# -- tool purity pins ----------------------------------------------------------


class TestPodTopCollectorRender:
    CUR = {
        "t": 20.0,
        "health": {
            "fleet": True, "ready": False,
            "scrape_interval_seconds": 0.5,
            "staleness_bound_seconds": 2.5,
            "aggregate_sample_age_seconds": 0.2,
            "nodes": {
                "pod-a": {"ready": True, "stale": False,
                          "sample_age_seconds": 0.4,
                          "consecutive_misses": 0, "last_error": None},
                "pod-b": {"ready": False, "stale": True,
                          "sample_age_seconds": 9.1,
                          "consecutive_misses": 3,
                          "last_error": "PodUnreachable: refused"},
                "relay-1": {"ready": True, "stale": False,
                            "sample_age_seconds": 0.3,
                            "consecutive_misses": 0, "last_error": None},
            },
        },
        "metrics": {
            "counters": {
                "gol_fleet_scrape_rounds": 12,
                "gol_fleet_scrape_misses{node=pod-b}": 3,
                "gol_controller_dispatches{node=pod-a,tenant=alice}": 100,
                "gol_relay_frames_out{node=relay-1}": 500,
            },
            "gauges": {},
            "histograms": {
                "gol_relay_frame_staleness_seconds{node=relay-1}": {
                    "buckets": [0.01, 0.05, 0.1],
                    "counts": [10, 5, 1, 0], "sum": 0.3, "count": 16,
                },
            },
            "info": {},
        },
    }
    PREV = {
        "t": 10.0,
        "health": CUR["health"],
        "metrics": {
            "counters": {
                "gol_controller_dispatches{node=pod-a,tenant=alice}": 50,
                "gol_relay_frames_out{node=relay-1}": 100,
            },
            "gauges": {},
            "histograms": {
                "gol_relay_frame_staleness_seconds{node=relay-1}": {
                    "buckets": [0.01, 0.05, 0.1],
                    "counts": [0, 0, 0, 0], "sum": 0.0, "count": 0,
                },
            },
            "info": {},
        },
    }

    def test_pinned_frame(self):
        assert pod_top.render_fleet_collector(self.CUR, self.PREV) == (
            "collector NOT-READY | 3 node(s) | scrape every 0.5s "
            "(staleness bound 2.5s) | rounds 12 misses 3 | "
            "aggregate sample 0.2s old\n"
            "NODE               STATE         AGE  MISS  DISP/S "
            " FRAMES/S  STALE-P99  LAST ERROR\n"
            "pod-a              ready        0.4s     0       5 "
            "        -          -  -\n"
            "pod-b              STALE        9.1s     3       - "
            "        -          -  PodUnreachable: refused\n"
            "relay-1            ready        0.3s     0       - "
            "       40       92ms  -"
        )

    def test_first_frame_has_no_rates(self):
        frame = pod_top.render_fleet_collector(self.CUR)
        assert " 5 " not in frame.splitlines()[2]
        assert "92ms" in frame  # since-start staleness p99 still renders


class TestFlightReportFleet:
    DOC = {
        "schema": "gol-fleet-flight-v1",
        "sources": ["broker", "pod-a"],
        "records": [
            {"t": 10.0, "kind": "pod_condemned", "node": "broker",
             "pod": "http://x", "misses": 2, "stranded": ["alice"]},
            {"t": 10.5, "kind": "failover", "node": "broker",
             "tenant": "alice", "from_pod": "http://x",
             "to_pod": "http://y", "checkpoint_turn": 42,
             "trace_id": "deadbeefcafe"},
            {"t": 10.6, "kind": "dispatch", "node": "dump:flight-1.json",
             "turn": 7, "cause": "Boom"},
        ],
    }

    def test_pinned_timeline(self):
        assert flight_report.render_fleet(self.DOC).splitlines() == [
            "fleet flight timeline (3 record(s) from 2 source(s): "
            "broker, pod-a)",
            "  +   0.000s  broker              pod_condemned    "
            "pod http://x CONDEMNED after 2 missed probe(s), "
            "stranding ['alice']",
            "  +   0.500s  broker              failover         "
            "tenant alice FAILED OVER http://x -> http://y "
            "from checkpoint turn 42 [trace deadbeef]",
            "  +   0.600s  dump:flight-1.json  dispatch         "
            "turn=7 cause=Boom",
        ]

    def test_wrong_schema_refused(self):
        with pytest.raises(ValueError):
            flight_report.render_fleet({"schema": "gol-flight-v1"})


class TestTraceExportFleetLanes:
    def test_one_process_lane_per_node(self):
        doc = tracing.stitch_traces({
            "broker": [trace_doc(
                "ee" * 16, "gol.broker.failover", 10.0,
                [{"name": "gol.broker.place", "span_id": "1",
                  "parent_id": None, "t0_ns": 0, "dur_ns": 1000}],
            )],
            "pod-b": [trace_doc(
                "ee" * 16, "gol.request", 10.1,
                [{"name": "gol.admission", "span_id": "1",
                  "parent_id": None, "t0_ns": 0, "dur_ns": 1000}],
            )],
        })
        chrome = trace_export.to_chrome(doc)
        lanes = {
            e["args"]["name"]: e["pid"]
            for e in chrome["traceEvents"]
            if e["ph"] == "M"
        }
        assert lanes == {
            "broker [gol.broker.failover]": 1, "pod-b [gol.request]": 2,
        }
        span_pids = {
            e["name"]: e["pid"]
            for e in chrome["traceEvents"]
            if e["ph"] == "X"
        }
        assert span_pids["gol.broker.place"] == 1
        assert span_pids["gol.admission"] == 2
        json.dumps(chrome)  # Chrome-loadable


# -- the chaos row -------------------------------------------------------------


class TestFleetChaos:
    def test_sigkill_failover_is_one_fleet_story(self, tmp_path):
        """Broker + two REAL subprocess pods + one relay under a live
        collector; SIGKILL the pod running alice mid-run and read the
        whole incident off the fleet plane."""
        root = tmp_path / "ckpt"
        alice_spec = spec_doc(12_000, seed=5, checkpoint_every=16)
        bob_spec = {
            **spec_doc(8_000, seed=9),
            "spectate": True,
            "viewport": [0, 0, 32, 32],
        }

        proc_a, pod_a = start_subprocess_pod(root)
        proc_b, pod_b = start_subprocess_pod(root)
        procs = {pod_a: proc_a, pod_b: proc_b}
        broker = relay = server = None
        try:
            broker = Broker(
                [pod_a, pod_b],
                BrokerConfig(
                    probe_interval_seconds=0.1,
                    probe_miss_threshold=2,
                    checkpoint_root=root,
                ),
            )
            client = GolClient(broker.url)
            wait_for(
                lambda: all(
                    p["ready"] and p["status"] == "ready"
                    for p in broker.pod_states()
                ),
                60, "both pods probed ready",
            )

            receipt = submit_via(client, "alice", alice_spec)
            victim = receipt["pod"]
            survivor = pod_b if victim == pod_a else pod_a
            # Placement scores the last PROBED health — wait for the
            # prober to see alice's cells before the second submit, so
            # headroom puts bob on the other pod.
            wait_for(
                lambda: any(
                    p["endpoint"] == victim and p["resident_cells"] > 0
                    for p in broker.pod_states()
                ),
                60, "the probe to reflect alice's placement",
            )
            bob_receipt = submit_via(client, "bob", bob_spec)
            assert bob_receipt["pod"] == survivor, (
                "headroom placement should spread the tenants"
            )
            bob_tid = bob_receipt["broker_trace_id"]

            # The relay leg: subscribed to bob's stream on the survivor,
            # scraped as a fleet node like any pod.
            relay = RelayServer(
                f"{survivor}/v1/sessions/bob/frames?queue=256",
                cache_deltas=4096, queue_depth=4096,
                backoff_initial=0.05, backoff_max=0.2,
            )
            wait_for(
                lambda: relay.health()["frames_in"] > 0,
                60, "relay ingesting bob's frames",
            )

            collector = FleetCollector(
                {
                    "pod-a": pod_a,
                    "pod-b": pod_b,
                    "relay": relay.url,
                },
                interval=0.1,
                scrape_timeout=1.0,
                checkpoint_root=root,
                objectives=SLOObjectives(
                    latency_seconds=30.0,
                    error_rate=0.5,
                    fast_window_seconds=2.0,
                    slow_window_seconds=6.0,
                    budget_window_seconds=60.0,
                ),
                local_name="broker",
                local_flight=broker.flight,
            )
            server = CollectorServer(collector, port=0)

            def alice_fleet_dispatches():
                row = collector.fleet_slo()["tenants"].get("alice")
                return row["dispatches_total"] if row else 0

            wait_for(
                lambda: alice_fleet_dispatches() > 0,
                60, "alice's dispatches visible on the fleet plane",
            )
            d0 = alice_fleet_dispatches()
            # Frame-header publish stamps observed end to end: the
            # relay's staleness histogram rides /fleet/metrics under
            # its node label.
            stale_key = (
                "gol_relay_frame_staleness_seconds{node=relay}"
            )
            wait_for(
                lambda: openmetrics.parse(collector.render_metrics())
                .get("histograms", {})
                .get(stale_key, {})
                .get("count", 0)
                > 0,
                60, "relay staleness histogram on the fleet page",
            )

            # SIGKILL alice's pod mid-run (past a durable checkpoint).
            wait_for(
                lambda: (broker_state(client, "alice") or {}).get(
                    "turn", 0
                ) >= 64,
                60, "alice past turn 64",
            )
            base_miss = counter("fleet.scrape_misses{node="
                                + ("pod-a" if victim == pod_a else "pod-b")
                                + "}")
            procs[victim].send_signal(signal.SIGKILL)
            wait_for(
                lambda: procs[victim].poll() is not None, 10, "pod death"
            )
            wait_for(
                lambda: broker.placement("alice") == survivor,
                60, "failover placement",
            )
            st = wait_for(
                lambda: (
                    (s := broker_state(client, "alice"))
                    and s["status"] in ("completed", "failed")
                    and s
                ),
                120, "alice completion on the survivor",
            )
            assert st["status"] == "completed"

            # (1) Budget continuity: the fleet series never reset.
            wait_for(
                lambda: alice_fleet_dispatches() >= d0,
                30, "fleet dispatch series monotone across failover",
            )

            # (2) The merged postmortem reads condemn -> failover in
            # one node-stamped sequence.
            merged = wait_for(
                lambda: (
                    (m := collector.merged_flight())
                    and any(
                        r["kind"] == "failover" for r in m["records"]
                    )
                    and m
                ),
                30, "failover in the merged flight timeline",
            )
            kinds = [
                r["kind"] for r in merged["records"]
                if r["node"] == "broker"
            ]
            assert kinds.index("pod_condemned") < kinds.index("failover")
            assert "broker" in merged["sources"]
            report = flight_report.render_fleet(merged)
            assert "CONDEMNED" in report and "FAILED OVER" in report

            # (3) The stitched failover trace spans >= 2 processes on
            # one shared id, and exports to Chrome lanes.
            failover = next(
                r for r in broker.flight.records()
                if r["kind"] == "failover"
            )
            tid = failover["trace_id"]
            stitched = wait_for(
                lambda: (
                    (d := collector.stitched_trace(tid))
                    and len(
                        {
                            n for n in d["nodes"]
                            if n == "broker" or n.startswith("pod-")
                        }
                    ) >= 2
                    and d
                ),
                30, "stitched trace across broker + survivor pod",
            )
            names = {s["name"] for s in stitched["spans"]}
            assert "gol.broker.place" in names
            assert "gol.admission" in names, "pod-side spans on the id"
            chrome = trace_export.to_chrome(stitched)
            span_pids = {
                e["pid"] for e in chrome["traceEvents"] if e["ph"] == "X"
            }
            assert len(span_pids) >= 2, "Chrome lanes span processes"

            # The relay joined bob's request trace via the re-exported
            # traceparent: one id from pod publish to relay subscribe.
            bob_stitched = collector.stitched_trace(bob_tid)
            assert bob_stitched is not None
            assert "gol.relay.subscribe" in {
                s["name"] for s in bob_stitched["spans"]
            }

            # (4) Never-block, fleet-sized: with one pod DEAD, every
            # /fleet/* endpoint answers in bounded time.
            assert counter(
                "fleet.scrape_misses{node="
                + ("pod-a" if victim == pod_a else "pod-b")
                + "}"
            ) > base_miss
            for path in (
                "/fleet/metrics",
                "/fleet/healthz",
                "/fleet/slo",
                "/fleet/flight",
                f"/fleet/traces/{tid}",
            ):
                t0 = time.monotonic()
                code, _ = http_get(server.url + path, timeout=10.0)
                elapsed = time.monotonic() - t0
                assert code in (200, 503), f"{path}: HTTP {code}"
                assert elapsed < 2.0, (
                    f"{path} took {elapsed:.2f}s with a dead pod"
                )
        finally:
            if server is not None:
                server.close()  # closes the collector too
            if relay is not None:
                relay.close()
            if broker is not None:
                broker.close()
            for proc in procs.values():
                if proc.poll() is None:
                    proc.kill()
                proc.wait(timeout=10)
