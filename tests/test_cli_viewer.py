"""CLI flag parity (main.go:17-46) and viewer loop/renderer behaviour."""

import io
import queue

import numpy as np
import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.__main__ import build_parser, main, params_from_args
from distributed_gol_tpu.viewer import render as R
from distributed_gol_tpu.viewer.loop import run_headless, run_terminal


class TestParser:
    def test_defaults_match_reference(self):
        a = build_parser().parse_args([])
        p = params_from_args(a)
        # main.go defaults: t=8, w=512, h=512, turns=10^10
        assert (p.threads, p.image_width, p.image_height) == (8, 512, 512)
        assert p.turns == 10_000_000_000
        assert p.no_vis is False

    def test_reference_flag_spelling(self):
        a = build_parser().parse_args(
            ["-t", "4", "-w", "64", "-h", "32", "-turns", "7", "-noVis"]
        )
        p = params_from_args(a)
        assert (p.threads, p.image_width, p.image_height, p.turns) == (4, 64, 32, 7)
        assert p.no_vis is True

    def test_h_is_height_not_help(self):
        assert build_parser().parse_args(["-h", "128"]).h == 128

    def test_tpu_extras(self):
        a = build_parser().parse_args(
            ["--rule", "B36/S23", "--mesh", "2x4", "--engine", "roll",
             "--superstep", "16"]
        )
        p = params_from_args(a)
        assert p.mesh_shape == (2, 4)
        assert p.superstep == 16
        assert p.rule.birth == frozenset({3, 6})


class TestCliRun:
    def test_headless_run(self, tmp_path, input_images, capsys):
        rc = main(
            ["-w", "16", "-h", "16", "-turns", "5", "-noVis",
             "--images-dir", str(input_images), "--out-dir", str(tmp_path)]
        )
        assert rc == 0
        assert (tmp_path / "16x16x5.pgm").exists()
        assert "Final turn 5" in capsys.readouterr().out


class TestRenderer:
    def test_downsample_maxpool(self):
        b = np.zeros((8, 8), np.uint8)
        b[0, 0] = 255
        small = R.downsample(b, 2, 2)
        assert small.shape == (2, 2)
        assert small[0, 0] == 255 and small[1, 1] == 0

    def test_downsample_keeps_trailing_cells(self):
        # Sizes not divisible by the factor are padded, not cropped: a live
        # cell in the last row/column must still light its tile (advisor
        # finding r2: the crop silently dropped it from every frame).
        b = np.zeros((10, 10), np.uint8)
        b[9, 9] = 255
        small = R.downsample(b, 4, 4)  # factor 3 -> padded to 12x12
        assert small.shape == (4, 4)
        assert small[3, 3] == 255

    def test_render_smoke(self):
        b = np.zeros((4, 4), np.uint8)
        b[0, 1] = 255
        frame = R.render(b, term_size=(4, 4))
        assert R.HALF in frame and "\x1b[" in frame

    def test_terminal_loop_consumes_stream(self, tmp_path, input_images):
        params = gol.Params(
            turns=3, image_width=16, image_height=16,
            images_dir=input_images, out_dir=tmp_path,
            no_vis=False, flip_events="cell",
        )
        events: queue.Queue = queue.Queue()
        gol.run(params, events)
        out = io.StringIO()
        final = run_terminal(params, events, max_fps=1000.0, out=out)
        assert final is not None and final.completed_turns == 3
        assert R.HALF in out.getvalue()

    def test_headless_loop_returns_final(self, tmp_path, input_images):
        params = gol.Params(
            turns=2, image_width=16, image_height=16,
            images_dir=input_images, out_dir=tmp_path,
        )
        events: queue.Queue = queue.Queue()
        gol.run(params, events)
        final = run_headless(params, events)
        assert final is not None and final.completed_turns == 2
