"""Sharded-tier 10k-turn discipline: the (1,1)-mesh 512² alive-count soak.

The single-device engines carry 10k-turn CSV soaks
(``tests/test_run_counts.py``, ``tools/hw_soak.py``); the sharded
pallas-packed tier had none — and round 6 added a second sharded
execution tier (the in-kernel ICI exchange megakernel), so BOTH tiers now
walk the reference's full 512² count series
(``/root/reference/check/alive/512x512.csv``, turns 1..10000) at dispatch
boundaries chosen to exercise megakernel chunks, the loose probing tail,
the remainder split, and both launch parities.  Interpret-mode on CPU
rigs (the (1,1) loopback build IS the hermetic form of the in-kernel
tier); ``bench.py --verify`` covers hardware.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_gol_tpu.models.life import CONWAY
from distributed_gol_tpu.ops import packed
from distributed_gol_tpu.parallel import pallas_halo
from distributed_gol_tpu.parallel.mesh import make_mesh
from distributed_gol_tpu.parallel.packed_halo import packed_sharding

from tests.test_run_counts import read_alive_csv


@pytest.mark.slow
@pytest.mark.parametrize("in_kernel", [True, False], ids=["ici", "ppermute"])
def test_sharded_512_alive_count_soak(input_images, golden_alive, in_kernel):
    expected = read_alive_csv(golden_alive / "512x512.csv")
    from distributed_gol_tpu.engine.pgm import read_pgm

    board = read_pgm(input_images / "512x512.pgm")
    mesh = make_mesh((1, 1))
    use, reason = pallas_halo.ici_tier_policy(mesh, in_kernel=in_kernel)
    assert use is in_kernel, reason
    p = packed.pack(jnp.asarray(board))
    pb = jax.device_put(np.asarray(p), packed_sharding(mesh))
    run = pallas_halo.make_superstep(
        mesh, CONWAY, skip_stable=True, in_kernel=in_kernel
    )
    # 977-turn dispatches: full = 54 launches at T=18 → six 8-launch
    # megakernel chunks + 6 loose probing launches + a 5-turn remainder
    # (split into its period-multiple part + tail) — every dispatch
    # crosses every execution path of the tier.
    turn = 0
    step = 977
    while turn < 10_000:
        k = min(step, 10_000 - turn)
        pb = run(pb, k)
        turn += k
        count = int(np.count_nonzero(np.asarray(packed.unpack(pb))))
        assert count == expected[turn], (
            f"tier={'ici' if in_kernel else 'ppermute'} turn {turn}: "
            f"{count} != {expected[turn]}"
        )
    # The settled period-2 tail (count_test.go:45-51): 5565 even / 5567
    # odd from turn 10000 on.
    pb = run(pb, 1)
    assert int(np.count_nonzero(np.asarray(packed.unpack(pb)))) == 5567
    pb = run(pb, 1)
    assert int(np.count_nonzero(np.asarray(packed.unpack(pb)))) == 5565
