"""Kernel unit tests: one-step correctness vs the NumPy oracle.

The reference has no kernel unit tests (SURVEY.md §4: black-box only); these
are the added coverage the survey's rebuild test plan calls for.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_gol_tpu.models.life import CONWAY, HIGHLIFE, RULES, SEEDS, parse_rule
from distributed_gol_tpu.ops.stencil import (
    alive_count,
    flip_mask,
    make_step_fn,
    step,
    steps_with_counts,
    superstep,
)
from tests.conftest import random_board
from tests.oracle import oracle_run, oracle_step


def jstep(board, rule=CONWAY):
    return np.asarray(step(jnp.asarray(board), jnp.asarray(rule.table)))


class TestSingleStep:
    def test_blinker_oscillates(self):
        """Period-2 blinker: the canonical hand-checkable pattern."""
        b = np.zeros((5, 5), dtype=np.uint8)
        b[2, 1:4] = 255  # horizontal bar
        expected = np.zeros((5, 5), dtype=np.uint8)
        expected[1:4, 2] = 255  # vertical bar
        np.testing.assert_array_equal(jstep(b), expected)
        np.testing.assert_array_equal(jstep(expected), b)

    def test_block_is_still(self):
        b = np.zeros((6, 6), dtype=np.uint8)
        b[2:4, 2:4] = 255
        np.testing.assert_array_equal(jstep(b), b)

    def test_toroidal_wrap_corner(self):
        """A 2x2 block straddling all four corners must survive: wrap is the
        behaviour the reference implements with edge branches
        (server/server.go:55-75)."""
        b = np.zeros((8, 8), dtype=np.uint8)
        for y in (0, 7):
            for x in (0, 7):
                b[y, x] = 255
        np.testing.assert_array_equal(jstep(b), b)

    def test_toroidal_wrap_blinker_on_edge(self):
        b = np.zeros((8, 8), dtype=np.uint8)
        b[0, 3] = b[7, 3] = b[1, 3] = 255  # vertical blinker across the seam
        np.testing.assert_array_equal(jstep(b), oracle_step(b))

    @pytest.mark.parametrize("shape", [(16, 16), (17, 31), (64, 64), (5, 128)])
    def test_random_boards_match_oracle(self, rng, shape):
        b = random_board(rng, *shape)
        np.testing.assert_array_equal(jstep(b), oracle_step(b))

    @pytest.mark.parametrize("rule", list(RULES.values()), ids=lambda r: r.name)
    def test_rule_zoo_matches_oracle(self, rng, rule):
        b = random_board(rng, 32, 32)
        np.testing.assert_array_equal(jstep(b, rule), oracle_step(b, rule))

    def test_make_step_fn(self, rng):
        b = random_board(rng, 16, 16)
        f = make_step_fn(HIGHLIFE)
        np.testing.assert_array_equal(np.asarray(f(jnp.asarray(b))), oracle_step(b, HIGHLIFE))


class TestMultiStep:
    def test_superstep_equals_iterated_step(self, rng):
        b = random_board(rng, 32, 32)
        table = jnp.asarray(CONWAY.table)
        got = np.asarray(superstep(jnp.asarray(b), table, 10))
        np.testing.assert_array_equal(got, oracle_run(b, 10))

    def test_steps_with_counts(self, rng):
        b = random_board(rng, 32, 32)
        table = jnp.asarray(CONWAY.table)
        final, counts = steps_with_counts(jnp.asarray(b), table, 8)
        expect = b
        for i in range(8):
            expect = oracle_step(expect)
            assert int(counts[i]) == int((expect == 255).sum()), f"turn {i + 1}"
        np.testing.assert_array_equal(np.asarray(final), expect)

    def test_zero_turns_identity(self, rng):
        b = random_board(rng, 16, 16)
        table = jnp.asarray(CONWAY.table)
        np.testing.assert_array_equal(np.asarray(superstep(jnp.asarray(b), table, 0)), b)


class TestHelpers:
    def test_alive_count(self, rng):
        b = random_board(rng, 33, 65)
        assert int(alive_count(jnp.asarray(b))) == int((b == 255).sum())

    def test_flip_mask(self, rng):
        a = random_board(rng, 16, 16)
        b = random_board(rng, 16, 16)
        got = np.asarray(flip_mask(jnp.asarray(a), jnp.asarray(b)))
        np.testing.assert_array_equal(got, (a != b).astype(np.uint8))


class TestRuleParsing:
    def test_named(self):
        assert parse_rule("conway") is CONWAY
        assert parse_rule("Seeds") is SEEDS

    def test_notation(self):
        r = parse_rule("B36/S23")
        assert r.birth == frozenset({3, 6}) and r.survive == frozenset({2, 3})

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            parse_rule("nope")

    def test_table_shape(self):
        t = CONWAY.table
        assert t.shape == (18,)
        assert t[3] == 255 and t[9 + 2] == 255 and t[9 + 3] == 255
        assert t.sum() == 3 * 255
