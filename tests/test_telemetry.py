"""The continuous telemetry plane suite (ISSUE 12).

Five contracts, asserted hermetically on CPU:

- **Sampler** (`obs/timeseries.py`): the ring is bounded, rates and
  histogram-delta percentiles derive from consecutive samples, the fast
  sampling path never evaluates lazy gauges, and staleness is
  observable.
- **OpenMetrics** (`obs/openmetrics.py`): every snapshot the suite
  produces — synthetic edge cases, a live registry, a real run's
  MetricsReport delta — renders to exposition text that re-parses into
  a schema-valid snapshot with identical values (the round-trip
  property).
- **SLOs** (`obs/slo.py`): burn-rate math over the ring, multi-window
  alert gating, edge-triggered flight records, error budgets.
- **Endpoints** (`serve/telemetry.py` + `tools/pod_top.py`): /metrics,
  /healthz, /slo answer bounded-time from the latest sample; the
  chaos row scrapes a pod with one hang-faulted tenant and one
  mid-supervisor-restart while an injected-latency tenant fires its
  burn-rate alert and healthy budgets stay intact (the ISSUE-12
  acceptance bar).
- **Correlation** (run_id satellite): MetricsReport, flight dumps, and
  checkpoint sidecars of one logical run share one run_id (+ tenant),
  stable across supervisor restarts; `tools/check_metric_docs.py`
  passes on the shipped tree so no metric ships undocumented.
"""

import json
import queue
import time
import urllib.error
import urllib.request
from pathlib import Path

import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.events import MetricsReport
from distributed_gol_tpu.engine.params import Params
from distributed_gol_tpu.engine.session import Session
from distributed_gol_tpu.obs import flight as flight_lib
from distributed_gol_tpu.obs import metrics as obs_metrics
from distributed_gol_tpu.obs import openmetrics
from distributed_gol_tpu.obs.slo import SLOObjectives, SLOTracker
from distributed_gol_tpu.obs.timeseries import (
    TelemetrySampler,
    fraction_above,
    histogram_delta_percentiles,
)
from distributed_gol_tpu.serve import (
    ServeConfig,
    ServePlane,
    serve_plane_telemetry,
)
from distributed_gol_tpu.testing.faults import (
    Fault,
    FaultInjectionBackend,
    FaultPlan,
)

REPO = Path(__file__).resolve().parent.parent

W = H = 16
SUPERSTEP = 4
TURNS = 24


def tenant_params(out_dir, seed, turns=TURNS, **kw):
    cfg = dict(
        engine="roll",
        mesh_shape=(1, 1),
        image_width=W,
        image_height=H,
        superstep=SUPERSTEP,
        turns=turns,
        soup_density=0.25,
        soup_seed=seed,
        out_dir=out_dir,
        cycle_check=0,
        ticker_period=60.0,
    )
    cfg.update(kw)
    return Params(**cfg)


def drain(events, timeout=60):
    """Drain a stream to the sentinel; returns the events seen."""
    seen = []
    while True:
        e = events.get(timeout=timeout)
        if e is None:
            return seen
        seen.append(e)


def _get(url, timeout=5.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


# -- sampler units -------------------------------------------------------------


class TestSampler:
    def _registry_with_counter(self):
        reg = obs_metrics.MetricsRegistry()
        return reg, reg.counter("controller.turns")

    def test_ring_is_bounded(self):
        reg, _ = self._registry_with_counter()
        s = TelemetrySampler(registry=reg, interval=1.0, depth=4)
        for _ in range(10):
            s.sample_now()
        assert len(s.samples()) == 4

    def test_rates_from_consecutive_samples(self):
        reg, turns = self._registry_with_counter()
        s = TelemetrySampler(registry=reg, interval=1.0)
        s.sample_now()
        t0 = s.latest().t
        turns.inc(500)
        s.sample_now()
        # Pin the timestamps so the rate math is exact.
        samples = s.samples()
        samples[0].t = t0
        samples[1].t = t0 + 2.0
        assert s.rate("controller.turns") == pytest.approx(250.0)
        d = s.derived()
        assert d["gens_per_s"] == pytest.approx(250.0)
        assert d["window_seconds"] == pytest.approx(2.0)

    def test_rates_sum_tenant_labels(self):
        reg = obs_metrics.MetricsRegistry()
        a = reg.counter(obs_metrics.labelled("controller.turns", "a"))
        b = reg.counter(obs_metrics.labelled("controller.turns", "b"))
        s = TelemetrySampler(registry=reg, interval=1.0)
        s.sample_now()
        t0 = s.latest().t
        a.inc(30)
        b.inc(70)
        s.sample_now()
        s.samples()[0].t = t0
        s.samples()[1].t = t0 + 1.0
        d = s.derived()
        assert d["gens_per_s"] == pytest.approx(100.0)
        assert d["tenants"]["a"]["gens_per_s"] == pytest.approx(30.0)
        assert d["tenants"]["b"]["gens_per_s"] == pytest.approx(70.0)

    def test_lazy_gauges_only_on_lazy_cadence(self):
        reg = obs_metrics.MetricsRegistry()
        calls = []
        reg.gauge_fn("backend.skip_fraction", lambda: calls.append(1) or 0.5)
        s = TelemetrySampler(registry=reg, interval=1.0, lazy_every=3)
        for _ in range(6):
            s.sample_now()
        # Ticks 3 and 6 are lazy; 1, 2, 4, 5 never touch the callback.
        assert len(calls) == 2
        lazies = [smp.lazy for smp in s.samples()]
        assert lazies == [False, False, True, False, False, True]

    def test_first_tick_never_lazy_even_at_lazy_every_one(self):
        """start()'s synchronous sample must not block pod startup on a
        device-forcing callback — even with lazy_every=1."""
        reg = obs_metrics.MetricsRegistry()
        calls = []
        reg.gauge_fn("backend.skip_fraction", lambda: calls.append(1) or 0.5)
        s = TelemetrySampler(registry=reg, interval=1.0, lazy_every=1)
        s.sample_now()
        assert calls == [] and not s.latest().lazy
        s.sample_now()
        assert calls == [1] and s.latest().lazy

    def test_window_clamps_to_ring(self):
        reg, turns = self._registry_with_counter()
        s = TelemetrySampler(registry=reg, interval=1.0)
        assert s.window(10.0) is None  # one sample: no delta yet
        s.sample_now()
        assert s.window(10.0) is None
        turns.inc(1)
        s.sample_now()
        old, new = s.window(1e-9)  # tighter than any real gap
        assert old is not new  # degrades to the adjacent pair

    def test_histogram_delta_percentiles(self):
        newh = {
            "buckets": [0.01, 0.1, 1.0],
            "counts": [10, 10, 0, 0],
            "sum": 1.0,
            "count": 20,
        }
        oldh = {
            "buckets": [0.01, 0.1, 1.0],
            "counts": [10, 0, 0, 0],
            "sum": 0.05,
            "count": 10,
        }
        # Window delta = 10 observations all in (0.01, 0.1].
        p = histogram_delta_percentiles(newh, oldh)
        assert 0.01 < p["p50"] <= 0.1
        assert 0.01 < p["p99"] <= 0.1
        # Since-start view: half under 0.01, p99 in the second bucket.
        p_all = histogram_delta_percentiles(newh, None)
        assert p_all["p50"] <= 0.01
        assert histogram_delta_percentiles(None, None) is None
        empty = dict(newh, counts=[0, 0, 0, 0], count=0)
        assert histogram_delta_percentiles(empty, None) is None

    def test_fraction_above_is_conservative(self):
        h = {
            "buckets": [0.01, 0.1, 1.0],
            "counts": [5, 5, 0, 0],
            "sum": 0.3,
            "count": 10,
        }
        assert fraction_above(h, None, 0.01) == pytest.approx(0.5)
        # A threshold between bounds rounds DOWN: the whole (0.01, 0.1]
        # bucket counts as violating a 0.05 objective.
        assert fraction_above(h, None, 0.05) == pytest.approx(0.5)
        assert fraction_above(h, None, 1.0) == pytest.approx(0.0)

    def test_staleness_and_daemon(self):
        reg, _ = self._registry_with_counter()
        s = TelemetrySampler(registry=reg, interval=0.05)
        assert s.staleness == float("inf")
        s.start()
        try:
            assert s.latest() is not None  # synchronous first sample
            deadline = time.monotonic() + 5
            while len(s.samples()) < 3 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert len(s.samples()) >= 3  # the daemon is ticking
            assert s.staleness < 1.0
        finally:
            s.stop()
        assert not s.running

    def test_validation(self):
        with pytest.raises(ValueError):
            TelemetrySampler(interval=0.0)
        with pytest.raises(ValueError):
            TelemetrySampler(depth=1)
        with pytest.raises(ValueError):
            TelemetrySampler(lazy_every=0)


# -- OpenMetrics round-trip (property over suite-produced snapshots) -----------


SYNTHETIC_SNAPSHOTS = [
    # empty
    {"schema": "gol-metrics-v1", "counters": {}, "gauges": {},
     "histograms": {}, "info": {}},
    # tenant labels with the full tenant charset, engine names with dashes
    {"schema": "gol-metrics-v1",
     "counters": {"controller.turns": 7,
                  "controller.turns{tenant=a.b-c_D9}": 3,
                  "backend.dispatches.pallas-packed": 2,
                  "faults.backoff_seconds": 1.25},
     "gauges": {"controller.superstep": 64,
                "slo.error_budget_remaining{tenant=x}": 0.875},
     "histograms": {
         "controller.dispatch_seconds": {
             "buckets": [0.001, 0.05, 2.5], "counts": [1, 2, 0, 3],
             "sum": 9.5, "count": 6},
         "controller.dispatch_seconds{tenant=x}": {
             "buckets": [0.5], "counts": [0, 1], "sum": 0.7, "count": 1}},
     "info": {"backend.engine": "pallas-packed",
              "mesh.device_blacklist": "",
              "backend.sharded_tier_policy": 'say "hi"\nnewline\\slash'}},
]


@pytest.mark.parametrize("snap", SYNTHETIC_SNAPSHOTS)
def test_openmetrics_roundtrip_synthetic(snap):
    assert openmetrics.check_roundtrip(snap) == []


def test_openmetrics_roundtrip_live_registry_and_run(tmp_path):
    """The property on REAL snapshots: the process registry (every
    instrument previous tests planted) and a real run's MetricsReport
    delta both round-trip clean."""
    events = queue.Queue()
    gol.run(tenant_params(tmp_path, 3, tenant="alice"), events)
    report = next(e for e in drain(events) if isinstance(e, MetricsReport))
    assert openmetrics.check_roundtrip(report.snapshot) == []
    live = obs_metrics.REGISTRY.snapshot().to_dict()
    assert openmetrics.check_roundtrip(live) == []


def test_openmetrics_renders_tenant_as_real_label():
    text = openmetrics.render(SYNTHETIC_SNAPSHOTS[1])
    assert 'gol_controller_turns_total{tenant="a.b-c_D9"} 3' in text
    assert "gol_controller_turns_total 7" in text
    assert 'le="+Inf"' in text
    assert text.rstrip().endswith("# EOF")


def test_openmetrics_parse_rejects_garbage():
    with pytest.raises(ValueError):
        openmetrics.parse("# TYPE gol_x counter\nnot a sample line at all\n")
    with pytest.raises(ValueError):
        openmetrics.parse("gol_never_declared 1\n")


# -- SLO tracking --------------------------------------------------------------


class _SLORig:
    """A hand-driven sampler + tracker over a private registry."""

    def __init__(self, **kw):
        defaults = dict(
            latency_seconds=0.05,
            fast_window_seconds=10.0,
            slow_window_seconds=30.0,
            burn_threshold=2.0,
            budget_window_seconds=100.0,
        )
        defaults.update(kw)
        self.reg = obs_metrics.MetricsRegistry()
        self.obj = SLOObjectives(**defaults)
        self.flight = flight_lib.FlightRecorder(64)
        self.tracker = SLOTracker(self.obj, self.reg, self.flight)
        self.sampler = TelemetrySampler(
            registry=self.reg, interval=1.0, depth=200
        )
        self.hist = self.reg.histogram(
            obs_metrics.labelled("controller.dispatch_seconds", "t1")
        )
        self.disp = self.reg.counter(
            obs_metrics.labelled("controller.dispatches", "t1")
        )
        self.t = time.time()

    def tick(self, seconds=1.0):
        self.sampler.sample_now()
        self.t += seconds
        self.sampler.latest().t = self.t
        return self.tracker.observe(self.sampler)


class TestSLO:
    def test_objectives_validation(self):
        with pytest.raises(ValueError):
            SLOObjectives(latency_seconds=-1)
        with pytest.raises(ValueError):
            SLOObjectives(latency_percentile=1.5)
        with pytest.raises(ValueError):
            SLOObjectives(fast_window_seconds=60, slow_window_seconds=30)
        assert not SLOObjectives().enabled
        assert SLOObjectives(latency_seconds=0.1).enabled

    def test_burn_alert_fires_and_resolves_edge_triggered(self):
        rig = _SLORig()
        rig.tick()
        # Sustained violation: every dispatch lands above the 0.05 s
        # objective -> bad fraction 1.0, burn 1.0/0.01 = 100x.
        for _ in range(6):
            rig.hist.observe(0.2)
            rig.disp.inc()
            table = rig.tick()
        row = table["t1"]["latency"]
        assert row["alerting"]
        assert row["burn_fast"] > rig.obj.burn_threshold
        alerts = [
            r for r in rig.flight.records() if r["kind"] == "slo_alert"
        ]
        assert len(alerts) == 1  # edge-triggered, not one per tick
        assert alerts[0]["tenant"] == "t1"
        assert alerts[0]["objective"] == "latency"
        # Recovery: fast dispatches until both windows cool off.
        for _ in range(40):
            rig.hist.observe(0.001)
            rig.disp.inc()
            table = rig.tick()
        assert not table["t1"]["latency"]["alerting"]
        kinds = [r["kind"] for r in rig.flight.records()]
        assert "slo_resolved" in kinds
        assert kinds.count("slo_alert") == 1

    def test_one_bad_sample_does_not_page(self):
        """Multi-window gating: a single violating tick inside an
        otherwise healthy slow window must not alert."""
        # p90 objective: the slow-window allowance is 10%, so ONE bad
        # tick in 20+ is well under sustainable pace while the fast
        # window (last tick: 100% bad) burns hard.
        rig = _SLORig(
            fast_window_seconds=1.5,
            slow_window_seconds=30.0,
            latency_percentile=0.9,
        )
        rig.tick()
        for _ in range(20):
            rig.hist.observe(0.001)
            rig.disp.inc()
            rig.tick()
        rig.hist.observe(0.2)
        rig.disp.inc()
        table = rig.tick()
        row = table["t1"]["latency"]
        assert row["burn_fast"] > rig.obj.burn_threshold  # fast window burns
        assert not row["alerting"]  # slow window holds the page back
        assert not any(
            r["kind"] == "slo_alert" for r in rig.flight.records()
        )

    def test_error_budget_remaining(self):
        rig = _SLORig()
        rig.tick()
        # 100-second budget window at a 1% allowance: 4 bad of 8 total
        # with allowance 0.01 -> budget fully burnt (clamped at 0).
        for bad in (True, True, False, False, True, True, False, False):
            rig.hist.observe(0.2 if bad else 0.001)
            rig.disp.inc()
            table = rig.tick()
        assert table["t1"]["latency"]["budget_remaining"] == 0.0
        snap = rig.reg.snapshot().to_dict()
        assert (
            snap["gauges"][
                obs_metrics.labelled("slo.error_budget_remaining", "t1")
            ]
            == 0.0
        )
        # A healthy tenant's budget stays intact.
        rig2 = _SLORig()
        rig2.tick()
        for _ in range(8):
            rig2.hist.observe(0.001)
            rig2.disp.inc()
            table = rig2.tick()
        assert table["t1"]["latency"]["budget_remaining"] == 1.0

    def test_error_rate_objective_reads_failure_counter(self):
        rig = _SLORig(latency_seconds=0.0, error_rate=0.1)
        fails = rig.reg.counter(
            obs_metrics.labelled("controller.dispatch_failures", "t1")
        )
        rig.tick()
        for _ in range(6):
            rig.disp.inc()
            fails.inc()  # 50% failure rate >> the 10% objective
            table = rig.tick()
        row = table["t1"]["errors"]
        assert row["alerting"]
        assert row["burn_fast"] == pytest.approx(5.0)

    def test_evicted_tenant_unlatches_and_reused_name_pages_again(self):
        """A tenant leaving the snapshot (terminal-handle eviction
        cleared its labelled instruments) must not haunt the alert set:
        the latch resolves, and a NEW session under the same name that
        burns again fires a fresh slo_alert."""
        rig = _SLORig()
        rig.tick()
        for _ in range(6):
            rig.hist.observe(0.2)
            rig.disp.inc()
            rig.tick()
        assert ("t1", "latency") in rig.tracker._alerting
        # Eviction: the plane clears the tenant's labelled instruments.
        rig.reg.clear_tenant("t1")
        table = rig.tick()
        assert "t1" not in table
        assert rig.tracker._alerting == set()
        resolved = [
            r for r in rig.flight.records() if r["kind"] == "slo_resolved"
        ]
        assert resolved and resolved[-1]["reason"] == "tenant evicted"
        assert "t1:latency" not in rig.tracker.summary()["alerting"]
        # Reused name burns again: a SECOND alert fires.
        rig.hist = rig.reg.histogram(
            obs_metrics.labelled("controller.dispatch_seconds", "t1")
        )
        rig.disp = rig.reg.counter(
            obs_metrics.labelled("controller.dispatches", "t1")
        )
        for _ in range(6):
            rig.hist.observe(0.2)
            rig.disp.inc()
            rig.tick()
        alerts = [
            r for r in rig.flight.records() if r["kind"] == "slo_alert"
        ]
        assert len(alerts) == 2

    def test_budget_gauge_is_worst_across_objectives(self):
        """With both objectives armed, the single per-tenant budget
        gauge publishes the MINIMUM remaining — a burnt latency budget
        cannot hide behind a clean error budget."""
        rig = _SLORig(error_rate=0.01)
        rig.tick()
        for _ in range(8):
            rig.hist.observe(0.2)  # latency budget burns...
            rig.disp.inc()  # ...while no dispatch ever fails
            table = rig.tick()
        assert table["t1"]["latency"]["budget_remaining"] == 0.0
        assert table["t1"]["errors"]["budget_remaining"] == 1.0
        gauge = rig.reg.snapshot().to_dict()["gauges"][
            obs_metrics.labelled("slo.error_budget_remaining", "t1")
        ]
        assert gauge == 0.0

    def test_serve_config_slo_requires_sampler(self):
        with pytest.raises(ValueError, match="sampler"):
            ServeConfig(slo_latency_seconds=0.1, telemetry_sample_seconds=0.0)
        cfg = ServeConfig(slo_latency_seconds=0.1)
        assert cfg.slo_objectives() is not None
        assert ServeConfig().slo_objectives() is None

    def test_serve_config_slow_window_must_fit_the_ring(self):
        """A ring shorter than the slow window would permanently turn
        the multi-window alert into fast-window-only — refused at
        construction, not silently degraded."""
        with pytest.raises(ValueError, match="slow burn"):
            ServeConfig(
                slo_latency_seconds=0.1,
                telemetry_sample_seconds=0.25,  # span 150 s < slow 300 s
            )
        ServeConfig(
            slo_latency_seconds=0.1,
            telemetry_sample_seconds=0.25,
            slo_slow_window_seconds=100.0,
        )  # shrunk window: fine
        # Unarmed configs never constrain the ring.
        ServeConfig(telemetry_sample_seconds=0.25)


# -- endpoints + dashboard -----------------------------------------------------


class TestEndpoints:
    def test_plane_endpoints_end_to_end(self, tmp_path):
        cfg = ServeConfig(
            max_sessions=2,
            telemetry_sample_seconds=0.1,
            slo_latency_seconds=10.0,  # generous: nothing should alert
            slo_fast_window_seconds=0.5,
            slo_slow_window_seconds=2.0,
        )
        with ServePlane(cfg, checkpoint_root=tmp_path / "ckpt") as plane:
            with serve_plane_telemetry(plane, port=0) as srv:
                plane.submit("alice", tenant_params(tmp_path / "a", 1))
                assert plane.wait_idle(timeout=120)
                status, body = _get(srv.url + "/metrics")
                assert status == 200
                parsed = openmetrics.parse(body.decode())
                assert obs_metrics.check_metrics_snapshot(parsed) == []
                assert "gol_controller_turns_total" in body.decode()
                status, body = _get(srv.url + "/healthz")
                assert status == 200
                hz = json.loads(body)
                assert hz["ready"] and hz["live"]
                assert hz["telemetry"]["sampling"]
                assert hz["tenants"]["alice"]["turns"] == TURNS
                assert hz["slo"] is not None
                status, body = _get(srv.url + "/slo")
                assert status == 200
                slo = json.loads(body)
                assert slo["alerting"] == []
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _get(srv.url + "/nope")
                assert ei.value.code == 404

    def test_healthz_503_when_not_ready(self, tmp_path):
        with ServePlane(
            ServeConfig(max_sessions=1, telemetry_sample_seconds=0.2),
        ) as plane:
            with serve_plane_telemetry(plane, port=0) as srv:
                plane.begin_drain()
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _get(srv.url + "/healthz")
                assert ei.value.code == 503
                body = json.loads(ei.value.read())
                assert body["draining"] is True  # the body still reports

    def test_slo_404_without_objectives(self, tmp_path):
        with ServePlane(
            ServeConfig(telemetry_sample_seconds=0.2)
        ) as plane:
            with serve_plane_telemetry(plane, port=0) as srv:
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _get(srv.url + "/slo")
                assert ei.value.code == 404

    def test_gol_run_telemetry_port(self, tmp_path):
        """The single-run spelling: gol.run(..., telemetry_port=0) — the
        endpoints live for the run's duration, discoverable via the
        ``telemetry.endpoint`` info label."""
        from distributed_gol_tpu.engine.gol import start

        events = queue.Queue()
        keys = queue.Queue()
        before = (
            obs_metrics.REGISTRY.snapshot()
            .to_dict()["info"]
            .get("telemetry.endpoint")
        )
        params = tenant_params(
            tmp_path, 5, turns=100_000, telemetry_sample_seconds=0.05
        )
        t = start(params, events, keys, Session(), telemetry_port=0)
        base = None
        deadline = time.monotonic() + 60
        while base is None and time.monotonic() < deadline:
            info = obs_metrics.REGISTRY.snapshot().to_dict()["info"]
            url = info.get("telemetry.endpoint")
            if url and url != before:
                base = url
            else:
                time.sleep(0.05)
        assert base is not None, "run never published its endpoint"
        status, body = _get(base + "/healthz", timeout=10)
        assert status == 200
        hz = json.loads(body)
        assert hz["live"] and hz["sampling"]
        status, body = _get(base + "/metrics", timeout=10)
        assert status == 200
        parsed = openmetrics.parse(body.decode())
        assert obs_metrics.check_metrics_snapshot(parsed) == []
        keys.put("q")
        drain(events, timeout=120)
        t.join(timeout=30)
        # Run over: the server is down and the sampler stopped.
        with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
            _get(base + "/healthz", timeout=2)

    def test_pod_top_renders_frames(self):
        from tools import pod_top

        health = {
            "ready": True,
            "live": True,
            "draining": False,
            "degraded": False,
            "resident_sessions": 2,
            "queued_sessions": 1,
            "resident_cells": 512,
            "watchdog_fires": 1,
            "supervisor_restarts": 2,
            "rejected": 3,
            "slo_alerts": 1,
            "telemetry": {"sampling": True, "sample_age_seconds": 0.4},
            "tenants": {
                "alice": {"status": "running", "dispatches": 10, "turns": 40},
                "bob": {"status": "parked", "dispatches": 5, "turns": 20},
            },
        }
        slo = {
            "alerting": ["alice:latency"],
            "tenants": {
                "alice": {
                    "resolve_latency": {"p50": 0.01, "p95": 0.2, "p99": 0.4},
                    "latency": {
                        "burn_fast": 12.0,
                        "burn_slow": 5.0,
                        "alerting": True,
                        "budget_remaining": 0.25,
                    },
                }
            },
        }
        prev = {
            "t": 100.0,
            "health": {
                "tenants": {
                    "alice": {"status": "running", "dispatches": 5,
                              "turns": 20},
                    "bob": {"status": "running", "dispatches": 5,
                            "turns": 20},
                }
            },
        }
        cur = {"t": 102.0, "health": health, "slo": slo}
        frame = pod_top.render_frame(cur, prev)
        assert "alice" in frame and "bob" in frame
        assert "ALERTING: alice:latency" in frame
        assert "10" in frame  # alice gens/s: (40-20)/2
        assert "400ms" in frame  # alice p99
        assert "lat:25%@12.0x!" in frame  # budget cell with alert marker
        assert "restarts 2" in frame
        # First frame (no prev): rates dash out, nothing crashes.
        first = pod_top.render_frame(cur, None)
        assert "-" in first

    def test_pod_top_scrapes_a_real_pod(self, tmp_path):
        from tools import pod_top

        with ServePlane(
            ServeConfig(max_sessions=2, telemetry_sample_seconds=0.1)
        ) as plane:
            with serve_plane_telemetry(plane, port=0) as srv:
                plane.submit("alice", tenant_params(tmp_path / "a", 1))
                assert plane.wait_idle(timeout=120)
                cur = pod_top.scrape(srv.url)
                frame = pod_top.render_frame(cur)
                assert "alice" in frame
                assert "completed" in frame


# -- correlation ids (run_id satellite) ----------------------------------------


class TestRunIdCorrelation:
    def test_clean_run_report_carries_run_id_and_tenant(self, tmp_path):
        events = queue.Queue()
        gol.run(tenant_params(tmp_path, 2, tenant="alice"), events)
        report = next(
            e for e in drain(events) if isinstance(e, MetricsReport)
        )
        assert report.tenant == "alice"
        assert report.run_id.startswith("alice-")
        # And a second run mints a distinct id.
        events = queue.Queue()
        gol.run(tenant_params(tmp_path / "b", 2, tenant="alice"), events)
        report2 = next(
            e for e in drain(events) if isinstance(e, MetricsReport)
        )
        assert report2.run_id != report.run_id

    def test_flight_dump_and_sidecar_share_the_run_id(self, tmp_path):
        """A crashed run's three artifacts — flight record, periodic
        checkpoint sidecar, (absent) report — join on one id."""
        params = tenant_params(
            tmp_path / "out",
            7,
            tenant="alice",
            retry_limit=0,
            checkpoint_every_turns=SUPERSTEP,
        )
        backend = FaultInjectionBackend(
            Backend(params), FaultPlan([Fault(3, "issue")])
        )
        session = Session(tmp_path / "ckpt")
        events = queue.Queue()
        with pytest.raises(RuntimeError):
            gol.run(params, events, session=session, backend=backend)
        drain(events)
        flight_path = flight_lib.latest_flight_record(tmp_path / "ckpt")
        assert flight_path is not None
        doc = flight_lib.load_flight_record(flight_path)
        assert doc["tenant"] == "alice"
        run_id = doc["run_id"]
        assert run_id.startswith("alice-")
        sidecars = [
            json.loads(p.read_text())
            for p in (tmp_path / "ckpt").glob("checkpoint-*.json")
        ]
        assert sidecars, "periodic checkpoint expected before the crash"
        assert all(m["run_id"] == run_id for m in sidecars)
        assert all(m["tenant"] == "alice" for m in sidecars)
        # tools/flight_report.py prints the stamp.
        from tools import flight_report

        rendered = flight_report.render(doc)
        assert f"run_id {run_id}" in rendered
        assert "tenant alice" in rendered

    def test_run_id_stable_across_supervisor_restarts(self, tmp_path):
        """One logical run = one id: the recovered run's report and the
        mid-run sidecars written by DIFFERENT attempts all agree."""
        params = tenant_params(
            tmp_path / "out",
            9,
            tenant="bob",
            retry_limit=0,
            checkpoint_every_turns=SUPERSTEP,
            restart_limit=2,
        )
        plan = FaultPlan([Fault(2, "issue")])

        def factory(p, attempt):
            b = Backend(p)
            return FaultInjectionBackend(b, plan) if attempt == 0 else b

        session = Session(tmp_path / "ckpt")
        events = queue.Queue()
        gol.run(params, events, session=session, backend_factory=factory)
        report = next(
            e for e in drain(events) if isinstance(e, MetricsReport)
        )
        assert report.snapshot["counters"]["supervisor.restarts"] == 1
        assert report.run_id.startswith("bob-")
        # The recovered run completed: no flight record (PR-4 contract),
        # and the run_id on the report is the supervisor's single id.
        assert flight_lib.latest_flight_record(tmp_path / "ckpt") is None


# -- docs gate (static-analysis satellite) -------------------------------------


def test_metric_docs_are_complete():
    """tools/check_metric_docs.py passes on the shipped tree: every
    registered instrument has a docs/API.md row and vice versa."""
    from tools import check_metric_docs

    assert check_metric_docs.check(REPO) == []


def test_metric_docs_checker_catches_drift(tmp_path):
    """The checker is a real gate: an undocumented registration and a
    stale docs row both fail."""
    from tools import check_metric_docs

    pkg = tmp_path / "distributed_gol_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        'REG.counter("shiny.new_metric")\n'
        'REG.counter(f"dyn.family.{kind}")\n'
    )
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "API.md").write_text(
        "| Metric | Kind | Meaning |\n"
        "|---|---|---|\n"
        "| `shiny.new_metric` | counter | Documented. |\n"
        "| `stale.never_registered` | counter | Gone. |\n"
    )
    problems = check_metric_docs.check(tmp_path)
    assert any("dyn.family." in p for p in problems)
    assert any("stale.never_registered" in p for p in problems)
    # Fix both: clean.
    (docs / "API.md").write_text(
        "| Metric | Kind | Meaning |\n"
        "|---|---|---|\n"
        "| `shiny.new_metric` | counter | Documented. |\n"
        "| `dyn.family.<kind>` | counter | Documented family. |\n"
    )
    assert check_metric_docs.check(tmp_path) == []


# -- the chaos row (ISSUE-12 acceptance) ---------------------------------------


@pytest.mark.chaos
class TestScrapeUnderChaos:
    SCRAPE_BOUND_S = 2.0

    def test_scrape_bounded_and_truthful_under_hang_restart_and_slo_burn(
        self, tmp_path
    ):
        """THE acceptance row: one tenant hang-faulted, one supervisor-
        restarting, one burning its latency SLO, one healthy.  Every
        /metrics + /healthz scrape during the storm answers within the
        bound; the SLO alert fires (flight record + health slo section)
        for the lagging tenant while the healthy tenant's budget stays
        intact; final statuses are truthful per tenant."""
        cfg = ServeConfig(
            max_sessions=4,
            telemetry_sample_seconds=0.1,
            slo_latency_seconds=0.05,
            slo_fast_window_seconds=0.4,
            slo_slow_window_seconds=1.2,
            slo_burn_threshold=2.0,
            slo_budget_window_seconds=30.0,
        )
        # Hang tenant: wedged dispatch, bounded by ITS OWN watchdog.
        hang_params = tenant_params(
            tmp_path / "hang", 31, dispatch_deadline_seconds=3.0
        )
        hang_backend = FaultInjectionBackend(
            Backend(hang_params), FaultPlan([Fault(1, "hang", seconds=60.0)])
        )
        # Restart tenant: terminal burst at dispatch 2, self-heals via
        # its own supervisor ladder.
        restart_params = tenant_params(
            tmp_path / "restart",
            32,
            retry_limit=0,
            checkpoint_every_turns=SUPERSTEP,
            restart_limit=2,
        )
        restart_plan = FaultPlan([Fault(2, "issue")])

        def restart_factory(p, attempt):
            b = Backend(p)
            return (
                FaultInjectionBackend(b, restart_plan) if attempt == 0 else b
            )

        # Lag tenant: every dispatch +0.15 s -> p99 far over the 50 ms
        # objective -> burn ~100x over both windows.
        lag_params = tenant_params(tmp_path / "lag", 33, turns=120)
        lag_backend = FaultInjectionBackend(
            Backend(lag_params),
            FaultPlan(
                [Fault(i, "latency", seconds=0.15) for i in range(40)]
            ),
        )
        try:
            with ServePlane(cfg, checkpoint_root=tmp_path / "ckpt") as plane:
                with serve_plane_telemetry(plane, port=0) as srv:
                    healthy = plane.submit(
                        "healthy", tenant_params(tmp_path / "healthy", 34)
                    )
                    hang = plane.submit(
                        "hang", hang_params, backend=hang_backend
                    )
                    restart = plane.submit(
                        "restart",
                        restart_params,
                        backend_factory=restart_factory,
                    )
                    lag = plane.submit("lag", lag_params, backend=lag_backend)

                    # Scrape THROUGH the storm: while the hang tenant is
                    # wedged and the restart tenant recovers, every
                    # response lands within the bound.
                    scrape_times = []
                    deadline = time.monotonic() + 120
                    while time.monotonic() < deadline:
                        t0 = time.monotonic()
                        s1, _ = _get(srv.url + "/metrics", timeout=10)
                        try:
                            s2, hz_body = _get(srv.url + "/healthz",
                                               timeout=10)
                        except urllib.error.HTTPError as e:
                            s2, hz_body = e.code, e.read()
                        scrape_times.append(time.monotonic() - t0)
                        assert s1 == 200
                        assert s2 in (200, 503)
                        if all(
                            h.done for h in (healthy, hang, restart, lag)
                        ):
                            break
                        time.sleep(0.1)
                    assert plane.wait_idle(timeout=60)
                    assert scrape_times, "no scrape completed"
                    worst = max(scrape_times)
                    assert worst < self.SCRAPE_BOUND_S, (
                        f"scrape took {worst:.2f}s with a wedged tenant "
                        f"resident (bound {self.SCRAPE_BOUND_S}s over "
                        f"{len(scrape_times)} scrapes)"
                    )

                    # Truthful per-tenant terminal statuses on /healthz.
                    _, hz_body = _get(srv.url + "/healthz", timeout=10)
                    hz = json.loads(hz_body)
                    statuses = {
                        t: row["status"] for t, row in hz["tenants"].items()
                    }
                    assert statuses["healthy"] == "completed"
                    assert statuses["restart"] == "completed"
                    assert statuses["hang"] == "parked"
                    assert statuses["lag"] == "completed"
                    assert hz["watchdog_fires"] >= 1
                    assert hz["supervisor_restarts"] == 1
                    assert "DispatchTimeout" in hang.error

                    # The SLO row: the lag tenant fired its burn-rate
                    # alert — flight record + health slo section — and
                    # the healthy tenant's budget is intact.
                    alerts = [
                        r
                        for r in plane.flight.records()
                        if r["kind"] == "slo_alert"
                    ]
                    assert any(a["tenant"] == "lag" for a in alerts), (
                        f"lag tenant never alerted; ring="
                        f"{plane.flight.records()}"
                    )
                    assert not any(
                        a["tenant"] == "healthy" for a in alerts
                    )
                    assert hz["slo_alerts"] >= 1
                    slo = hz["slo"]
                    lag_row = slo["tenants"]["lag"]["latency"]
                    assert lag_row["budget_remaining"] < 1.0
                    healthy_row = slo["tenants"].get("healthy", {}).get(
                        "latency"
                    )
                    if healthy_row is not None:
                        assert healthy_row["budget_remaining"] == 1.0
                        assert not healthy_row["alerting"]
        finally:
            hang_backend.release_hangs()
