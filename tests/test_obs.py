"""The run-telemetry subsystem (ISSUE 4): metrics registry, annotated
spans, flight recorder — unit contracts plus the controller integration
(MetricsReport emission, clean runs leaving no flight record).

The snapshot-schema rejection tests mirror ``tests/test_measure.py``'s
malformed-record shape tests: the lint is the artifact contract, so a
snapshot that drifts must FAIL the lint, not slide into a published
record.
"""

import json
import queue
import tempfile
from pathlib import Path

import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.session import Session
from distributed_gol_tpu.obs import flight as flight_lib
from distributed_gol_tpu.obs import metrics as m
from distributed_gol_tpu.obs import spans


class TestRegistry:
    def test_counter_gauge_histogram_roundtrip(self):
        reg = m.MetricsRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(3)
        reg.gauge("g").set(2.5)
        h = reg.histogram("h", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(99.0)
        reg.info("i", "label")
        snap = reg.snapshot().to_dict()
        assert m.check_metrics_snapshot(snap) == []
        assert snap["counters"]["c"] == 4
        assert snap["gauges"]["g"] == 2.5
        assert snap["histograms"]["h"]["counts"] == [1, 1, 1]
        assert snap["histograms"]["h"]["count"] == 3
        assert snap["info"]["i"] == "label"

    def test_same_name_same_instrument(self):
        reg = m.MetricsRegistry()
        assert reg.counter("x") is reg.counter("x")
        assert reg.gauge("y") is reg.gauge("y")

    def test_unset_gauge_omitted(self):
        reg = m.MetricsRegistry()
        reg.gauge("never")
        assert "never" not in reg.snapshot().to_dict()["gauges"]

    def test_gauge_fn_evaluated_at_snapshot_only(self):
        reg = m.MetricsRegistry()
        calls = []
        reg.gauge_fn("lazy", lambda: calls.append(1) or 7.0)
        assert calls == []
        assert reg.snapshot().to_dict()["gauges"]["lazy"] == 7.0
        assert calls == [1]

    def test_snapshot_without_lazy_skips_callbacks(self):
        """The abort-path contract (review finding): a flight-dump
        snapshot must not invoke callback gauges — they force on-device
        values, and the wedged device being documented would hang the
        abort forever."""
        reg = m.MetricsRegistry()
        reg.counter("c").inc()
        calls = []
        reg.gauge_fn("device.bound", lambda: calls.append(1) or 1.0)
        snap = reg.snapshot(include_lazy=False).to_dict()
        assert calls == []
        assert "device.bound" not in snap["gauges"]
        assert snap["counters"]["c"] == 1  # instruments still copied

    def test_gauge_fn_none_and_raising_are_omitted(self):
        reg = m.MetricsRegistry()
        reg.gauge_fn("none", lambda: None)
        reg.gauge_fn("boom", lambda: 1 / 0)
        snap = reg.snapshot().to_dict()
        assert "none" not in snap["gauges"] and "boom" not in snap["gauges"]
        assert m.check_metrics_snapshot(snap) == []

    def test_null_registry_is_inert(self):
        reg = m.NULL
        reg.counter("c").inc()
        reg.gauge("g").set(1)
        reg.histogram("h").observe(2)
        reg.info("i", "x")
        reg.gauge_fn("f", lambda: 1.0)
        snap = reg.snapshot().to_dict()
        assert m.check_metrics_snapshot(snap) == []
        assert not snap["counters"] and not snap["gauges"]
        assert m.registry_for(False) is m.NULL
        assert m.registry_for(True) is m.REGISTRY

    def test_delta_subtracts_counters_and_histograms(self):
        reg = m.MetricsRegistry()
        c = reg.counter("c")
        h = reg.histogram("h", buckets=(1.0,))
        c.inc(5)
        h.observe(0.5)
        start = reg.snapshot()
        c.inc(2)
        h.observe(2.0)
        delta = reg.snapshot().delta(start).to_dict()
        assert m.check_metrics_snapshot(delta) == []
        assert delta["counters"]["c"] == 2
        assert delta["histograms"]["h"]["counts"] == [0, 1]
        assert delta["histograms"]["h"]["count"] == 1

    def test_aggregate_sums_counters_maxes_gauges(self):
        a = {
            "schema": m.SCHEMA,
            "counters": {"c": 1},
            "gauges": {"g": 3.0},
            "histograms": {
                "h": {"buckets": [1.0], "counts": [1, 0], "sum": 0.5, "count": 1}
            },
            "info": {"i": "a"},
        }
        b = {
            "schema": m.SCHEMA,
            "counters": {"c": 2, "d": 1},
            "gauges": {"g": 1.0},
            "histograms": {
                "h": {"buckets": [1.0], "counts": [0, 2], "sum": 4.0, "count": 2}
            },
            "info": {"i": "b"},
        }
        agg = m.aggregate_snapshots([a, b])
        assert m.check_metrics_snapshot(agg) == []
        assert agg["counters"] == {"c": 3, "d": 1}
        assert agg["gauges"]["g"] == 3.0
        assert agg["histograms"]["h"]["counts"] == [1, 2]
        assert agg["histograms"]["h"]["count"] == 3
        assert agg["info"]["i"] == "a"  # first process wins

    def test_clear_labels_drops_prefixed_gauges_only(self):
        reg = m.MetricsRegistry()
        reg.counter("backend.dispatches.roll").inc()
        reg.gauge("backend.skip_fraction").set(0.9)
        reg.gauge_fn("backend.megakernel_cache_hits", lambda: 1.0)
        reg.info("backend.sharded_tier", "ici-megakernel")
        reg.gauge("controller.superstep").set(50)
        reg.clear_labels("backend.")
        snap = reg.snapshot().to_dict()
        assert "backend.skip_fraction" not in snap["gauges"]
        assert "backend.megakernel_cache_hits" not in snap["gauges"]
        assert snap["info"] == {}
        # Counters are cumulative and survive; other prefixes untouched.
        assert snap["counters"]["backend.dispatches.roll"] == 1
        assert snap["gauges"]["controller.superstep"] == 50

    def test_new_backend_clears_previous_runs_labels(self, tmp_path):
        """A run must not inherit a previous backend's tier label or skip
        fraction in its own snapshots (review finding): constructing a
        plain-engine Backend after an adaptive sharded one leaves no
        stale backend.* gauges/info behind."""
        from distributed_gol_tpu.engine.backend import Backend

        adaptive = gol.Params(
            image_width=128,
            image_height=64,
            engine="pallas-packed",
            mesh_shape=(2, 1),
            skip_stable=True,
            superstep=6,
        )
        Backend(adaptive)
        snap = m.REGISTRY.snapshot().to_dict()
        assert snap["info"]["backend.sharded_tier"]
        # The callback is registered (it reports None = omitted until
        # enough dispatches have run; membership is what matters here).
        assert "backend.skip_fraction" in m.REGISTRY._gauge_fns
        Backend(gol.Params(image_width=16, image_height=16, engine="roll"))
        snap = m.REGISTRY.snapshot().to_dict()
        assert "backend.sharded_tier" not in snap["info"]
        assert "backend.skip_fraction" not in m.REGISTRY._gauge_fns
        assert "backend.skip_fraction" not in snap["gauges"]
        assert snap["info"]["backend.engine"] == "roll"
        # And a metrics=OFF backend still clears the REAL registry — the
        # clear must not be gated on this run's metrics flag (review
        # finding: the stale callbacks would pin the old Backend alive).
        Backend(adaptive)
        assert "backend.skip_fraction" in m.REGISTRY._gauge_fns
        from dataclasses import replace

        Backend(
            replace(
                gol.Params(image_width=16, image_height=16, engine="roll"),
                metrics=False,
            )
        )
        assert "backend.skip_fraction" not in m.REGISTRY._gauge_fns
        assert "backend.sharded_tier" not in m.REGISTRY.snapshot().to_dict()["info"]

    def test_to_json_roundtrip(self):
        reg = m.MetricsRegistry()
        reg.counter("c").inc()
        snap = m.MetricsSnapshot.from_json(reg.snapshot().to_json())
        assert snap.to_dict()["counters"]["c"] == 1


class TestSnapshotSchemaLint:
    """Malformed-snapshot rejection — the test_measure.py bare-value shape
    test, transplanted to the metrics schema."""

    def good(self):
        return {
            "schema": m.SCHEMA,
            "counters": {"c": 1},
            "gauges": {"g": 0.5},
            "histograms": {
                "h": {"buckets": [1.0], "counts": [1, 0], "sum": 0.1, "count": 1}
            },
            "info": {"i": "x"},
        }

    def test_good_snapshot_is_clean(self):
        assert m.check_metrics_snapshot(self.good()) == []

    @pytest.mark.parametrize(
        "mutate, fragment",
        [
            (lambda s: s.update(schema="bogus"), "schema"),
            (lambda s: s["counters"].update(c=-1), "counters.c"),
            (lambda s: s["counters"].update(c=float("nan")), "counters.c"),
            (lambda s: s["gauges"].update(g="fast"), "gauges.g"),
            (lambda s: s["histograms"]["h"].update(counts=[1]), "counts"),
            (lambda s: s["histograms"]["h"].update(count=99), "count"),
            (lambda s: s["histograms"]["h"].update(buckets=[2.0, 1.0]),
             "increasing"),
            (lambda s: s["info"].update(i=3), "info.i"),
        ],
    )
    def test_malformed_snapshots_rejected(self, mutate, fragment):
        snap = self.good()
        mutate(snap)
        problems = m.check_metrics_snapshot(snap)
        assert problems and any(fragment in p for p in problems), problems
        with pytest.raises(m.MalformedSnapshot):
            m.require_metrics_snapshot(snap)

    def test_not_a_dict_rejected(self):
        assert m.check_metrics_snapshot(42)

    @pytest.mark.parametrize("section", ["counters", "gauges", "histograms", "info"])
    def test_non_dict_section_is_a_violation_not_a_crash(self, section):
        """A corrupted on-disk snapshot (flight record, sidecar) whose
        section is a list/string must lint as a violation — the loader
        path must raise MalformedFlightRecord/MalformedSnapshot, never an
        AttributeError out of the lint itself (review finding)."""
        snap = self.good()
        snap[section] = [1, 2]
        problems = m.check_metrics_snapshot(snap)
        assert problems and any(section in p for p in problems), problems

    def test_embedded_walk_finds_nested_snapshots(self):
        record = {"rows": [{"metrics": self.good()}]}
        assert m.check_embedded_metrics(record) == []
        record["rows"][0]["metrics"]["counters"]["c"] = -5
        with pytest.raises(m.MalformedSnapshot):
            m.require_embedded_metrics(record)


class TestSpans:
    def test_span_and_step_span_enter_exit(self):
        # With jax importable these are real TraceAnnotations; either way
        # they must behave as context managers and never raise.
        with spans.span("gol.test", turn=3, tier="roll"):
            pass
        with spans.step_span("gol.test.step", 7, k=50):
            pass

    def test_span_degrades_to_noop_without_profiler(self, monkeypatch):
        monkeypatch.setattr(spans, "_TRACE_CLS", None)
        monkeypatch.setattr(spans, "_STEP_CLS", None)
        with spans.span("gol.test"):
            pass
        with spans.step_span("gol.test", 1):
            pass


class TestFlightRecorder:
    def test_ring_is_bounded_and_ordered(self):
        fr = flight_lib.FlightRecorder(depth=3)
        for i in range(10):
            fr.record("dispatch", turn=i)
        recs = fr.records()
        assert [r["turn"] for r in recs] == [7, 8, 9]
        assert all(r["kind"] == "dispatch" and r["t"] > 0 for r in recs)

    def test_depth_zero_disables(self, tmp_path):
        fr = flight_lib.FlightRecorder(depth=0)
        fr.record("dispatch", turn=1)
        assert fr.records() == []
        assert fr.dump(tmp_path, cause="X") is None
        assert not list(tmp_path.glob("flight-*.json"))

    def test_dump_appends_abort_tail_and_parses(self, tmp_path):
        fr = flight_lib.FlightRecorder(depth=8)
        fr.record("dispatch", turn=4, k=4, s=0.01)
        path = fr.dump(tmp_path, cause="RuntimeError", error="boom", turn=4)
        assert path is not None and path.name.startswith("flight-")
        doc = flight_lib.load_flight_record(path)
        assert doc["cause"] == "RuntimeError" and doc["turn"] == 4
        assert doc["records"][-1]["kind"] == "abort"
        assert doc["records"][-1]["cause"] == "RuntimeError"

    def test_malformed_flight_records_rejected(self, tmp_path):
        good = {
            "schema": flight_lib.SCHEMA,
            "cause": "X",
            "error": "",
            "turn": 0,
            "written_at": 0.0,
            "records": [{"kind": "abort", "t": 1.0, "cause": "X"}],
        }
        assert flight_lib.check_flight_record(good) == []
        for mutate in (
            lambda d: d.update(schema="nope"),
            lambda d: d.update(cause=""),
            lambda d: d.update(turn="four"),
            lambda d: d.update(records=[]),
            lambda d: d.update(records=[{"kind": "dispatch", "t": 1.0}]),
            lambda d: d.update(records=[{"t": 1.0}]),
        ):
            bad = json.loads(json.dumps(good))
            mutate(bad)
            assert flight_lib.check_flight_record(bad), mutate
        p = tmp_path / "flight-1.json"
        p.write_text(json.dumps({"schema": "nope"}))
        with pytest.raises(flight_lib.MalformedFlightRecord):
            flight_lib.load_flight_record(p)

    def test_flight_report_renders(self, tmp_path, capsys):
        from tools import flight_report

        fr = flight_lib.FlightRecorder(depth=8)
        fr.record("dispatch", turn=4, k=4, s=0.01)
        fr.record("retry", turn=4, attempt=1, cause="RuntimeError")
        fr.dump(
            tmp_path,
            cause="DispatchTimeout",
            error="wedged",
            turn=4,
            metrics={
                "schema": m.SCHEMA,
                "counters": {"faults.retries": 1},
                "gauges": {},
                "histograms": {},
                "info": {"backend.engine": "roll"},
            },
        )
        assert flight_report.main([str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "cause: DispatchTimeout at turn 4" in out
        assert "retry" in out and "faults.retries = 1" in out

    def test_flight_report_empty_dir_fails_cleanly(self, tmp_path):
        from tools import flight_report

        assert flight_report.main([str(tmp_path)]) == 1


def _run(params, session=None):
    ev = queue.Queue()
    gol.run(params, ev, session=session if session is not None else Session())
    out = []
    while (e := ev.get(timeout=60)) is not None:
        out.append(e)
    return out


def _params(out_dir, **kw):
    base = dict(
        turns=20,
        image_width=16,
        image_height=16,
        soup_density=0.3,
        soup_seed=3,
        out_dir=out_dir,
        superstep=4,
        cycle_check=0,
        ticker_period=60.0,
    )
    base.update(kw)
    return gol.Params(**base)


class TestControllerIntegration:
    def test_metrics_report_emitted_with_run_delta(self, tmp_path):
        stream = _run(_params(tmp_path))
        reports = [e for e in stream if isinstance(e, gol.MetricsReport)]
        assert len(reports) == 1
        snap = reports[0].snapshot
        assert m.check_metrics_snapshot(snap) == []
        # The per-run DELTA: exactly this run's 5 dispatches of 4 turns,
        # not the process-wide totals.
        assert snap["counters"]["controller.dispatches"] == 5
        assert snap["counters"]["controller.turns"] == 20
        assert snap["histograms"]["controller.dispatch_seconds"]["count"] == 5
        assert snap["counters"]["backend.dispatches.roll"] == 5
        assert snap["info"]["backend.engine"] == "roll"
        assert reports[0].processes == 1
        # MetricsReport precedes FinalTurnComplete (terminal rollup).
        kinds = [type(e).__name__ for e in stream]
        assert kinds.index("MetricsReport") < kinds.index("FinalTurnComplete")

    def test_metrics_off_suppresses_report(self, tmp_path):
        stream = _run(_params(tmp_path, metrics=False))
        assert not [e for e in stream if isinstance(e, gol.MetricsReport)]

    def test_clean_run_leaves_no_flight_record(self, tmp_path):
        _run(_params(tmp_path))
        assert not list(Path(tmp_path).glob("flight-*.json"))

    def test_timing_events_identical_from_unified_helper(self, tmp_path):
        """The two old hand-rolled TurnTiming sites are one helper now;
        both the sync viewer path and the pipelined headless path must
        still produce the exact per-dispatch stream."""
        headless = _run(_params(tmp_path, emit_timing=True))
        timings = [e for e in headless if isinstance(e, gol.TurnTiming)]
        assert [t.turns for t in timings] == [4] * 5
        assert [t.completed_turns for t in timings] == [4, 8, 12, 16, 20]
        viewer = _run(
            _params(
                tmp_path, emit_timing=True, no_vis=False, flip_events="batch"
            )
        )
        vtimings = [e for e in viewer if isinstance(e, gol.TurnTiming)]
        assert [t.completed_turns for t in vtimings] == list(range(1, 21))
        assert all(t.seconds > 0 for t in timings + vtimings)

    def test_sidecar_embeds_metrics_snapshot(self, tmp_path):
        ckpt = tmp_path / "ckpt"
        session = Session(ckpt)
        _run(
            _params(
                tmp_path,
                turns=8,
                checkpoint_every_turns=4,
                checkpoint_keep=2,
            ),
            session=session,
        )
        # The completed run discards its periodic pairs; write one more
        # directly to inspect the sidecar contract.
        import numpy as np

        session.save_checkpoint(
            np.zeros((16, 16), np.uint8),
            4,
            rule="B3/S23",
            metrics=m.REGISTRY.snapshot().to_dict(),
        )
        meta = json.loads((ckpt / "checkpoint-000000000004.json").read_text())
        assert m.check_metrics_snapshot(meta["metrics"]) == []

    def test_gather_snapshots_seam_single_process(self, tmp_path):
        """The multihost aggregation transport, exercised at world size 1:
        gather returns this process's snapshot and the aggregate equals
        it (the real multi-process path rides the same collectives in
        tests/test_multihost.py's worker)."""
        from distributed_gol_tpu.parallel.multihost import (
            gather_metrics_snapshots,
        )

        snap = {
            "schema": m.SCHEMA,
            "counters": {"c": 2},
            "gauges": {},
            "histograms": {},
            "info": {},
        }
        got = gather_metrics_snapshots(snap)
        assert got == [snap]
        assert m.aggregate_snapshots(got)["counters"]["c"] == 2


class TestCliFlags:
    def test_observability_flags_map_to_params(self):
        from distributed_gol_tpu.__main__ import build_parser, params_from_args

        args = build_parser().parse_args(
            ["-noVis", "--no-metrics", "--flight-recorder-depth", "7"]
        )
        p = params_from_args(args)
        assert p.metrics is False
        assert p.flight_recorder_depth == 7
        # And the defaults: always-on metrics, 256-deep recorder.
        p = params_from_args(build_parser().parse_args(["-noVis"]))
        assert p.metrics is True
        assert p.flight_recorder_depth == 256

    def test_flight_depth_validated(self):
        with pytest.raises(ValueError):
            gol.Params(flight_recorder_depth=-1)


def test_metrics_registry_clean_path_is_plain_attributes():
    """The overhead contract, structurally: a counter bump is one
    attribute add on a pre-resolved object (no locks, no dict lookups) —
    pin the shape so a 'helpful' refactor can't sneak a lock onto the
    dispatch path.  (The rate-level check is
    tests/test_bench_pilot.py::test_metrics_overhead_within_rep_spread.)"""
    assert m.Counter.__slots__ == ("value",)
    assert m.Gauge.__slots__ == ("value",)
    assert "buckets" in m.Histogram.__slots__
    assert not hasattr(m.Counter(), "__dict__")
