"""Golden-oracle lock-in: the kernel vs the reference's check/ data.

These are the reference's own correctness baselines (SURVEY.md §6, BASELINE.md):
golden boards {16², 64², 512²} × {0, 1, 100} turns (check/images/*.pgm,
gol_test.go:24-28) and the 10k-turn alive-count series (check/alive/*.csv,
count_test.go) including the 512² period-2 steady state (5565 even / 5567 odd).
"""

import csv

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_gol_tpu.engine.pgm import read_pgm
from distributed_gol_tpu.models.life import CONWAY
from distributed_gol_tpu.ops.stencil import steps_with_counts, superstep
from distributed_gol_tpu.utils.visualise import boards_to_string

TABLE = jnp.asarray(CONWAY.table)

SIZES = [16, 64, 512]
TURNS = [0, 1, 100]


def read_alive_csv(path):
    with open(path) as f:
        rows = list(csv.reader(f))
    assert rows[0] == ["completed_turns", "alive_cells"]
    return {int(t): int(c) for t, c in rows[1:]}


@pytest.mark.parametrize("size", SIZES)
@pytest.mark.parametrize("turns", TURNS)
def test_golden_boards(input_images, golden_images, size, turns):
    start = read_pgm(input_images / f"{size}x{size}.pgm")
    expected = read_pgm(golden_images / f"{size}x{size}x{turns}.pgm")
    got = np.asarray(superstep(jnp.asarray(start), TABLE, turns))
    if size == 16 and not np.array_equal(got, expected):
        pytest.fail("board mismatch:\n" + boards_to_string(expected, got))
    np.testing.assert_array_equal(got, expected)


@pytest.mark.parametrize("size", SIZES)
def test_golden_count_series_10k(input_images, golden_alive, size):
    """The 10,000-turn soak — catches torus-seam off-by-ones nothing else
    does (SURVEY.md §7 hard part 1).  One scan dispatch, counts on device."""
    expected = read_alive_csv(golden_alive / f"{size}x{size}.csv")
    start = read_pgm(input_images / f"{size}x{size}.pgm")
    _, counts = steps_with_counts(jnp.asarray(start), TABLE, 10_000)
    counts = np.asarray(counts)
    assert len(expected) == 10_000
    mismatches = [
        (t, expected[t], int(counts[t - 1]))
        for t in expected
        if int(counts[t - 1]) != expected[t]
    ]
    assert not mismatches, f"first mismatches: {mismatches[:5]}"


def test_steady_state_512_period_2(input_images):
    """After turn 10000 the 512² soup is a period-2 oscillator: 5565 alive on
    even turns, 5567 on odd (count_test.go:45-51)."""
    start = read_pgm(input_images / "512x512.pgm")
    board = superstep(jnp.asarray(start), TABLE, 10_000)
    _, counts = steps_with_counts(board, TABLE, 6)
    for i, c in enumerate(np.asarray(counts)):
        turn = 10_001 + i
        assert int(c) == (5567 if turn % 2 else 5565), f"turn {turn}"
