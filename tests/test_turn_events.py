"""Batch turn telemetry and the pipelined headless dispatch path.

``Params.turn_events="batch"`` replaces the reference-exact one-
TurnComplete-per-generation stream (``gol/event.go:53-58``) with one
``TurnsCompleted(first, last)`` per device dispatch, so a headless
``gol.run()`` is no longer bounded by Python queue throughput (round-2
verdict, weak-1).  These tests pin the exact-accounting contract: the
ranges tile the run with no gaps or overlaps, results are bit-identical
to the per-turn stream, and the interactive keys keep their semantics.
"""

import queue
import threading

import numpy as np

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.controller import Controller
from distributed_gol_tpu.engine.events import (
    FinalTurnComplete,
    StateChange,
    TurnComplete,
    TurnsCompleted,
)


def make_params(tmp_path, input_images, **kw):
    defaults = dict(
        turns=100,
        image_width=16,
        image_height=16,
        images_dir=input_images,
        out_dir=tmp_path,
        engine="roll",
    )
    defaults.update(kw)
    return gol.Params(**defaults)


def drain(events):
    out = []
    while (e := events.get(timeout=60)) is not None:
        out.append(e)
    return out


def test_batch_ranges_tile_the_run_exactly(tmp_path, input_images):
    # superstep=7 does not divide 100: the final range must be a remainder.
    params = make_params(
        tmp_path, input_images, turn_events="batch", superstep=7
    )
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    stream = drain(events)

    assert not any(isinstance(e, TurnComplete) for e in stream)
    ranges = [
        (e.first_turn, e.completed_turns)
        for e in stream
        if isinstance(e, TurnsCompleted)
    ]
    # Ranges are contiguous, ordered, and tile [1, turns] exactly.
    assert ranges[0][0] == 1
    assert ranges[-1][1] == params.turns
    for (f0, l0), (f1, _) in zip(ranges, ranges[1:]):
        assert f1 == l0 + 1
    assert all(f <= l for f, l in ranges)

    final = [e for e in stream if isinstance(e, FinalTurnComplete)][0]
    assert final.completed_turns == params.turns
    assert (tmp_path / "16x16x100.pgm").exists()


def test_batch_results_match_per_turn(tmp_path, input_images):
    per_turn = make_params(tmp_path / "a", input_images)
    batch = make_params(tmp_path / "b", input_images, turn_events="batch")
    (tmp_path / "a").mkdir()
    (tmp_path / "b").mkdir()

    finals = []
    for p in (per_turn, batch):
        events: queue.Queue = queue.Queue()
        gol.run(p, events)
        finals.append(
            [e for e in drain(events) if isinstance(e, FinalTurnComplete)][0]
        )
    assert sorted(finals[0].alive) == sorted(finals[1].alive)
    a = (tmp_path / "a" / "16x16x100.pgm").read_bytes()
    b = (tmp_path / "b" / "16x16x100.pgm").read_bytes()
    assert a == b


def test_batch_adaptive_cap_is_effectively_unbounded():
    assert Controller._ADAPT_CAP_BATCH >= 1 << 20
    assert Controller._ADAPT_CAP_BATCH > Controller._ADAPT_CAP


def test_batch_keys_pause_resume_detach(tmp_path, input_images):
    """s/p/q semantics survive batch mode and the pipelined loop: the
    detach turn is exact and the checkpoint resumes to the golden end."""
    from distributed_gol_tpu.engine.session import Session

    session = Session()
    params = make_params(
        tmp_path, input_images, turn_events="batch", superstep=4, turns=40
    )
    events: queue.Queue = queue.Queue()
    keys: queue.Queue = queue.Queue()
    t = gol.start(params, events, keys, session)

    # Wait until some progress, then pause/resume, then detach.
    seen_last = 0
    while seen_last < 8:
        e = events.get(timeout=60)
        if isinstance(e, TurnsCompleted):
            seen_last = e.completed_turns
    keys.put("p")
    keys.put("p")
    keys.put("q")
    stream = drain(events)
    t.join(timeout=60)

    states = [e for e in stream if isinstance(e, StateChange)]
    assert [str(s.new_state) for s in states] == [
        "Paused",
        "Executing",
        "Quitting",
    ]
    ckpt = session.check_states(16, 16)
    assert ckpt is not None
    # Detach turn is a dispatch boundary and matches the checkpoint.
    final = [e for e in stream if isinstance(e, FinalTurnComplete)][0]
    assert final.completed_turns == ckpt.turn
    assert ckpt.turn % 4 == 0 and 8 <= ckpt.turn < 40

    # Resume completes the run; end state equals an uninterrupted run.
    (tmp_path / "ref").mkdir()
    ref_events: queue.Queue = queue.Queue()
    gol.run(make_params(tmp_path / "ref", input_images, turns=40), ref_events)
    want = [e for e in drain(ref_events) if isinstance(e, FinalTurnComplete)][0]

    events2: queue.Queue = queue.Queue()
    gol.run(params, events2, session=session)
    got = [e for e in drain(events2) if isinstance(e, FinalTurnComplete)][0]
    assert got.completed_turns == 40
    assert sorted(got.alive) == sorted(want.alive)


def test_per_turn_remains_default_and_dense(tmp_path, input_images):
    params = make_params(tmp_path, input_images, turns=30)
    assert params.turn_events == "per-turn"
    events: queue.Queue = queue.Queue()
    gol.run(params, events)
    stream = drain(events)
    assert not any(isinstance(e, TurnsCompleted) for e in stream)
    tc = [e.completed_turns for e in stream if isinstance(e, TurnComplete)]
    assert tc == list(range(1, 31))
