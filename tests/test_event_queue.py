"""EventQueue: the per-dispatch batched transport for per-turn TurnComplete
streams (round-3 verdict, weak-3: one ``queue.Queue.put`` per generation
bounded the reference-exact path at 14% of the engine rate at 512²).

The contract under test: a consumer draining an :class:`EventQueue` sees the
EXACT per-turn reference stream (``gol/event.go:53-58``) — same events, same
order — while the producer pays one queue entry per dispatch.  A plain
``queue.Queue`` keeps the per-event puts (drop-in compatibility), so the two
streams must be indistinguishable.
"""

import queue
import tempfile

import pytest

from distributed_gol_tpu.engine.events import (
    AliveCellsCount,
    EventQueue,
    FinalTurnComplete,
    StateChange,
    TurnComplete,
    TurnTiming,
)
from distributed_gol_tpu.engine.gol import run
from distributed_gol_tpu.engine.params import Params
from distributed_gol_tpu.engine.session import Session


def drain(events):
    out = []
    while (e := events.get(timeout=30)) is not None:
        out.append(e)
    return out


class TestEventQueueUnit:
    def test_single_turn_and_range_expand_in_order(self):
        q = EventQueue()
        q.put_turns(5, 5)
        q.put(AliveCellsCount(5, 10))
        q.put_turns(6, 9)
        q.put(None)
        got = drain(q)
        assert got[0] == TurnComplete(5)
        assert got[1] == AliveCellsCount(5, 10)
        assert got[2:] == [TurnComplete(t) for t in range(6, 10)]

    def test_empty_reflects_pending_expansion(self):
        q = EventQueue()
        q.put_turns(1, 3)
        assert not q.empty()
        assert q.get(block=False) == TurnComplete(1)
        # Two expansions still pending: the queue must not look drained.
        assert not q.empty()
        assert q.get(block=False) == TurnComplete(2)
        assert q.get(block=False) == TurnComplete(3)
        assert q.empty()
        with pytest.raises(queue.Empty):
            q.get(block=False)

    def test_inverted_range_is_a_noop(self):
        q = EventQueue()
        q.put_turns(4, 3)
        assert q.empty()

    def test_task_done_join_with_canonical_consumer(self):
        # The standard `get(); ...; task_done()` worker pattern must keep
        # working although a range is ONE underlying entry: surplus
        # task_done calls from expanded events are absorbed.
        q = EventQueue()
        q.put_turns(1, 5)
        q.put(AliveCellsCount(5, 7))
        for _ in range(6):  # 5 expanded TurnCompletes + 1 plain event
            q.get(block=False)
            q.task_done()
        q.join()  # returns immediately: all entries accounted
        with pytest.raises(ValueError):
            q.task_done()  # a 7th call is still an error, as on queue.Queue


def _stream(events_queue, turns=20, **kw):
    # Hermetic (round 6): a seeded soup instead of the reference images
    # mount — this suite compares two runs of OUR system against each
    # other, so it must run on rigs without /root/reference.
    kw.setdefault("cycle_check", 0)
    p = Params(
        turns=turns,
        image_width=64,
        image_height=64,
        soup_density=0.3,
        soup_seed=7,  # settles to ash (period <= 6) by ~turn 600
        out_dir=tempfile.mkdtemp(prefix="gol_evq_"),
        **kw,
    )
    run(p, events_queue, session=Session())
    return drain(events_queue)


def _comparable(stream):
    """Ticker events and timings are wall-clock-dependent; everything else
    must match between transports."""
    return [e for e in stream if not isinstance(e, (AliveCellsCount, TurnTiming))]


class TestEventQueueStreamParity:
    def test_headless_per_turn_stream_identical_to_plain_queue(self):
        plain = _comparable(_stream(queue.Queue()))
        fast = _comparable(_stream(EventQueue()))
        assert plain == fast
        # And the stream is the reference contract: dense TurnComplete then
        # the final events.
        assert [e for e in fast if isinstance(e, TurnComplete)] == [
            TurnComplete(t) for t in range(1, 21)
        ]
        assert isinstance(fast[-2], FinalTurnComplete)
        assert isinstance(fast[-1], StateChange)

    def test_cycle_fast_forward_stream_identical(self):
        # The seeded soup settles by ~turn 600; 5000 turns leaves the
        # probe schedule (every 4 dispatches, forced a probe later) room
        # to fire well before the end, and the fast-forward's chunked
        # emission must expand to the same dense stream.
        plain = _comparable(_stream(queue.Queue(), turns=5000, cycle_check=4))
        fast = _comparable(_stream(EventQueue(), turns=5000, cycle_check=4))
        assert plain == fast
        # The comparison only means something if the fast-forward really
        # ran: the seeded soup must settle and the probe must fire.
        from distributed_gol_tpu.engine.events import CycleDetected

        assert any(isinstance(e, CycleDetected) for e in fast)


class TestGetMany:
    """Batched drain (round 5): ``get_many`` keeps turn runs compressed as
    public ``TurnsCompleted`` events — exact ordering and turn accounting
    with no per-generation object — while plain ``get`` users keep the
    reference-exact per-turn stream."""

    def test_runs_stay_compressed_in_order(self):
        from distributed_gol_tpu.engine.events import TurnsCompleted

        q = EventQueue()
        q.put_turns(1, 1)
        q.put(AliveCellsCount(1, 7))
        q.put_turns(2, 100)
        q.put(FinalTurnComplete(100, []))
        q.put(None)
        got = q.get_many()
        assert got == [
            TurnComplete(1),
            AliveCellsCount(1, 7),
            TurnsCompleted(completed_turns=100, first_turn=2),
            FinalTurnComplete(100, []),
            None,
        ]

    def test_max_n_and_nonblocking_tail(self):
        q = EventQueue()
        for t in range(5):
            q.put_turns(10 * t, 10 * t + 9)
        got = q.get_many(max_n=3)
        assert len(got) == 3 and got[0].first_turn == 0
        rest = q.get_many(max_n=100, block=False)
        assert len(rest) == 2 and rest[-1].completed_turns == 49

    def test_empty_raises_like_get(self):
        q = EventQueue()
        with pytest.raises(queue.Empty):
            q.get_many(block=False)
        with pytest.raises(queue.Empty):
            q.get_many(timeout=0.01)

    def test_mixed_get_then_get_many_collapses_leftover(self):
        from distributed_gol_tpu.engine.events import TurnsCompleted

        q = EventQueue()
        q.put_turns(0, 9)
        q.put(None)
        first = q.get()
        assert first == TurnComplete(0)
        got = q.get_many()
        assert got == [TurnsCompleted(completed_turns=9, first_turn=1), None]

    def test_task_done_join_with_batched_consumer(self):
        import threading

        q = EventQueue()
        q.put_turns(0, 99)
        q.put(AliveCellsCount(99, 1))
        q.put_turns(100, 100)
        done = threading.Event()

        def consumer():
            n = 0
            while n < 3:
                for e in q.get_many():
                    q.task_done()
                    n += 1
            done.set()

        threading.Thread(target=consumer, daemon=True).start()
        q.join()  # returns only if task_done bookkeeping balances
        assert done.wait(5)

    def test_mixed_get_and_task_done_then_batch_join(self):
        q = EventQueue()
        q.put_turns(0, 9)
        q.get()  # expands one of ten
        q.task_done()
        rest = q.get_many()
        assert len(rest) == 1
        q.task_done()
        q.join()  # the collapsed tail maps to exactly one real task_done
