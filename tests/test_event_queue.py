"""EventQueue: the per-dispatch batched transport for per-turn TurnComplete
streams (round-3 verdict, weak-3: one ``queue.Queue.put`` per generation
bounded the reference-exact path at 14% of the engine rate at 512²).

The contract under test: a consumer draining an :class:`EventQueue` sees the
EXACT per-turn reference stream (``gol/event.go:53-58``) — same events, same
order — while the producer pays one queue entry per dispatch.  A plain
``queue.Queue`` keeps the per-event puts (drop-in compatibility), so the two
streams must be indistinguishable.
"""

import queue
import tempfile

import pytest

from distributed_gol_tpu.engine.events import (
    AliveCellsCount,
    EventQueue,
    FinalTurnComplete,
    StateChange,
    TurnComplete,
    TurnTiming,
)
from distributed_gol_tpu.engine.gol import run
from distributed_gol_tpu.engine.params import Params
from distributed_gol_tpu.engine.session import Session


def drain(events):
    out = []
    while (e := events.get(timeout=30)) is not None:
        out.append(e)
    return out


class TestEventQueueUnit:
    def test_single_turn_and_range_expand_in_order(self):
        q = EventQueue()
        q.put_turns(5, 5)
        q.put(AliveCellsCount(5, 10))
        q.put_turns(6, 9)
        q.put(None)
        got = drain(q)
        assert got[0] == TurnComplete(5)
        assert got[1] == AliveCellsCount(5, 10)
        assert got[2:] == [TurnComplete(t) for t in range(6, 10)]

    def test_empty_reflects_pending_expansion(self):
        q = EventQueue()
        q.put_turns(1, 3)
        assert not q.empty()
        assert q.get(block=False) == TurnComplete(1)
        # Two expansions still pending: the queue must not look drained.
        assert not q.empty()
        assert q.get(block=False) == TurnComplete(2)
        assert q.get(block=False) == TurnComplete(3)
        assert q.empty()
        with pytest.raises(queue.Empty):
            q.get(block=False)

    def test_inverted_range_is_a_noop(self):
        q = EventQueue()
        q.put_turns(4, 3)
        assert q.empty()

    def test_task_done_join_with_canonical_consumer(self):
        # The standard `get(); ...; task_done()` worker pattern must keep
        # working although a range is ONE underlying entry: surplus
        # task_done calls from expanded events are absorbed.
        q = EventQueue()
        q.put_turns(1, 5)
        q.put(AliveCellsCount(5, 7))
        for _ in range(6):  # 5 expanded TurnCompletes + 1 plain event
            q.get(block=False)
            q.task_done()
        q.join()  # returns immediately: all entries accounted
        with pytest.raises(ValueError):
            q.task_done()  # a 7th call is still an error, as on queue.Queue


def _stream(events_queue, turns=20, **kw):
    kw.setdefault("cycle_check", 0)
    p = Params(
        turns=turns,
        image_width=64,
        image_height=64,
        images_dir="/root/reference/images",
        out_dir=tempfile.mkdtemp(prefix="gol_evq_"),
        **kw,
    )
    run(p, events_queue, session=Session())
    return drain(events_queue)


def _comparable(stream):
    """Ticker events and timings are wall-clock-dependent; everything else
    must match between transports."""
    return [e for e in stream if not isinstance(e, (AliveCellsCount, TurnTiming))]


class TestEventQueueStreamParity:
    def test_headless_per_turn_stream_identical_to_plain_queue(self):
        plain = _comparable(_stream(queue.Queue()))
        fast = _comparable(_stream(EventQueue()))
        assert plain == fast
        # And the stream is the reference contract: dense TurnComplete then
        # the final events.
        assert [e for e in fast if isinstance(e, TurnComplete)] == [
            TurnComplete(t) for t in range(1, 21)
        ]
        assert isinstance(fast[-2], FinalTurnComplete)
        assert isinstance(fast[-1], StateChange)

    def test_cycle_fast_forward_stream_identical(self):
        # 64² settles well inside 1000 turns; the fast-forward's chunked
        # emission must expand to the same dense stream.
        plain = _comparable(_stream(queue.Queue(), turns=1000, cycle_check=4))
        fast = _comparable(_stream(EventQueue(), turns=1000, cycle_check=4))
        assert plain == fast
