"""Request-scoped tracing suite (ISSUE 15).

The acceptance paths, asserted hermetically on CPU:

- **End-to-end wire trace**: a session submitted through the gateway
  with a W3C ``traceparent`` yields ONE trace whose spans cover request
  handling, admission, the session run, dispatches, and the first
  published frame — and ``tools/trace_export.py`` renders it to valid
  Chrome Trace Event JSON.
- **Tail retention**: a hang-faulted tenant's trace is retained with
  the watchdog-fire event inside it even at sample rate 0 (error traces
  are never lost), while a clean run's trace IS head-sampled out.
- **Cohort linking**: a batched launch records a ``gol.cohort.launch``
  span into >= 2 member traces sharing one launch id with cross-links.
- **Overhead**: tracing-on lands within the measured rep spread of
  tracing-off at pilot scale (the ``utils/measure.py`` discipline, like
  the ISSUE-4 metrics-overhead test).
- **Docs lint**: every recorded ``gol.*`` span name appears in the
  docs/API.md span table, both directions
  (``tools/check_metric_docs.check_spans``).
"""

import json
import queue
import time

import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.params import Params
from distributed_gol_tpu.engine.session import Session
from distributed_gol_tpu.obs import metrics as obs_metrics
from distributed_gol_tpu.obs import spans, tracing
from distributed_gol_tpu.serve import ServeConfig, ServePlane
from distributed_gol_tpu.serve.gateway import GatewayServer
from distributed_gol_tpu.testing.faults import (
    Fault,
    FaultInjectionBackend,
    FaultPlan,
)

W = H = 16
SUPERSTEP = 4
TURNS = 24


def tenant_params(out_dir, seed, turns=TURNS, **kw):
    cfg = dict(
        engine="roll",
        mesh_shape=(1, 1),
        image_width=W,
        image_height=H,
        superstep=SUPERSTEP,
        turns=turns,
        soup_density=0.25,
        soup_seed=seed,
        out_dir=out_dir,
        cycle_check=0,
        ticker_period=60.0,
    )
    cfg.update(kw)
    return Params(**cfg)


@pytest.fixture(autouse=True)
def _fresh_tracer():
    """Every test sees an empty store at the default knobs (the store is
    process-wide, like the metrics registry)."""
    tracing.TRACER.configure(sample_rate=1.0, ring_depth=256, max_spans=512)
    tracing.TRACER.clear()
    yield
    tracing.TRACER.configure(sample_rate=1.0, ring_depth=256, max_spans=512)
    tracing.TRACER.clear()


# -- W3C propagation -----------------------------------------------------------


class TestTraceparent:
    def test_roundtrip(self):
        tid, sid = "ab" * 16, "cd" * 8
        header = tracing.format_traceparent(tid, sid, sampled=True)
        assert header == f"00-{tid}-{sid}-01"
        assert tracing.parse_traceparent(header) == (tid, sid, True)
        assert tracing.parse_traceparent(
            tracing.format_traceparent(tid, sid, sampled=False)
        ) == (tid, sid, False)

    @pytest.mark.parametrize(
        "bad",
        [
            None,
            "",
            "garbage",
            "00-short-cdcdcdcdcdcdcdcd-01",
            "00-" + "0" * 32 + "-" + "cd" * 8 + "-01",  # all-zero trace id
            "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # all-zero span id
        ],
    )
    def test_malformed_headers_start_fresh(self, bad):
        assert tracing.parse_traceparent(bad) is None
        # ...and a malformed header never fails the request: start_trace
        # just mints a new id.
        t = tracing.TRACER.start_trace(traceparent=bad)
        assert len(t.trace_id) == 32

    def test_inbound_id_and_sampled_flag_are_adopted(self):
        header = tracing.format_traceparent("12" * 16, "34" * 8, sampled=True)
        tracing.TRACER.configure(sample_rate=0.0)  # head-drop everything...
        t = tracing.TRACER.start_trace(traceparent=header)
        assert t.trace_id == "12" * 16
        assert t.parent_span_id == "34" * 8
        assert t.sampled  # ...but the caller asked: retention forced

    def test_head_sampling_is_deterministic(self):
        tid = tracing.new_trace_id()
        assert tracing.head_sampled(tid, 1.0)
        assert not tracing.head_sampled(tid, 0.0)
        assert tracing.head_sampled(tid, 0.5) == tracing.head_sampled(tid, 0.5)


# -- the span store ------------------------------------------------------------


class TestTraceStore:
    def test_span_nesting_parent_links(self):
        t = tracing.TRACER.start_trace(tenant="a")
        with tracing.activate(t):
            with tracing.span("outer"):
                with tracing.span("inner"):
                    pass
        tracing.TRACER.end_trace(t, status="ok")
        doc = tracing.TRACER.lookup(t.trace_id)
        by_name = {s["name"]: s for s in doc["spans"]}
        assert by_name["inner"]["parent_id"] == by_name["outer"]["span_id"]
        assert by_name["outer"]["parent_id"] == doc["root_span_id"]
        # The root span is the whole-request bar, appended at end.
        assert doc["spans"][-1]["name"] == "gol.request"
        assert doc["spans"][-1]["dur_ns"] == doc["duration_ns"]

    def test_span_cap_keeps_head_and_counts_tail(self):
        tracing.TRACER.configure(max_spans=16)
        t = tracing.TRACER.start_trace()
        with tracing.activate(t):
            for i in range(40):
                with tracing.span("s", i=i):
                    pass
        tracing.TRACER.end_trace(t)
        doc = tracing.TRACER.lookup(t.trace_id)
        body = [s for s in doc["spans"] if s["name"] == "s"]
        assert len(body) == 16
        assert [s["labels"]["i"] for s in body] == list(range(16))  # the HEAD
        assert doc["dropped_spans"] == 24
        # Always-retained events survive the cap.
        t2 = tracing.TRACER.start_trace()
        t2.add_event("gol.watchdog.fire", turn=9)
        tracing.TRACER.end_trace(t2)
        ev = tracing.TRACER.lookup(t2.trace_id)["events"][0]
        assert ev["name"] == "gol.watchdog.fire" and ev["labels"]["turn"] == 9

    def test_tail_retention_and_head_drop(self):
        tracing.TRACER.configure(sample_rate=0.0)
        clean = tracing.TRACER.start_trace(tenant="clean")
        tracing.TRACER.end_trace(clean, status="completed")
        assert tracing.TRACER.lookup(clean.trace_id) is None  # head-dropped
        bad = tracing.TRACER.start_trace(tenant="bad")
        bad.flag("watchdog_fire")
        tracing.TRACER.end_trace(bad, status="parked", error="boom")
        doc = tracing.TRACER.lookup(bad.trace_id)
        assert doc is not None and doc["flagged"] == "watchdog_fire"
        assert doc["status"] == "parked" and doc["error"] == "boom"

    def test_end_is_idempotent_and_recent_filters_by_tenant(self):
        a = tracing.TRACER.start_trace(tenant="a")
        b = tracing.TRACER.start_trace(tenant="b")
        tracing.TRACER.end_trace(a)
        tracing.TRACER.end_trace(a)  # no double-retention
        tracing.TRACER.end_trace(b)
        assert len(tracing.TRACER.recent()) == 2
        only_a = tracing.TRACER.recent(tenant="a")
        assert [d["tenant"] for d in only_a] == ["a"]
        # Prefix lookup resolves.
        assert tracing.TRACER.lookup(b.trace_id[:8])["trace_id"] == b.trace_id

    def test_mark_fires_once(self):
        t = tracing.TRACER.start_trace()
        first = t.mark("first_dispatch")
        assert first is not None and first >= 0
        assert t.mark("first_dispatch") is None
        tracing.TRACER.end_trace(t)
        assert "first_dispatch" in tracing.TRACER.lookup(t.trace_id)["marks"]

    def test_http_traces_payload(self):
        t = tracing.TRACER.start_trace(tenant="x")
        tracing.TRACER.end_trace(t)
        code, obj = tracing.http_traces({})
        assert code == 200 and obj["traces"][0]["trace_id"] == t.trace_id
        code, obj = tracing.http_traces({"trace_id": t.trace_id[:10]})
        assert code == 200 and obj["trace_id"] == t.trace_id
        code, obj = tracing.http_traces({"trace_id": "f" * 32})
        assert code == 404

    def test_spans_module_feeds_the_active_trace(self, monkeypatch):
        """obs.spans call sites feed the host store from the SAME call
        site as the jax.profiler annotation — including on a
        profiler-less build (the single degradation seam)."""
        monkeypatch.setattr(spans, "_TRACE_CLS", None)
        monkeypatch.setattr(spans, "_STEP_CLS", None)
        t = tracing.TRACER.start_trace()
        with tracing.activate(t):
            with spans.span("gol.test", turn=3):
                pass
            with spans.step_span("gol.test.step", 7, k=50):
                pass
        tracing.TRACER.end_trace(t)
        names = [s["name"] for s in tracing.TRACER.lookup(t.trace_id)["spans"]]
        assert "gol.test" in names and "gol.test.step" in names
        # With NO active trace the same sites are free no-ops.
        with spans.span("gol.test", turn=4):
            pass


class TestProfilerSeam:
    def test_one_resolution_home_degrades_both_consumers(self, monkeypatch):
        """ISSUE 15 satellite: utils.profiling.profiler() is the ONE
        jax.profiler resolution home — stubbing it degrades BOTH
        utils.profiling.trace and obs.spans through the same path."""
        from distributed_gol_tpu.utils import profiling

        monkeypatch.setattr(profiling, "_PROFILER", None)  # stripped build
        spans._reset()
        try:
            cls, step_cls = spans._resolve()
            assert cls is None and step_cls is None
            with spans.span("gol.test"):
                pass
            with pytest.warns(RuntimeWarning, match="profiler unavailable"):
                with profiling.trace("/tmp/never-used"):
                    pass
        finally:
            spans._reset()

    def test_real_resolution_is_cached(self):
        from distributed_gol_tpu.utils import profiling

        profiling._reset_profiler_cache()
        spans._reset()
        assert profiling.profiler() is profiling.profiler()
        cls, _ = spans._resolve()
        import jax

        assert cls is jax.profiler.TraceAnnotation


# -- the end-to-end wire acceptance path ---------------------------------------


class TestWireTrace:
    def test_gateway_submission_reconstructs_end_to_end(self, tmp_path):
        """THE acceptance row: traceparent in → one trace whose spans
        cover request handling, admission, session run, dispatches, and
        the first published frame; the receipt carries the id; /traces
        serves it; trace_export renders valid Chrome Trace JSON."""
        from tools.gol_client import GolClient, render_trace
        from tools import trace_export

        sent_id = "fe" * 16
        header = tracing.format_traceparent(sent_id, "12" * 8, sampled=True)
        plane = ServePlane(
            ServeConfig(max_sessions=2), checkpoint_root=tmp_path / "ckpt"
        )
        gateway = GatewayServer(plane, port=0)
        client = GolClient(gateway.url)
        try:
            receipt = client._request(
                "POST",
                "/v1/sessions",
                {
                    "tenant": "alice",
                    "params": {
                        "width": W,
                        "height": H,
                        "turns": TURNS,
                        "engine": "roll",
                        "cycle_check": 0,
                        "ticker_period": 60.0,
                    },
                    "soup": {"density": 0.25, "seed": 7},
                    "spectate": True,
                    "viewport": [0, 0, W, H],
                },
                headers={"traceparent": header},
            )
            assert receipt["trace_id"] == sent_id
            assert receipt["traceparent"].split("-")[1] == sent_id
            assert receipt["links"]["trace"].endswith(sent_id)
            # A spectator on the wire: its first frame becomes the
            # trace's last-hop event.
            with client.spectate("alice", rect=(0, 0, 8, 8)) as stream:
                deadline = time.monotonic() + 120
                got_frame = False
                while time.monotonic() < deadline:
                    ev = stream.recv(timeout=120)
                    if isinstance(ev, dict):
                        if ev.get("type") == "end":
                            break
                        continue
                    got_frame = True
                assert got_frame
            handle = plane.handle("alice")
            assert handle.wait(timeout=120)
            assert handle.status == "completed"
            # State responses carry the correlation header.
            doc, hdrs = _get_with_headers(client, "/v1/sessions/alice/state")
            assert hdrs.get("X-Gol-Trace-Id") == sent_id
            # The retained trace, over the wire (the gateway serves
            # /traces too).  wait() returns a hair before the plane's
            # end_trace finalizes — poll the terminal status briefly.
            deadline = time.monotonic() + 30
            while True:
                trace = client.traces(trace_id=sent_id[:12])
                if trace["status"] == "completed" or time.monotonic() > deadline:
                    break
                time.sleep(0.05)
            names = {s["name"] for s in trace["spans"]}
            assert {
                "gol.request",
                "gol.admission",
                "gol.session.run",
                "gol.dispatch.sync",
                "gol.frame.publish",
            } <= names, names
            assert trace["tenant"] == "alice"
            assert trace["status"] == "completed"
            # SLI marks: first dispatch + first frame stamped once.
            assert "first_dispatch" in trace["marks"]
            assert "first_frame" in trace["marks"]
            event_names = {e["name"] for e in trace["events"]}
            assert "gol.spectator.first_send" in event_names
            # The terminal MetricsReport and the SLI histograms join on
            # the same identifiers.
            assert handle.report.trace_id == sent_id
            hists = handle.report.snapshot["histograms"]
            assert (
                hists[
                    obs_metrics.labelled(
                        "sli.time_to_first_dispatch_seconds", "alice"
                    )
                ]["count"]
                >= 1
            )
            assert (
                hists[
                    obs_metrics.labelled(
                        "sli.time_to_first_frame_seconds", "alice"
                    )
                ]["count"]
                >= 1
            )
            # Chrome Trace Event export is valid, loadable JSON.
            chrome = trace_export.to_chrome(trace)
            blob = json.loads(json.dumps(chrome))
            assert blob["traceEvents"], "no events exported"
            assert all(
                ("ts" in e and "ph" in e and "name" in e) or e["ph"] == "M"
                for e in blob["traceEvents"]
            )
            assert any(
                e["name"] == "mark:first_dispatch"
                for e in blob["traceEvents"]
            )
            # ...and the human renderer mentions the key hops.
            text = render_trace(trace)
            assert "gol.session.run" in text and "first_dispatch" in text
        finally:
            gateway.close()
            plane.close()

    def test_queue_wait_is_a_span_and_an_sli(self, tmp_path):
        """A queued admission's wait (submit → worker pickup) lands as
        the gol.queue.wait span AND the sli.queue_wait_seconds
        observation the queue-wait SLO judges."""
        with ServePlane(
            ServeConfig(max_sessions=1), checkpoint_root=tmp_path / "ckpt"
        ) as plane:
            first = plane.submit("first", tenant_params(tmp_path / "a", 1))
            queued = plane.submit("queued", tenant_params(tmp_path / "b", 2))
            assert queued.admitted_as == "queue"
            assert plane.wait_idle(timeout=120)
            assert first.status == queued.status == "completed"
            doc = queued.trace.to_dict()
            names = [s["name"] for s in doc["spans"]]
            assert "gol.queue.wait" in names
            snap = obs_metrics.REGISTRY.snapshot()
            hist = snap.data["histograms"][
                obs_metrics.labelled("sli.queue_wait_seconds", "queued")
            ]
            assert hist["count"] >= 1
            # Run-now admissions observe their (near-zero) wait too, so
            # the queue-wait SLO's fraction covers ALL requests — but
            # no gol.queue.wait span pollutes their timeline.
            run_now = snap.data["histograms"][
                obs_metrics.labelled("sli.queue_wait_seconds", "first")
            ]
            assert run_now["count"] >= 1
            assert run_now["sum"] < hist["sum"]
            assert "gol.queue.wait" not in [
                s["name"] for s in first.trace.to_dict()["spans"]
            ]

    def test_rejection_yields_a_rejected_trace_with_the_reason(self, tmp_path):
        with ServePlane(
            ServeConfig(max_sessions=1, max_queued=0),
            checkpoint_root=tmp_path / "ckpt",
        ) as plane:
            plane.submit("a", tenant_params(tmp_path / "a", 1))
            from distributed_gol_tpu.serve import AdmissionRejected

            with pytest.raises(AdmissionRejected):
                plane.submit("b", tenant_params(tmp_path / "b", 2))
            assert plane.wait_idle(timeout=120)
            shed = [
                d
                for d in tracing.TRACER.recent()
                if d["status"] == "rejected"
            ]
            assert shed and "pod full" in shed[0]["error"]


def _get_with_headers(client, path):
    """GET returning (json body, response headers) — the X-Gol-Trace-Id
    assertion needs the raw header surface GolClient doesn't expose."""
    import http.client

    conn = http.client.HTTPConnection(client.host, client.port, timeout=30)
    try:
        conn.request("GET", path)
        resp = conn.getresponse()
        return json.loads(resp.read()), dict(resp.getheaders())
    finally:
        conn.close()


# -- tail retention under faults (chaos) ---------------------------------------


@pytest.mark.chaos
class TestFaultTraces:
    def test_hang_trace_is_tail_retained_with_the_watchdog_fire(
        self, tmp_path
    ):
        """Head sampling at 0 drops every clean trace — but the
        hang-faulted tenant's trace survives, with the watchdog fire in
        its always-retained event ring (error traces are never lost)."""
        sick_params = tenant_params(tmp_path / "sick", 999)
        sick_backend = FaultInjectionBackend(
            Backend(sick_params),
            FaultPlan([Fault(1, "hang", seconds=90.0)]),
        )
        try:
            with ServePlane(
                ServeConfig(
                    max_sessions=2,
                    default_deadline_seconds=1.0,
                    trace_sample_rate=0.0,
                ),
                checkpoint_root=tmp_path / "ckpt",
            ) as plane:
                healthy = plane.submit(
                    "healthy", tenant_params(tmp_path / "good", 101)
                )
                sick = plane.submit("sick", sick_params, backend=sick_backend)
                assert plane.wait_idle(timeout=120)
                assert healthy.status == "completed"
                assert sick.status == "parked"
                assert "DispatchTimeout" in sick.error
                # The clean trace was head-sampled out; the sick one was
                # tail-retained with the fire inside it.
                assert tracing.TRACER.lookup(healthy.trace.trace_id) is None
                doc = tracing.TRACER.lookup(sick.trace.trace_id)
                assert doc is not None
                assert doc["flagged"] == "watchdog_fire"
                assert any(
                    e["name"] == "gol.watchdog.fire" for e in doc["events"]
                )
                assert doc["status"] == "parked"
        finally:
            sick_backend.release_hangs()

    def test_supervisor_dump_carries_the_trace_correlation(self, tmp_path):
        """Satellite: a REAL supervisor-produced flight dump joins the
        request timeline — trace_id in the header, the short id on
        dispatch and restart rows, and flight_report prints all three."""
        from distributed_gol_tpu.engine.supervisor import supervise
        from tools import flight_report

        plan = FaultPlan([Fault(2, "issue"), Fault(3, "issue")])

        def always_faulty(p, attempt):
            return FaultInjectionBackend(Backend(p), plan)

        params = tenant_params(
            tmp_path / "out",
            999,
            checkpoint_every_turns=SUPERSTEP,
            restart_limit=1,
        )
        session = Session(tmp_path / "ckpt")
        events: queue.Queue = queue.Queue()
        req = tracing.TRACER.start_trace(tenant="sup")
        with tracing.activate(req):
            with pytest.raises(RuntimeError):
                supervise(
                    params, events, session=session,
                    backend_factory=always_faulty,
                )
        tracing.TRACER.end_trace(req, status="failed", error="exhausted")
        from distributed_gol_tpu.obs import flight as flight_lib

        dump = flight_lib.latest_flight_record(tmp_path / "ckpt")
        assert dump is not None
        doc = flight_lib.load_flight_record(dump)
        assert doc["trace_id"] == req.trace_id
        kinds = {}
        for r in doc["records"]:
            kinds.setdefault(r["kind"], []).append(r)
        assert kinds["dispatch"][0]["trace"] == req.trace_id[:8]
        assert kinds["restart"][0]["trace"] == req.trace_id[:8]
        text = flight_report.render(doc)
        assert f"trace_id {req.trace_id}" in text
        assert f"[trace {req.trace_id[:8]}]" in text
        # The trace itself was flagged by the restart and records it.
        tdoc = tracing.TRACER.lookup(req.trace_id)
        assert tdoc["flagged"] == "restart"
        assert any(
            e["name"] == "gol.supervisor.restart" for e in tdoc["events"]
        )


# -- cohort-batched launches link member traces --------------------------------


@pytest.mark.chaos
class TestCohortTraces:
    def test_batched_launch_links_member_traces(self, tmp_path):
        with ServePlane(
            ServeConfig(max_sessions=4, batched=True),
            checkpoint_root=tmp_path / "ckpt",
        ) as plane:
            a = plane.submit("a", tenant_params(tmp_path / "a", 11))
            b = plane.submit("b", tenant_params(tmp_path / "b", 22))
            assert plane.wait_idle(timeout=180)
            assert a.status == b.status == "completed"
            docs = {h.tenant: h.trace.to_dict() for h in (a, b)}
            launches = {
                t: [
                    s
                    for s in d["spans"]
                    if s["name"] == "gol.cohort.launch"
                ]
                for t, d in docs.items()
            }
            assert launches["a"] and launches["b"], launches
            shared = {
                s["labels"]["launch"] for s in launches["a"]
            } & {s["labels"]["launch"] for s in launches["b"]}
            assert shared, "no launch id shared across the two member traces"
            lid = next(iter(shared))
            span_a = next(
                s for s in launches["a"] if s["labels"]["launch"] == lid
            )
            assert span_a["labels"]["boards"] >= 2
            assert docs["b"]["trace_id"] in span_a["labels"]["links"]


# -- overhead (the tier-1 acceptance bar) --------------------------------------


def test_tracing_overhead_within_rep_spread():
    """Tracing-on (a live request trace recording host spans on every
    dispatch) lands within the measured rep spread of tracing-off (the
    always-on baseline: one ContextVar read per span site) at pilot
    scale — interleaved A/B medians, each arm's own rep envelope,
    floored at 30% for quiet rigs (the ISSUE-4 methodology)."""
    import bench
    from distributed_gol_tpu.utils import measure

    off_rates, on_rates = [], []
    for _ in range(3):
        gps, _ = bench.bench_controller_path(
            256, budget_seconds=1.5, superstep=256
        )
        if gps > 0:
            off_rates.append(gps)
        gps, _ = bench.bench_controller_path(
            256, budget_seconds=1.5, superstep=256, trace_request=True
        )
        if gps > 0:
            on_rates.append(gps)
    assert off_rates and on_rates, (off_rates, on_rates)
    # The traced arm actually traced: retained traces carry dispatches.
    traced = [d for d in tracing.TRACER.recent() if d["status"] == "completed"]
    assert traced and any(
        s["name"] == "gol.resolve" for s in traced[0]["spans"]
    )
    med_off = measure.median(off_rates)
    med_on = measure.median(on_rates)
    envelope = (
        (measure.spread(off_rates) if len(off_rates) > 1 else 0.0)
        + (measure.spread(on_rates) if len(on_rates) > 1 else 0.0)
    )
    tol = max(0.3, envelope)
    rel = abs(med_on - med_off) / med_off
    assert rel <= tol, (
        f"tracing-on median {med_on:,.0f} vs off {med_off:,.0f}: "
        f"{rel:.1%} apart, tolerance {tol:.1%} "
        f"(off reps {off_rates}, on reps {on_rates})"
    )


# -- config + docs lint --------------------------------------------------------


class TestConfig:
    def test_trace_knobs_validate(self):
        with pytest.raises(ValueError):
            ServeConfig(trace_sample_rate=1.5)
        with pytest.raises(ValueError):
            ServeConfig(trace_ring_depth=0)
        with pytest.raises(ValueError):
            ServeConfig(trace_max_spans=4)
        with pytest.raises(ValueError):
            ServeConfig(slo_queue_wait_seconds=-1)
        cfg = ServeConfig(slo_queue_wait_seconds=0.5)
        obj = cfg.slo_objectives()
        assert obj is not None and obj.queue_wait_seconds == 0.5

    def test_queue_wait_objective_enables_slo(self):
        from distributed_gol_tpu.obs.slo import SLOObjectives

        assert not SLOObjectives().enabled
        assert SLOObjectives(queue_wait_seconds=1.0).enabled


class TestSpanDocsLint:
    def test_shipped_tree_is_clean(self):
        from tools import check_metric_docs

        assert check_metric_docs.check_spans() == []

    def test_drift_fails_both_directions(self, tmp_path):
        from tools import check_metric_docs

        pkg = tmp_path / "distributed_gol_tpu"
        pkg.mkdir()
        (pkg / "mod.py").write_text(
            'from x import spans\n'
            'def f():\n'
            '    with spans.span("gol.only_in_code"):\n'
            '        pass\n'
        )
        docs = tmp_path / "docs"
        docs.mkdir()
        (docs / "API.md").write_text(
            "| Span | Where |\n|---|---|\n"
            "| `gol.only_in_docs` | nowhere |\n"
        )
        problems = check_metric_docs.check_spans(tmp_path)
        assert any("gol.only_in_code" in p for p in problems)
        assert any("gol.only_in_docs" in p for p in problems)
        # Fixing both directions clears it.
        (docs / "API.md").write_text(
            "| Span | Where |\n|---|---|\n"
            "| `gol.only_in_code` | mod.f |\n"
        )
        assert check_metric_docs.check_spans(tmp_path) == []
