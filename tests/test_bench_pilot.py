"""Tier-1 smoke for the bench/decompose harnesses (round-6 satellite):
a bench-harness regression must fail tests, not burn a TPU session.

``bench.py --pilot`` runs in a subprocess (the driver's real invocation
path: stdout must carry exactly one lint-clean JSON line);
``tools/decompose.py``'s pilot runs in-process (it shares this process's
jax) — both at toy scale, both producing the full round-6 record shape.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

from distributed_gol_tpu.utils import measure  # noqa: E402


def test_bench_pilot_record_shape():
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        GOL_BENCH_NO_PROBE="1",  # skip the wedged-backend probe subprocess
        XLA_FLAGS="",  # no virtual mesh needed; keep startup lean
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--pilot"],
        capture_output=True,
        text=True,
        timeout=240,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    # stdout is EXACTLY one JSON line (the driver contract).
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    assert record["pilot"] is True
    assert record["unit"] == "generations/sec"
    # Every headline row carries {reps, median, spread} — the round-6
    # acceptance bar, machine-checked.
    assert measure.check_headline_stats(record) == []
    assert record["reps"] >= 2 and record["median"] > 0
    assert record["bit_identical"] is True
    cp = record.get("controller_path")
    assert cp is None or cp["median"] > 0


def test_decompose_pilot_record_shape():
    from tools import decompose

    record = decompose.pilot_record()
    assert record["pilot"] is True
    assert measure.check_headline_stats(record) == []
    # The decomposition structure: floor + settled + geometry A/B rows
    # with bit-identity, the cap sweep, and the per-launch term fit.
    assert record["floor"]["median"] > 0
    assert record["settled"]["skip_fraction"] is not None
    geoms = record["geometries"]
    assert set(geoms) == {"m96c256", "m64c128"}
    for row in geoms.values():
        assert row["bit_identical"] is True
        assert row["median"] > 0
    assert record["col_window"] == 256  # wp=512: the column tier engages
    assert geoms["m64c128"]["col_window"] == 128
    terms = record["per_launch_terms"]
    assert terms["floor_us_per_launch"] > 0
    assert "us_per_active_stripe" in terms
    assert record["caps"]["512"]["skip_fraction"] is not None


def test_geometry_cli_spelling():
    """bench.py --plan-geometry parses to the same PlanGeometry the
    candidates enumerate (no subprocess: just the parse + install)."""
    from distributed_gol_tpu.ops import pallas_packed as pp

    prev = pp.plan_geometry()
    try:
        pp.set_plan_geometry(pp.PlanGeometry(64, 128))
        assert pp.plan_geometry().label == "m64c128"
        assert pp.plan_geometry() in pp.geometry_candidates()
    finally:
        pp.set_plan_geometry(prev)
