"""Tier-1 smoke for the bench/decompose harnesses (round-6 satellite):
a bench-harness regression must fail tests, not burn a TPU session.

``bench.py --pilot`` runs in a subprocess (the driver's real invocation
path: stdout must carry exactly one lint-clean JSON line);
``tools/decompose.py``'s pilot runs in-process (it shares this process's
jax) — both at toy scale, both producing the full round-6 record shape.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

from distributed_gol_tpu.utils import measure  # noqa: E402


def test_bench_pilot_record_shape():
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        GOL_BENCH_NO_PROBE="1",  # skip the wedged-backend probe subprocess
        XLA_FLAGS="",  # no virtual mesh needed; keep startup lean
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--pilot"],
        capture_output=True,
        text=True,
        timeout=240,
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    # stdout is EXACTLY one JSON line (the driver contract).
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    assert record["pilot"] is True
    assert record["unit"] == "generations/sec"
    # Every headline row carries {reps, median, spread} — the round-6
    # acceptance bar, machine-checked.
    assert measure.check_headline_stats(record) == []
    assert record["reps"] >= 2 and record["median"] > 0
    assert record["bit_identical"] is True
    cp = record.get("controller_path")
    assert cp is None or cp["median"] > 0
    # The embedded run telemetry (ISSUE 4): a schema-valid gol-metrics-v1
    # snapshot with the controller-path run's dispatch counts in it.
    from distributed_gol_tpu.obs import metrics as obs_metrics

    assert obs_metrics.check_embedded_metrics(record) == []
    snap = record["metrics"]
    assert obs_metrics.check_metrics_snapshot(snap) == []
    assert snap["counters"]["controller.dispatches"] >= 1


def test_decompose_pilot_record_shape():
    from tools import decompose

    record = decompose.pilot_record()
    assert record["pilot"] is True
    assert measure.check_headline_stats(record) == []
    # The decomposition structure: floor + settled + geometry A/B rows
    # with bit-identity, the cap sweep, and the per-launch term fit.
    assert record["floor"]["median"] > 0
    assert record["settled"]["skip_fraction"] is not None
    geoms = record["geometries"]
    assert set(geoms) == {"m96c256", "m64c128"}
    for row in geoms.values():
        assert row["bit_identical"] is True
        assert row["median"] > 0
    assert record["col_window"] == 256  # wp=512: the column tier engages
    assert geoms["m64c128"]["col_window"] == 128
    terms = record["per_launch_terms"]
    assert terms["floor_us_per_launch"] > 0
    assert "us_per_active_stripe" in terms
    assert record["caps"]["512"]["skip_fraction"] is not None


def test_metrics_overhead_within_rep_spread():
    """The ISSUE-4 acceptance bar at pilot scale: a metrics-on controller-
    path run's rate is within the measured rep spread of metrics-off —
    overhead is noise.  Interleaved A/B reps with medians, exactly the
    bench_faults methodology: background-load drift on a shared rig hits
    both arms alike, and the tolerance is each arm's OWN measured
    inter-rep envelope (a single on-vs-off pair flaked ~70% apart under
    load — review finding), floored for the quiet-rig case where both
    envelopes land tiny."""
    import bench
    from distributed_gol_tpu.utils import measure

    off_rates, on_rates = [], []
    off_stats: dict = {}
    on_stats: dict = {}
    for _ in range(3):
        off_stats = {}
        gps, _ = bench.bench_controller_path(
            256,
            budget_seconds=2.0,
            superstep=256,
            params_overrides=dict(metrics=False, flight_recorder_depth=0),
            out_stats=off_stats,
        )
        off_rates.append(gps)
        on_stats = {}
        gps, _ = bench.bench_controller_path(
            256, budget_seconds=2.0, superstep=256, out_stats=on_stats
        )
        on_rates.append(gps)
    off_rates = [r for r in off_rates if r > 0]
    on_rates = [r for r in on_rates if r > 0]
    assert off_rates and on_rates, (off_rates, on_rates)
    # metrics=False must actually disable: the off run's delta is empty.
    assert not off_stats["metrics"]["counters"]
    assert on_stats["metrics"]["counters"]["controller.dispatches"] >= 1
    med_off = measure.median(off_rates)
    med_on = measure.median(on_rates)
    envelope = (
        (measure.spread(off_rates) if len(off_rates) > 1 else 0.0)
        + (measure.spread(on_rates) if len(on_rates) > 1 else 0.0)
    )
    tol = max(0.3, envelope)
    rel = abs(med_on - med_off) / med_off
    assert rel <= tol, (
        f"metrics-on median {med_on:,.0f} vs off {med_off:,.0f}: "
        f"{rel:.1%} apart, tolerance {tol:.1%} "
        f"(off reps {off_rates}, on reps {on_rates})"
    )


def test_geometry_cli_spelling():
    """bench.py --plan-geometry parses to the same PlanGeometry the
    candidates enumerate (no subprocess: just the parse + install)."""
    from distributed_gol_tpu.ops import pallas_packed as pp

    prev = pp.plan_geometry()
    try:
        pp.set_plan_geometry(pp.PlanGeometry(64, 128))
        assert pp.plan_geometry().label == "m64c128"
        assert pp.plan_geometry() in pp.geometry_candidates()
    finally:
        pp.set_plan_geometry(prev)
