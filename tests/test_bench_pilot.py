"""Tier-1 smoke for the bench/decompose harnesses (round-6 satellite):
a bench-harness regression must fail tests, not burn a TPU session.

``bench.py --pilot`` runs in a subprocess (the driver's real invocation
path: stdout must carry exactly one lint-clean JSON line);
``tools/decompose.py``'s pilot runs in-process (it shares this process's
jax) — both at toy scale, both producing the full round-6 record shape.
"""

import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

from distributed_gol_tpu.utils import measure  # noqa: E402


def test_bench_pilot_record_shape(tmp_path):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        GOL_BENCH_NO_PROBE="1",  # skip the wedged-backend probe subprocess
        XLA_FLAGS="",  # no virtual mesh needed; keep startup lean
    )
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--pilot"],
        capture_output=True,
        text=True,
        timeout=420,  # the pilot grew telemetry + tracing + timecomp arms
        cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    # stdout is EXACTLY one JSON line (the driver contract).
    lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(lines) == 1, proc.stdout
    record = json.loads(lines[0])
    assert record["pilot"] is True
    assert record["unit"] == "generations/sec"
    # Every headline row carries {reps, median, spread} — the round-6
    # acceptance bar, machine-checked.
    assert measure.check_headline_stats(record) == []
    assert record["reps"] >= 2 and record["median"] > 0
    assert record["bit_identical"] is True
    cp = record.get("controller_path")
    assert cp is None or cp["median"] > 0
    # The embedded run telemetry (ISSUE 4): a schema-valid gol-metrics-v1
    # snapshot with the controller-path run's dispatch counts in it.
    from distributed_gol_tpu.obs import metrics as obs_metrics

    assert obs_metrics.check_embedded_metrics(record) == []
    snap = record["metrics"]
    assert obs_metrics.check_metrics_snapshot(snap) == []
    assert snap["counters"]["controller.dispatches"] >= 1
    # Telemetry-overhead arm (ISSUE 12): the interleaved sampler-on/off
    # A/B ran, carries full stats, and lands within the rep spread —
    # the tier-1 proof of the sampler-overhead acceptance bar.
    arm = record["telemetry_overhead"]
    assert arm["sampler_off"]["median"] > 0 and arm["median"] > 0
    assert arm["within_rep_spread"] is True, (
        f"sampler overhead {arm['overhead_rel']:.1%} exceeds the "
        f"measured rep envelope {arm['tolerance']:.1%} "
        f"(on {arm['rates']}, off {arm['sampler_off']['rates']})"
    )
    # Tracing-overhead arm (ISSUE 15): request trace on vs off,
    # interleaved, within the rep spread — the tier-1 proof of the
    # always-on tracing acceptance bar.
    arm = record["tracing_overhead"]
    assert arm["tracing_off"]["median"] > 0 and arm["median"] > 0
    assert arm["within_rep_spread"] is True, (
        f"tracing overhead {arm['overhead_rel']:.1%} exceeds the "
        f"measured rep envelope {arm['tolerance']:.1%} "
        f"(on {arm['rates']}, off {arm['tracing_off']['rates']})"
    )
    # Collector-overhead arm (ISSUE 19): fleet scrape on vs off,
    # interleaved, within the rep spread — the tier-1 proof that being
    # scraped costs a pod nothing it can feel.
    arm = record["collector_overhead"]
    assert arm["scrape_off"]["median"] > 0 and arm["median"] > 0
    assert arm["within_rep_spread"] is True, (
        f"collector overhead {arm['overhead_rel']:.1%} exceeds the "
        f"measured rep envelope {arm['tolerance']:.1%} "
        f"(on {arm['rates']}, off {arm['scrape_off']['rates']})"
    )
    # Wire-hardening arm (ISSUE 20): every wire guard on vs off over
    # fresh-connection /healthz round-trips, interleaved, within the rep
    # spread — the tier-1 proof that hardening the wire costs the clean
    # path nothing it can feel.
    arm = record["wire_overhead"]
    assert arm["unit"] == "requests/sec"
    assert arm["hardening_off"]["median"] > 0 and arm["median"] > 0
    assert arm["within_rep_spread"] is True, (
        f"wire-hardening overhead {arm['overhead_rel']:.1%} exceeds the "
        f"measured rep envelope {arm['tolerance']:.1%} "
        f"(on {arm['rates']}, off {arm['hardening_off']['rates']})"
    )
    # Time-compression arm (ISSUE 16): the effective-rate row carries the
    # computed side (the stats lint refuses it otherwise — asserted here
    # through the real record), and the ash-dominated pilot board clears
    # the >=10x effective-vs-computed acceptance floor on any rig.
    arm = record["timecomp"]
    assert "effective" in arm["unit"]
    assert arm["median"] > 0 and arm["computed_gens_per_s"] > 0
    assert isinstance(arm["effective_turns"], int)
    assert isinstance(arm["computed_turns"], int)
    assert arm["computed_turns"] < arm["effective_turns"]
    assert arm["speedup"] >= 10, (
        f"timecomp speedup {arm['speedup']} below the 10x floor "
        f"(effective {arm['median']:,.0f}, computed "
        f"{arm['computed_gens_per_s']:,.0f} gens/s)"
    )
    assert arm["dense"]["median"] > 0
    assert arm["timecomp_counters"]["timecomp.skipped_turns"] > 0
    # The record survives the bench gate against itself (zero drift),
    # end to end through the CLI.
    from tools import bench_gate

    path = tmp_path / "pilot.json"
    path.write_text(json.dumps(record))
    assert bench_gate.main([str(path), str(path), "--quiet"]) == 0


def test_committed_netchaos_artifact_pins_wire_verdicts():
    """The committed ISSUE-20 artifact carries both wire verdicts: the
    chaos arm observed at least the injected latency (the fault injector
    actually fired), and the hardened-on/off clean-path overhead landed
    within the recording rig's rep spread."""
    from distributed_gol_tpu.obs import metrics as obs_metrics

    record = json.loads((REPO / "BENCH_NETCHAOS_PR20.json").read_text())
    assert measure.check_headline_stats(record) == []
    assert obs_metrics.check_embedded_metrics(record) == []
    assert record["unit"] == "requests/sec"
    assert record["faults_fired"] > 0
    assert record["injected_latency_seconds"] > 0
    # The chaos arm must be at least as slow as the injected delay
    # accounts for (proxy hop overhead rides on top, so >=).
    assert (
        record["observed_added_seconds"]
        >= record["injected_latency_seconds"] * 0.5
    )
    assert record["clean"]["median"] > record["median"]
    arm = record["wire_overhead"]
    assert arm["hardening_off"]["median"] > 0
    assert arm["within_rep_spread"] is True, (
        f"committed wire-hardening overhead {arm['overhead_rel']:.1%} "
        f"exceeds its recorded envelope {arm['tolerance']:.1%}"
    )


def test_decompose_pilot_record_shape():
    from tools import decompose

    record = decompose.pilot_record()
    assert record["pilot"] is True
    assert measure.check_headline_stats(record) == []
    # The decomposition structure: floor + settled + geometry A/B rows
    # with bit-identity, the cap sweep, and the per-launch term fit.
    assert record["floor"]["median"] > 0
    assert record["settled"]["skip_fraction"] is not None
    geoms = record["geometries"]
    assert set(geoms) == {"m96c256", "m64c128"}
    for row in geoms.values():
        assert row["bit_identical"] is True
        assert row["median"] > 0
    assert record["col_window"] == 256  # wp=512: the column tier engages
    assert geoms["m64c128"]["col_window"] == 128
    terms = record["per_launch_terms"]
    assert terms["floor_us_per_launch"] > 0
    assert "us_per_active_stripe" in terms
    assert record["caps"]["512"]["skip_fraction"] is not None


def test_metrics_overhead_within_rep_spread():
    """The ISSUE-4 acceptance bar at pilot scale: a metrics-on controller-
    path run's rate is within the measured rep spread of metrics-off —
    overhead is noise.  Interleaved A/B reps with medians, exactly the
    bench_faults methodology: background-load drift on a shared rig hits
    both arms alike, and the tolerance is each arm's OWN measured
    inter-rep envelope (a single on-vs-off pair flaked ~70% apart under
    load — review finding), floored for the quiet-rig case where both
    envelopes land tiny."""
    import bench
    from distributed_gol_tpu.utils import measure

    off_rates, on_rates = [], []
    off_stats: dict = {}
    on_stats: dict = {}
    for _ in range(3):
        off_stats = {}
        gps, _ = bench.bench_controller_path(
            256,
            budget_seconds=1.5,
            superstep=256,
            params_overrides=dict(metrics=False, flight_recorder_depth=0),
            out_stats=off_stats,
        )
        off_rates.append(gps)
        on_stats = {}
        gps, _ = bench.bench_controller_path(
            256, budget_seconds=1.5, superstep=256, out_stats=on_stats
        )
        on_rates.append(gps)
    off_rates = [r for r in off_rates if r > 0]
    on_rates = [r for r in on_rates if r > 0]
    assert off_rates and on_rates, (off_rates, on_rates)
    # metrics=False must actually disable: the off run's delta is empty.
    assert not off_stats["metrics"]["counters"]
    assert on_stats["metrics"]["counters"]["controller.dispatches"] >= 1
    med_off = measure.median(off_rates)
    med_on = measure.median(on_rates)
    envelope = (
        (measure.spread(off_rates) if len(off_rates) > 1 else 0.0)
        + (measure.spread(on_rates) if len(on_rates) > 1 else 0.0)
    )
    tol = max(0.3, envelope)
    rel = abs(med_on - med_off) / med_off
    assert rel <= tol, (
        f"metrics-on median {med_on:,.0f} vs off {med_off:,.0f}: "
        f"{rel:.1%} apart, tolerance {tol:.1%} "
        f"(off reps {off_rates}, on reps {on_rates})"
    )


def _row(metric, median, spread, unit="generations/sec"):
    return {
        "metric": metric,
        "value": median,
        "unit": unit,
        "reps": 3,
        "median": median,
        "spread": spread,
        "rates": [median] * 3,
    }


class TestBenchGate:
    """tools/bench_gate.py mechanics (ISSUE 12 satellite): regressions
    beyond the recorded rep spread fail, spread-sized drift does not.
    Cross-rig number comparisons only mean anything on the recording
    rig, so tier-1 gates the MECHANICS (plus the real pilot record vs
    itself, above)."""

    def test_regression_beyond_spread_fails(self):
        from tools import bench_gate

        base = _row("gol_x", 1000.0, 0.05)
        fresh = _row("gol_x", 800.0, 0.05)  # -20% vs ±15% tolerance
        regressions, _ = bench_gate.compare(fresh, base)
        assert len(regressions) == 1
        assert "gol_x" in regressions[0]

    def test_drift_within_spread_passes(self):
        from tools import bench_gate

        base = _row("gol_x", 1000.0, 0.10)
        fresh = _row("gol_x", 930.0, 0.05)  # -7% vs ±20% tolerance
        regressions, notes = bench_gate.compare(fresh, base)
        assert regressions == []
        assert any("ok gol_x" in n for n in notes)

    def test_latency_rows_regress_upward(self):
        from tools import bench_gate

        base = _row("gol_lat", 0.010, 0.02, unit="seconds")
        faster = _row("gol_lat", 0.005, 0.02, unit="seconds")
        slower = _row("gol_lat", 0.020, 0.02, unit="seconds")
        assert bench_gate.compare(faster, base)[0] == []
        assert len(bench_gate.compare(slower, base)[0]) == 1

    def test_nested_rows_matched_and_one_sided_rows_noted(self):
        from tools import bench_gate

        base = {
            **_row("gol_top", 100.0, 0.05),
            "controller_path": _row("gol_cp", 50.0, 0.05),
            "only_in_base": _row("gol_gone", 1.0, 0.0),
        }
        fresh = {
            **_row("gol_top", 99.0, 0.05),
            "controller_path": _row("gol_cp", 20.0, 0.05),  # regressed
        }
        regressions, notes = bench_gate.compare(fresh, base)
        assert len(regressions) == 1 and "gol_cp" in regressions[0]
        assert any("gol_gone" in n and "only in baseline" in n
                   for n in notes)

    def test_cli_rejects_unlinted_fresh_record(self, tmp_path):
        from tools import bench_gate

        bad = {"metric": "gol_bare", "value": 123.0, "unit": "g/s"}
        p = tmp_path / "bad.json"
        p.write_text(json.dumps(bad))
        assert bench_gate.main([str(p), str(p)]) == 2

    def test_committed_baseline_parses_and_self_gates(self):
        """The committed pilot artifact is gate-compatible: comparing it
        against itself is clean (the pilot-sized tier-1 invocation runs
        against the FRESH record in test_bench_pilot_record_shape)."""
        from tools import bench_gate

        baseline = REPO / "BENCH_PILOT_PR3.json"
        record = json.loads(baseline.read_text())
        regressions, _ = bench_gate.compare(record, record)
        assert regressions == []
        rows = bench_gate.headline_rows(record)
        assert rows, "baseline carries no gateable rows?"


def test_geometry_cli_spelling():
    """bench.py --plan-geometry parses to the same PlanGeometry the
    candidates enumerate (no subprocess: just the parse + install)."""
    from distributed_gol_tpu.ops import pallas_packed as pp

    prev = pp.plan_geometry()
    try:
        pp.set_plan_geometry(pp.PlanGeometry(64, 128))
        assert pp.plan_geometry().label == "m64c128"
        assert pp.plan_geometry() in pp.geometry_candidates()
    finally:
        pp.set_plan_geometry(prev)
