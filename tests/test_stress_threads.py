"""Race-analog stress job (SURVEY.md §5).

The reference is channel-first Go whose race story is ``go test -race``;
the rebuild's host side is Python threads around a queue, so the analog is
a stress test hammering the controller's thread-crossing surfaces —
pause/resume toggles, snapshot requests, session checkpoint reads, and the
alive-count ticker — from multiple threads at once, under ``faulthandler``
so a deadlock dumps every stack instead of hanging CI silently.

Invariants checked: the stream stays well-formed (one FinalTurnComplete,
sentinel last), StateChange events strictly alternate Paused/Executing,
every snapshot request produces exactly one ImageOutputComplete + file,
and the run detaches cleanly with a resumable checkpoint.
"""

import faulthandler
import queue
import threading
import time

import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.session import Session

PAUSE_TOGGLES = 40  # even: ends unpaused
SNAPSHOTS = 12


@pytest.fixture(autouse=True)
def watchdog():
    # A wedged queue/lock interaction should dump all thread stacks and
    # fail loudly, not hang the suite.
    faulthandler.dump_traceback_later(120, exit=True)
    yield
    faulthandler.cancel_dump_traceback_later()


def test_threaded_pause_snapshot_checkpoint_stress(tmp_path, input_images):
    params = gol.Params(
        turns=10**6,
        image_width=64,
        image_height=64,
        images_dir=input_images,
        out_dir=tmp_path,
        superstep=2,
        ticker_period=0.01,  # hammer the ticker thread too
        engine="roll",
    )
    session = Session()
    events: queue.Queue = queue.Queue()
    keys: queue.Queue = queue.Queue()
    run_thread = gol.start(params, events, keys, session)

    stop = threading.Event()
    seen: list = []
    collector_done = threading.Event()

    def collect():
        while True:
            e = events.get()
            seen.append(e)
            if e is None:
                collector_done.set()
                return

    def toggle_pause():
        for _ in range(PAUSE_TOGGLES):
            keys.put("p")
            time.sleep(0.005)

    def snapshot():
        for _ in range(SNAPSHOTS):
            keys.put("s")
            time.sleep(0.02)

    def read_checkpoints():
        # The resume-negotiation path racing the pause writes; it must
        # never throw or corrupt state (any result is legal mid-run).
        while not stop.is_set():
            session.check_states(64, 64)
            time.sleep(0.002)

    threads = [
        threading.Thread(target=collect, daemon=True),
        threading.Thread(target=toggle_pause, daemon=True),
        threading.Thread(target=snapshot, daemon=True),
        threading.Thread(target=read_checkpoints, daemon=True),
    ]
    for t in threads:
        t.start()
    for t in threads[1:3]:
        t.join(timeout=60)
        assert not t.is_alive(), "stress thread wedged"
    stop.set()
    threads[3].join(timeout=10)

    keys.put("q")  # detach: parks a checkpoint, ends the stream
    assert collector_done.wait(timeout=60), "event stream never ended"
    run_thread.join(timeout=10)
    assert not run_thread.is_alive()

    # Stream shape: sentinel last, exactly one final event.
    assert seen[-1] is None
    finals = [e for e in seen if isinstance(e, gol.FinalTurnComplete)]
    assert len(finals) == 1

    # StateChange alternation: paused/executing strictly interleave until
    # the quitting transition (single-threaded controller discipline held).
    changes = [
        e.new_state
        for e in seen
        if isinstance(e, gol.StateChange) and e.new_state != gol.State.QUITTING
    ]
    assert len(changes) == PAUSE_TOGGLES
    for i, s in enumerate(changes):
        want = gol.State.PAUSED if i % 2 == 0 else gol.State.EXECUTING
        assert s == want, f"StateChange[{i}] = {s}, want {want}"

    # Every snapshot produced its event and its file (distinct names).
    snaps = [e for e in seen if isinstance(e, gol.ImageOutputComplete)]
    assert len(snaps) == SNAPSHOTS
    for e in snaps:
        assert (tmp_path / f"{e.filename}.pgm").exists()

    # The detach parked a resumable checkpoint at the final turn.
    ckpt = session.check_states(64, 64)
    assert ckpt is not None
    assert ckpt.turn == finals[0].completed_turns
