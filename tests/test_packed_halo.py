"""Sharded packed engine: bit-identity with single-device engines on the
hermetic 8-virtual-device CPU mesh (conftest), all mesh shapes including
word-granular x-sharding."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_gol_tpu.models.life import CONWAY, HIGHLIFE
from distributed_gol_tpu.ops import packed
from distributed_gol_tpu.parallel import packed_halo
from distributed_gol_tpu.parallel.mesh import make_mesh
from tests.conftest import random_board
from tests.oracle import oracle_run


@pytest.mark.parametrize("mesh_shape", [(1, 1), (2, 1), (1, 2), (2, 2), (8, 1), (1, 8), (2, 4)])
def test_sharded_matches_oracle(rng, mesh_shape):
    """64x256 board over every 8-device factorisation; every device owns at
    least one uint32 word column."""
    b = random_board(rng, 64, 256)
    mesh = make_mesh(mesh_shape)
    p = jax.device_put(np.asarray(packed.pack(jnp.asarray(b))), packed_halo.packed_sharding(mesh))
    run = packed_halo.sharded_superstep(mesh, CONWAY)
    got = np.asarray(packed.unpack(jax.device_get(run(p, 10))))
    np.testing.assert_array_equal(got, oracle_run(b, 10))


def test_sharded_counts_match_single_device(rng):
    b = random_board(rng, 32, 128)
    mesh = make_mesh((2, 2))
    p = jax.device_put(np.asarray(packed.pack(jnp.asarray(b))), packed_halo.packed_sharding(mesh))
    run = packed_halo.sharded_steps_with_counts(mesh, CONWAY)
    final, counts = run(p, 12)
    ref_final, ref_counts = packed.steps_with_counts(packed.pack(jnp.asarray(b)), CONWAY, 12)
    np.testing.assert_array_equal(np.asarray(packed.unpack(final)), np.asarray(packed.unpack(ref_final)))
    np.testing.assert_array_equal(np.asarray(counts), np.asarray(ref_counts))


def test_sharded_rule_zoo(rng):
    b = random_board(rng, 16, 64)
    mesh = make_mesh((2, 2))
    p = jax.device_put(np.asarray(packed.pack(jnp.asarray(b))), packed_halo.packed_sharding(mesh))
    run = packed_halo.sharded_superstep(mesh, HIGHLIFE)
    got = np.asarray(packed.unpack(jax.device_get(run(p, 6))))
    np.testing.assert_array_equal(got, oracle_run(b, 6, HIGHLIFE))
