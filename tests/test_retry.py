"""Host-level dispatch retry — the broker re-queue analog.

Reference: ``broker/broker.go:67-73`` re-queues a failed worker RPC back
onto the publish channel (SURVEY.md §5 failure mechanism 2).  The TPU
rebuild's equivalent: the controller retries a failed device superstep once
from the last good board; a second failure parks that board as a paused
checkpoint on the session (resumable exactly like a 'q' detach) and the
stream still ends with the sentinel.
"""

import queue

import numpy as np
import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.events import DispatchError
from distributed_gol_tpu.engine.session import Session


class FlakyBackend(Backend):
    """Injects ``fail`` consecutive dispatch failures, then works.

    Overrides ``run_turns_async`` — the seam both the pipelined headless
    path and the sync ``run_turns`` retry path go through — so a failure
    here surfaces at issue time, like a Python-level dispatch error."""

    def __init__(self, params, fail: int):
        super().__init__(params)
        self.failures_left = fail
        self.dispatches = 0

    def run_turns_async(self, board, turns):
        self.dispatches += 1
        if self.failures_left:
            self.failures_left -= 1
            raise RuntimeError("injected device failure")
        return super().run_turns_async(board, turns)


class _PoisonCount:
    """A device-count stand-in whose resolution fails — models a dispatch
    that issues fine but whose computation dies on device (the async
    failure mode: the error surfaces when the count is forced)."""

    def __init__(self, real, poisoned: bool):
        self._real = real
        self._poisoned = poisoned

    def __int__(self):
        if self._poisoned:
            raise RuntimeError("injected resolve-time failure")
        return int(self._real)


class ResolveFlakyBackend(Backend):
    """Injects ``fail`` dispatches whose counts fail to RESOLVE (the board
    result is also poisoned conceptually; the controller must discard any
    dispatch speculatively issued on top of it)."""

    def __init__(self, params, fail: int):
        super().__init__(params)
        self.failures_left = fail
        self.dispatches = 0

    def run_turns_async(self, board, turns):
        self.dispatches += 1
        new_board, count = super().run_turns_async(board, turns)
        if self.failures_left:
            self.failures_left -= 1
            return new_board, _PoisonCount(count, True)
        return new_board, count


def make_params(tmp_path, input_images, **kw):
    defaults = dict(
        turns=20,
        image_width=16,
        image_height=16,
        images_dir=input_images,
        out_dir=tmp_path,
        superstep=5,
        engine="roll",
    )
    defaults.update(kw)
    return gol.Params(**defaults)


def drain(events):
    out = []
    while (e := events.get(timeout=30)) is not None:
        out.append(e)
    return out


def reference_final(params, tmp_path, input_images):
    """The same run through an unfaulted backend, for comparison."""
    events: queue.Queue = queue.Queue()
    gol.run(make_params(tmp_path / "ref", input_images), events)
    final = [e for e in drain(events) if isinstance(e, gol.FinalTurnComplete)]
    return final[0]


def test_single_failure_is_retried_and_run_completes(tmp_path, input_images):
    (tmp_path / "ref").mkdir()
    params = make_params(tmp_path, input_images)
    want = reference_final(params, tmp_path, input_images)

    backend = FlakyBackend(params, fail=1)
    session = Session()
    events: queue.Queue = queue.Queue()
    gol.run(params, events, session=session, backend=backend)
    stream = drain(events)

    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert len(errors) == 1 and errors[0].will_retry
    assert "injected device failure" in errors[0].error

    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)]
    assert len(final) == 1
    assert final[0].completed_turns == params.turns
    # Retry restarted from the last good board: results identical.
    assert sorted(final[0].alive) == sorted(want.alive)
    # No checkpoint left behind — the run completed.
    assert session.check_states(16, 16) is None


def test_double_failure_checkpoints_and_aborts(tmp_path, input_images):
    params = make_params(tmp_path, input_images, superstep=4)
    backend = FlakyBackend(params, fail=2)
    session = Session()
    events: queue.Queue = queue.Queue()

    with pytest.raises(RuntimeError, match="injected device failure"):
        gol.run(params, events, session=session, backend=backend)

    # Sentinel guaranteed even on the failure path.
    stream = []
    while (e := events.get(timeout=5)) is not None:
        stream.append(e)

    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [True, False]
    assert errors[1].checkpointed

    # The parked checkpoint is the untouched initial board at turn 0,
    # resumable by a fresh controller (the 'q'-detach contract).
    ckpt = session.check_states(16, 16)
    assert ckpt is not None and ckpt.turn == 0
    from distributed_gol_tpu.engine.pgm import read_pgm

    start = read_pgm(input_images / "16x16.pgm")
    assert np.array_equal(ckpt.world, start)


def test_resolve_time_failure_is_retried(tmp_path, input_images):
    """A dispatch that issues fine but dies on device surfaces when its
    count is forced; the pipelined controller must retry it AND discard
    the dispatch it speculatively issued on the poisoned board."""
    (tmp_path / "ref").mkdir()
    params = make_params(tmp_path, input_images)
    want = reference_final(params, tmp_path, input_images)

    backend = ResolveFlakyBackend(params, fail=1)
    session = Session()
    events: queue.Queue = queue.Queue()
    gol.run(params, events, session=session, backend=backend)
    stream = drain(events)

    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert len(errors) == 1 and errors[0].will_retry
    assert "resolve-time" in errors[0].error

    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.completed_turns == params.turns
    assert sorted(final.alive) == sorted(want.alive)
    # The TurnComplete stream stays dense despite the discarded
    # speculative dispatch.
    tc = [e.completed_turns for e in stream if isinstance(e, gol.TurnComplete)]
    assert tc == list(range(1, params.turns + 1))
    assert session.check_states(16, 16) is None


def test_resolve_time_terminal_failure_checkpoints(tmp_path, input_images):
    """fail=3: the first resolve fails, its speculative successor is
    poisoned too (discarded), and the sync retry also fails -> park the
    last good board, emit the terminal DispatchError, raise."""
    params = make_params(tmp_path, input_images, superstep=4)
    backend = ResolveFlakyBackend(params, fail=3)
    session = Session()
    events: queue.Queue = queue.Queue()

    with pytest.raises(RuntimeError, match="resolve-time"):
        gol.run(params, events, session=session, backend=backend)
    stream = []
    while (e := events.get(timeout=5)) is not None:
        stream.append(e)

    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [True, False]
    assert errors[1].checkpointed
    ckpt = session.check_states(16, 16)
    assert ckpt is not None and ckpt.turn == 0


def test_failure_mid_run_checkpoints_last_good_turn(tmp_path, input_images):
    """Failures after progress park the *latest* completed board."""
    params = make_params(tmp_path, input_images, superstep=4, turns=20)

    class FailAfter(FlakyBackend):
        def run_turns_async(self, board, turns):
            # Succeed twice (8 turns), then fail the rest of the run.
            if self.dispatches >= 2:
                self.failures_left = 2
            return super().run_turns_async(board, turns)

    backend = FailAfter(params, fail=0)
    session = Session()
    events: queue.Queue = queue.Queue()
    with pytest.raises(RuntimeError):
        gol.run(params, events, session=session, backend=backend)
    while events.get(timeout=5) is not None:
        pass

    ckpt = session.check_states(16, 16)
    assert ckpt is not None and ckpt.turn == 8

    # And a fresh run resumes from it, finishing the remaining turns.
    events2: queue.Queue = queue.Queue()
    gol.run(make_params(tmp_path, input_images, turns=20), events2, session=session)
    stream = [e for e in drain(events2)]
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.completed_turns == 20
