"""Host-level dispatch retry — the broker re-queue analog.

Reference: ``broker/broker.go:67-73`` re-queues a failed worker RPC back
onto the publish channel (SURVEY.md §5 failure mechanism 2).  The TPU
rebuild's equivalent: the controller retries a failed device superstep once
from the last good board; a second failure parks that board as a paused
checkpoint on the session (resumable exactly like a 'q' detach) and the
stream still ends with the sentinel.
"""

import queue

import numpy as np
import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.events import DispatchError
from distributed_gol_tpu.engine.session import Session


class FlakyBackend(Backend):
    """Injects ``fail`` consecutive dispatch failures, then works."""

    def __init__(self, params, fail: int):
        super().__init__(params)
        self.failures_left = fail
        self.dispatches = 0

    def run_turns(self, board, turns):
        self.dispatches += 1
        if self.failures_left:
            self.failures_left -= 1
            raise RuntimeError("injected device failure")
        return super().run_turns(board, turns)


def make_params(tmp_path, input_images, **kw):
    defaults = dict(
        turns=20,
        image_width=16,
        image_height=16,
        images_dir=input_images,
        out_dir=tmp_path,
        superstep=5,
        engine="roll",
    )
    defaults.update(kw)
    return gol.Params(**defaults)


def drain(events):
    out = []
    while (e := events.get(timeout=30)) is not None:
        out.append(e)
    return out


def reference_final(params, tmp_path, input_images):
    """The same run through an unfaulted backend, for comparison."""
    events: queue.Queue = queue.Queue()
    gol.run(make_params(tmp_path / "ref", input_images), events)
    final = [e for e in drain(events) if isinstance(e, gol.FinalTurnComplete)]
    return final[0]


def test_single_failure_is_retried_and_run_completes(tmp_path, input_images):
    (tmp_path / "ref").mkdir()
    params = make_params(tmp_path, input_images)
    want = reference_final(params, tmp_path, input_images)

    backend = FlakyBackend(params, fail=1)
    session = Session()
    events: queue.Queue = queue.Queue()
    gol.run(params, events, session=session, backend=backend)
    stream = drain(events)

    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert len(errors) == 1 and errors[0].will_retry
    assert "injected device failure" in errors[0].error

    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)]
    assert len(final) == 1
    assert final[0].completed_turns == params.turns
    # Retry restarted from the last good board: results identical.
    assert sorted(final[0].alive) == sorted(want.alive)
    # No checkpoint left behind — the run completed.
    assert session.check_states(16, 16) is None


def test_double_failure_checkpoints_and_aborts(tmp_path, input_images):
    params = make_params(tmp_path, input_images, superstep=4)
    backend = FlakyBackend(params, fail=2)
    session = Session()
    events: queue.Queue = queue.Queue()

    with pytest.raises(RuntimeError, match="injected device failure"):
        gol.run(params, events, session=session, backend=backend)

    # Sentinel guaranteed even on the failure path.
    stream = []
    while (e := events.get(timeout=5)) is not None:
        stream.append(e)

    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [True, False]
    assert errors[1].checkpointed

    # The parked checkpoint is the untouched initial board at turn 0,
    # resumable by a fresh controller (the 'q'-detach contract).
    ckpt = session.check_states(16, 16)
    assert ckpt is not None and ckpt.turn == 0
    from distributed_gol_tpu.engine.pgm import read_pgm

    start = read_pgm(input_images / "16x16.pgm")
    assert np.array_equal(ckpt.world, start)


def test_failure_mid_run_checkpoints_last_good_turn(tmp_path, input_images):
    """Failures after progress park the *latest* completed board."""
    params = make_params(tmp_path, input_images, superstep=4, turns=20)

    class FailAfter(FlakyBackend):
        def run_turns(self, board, turns):
            # Succeed twice (8 turns), then fail the rest of the run.
            if self.dispatches >= 2:
                self.failures_left = 2
            return super().run_turns(board, turns)

    backend = FailAfter(params, fail=0)
    session = Session()
    events: queue.Queue = queue.Queue()
    with pytest.raises(RuntimeError):
        gol.run(params, events, session=session, backend=backend)
    while events.get(timeout=5) is not None:
        pass

    ckpt = session.check_states(16, 16)
    assert ckpt is not None and ckpt.turn == 8

    # And a fresh run resumes from it, finishing the remaining turns.
    events2: queue.Queue = queue.Queue()
    gol.run(make_params(tmp_path, input_images, turns=20), events2, session=session)
    stream = [e for e in drain(events2)]
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.completed_turns == 20
