"""Host-level dispatch retry policy — the broker re-queue analog, generalised.

Reference: ``broker/broker.go:67-73`` re-queues a failed worker RPC back
onto the publish channel (SURVEY.md §5 failure mechanism 2).  The TPU
rebuild's equivalent is a policy (ISSUE 2): the controller retries a failed
device superstep from the last good board up to ``Params.retry_limit``
times with deterministic exponential backoff; a terminal failure (retries
exhausted / ``failure_budget`` spent) parks that board as a paused
checkpoint on the session (resumable exactly like a 'q' detach) and the
stream still ends with the sentinel.

All failures here are produced by the deterministic fault-injection
harness (``distributed_gol_tpu.testing.faults``), which replaced the
ad-hoc flaky backends this file used to carry; the full tier × fault-kind
matrix lives in ``test_chaos.py``.  Boards are seeded soups, so the suite
is hermetic (no reference data needed).
"""

import queue
import time

import numpy as np
import pytest

import distributed_gol_tpu as gol
from distributed_gol_tpu.engine.backend import Backend
from distributed_gol_tpu.engine.events import DispatchError
from distributed_gol_tpu.engine.session import Session
from distributed_gol_tpu.testing.faults import (
    Fault,
    FaultInjectionBackend,
    FaultPlan,
)
from distributed_gol_tpu.utils.soup import random_soup


def make_params(tmp_path, **kw):
    defaults = dict(
        turns=20,
        image_width=16,
        image_height=16,
        soup_density=0.3,
        soup_seed=7,
        out_dir=tmp_path,
        superstep=5,
        engine="roll",
        cycle_check=0,  # keep the dispatch schedule = plan indices exact
    )
    defaults.update(kw)
    return gol.Params(**defaults)


def faulty(params, faults):
    return FaultInjectionBackend(Backend(params), FaultPlan(faults))


def drain(events):
    out = []
    while (e := events.get(timeout=30)) is not None:
        out.append(e)
    return out


def run_collecting(params, backend=None, session=None):
    session = session if session is not None else Session()
    events: queue.Queue = queue.Queue()
    gol.run(params, events, session=session, backend=backend)
    return drain(events), session


def reference_final(params, tmp_path):
    """The same run through an unfaulted backend, for comparison."""
    from dataclasses import replace

    ref_dir = tmp_path / "ref"
    ref_dir.mkdir(exist_ok=True)
    stream, _ = run_collecting(replace(params, out_dir=ref_dir))
    return [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]


def test_single_failure_is_retried_and_run_completes(tmp_path):
    params = make_params(tmp_path)
    want = reference_final(params, tmp_path)

    backend = faulty(params, [Fault(0, "issue")])
    stream, session = run_collecting(params, backend)

    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert len(errors) == 1 and errors[0].will_retry
    assert errors[0].attempt == 1
    assert "injected issue-time failure" in errors[0].error

    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)]
    assert len(final) == 1
    assert final[0].completed_turns == params.turns
    # Retry restarted from the last good board: results identical.
    assert sorted(final[0].alive) == sorted(want.alive)
    # No checkpoint left behind — the run completed.
    assert session.check_states(16, 16) is None


def test_double_failure_checkpoints_and_aborts(tmp_path):
    params = make_params(tmp_path, superstep=4)
    # The retry (dispatch 1) is faulted too: a burst that defeats the
    # default retry_limit=1 budget.
    backend = faulty(params, [Fault(0, "issue"), Fault(1, "issue")])
    session = Session()
    events: queue.Queue = queue.Queue()

    with pytest.raises(RuntimeError, match="injected issue-time failure"):
        gol.run(params, events, session=session, backend=backend)

    # Sentinel guaranteed even on the failure path.
    stream = []
    while (e := events.get(timeout=5)) is not None:
        stream.append(e)

    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [True, False]
    assert [e.attempt for e in errors] == [1, 2]
    assert errors[1].checkpointed

    # The parked checkpoint is the untouched initial board at turn 0,
    # resumable by a fresh controller (the 'q'-detach contract).
    ckpt = session.check_states(16, 16)
    assert ckpt is not None and ckpt.turn == 0
    start = random_soup(16, 16, 0.3, 7)
    assert np.array_equal(ckpt.world, start)


def test_resolve_time_failure_is_retried(tmp_path):
    """A dispatch that issues fine but dies on device surfaces when its
    count is forced; the pipelined controller must retry it AND discard
    the dispatch it speculatively issued on the poisoned board."""
    params = make_params(tmp_path)
    want = reference_final(params, tmp_path)

    backend = faulty(params, [Fault(0, "resolve")])
    stream, session = run_collecting(params, backend)

    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert len(errors) == 1 and errors[0].will_retry
    assert "resolve-time" in errors[0].error

    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.completed_turns == params.turns
    assert sorted(final.alive) == sorted(want.alive)
    # The TurnComplete stream stays dense despite the discarded
    # speculative dispatch.
    tc = [e.completed_turns for e in stream if isinstance(e, gol.TurnComplete)]
    assert tc == list(range(1, params.turns + 1))
    assert session.check_states(16, 16) is None


def test_resolve_time_terminal_failure_checkpoints(tmp_path):
    """A resolve-time burst: the first resolve fails, its speculative
    successor is poisoned too (discarded), and the sync retry also fails
    -> park the last good board, emit the terminal DispatchError, raise."""
    params = make_params(tmp_path, superstep=4)
    backend = faulty(
        params,
        [Fault(0, "resolve"), Fault(1, "resolve"), Fault(2, "resolve")],
    )
    session = Session()
    events: queue.Queue = queue.Queue()

    with pytest.raises(RuntimeError, match="resolve-time"):
        gol.run(params, events, session=session, backend=backend)
    stream = []
    while (e := events.get(timeout=5)) is not None:
        stream.append(e)

    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [True, False]
    assert errors[1].checkpointed
    ckpt = session.check_states(16, 16)
    assert ckpt is not None and ckpt.turn == 0


def test_failure_mid_run_checkpoints_last_good_turn(tmp_path):
    """Failures after progress park the *latest* completed board, and a
    fresh run resumes from it."""
    params = make_params(tmp_path, superstep=4, turns=20)
    # Dispatches 0 and 1 succeed (8 turns); dispatch 2 fails at issue and
    # its retry (dispatch 3) fails too.
    backend = faulty(params, [Fault(2, "issue"), Fault(3, "issue")])
    session = Session()
    events: queue.Queue = queue.Queue()
    with pytest.raises(RuntimeError):
        gol.run(params, events, session=session, backend=backend)
    while events.get(timeout=5) is not None:
        pass

    ckpt = session.check_states(16, 16)
    assert ckpt is not None and ckpt.turn == 8

    # And a fresh run resumes from it, finishing the remaining turns.
    session.pause(True, world=ckpt.world, turn=ckpt.turn)
    stream, _ = run_collecting(make_params(tmp_path, turns=20), session=session)
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert final.completed_turns == 20


# -- the configurable policy (ISSUE 2) ----------------------------------------


def test_retry_limit_exhausts_a_longer_burst(tmp_path):
    """retry_limit=3 survives a 3-failure burst that would kill the
    default policy; every attempt is announced with its count."""
    params = make_params(tmp_path, retry_limit=3)
    want = reference_final(params, tmp_path)

    backend = faulty(
        params, [Fault(0, "issue"), Fault(1, "issue"), Fault(2, "issue")]
    )
    stream, session = run_collecting(params, backend)

    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [True, True, True]
    assert [e.attempt for e in errors] == [1, 2, 3]
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert sorted(final.alive) == sorted(want.alive)
    assert session.check_states(16, 16) is None


def test_retry_limit_zero_is_terminal_immediately(tmp_path):
    params = make_params(tmp_path, retry_limit=0, superstep=4)
    backend = faulty(params, [Fault(0, "issue")])
    session = Session()
    events: queue.Queue = queue.Queue()
    with pytest.raises(RuntimeError, match="injected issue-time failure"):
        gol.run(params, events, session=session, backend=backend)
    stream = []
    while (e := events.get(timeout=5)) is not None:
        stream.append(e)
    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [False]
    assert errors[0].attempt == 1 and errors[0].checkpointed
    assert backend.dispatches == 1  # no retry dispatch was issued
    assert session.check_states(16, 16) is not None


def test_backoff_is_deterministic_exponential(tmp_path):
    """base=0.05: the two retries sleep 0.05 then 0.1 seconds — the run
    must take at least their sum; the (tight) cap clamps the second."""
    params = make_params(
        tmp_path,
        retry_limit=3,
        retry_backoff_seconds=0.05,
        retry_backoff_max_seconds=0.08,
    )
    backend = faulty(params, [Fault(0, "issue"), Fault(1, "issue")])
    t0 = time.perf_counter()
    stream, _ = run_collecting(params, backend)
    elapsed = time.perf_counter() - t0
    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.attempt for e in errors] == [1, 2]
    # attempt-1 retry sleeps 0.05, attempt-2 retry sleeps min(0.1, 0.08).
    assert elapsed >= 0.13
    assert [e for e in stream if isinstance(e, gol.FinalTurnComplete)]


def test_failure_budget_caps_a_flapping_run(tmp_path):
    """failure_budget=1: the first failure retries, the second (over
    budget) is terminal even though retry_limit would allow more."""
    params = make_params(
        tmp_path, retry_limit=5, failure_budget=1, superstep=4
    )
    backend = faulty(
        params, [Fault(1, "issue"), Fault(3, "issue")]
    )  # two separated transients
    session = Session()
    events: queue.Queue = queue.Queue()
    with pytest.raises(RuntimeError):
        gol.run(params, events, session=session, backend=backend)
    stream = []
    while (e := events.get(timeout=5)) is not None:
        stream.append(e)
    errors = [e for e in stream if isinstance(e, DispatchError)]
    assert [e.will_retry for e in errors] == [True, False]
    # The terminal failure still parked a resumable checkpoint mid-run.
    ckpt = session.check_states(16, 16)
    assert ckpt is not None and ckpt.turn > 0


def test_latency_fault_is_not_a_failure(tmp_path):
    params = make_params(tmp_path)
    want = reference_final(params, tmp_path)
    backend = faulty(params, [Fault(1, "latency", seconds=0.05)])
    stream, _ = run_collecting(params, backend)
    assert not [e for e in stream if isinstance(e, DispatchError)]
    final = [e for e in stream if isinstance(e, gol.FinalTurnComplete)][0]
    assert sorted(final.alive) == sorted(want.alive)


# -- the plan value itself ----------------------------------------------------


def test_fault_plan_seeded_determinism_and_json_round_trip():
    a = FaultPlan.random(42, 64, p_fault=0.25, kinds=("issue", "hang"), burst=2)
    b = FaultPlan.random(42, 64, p_fault=0.25, kinds=("issue", "hang"), burst=2)
    assert a == b and len(a) > 0
    c = FaultPlan.random(43, 64, p_fault=0.25, kinds=("issue", "hang"), burst=2)
    assert a != c  # a different seed is a different schedule

    spec = (
        '{"seed": 42, "n_dispatches": 64, "p_fault": 0.25,'
        ' "kinds": ["issue", "hang"], "burst": 2}'
    )
    assert FaultPlan.from_json(spec) == a
    scripted = FaultPlan.from_json(
        '{"faults": [{"at": 3, "kind": "issue"},'
        ' {"at": 7, "kind": "latency", "seconds": 0.05}]}'
    )
    assert scripted.fault_at(3).kind == "issue"
    assert scripted.fault_at(7).seconds == 0.05
    assert scripted.fault_at(4) is None
    assert len(FaultPlan.from_json("{}")) == 0  # the clean-path plan

    with pytest.raises(ValueError):
        FaultPlan([Fault(1, "issue"), Fault(1, "resolve")])
    with pytest.raises(ValueError):
        Fault(0, "explode")
