"""The spectator relay tier suite (ISSUE 18).

Contracts, asserted hermetically on CPU over REAL loopback sockets:

- **Broadcast tree**: a depth-3 relay chain (gateway → r1 → r2 → r3)
  delivers a stream BYTE-IDENTICAL to a direct gateway spectator —
  same turns, same keyframe/delta kinds, same wire blobs — while the
  pod holds exactly one spectator socket per relay subtree.
- **Fan-out economics**: 256 viewers behind two chained relays cost
  the pod ONE spectator socket and 1.00 device fetches per published
  frame; every viewer reconstructs bit-identically to the final board.
- **Chaos**: a stalled downstream is isolated (siblings on schedule,
  the stalled viewer re-anchors via drop-oldest + cache resync and
  still converges); a killed mid-chain relay is resubscribed to with
  capped backoff and the new subscription's keyframe re-keyframes the
  subtree; deltas arriving across a seq gap are refused, never
  relayed.
- **Cache**: a late joiner after session end is served entirely from
  the relay's re-keyframe cache — zero upstream round trips, the pod's
  fetch counters do not move; a small-cache relay compacts its delta
  tail into a synthesized keyframe and still serves a correct board.
- **Hot-path pins**: the relay encodes each upstream frame exactly
  ONCE regardless of client count (single-serialize/multi-write); the
  FramePlane delta-encodes each published turn once per DISTINCT rect
  (the satellite-1 dedup); the ws codec's in-place mask/unmask rewrite
  is byte-for-byte identical to a naive RFC 6455 reference framer.
"""

import io
import json
import threading
import time

import numpy as np
import pytest

from distributed_gol_tpu.engine import frames as frames_lib
from distributed_gol_tpu.engine.events import FrameDelta, FrameReady
from distributed_gol_tpu.obs import metrics as obs_metrics
from distributed_gol_tpu.serve import (
    GatewayServer,
    RelayServer,
    ServeConfig,
    ServePlane,
)
from distributed_gol_tpu.serve import wire
from distributed_gol_tpu.serve import ws as ws_lib
from distributed_gol_tpu.serve.frames import FramePlane
from tools.gol_client import GolClient

#: Tight resubscribe knobs for chaos tests — outages heal in ~0.1 s
#: instead of the production 0.25 s → 5 s curve.
TIGHT = {"backoff_initial": 0.05, "backoff_max": 0.2,
         "connect_timeout": 5.0}


def spectate_spec(size: int, turns: int, seed: int = 11) -> dict:
    """A spectate-enabled wire spec: full-board viewport, cycle probe
    off so frame streams tile the whole run deterministically."""
    return {
        "params": {
            "width": size,
            "height": size,
            "turns": turns,
            "engine": "roll",
            "superstep": 4,
            "cycle_check": 0,
            "ticker_period": 60.0,
        },
        "soup": {"density": 0.3, "seed": seed},
        "spectate": True,
        "viewport": [0, 0, size, size],
    }


@pytest.fixture
def pod(tmp_path):
    plane = ServePlane(
        ServeConfig(max_sessions=4, telemetry_sample_seconds=0.1),
        checkpoint_root=tmp_path / "ckpt",
    )
    gateway = GatewayServer(plane, port=0)
    client = GolClient(gateway.url)
    yield plane, gateway, client
    gateway.close()
    plane.close()


def submit_spec(client: GolClient, tenant: str, spec: dict) -> dict:
    return client._request(
        "POST", "/v1/sessions", {"tenant": tenant, **spec}
    )


def wait_status(client, tenant, statuses, timeout=120.0) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = client.state(tenant)
        if st["status"] in statuses:
            return st
        time.sleep(0.05)
    raise AssertionError(
        f"{tenant} never reached {statuses}: {client.state(tenant)}"
    )


def pause_run(client, gateway, tenant, timeout=60.0) -> dict:
    """REST-pause the run and wait for the engine's authoritative
    ``StateChange("Paused")`` echo (not just the pause TARGET) — the
    deterministic attach point every relay/subscriber test anchors
    at, so streams compare exactly."""
    client.pause(tenant)
    session = gateway._sessions[tenant]
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = client.state(tenant)
        assert st["status"] != "completed", (
            "run completed before the pause landed — spec turns too low"
        )
        if session.paused:
            return st
        time.sleep(0.05)
    raise AssertionError(f"{tenant} never quiesced after pause")


def wait_until(fn, timeout=30.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def final_board(client, tenant: str, size: int) -> np.ndarray:
    """The final board via the controller replay ring (the oracle the
    relay tree never touches)."""
    with client.controller(tenant) as ctrl:
        while True:
            msg = ctrl.recv(timeout=30)
            if msg["type"] == "final":
                board = np.zeros((size, size), np.uint8)
                for x, y in msg["alive"]:
                    board[y, x] = 255
                return board
            if msg["type"] == "end":
                raise AssertionError("stream ended without a final")


def make_relay(upstream: str, turns: int, **kw) -> RelayServer:
    """A test relay sized so a full post-pause run fits its cache and
    queues (no drops, no compaction unless a test asks for them)."""
    opts = dict(
        cache_deltas=turns + 16, queue_depth=turns + 8, **TIGHT
    )
    opts.update(kw)
    return RelayServer(upstream, **opts)


class RawDrain:
    """One raw spectator socket, upstream-format bookkeeping included:
    every binary frame is recorded as ``(turn, kind, wire blob)`` and
    folded into a reconstruction buffer — the byte-level oracle the
    bit-identity assertions compare."""

    def __init__(self, host: str, port: int, path: str,
                 recv_buffer=None):
        self.ws = ws_lib.client_connect(
            host, port, path, timeout=30.0, recv_buffer=recv_buffer
        )
        self.hello = None
        self.frames: list[tuple[int, str, bytes]] = []
        self.buf = None
        self.turn = 0
        self.keyframes = 0
        self.ended = False
        self.error = None

    def step(self, timeout=60.0) -> bool:
        """Consume one ws message; False once the stream ended."""
        if self.ended:
            return False
        self.ws.settimeout(timeout)
        op, payload = self.ws.recv()
        if op == ws_lib.OP_TEXT:
            msg = json.loads(payload)
            if msg.get("type") == "hello":
                self.hello = msg
            elif msg.get("type") == "end":
                self.ended = True
            return not self.ended
        blob = bytes(payload)
        ev = wire.decode_frame_event(blob)
        if isinstance(ev, FrameReady):
            self.buf = np.array(ev.frame, dtype=np.uint8, copy=True)
            self.keyframes += 1
            kind = "keyframe"
        else:
            if self.buf is not None:
                frames_lib.apply_bands(self.buf, ev.bands)
            kind = "delta"
        self.turn = ev.completed_turns
        self.frames.append((ev.completed_turns, kind, blob))
        return True

    def drain(self, timeout=60.0):
        try:
            while self.step(timeout=timeout):
                pass
        except Exception as e:  # joined and re-raised by the caller
            self.error = e

    def by_turn(self) -> dict[int, tuple[str, bytes]]:
        out: dict[int, tuple[str, bytes]] = {}
        for turn, kind, blob in self.frames:
            assert turn not in out, f"duplicate frame for turn {turn}"
            out[turn] = (kind, blob)
        return out

    def close(self):
        self.ws.close()


def want_board(final: np.ndarray) -> np.ndarray:
    return (final != 0) * np.uint8(255)


# -- the broadcast tree --------------------------------------------------------


class TestRelayTree:
    def test_depth3_chain_bit_identical_vs_direct_oracle(self, pod):
        """gateway → r1 → r2 → r3: the leaf of a depth-3 chain and a
        direct gateway spectator attached at the same pause point see
        the SAME stream — identical turn sets, kinds, and wire blobs —
        and the pod carries one spectator socket per subtree edge."""
        plane, gateway, client = pod
        size, turns = 32, 400
        submit_spec(client, "alice", spectate_spec(size, turns))
        pause_run(client, gateway, "alice")

        upstream = (
            f"{gateway.url}/v1/sessions/alice/frames?queue={turns + 8}"
        )
        r1 = make_relay(upstream, turns)
        r2 = make_relay(r1.url + "/v1/frames", turns)
        r3 = make_relay(r2.url + "/v1/frames", turns)
        direct = leaf = None
        try:
            for r in (r1, r2, r3):
                wait_until(
                    lambda r=r: r.health()["connected"],
                    msg=f"relay {r.url} connected",
                )
            wait_until(
                lambda: gateway._n_spectators == 1,
                msg="r1's one upstream subscription",
            )
            direct = RawDrain(
                gateway.host, gateway.port,
                f"/v1/sessions/alice/frames?queue={turns + 8}",
            )
            leaf = RawDrain(
                r3.host, r3.port, f"/v1/frames?queue={turns + 8}"
            )
            wait_until(
                lambda: gateway._n_spectators == 2,
                msg="direct spectator registered",
            )
            # One spectator socket per subtree edge, all the way down
            # (asserted while paused — sockets tear down after `end`).
            wait_until(
                lambda: r1.health()["clients"] == 1
                and r2.health()["clients"] == 1
                and r3.health()["clients"] == 1,
                msg="one downstream per relay edge",
            )
            client.resume("alice")
            threads = [
                threading.Thread(target=d.drain) for d in (direct, leaf)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "drain wedged"
            for d in (direct, leaf):
                if d.error is not None:
                    raise d.error
            wait_status(client, "alice", ("completed",))

            assert leaf.hello is not None and leaf.hello.get("relay")
            assert leaf.hello.get("tenant") == "alice"
            # The bit-identity: both subscribers anchored at the same
            # paused turn, so the maps must agree on EVERYTHING —
            # including the one initial keyframe each.
            assert direct.keyframes == 1
            assert leaf.keyframes == 1
            assert leaf.by_turn() == direct.by_turn()
            assert leaf.turn == direct.turn == turns
            want = want_board(final_board(client, "alice", size))
            assert np.array_equal(direct.buf, want)
            assert np.array_equal(leaf.buf, want)
            # Relay economics: every relay ingested each published
            # frame exactly once.
            n = len(leaf.frames)
            for r in (r1, r2, r3):
                assert r.health()["frames_in"] == n
        finally:
            for d in (direct, leaf):
                if d is not None:
                    d.close()
            for r in (r3, r2, r1):
                r.close()

    def test_256_clients_behind_two_relays_one_upstream_socket(
        self, pod
    ):
        """The fan-out economics pin: 256 viewers split across a
        chained relay pair cost the pod ONE spectator socket and 1.00
        fetches per published frame, and every viewer reconstructs
        bit-identically to the final board."""
        plane, gateway, client = pod
        size, turns, n_clients = 16, 300, 256
        submit_spec(client, "alice", spectate_spec(size, turns))
        pause_run(client, gateway, "alice")

        upstream = (
            f"{gateway.url}/v1/sessions/alice/frames?queue={turns + 8}"
        )
        r1 = make_relay(upstream, turns)
        r2 = make_relay(r1.url + "/v1/frames", turns)
        leaves: list[RawDrain] = []
        try:
            for r in (r1, r2):
                wait_until(
                    lambda r=r: r.health()["connected"],
                    msg=f"relay {r.url} connected",
                )
            reg = obs_metrics.REGISTRY
            fetches0 = reg.counter("frames.fetches").value
            publishes0 = reg.counter("frames.publishes").value
            # Sequential connects: socketserver's default accept
            # backlog is 5, so a thundering herd would need retries.
            for i in range(n_clients):
                r = r2 if i % 2 else r1
                leaves.append(
                    RawDrain(
                        r.host, r.port, f"/v1/frames?queue={turns + 8}"
                    )
                )
            # The whole tree still costs the pod ONE spectator socket.
            wait_until(
                lambda: r1.health()["clients"] == n_clients // 2 + 1
                and r2.health()["clients"] == n_clients // 2,
                msg="all leaves registered",
            )
            assert gateway._n_spectators == 1

            threads = [
                threading.Thread(target=d.drain) for d in leaves
            ]
            for t in threads:
                t.start()
            client.resume("alice")
            wait_status(client, "alice", ("completed",))
            for t in threads:
                t.join(timeout=180)
                assert not t.is_alive(), "leaf drain wedged"
            for d in leaves:
                if d.error is not None:
                    raise d.error

            # Pod economics, measured: fetches/frame == 1.00 for the
            # whole post-pause tail, and the relay ingested each
            # published frame exactly once.
            fetches = reg.counter("frames.fetches").value - fetches0
            publishes = (
                reg.counter("frames.publishes").value - publishes0
            )
            assert publishes > 0
            assert fetches == publishes, "fetches/frame != 1.00"
            assert r1.health()["frames_in"] == publishes
            # Egress amplification: the tree multiplied one upstream
            # stream into 256 client streams.
            assert (
                r1.health()["frames_out"] + r2.health()["frames_out"]
                >= n_clients * publishes
            )
            want = want_board(final_board(client, "alice", size))
            for d in leaves:
                assert d.turn == turns
                assert np.array_equal(d.buf, want)
        finally:
            for d in leaves:
                d.close()
            r2.close()
            r1.close()


# -- chaos ---------------------------------------------------------------------


class TestRelayChaos:
    def test_stalled_downstream_is_isolated(self, pod):
        """One viewer that attaches and reads NOTHING while the run
        completes: siblings stay on schedule with zero drops (exactly
        one keyframe, contiguous turns), the run finishes on time, and
        the stalled viewer re-anchors from the relay's cache —
        observed as >=2 keyframes on its wire — still converging to
        the final board."""
        plane, gateway, client = pod
        size, turns = 64, 150
        submit_spec(client, "alice", spectate_spec(size, turns))
        upstream = (
            f"{gateway.url}/v1/sessions/alice/frames?queue={turns + 8}"
        )
        r1 = make_relay(upstream, turns)
        siblings: list[RawDrain] = []
        stalled = None
        try:
            wait_until(
                lambda: r1.health()["connected"], msg="relay connected"
            )
            siblings = [
                RawDrain(
                    r1.host, r1.port, f"/v1/frames?queue={turns + 8}"
                )
                for _ in range(2)
            ]
            threads = [
                threading.Thread(target=d.drain) for d in siblings
            ]
            for t in threads:
                t.start()
            # The stall, deterministically: a pinned 4 KiB receive
            # buffer against the relay's bounded SO_SNDBUF wedges the
            # socket after a handful of keyframe-sized writes, and the
            # depth-2 queue must drop-oldest long before the run ends.
            stalled = RawDrain(
                r1.host, r1.port, "/v1/frames?queue=2",
                recv_buffer=4096,
            )
            st = wait_status(client, "alice", ("completed",))
            assert st["turn"] == turns, "stalled viewer wedged the run"
            for t in threads:
                t.join(timeout=60)
                assert not t.is_alive(), "sibling drain wedged"
            for d in siblings:
                if d.error is not None:
                    raise d.error

            want = want_board(final_board(client, "alice", size))
            for d in siblings:
                # On schedule, no drops: one keyframe, then every
                # turn in order.
                assert d.keyframes == 1
                seen = [turn for turn, _, _ in d.frames]
                assert seen == list(range(seen[0], turns + 1))
                assert np.array_equal(d.buf, want)

            # The stalled viewer finally drains: it lost frames
            # (drop-oldest), re-anchored via the cache resync
            # keyframe, and still converges.
            stalled.drain(timeout=60)
            if stalled.error is not None:
                raise stalled.error
            assert stalled.keyframes >= 2, "no re-keyframe on the wire"
            assert stalled.turn == turns
            assert np.array_equal(stalled.buf, want)
            health = r1.health()
            assert health["drops"] > 0
            assert health["cache_serves"] > 0
        finally:
            if stalled is not None:
                stalled.close()
            for d in siblings:
                d.close()
            r1.close()

    def test_upstream_kill_resubscribes_and_rekeyframes(self, pod):
        """Kill the MIDDLE of a gateway → r1 → r2 chain: r2's
        capped-backoff resubscribe finds the replacement relay on the
        same port, and the replacement's first keyframe — relayed
        verbatim — re-keyframes r2's whole subtree (the leaf observes
        a second FrameReady and still converges)."""
        plane, gateway, client = pod
        size, turns = 32, 400
        submit_spec(client, "alice", spectate_spec(size, turns))
        pause_run(client, gateway, "alice")

        upstream = (
            f"{gateway.url}/v1/sessions/alice/frames?queue={turns + 8}"
        )
        r1 = make_relay(upstream, turns)
        r2 = make_relay(r1.url + "/v1/frames", turns)
        r1b = leaf = None
        try:
            for r in (r1, r2):
                wait_until(
                    lambda r=r: r.health()["connected"],
                    msg=f"relay {r.url} connected",
                )
            leaf = RawDrain(
                r2.host, r2.port, f"/v1/frames?queue={turns + 8}"
            )
            client.resume("alice")
            # Let frames flow through the intact chain first.
            while len(leaf.frames) < 5:
                assert leaf.step(timeout=60)
            assert leaf.keyframes == 1
            turn_before_kill = leaf.turn

            # Quiesce, then kill r1 and rebind a replacement on the
            # SAME port (what a supervisor restart looks like to r2).
            pause_run(client, gateway, "alice")
            old_port = r1.port
            r1.close()
            r1b = make_relay(upstream, turns, port=old_port)
            wait_until(
                lambda: r2.health()["resubscribes"] >= 1
                and r2.health()["connected"],
                msg="r2 resubscribed to the replacement",
            )
            wait_until(
                lambda: r1b.health()["connected"],
                msg="replacement relay connected upstream",
            )

            client.resume("alice")
            leaf.drain(timeout=120)
            if leaf.error is not None:
                raise leaf.error
            # The seq-gap re-keyframe, observed at the leaf: a SECOND
            # FrameReady, later in the run than everything before the
            # kill, then contiguous deltas to the end.
            assert leaf.keyframes >= 2
            rekey_turns = [
                turn for turn, kind, _ in leaf.frames
                if kind == "keyframe"
            ]
            assert rekey_turns[-1] > turn_before_kill
            tail = [
                turn for turn, _, _ in leaf.frames
                if turn >= rekey_turns[-1]
            ]
            assert tail == list(range(rekey_turns[-1], turns + 1))
            assert leaf.turn == turns
            want = want_board(final_board(client, "alice", size))
            assert np.array_equal(leaf.buf, want)
        finally:
            if leaf is not None:
                leaf.close()
            for r in (r2, r1b, r1):
                if r is not None:
                    r.close()

    def test_gap_deltas_refused_until_keyframe(self):
        """The seq-gap latch, pinned at the ingest seam: a delta with
        no contiguous anchor is dropped (counted, never relayed); the
        next keyframe re-anchors, after which deltas relay verbatim."""
        rng = np.random.default_rng(3)
        prev = (rng.random((8, 8)) < 0.4).astype(np.uint8) * 255
        new = prev.copy()
        new[2, :] ^= 255
        kb = wire.encode_frame_event(
            FrameReady(3, prev, rect=(0, 0, 8, 8))
        )
        db = wire.encode_frame_event(
            FrameDelta(
                4, bands=frames_lib.delta_bands(prev, new),
                rect=(0, 0, 8, 8),
            )
        )
        # Port 9 (discard) refuses instantly: the upstream loop spins
        # harmlessly while the test feeds the ingest seam directly.
        r = RelayServer("http://127.0.0.1:9/v1/frames", **TIGHT)
        leaf = None
        try:
            leaf = RawDrain(r.host, r.port, "/v1/frames?queue=8")
            r._ingest(db)  # pre-anchor: refused
            assert r.health()["drops"] == 1
            assert not r.health()["cache"]["anchored"]
            r._ingest(kb)
            r._ingest(db)
            r._on_text(json.dumps({"type": "end"}).encode())
            leaf.drain(timeout=30)
            if leaf.error is not None:
                raise leaf.error
            # The refused delta never reached the wire; the relayed
            # pair is verbatim.
            assert [
                (turn, kind) for turn, kind, _ in leaf.frames
            ] == [(3, "keyframe"), (4, "delta")]
            assert leaf.frames[0][2] == kb
            assert leaf.frames[1][2] == db
            assert np.array_equal(leaf.buf, new)
            health = r.health()
            assert health["frames_in"] == 3
            assert health["cache"] == {
                "anchored": True, "keyframe_turn": 3, "deltas": 1,
            }
        finally:
            if leaf is not None:
                leaf.close()
            r.close()

    def test_late_joiner_served_from_cache_zero_upstream(self, pod):
        """A viewer joining AFTER the session ended is served the
        whole stream from the relay cache: first event a keyframe,
        zero new upstream frames, the pod's fetch counter untouched —
        and a small-cache relay serves the same board off its
        COMPACTED synthesized keyframe."""
        plane, gateway, client = pod
        size, turns = 32, 300
        submit_spec(client, "alice", spectate_spec(size, turns))
        pause_run(client, gateway, "alice")
        upstream = (
            f"{gateway.url}/v1/sessions/alice/frames?queue={turns + 8}"
        )
        r_full = make_relay(upstream, turns)
        r_small = make_relay(upstream, turns, cache_deltas=8)
        late = late_small = None
        try:
            for r in (r_full, r_small):
                wait_until(
                    lambda r=r: r.health()["connected"],
                    msg=f"relay {r.url} connected",
                )
            client.resume("alice")
            wait_status(client, "alice", ("completed",))
            for r in (r_full, r_small):
                wait_until(
                    lambda r=r: r.health()["ended"],
                    msg="end propagated to the relay",
                )
            want = want_board(final_board(client, "alice", size))
            reg = obs_metrics.REGISTRY
            fetches0 = reg.counter("frames.fetches").value
            frames_in0 = r_full.health()["frames_in"]
            serves0 = r_full.health()["cache_serves"]

            late = RawDrain(r_full.host, r_full.port, "/v1/frames")
            late.drain(timeout=60)
            if late.error is not None:
                raise late.error
            assert late.frames[0][1] == "keyframe"
            assert late.turn == turns
            assert np.array_equal(late.buf, want)
            # Zero upstream round trips: no new relay ingests, no new
            # pod fetches — every frame came off the cache.
            health = r_full.health()
            assert health["frames_in"] == frames_in0
            assert (
                health["cache_serves"] - serves0 == len(late.frames)
            )
            assert reg.counter("frames.fetches").value == fetches0

            # The compaction path: the small cache folded its tail
            # into a synthesized keyframe and still serves a correct
            # board in <= 1 + cache_deltas frames.
            assert r_small.health()["cache"]["deltas"] <= 8
            late_small = RawDrain(
                r_small.host, r_small.port, "/v1/frames"
            )
            late_small.drain(timeout=60)
            if late_small.error is not None:
                raise late_small.error
            assert late_small.frames[0][1] == "keyframe"
            assert len(late_small.frames) <= 9
            assert late_small.turn == turns
            assert np.array_equal(late_small.buf, want)
            assert reg.counter("frames.fetches").value == fetches0
        finally:
            for d in (late, late_small):
                if d is not None:
                    d.close()
            r_small.close()
            r_full.close()


# -- hot-path pins -------------------------------------------------------------


class TestHotPathPins:
    def test_relay_encodes_each_frame_once_for_any_client_count(
        self, pod, monkeypatch
    ):
        """The single-serialize/multi-write pin: with 3 viewers
        attached, ``encode_server_frame`` runs exactly once per
        upstream frame (spied), while ``frames_out`` shows each of
        those encodes written 3 times."""
        plane, gateway, client = pod
        size, turns = 16, 200
        submit_spec(client, "alice", spectate_spec(size, turns))
        pause_run(client, gateway, "alice")

        calls = {"binary": 0}
        count_lock = threading.Lock()
        real = ws_lib.encode_server_frame

        def spy(opcode, payload):
            if opcode == ws_lib.OP_BINARY:
                with count_lock:
                    calls["binary"] += 1
            return real(opcode, payload)

        monkeypatch.setattr(ws_lib, "encode_server_frame", spy)
        upstream = (
            f"{gateway.url}/v1/sessions/alice/frames?queue={turns + 8}"
        )
        r1 = make_relay(upstream, turns)
        leaves = []
        try:
            wait_until(
                lambda: r1.health()["connected"], msg="relay connected"
            )
            leaves = [
                RawDrain(
                    r1.host, r1.port, f"/v1/frames?queue={turns + 8}"
                )
                for _ in range(3)
            ]
            threads = [
                threading.Thread(target=d.drain) for d in leaves
            ]
            for t in threads:
                t.start()
            client.resume("alice")
            wait_status(client, "alice", ("completed",))
            for t in threads:
                t.join(timeout=120)
                assert not t.is_alive(), "leaf drain wedged"
            for d in leaves:
                if d.error is not None:
                    raise d.error
            health = r1.health()
            assert health["frames_in"] > 0
            # ONE binary encode per upstream frame — not one per
            # (frame, client) pair.
            assert calls["binary"] == health["frames_in"]
            assert health["frames_out"] == 3 * health["frames_in"]
            for d in leaves:
                assert len(d.frames) == health["frames_in"]
        finally:
            for d in leaves:
                d.close()
            r1.close()

    def test_frame_plane_one_delta_encode_per_distinct_rect(
        self, monkeypatch
    ):
        """The satellite-1 dedup pin: N same-rect subscribers share
        ONE ``delta_bands`` call per publish (and the very bands
        object), so a publish costs one encode per DISTINCT rect."""
        calls = {"n": 0}
        real = frames_lib.delta_bands

        def spy(prev, new, *a, **kw):
            calls["n"] += 1
            return real(prev, new, *a, **kw)

        monkeypatch.setattr(frames_lib, "delta_bands", spy)
        h = w = 32
        rng = np.random.default_rng(9)
        board = (rng.random((h, w)) < 0.4).astype(np.uint8) * 255

        def fetch(rect):
            y0, x0, vh, vw = rect
            rows = (np.arange(vh) + y0) % h
            cols = (np.arange(vw) + x0) % w
            return board[rows[:, None], cols[None, :]]

        hub = FramePlane(board_shape=(h, w), metrics=False)
        same = [hub.subscribe((0, 0, 16, 16)) for _ in range(5)]
        others = [
            hub.subscribe((8, 8, 8, 8)), hub.subscribe((4, 4, 12, 12))
        ]
        hub.publish(1, fetch)
        assert calls["n"] == 0  # everyone keyframes first

        board[3, :] ^= 255
        hub.publish(2, fetch)
        assert calls["n"] == 3  # one encode per DISTINCT rect
        board[9, :] ^= 255
        hub.publish(3, fetch)
        assert calls["n"] == 6

        # The shared encode is the SAME bands object across same-rect
        # subscribers, and every stream still reconstructs exactly.
        deltas = []
        for sub in same:
            evs = []
            while not sub.events.empty():
                evs.append(sub.events.get_nowait())
            assert [type(e) for e in evs] == [
                FrameReady, FrameDelta, FrameDelta
            ]
            deltas.append(evs[1].bands)
            buf = np.array(evs[0].frame, np.uint8, copy=True)
            frames_lib.apply_bands(buf, evs[1].bands)
            frames_lib.apply_bands(buf, evs[2].bands)
            assert np.array_equal(buf, fetch((0, 0, 16, 16)))
        assert all(b is deltas[0] for b in deltas[1:])
        for sub in others:
            buf = sub.reconstruct()
            assert np.array_equal(buf, fetch(sub.rect))

    def test_ws_codec_byte_for_byte_vs_reference_framer(
        self, monkeypatch
    ):
        """The satellite-2 regression pin: the in-place mask/unmask +
        readinto rewrite emits EXACTLY the bytes of a naive RFC 6455
        framer, across every length-field regime, masked and
        unmasked — and ``encode_server_frame`` matches the server
        endpoint's ``_send`` verbatim."""
        import struct as struct_mod

        def reference_frame(opcode, payload, key=None):
            head = bytearray([0x80 | opcode])
            mask_bit = 0x80 if key is not None else 0
            n = len(payload)
            if n < 126:
                head.append(mask_bit | n)
            elif n < 1 << 16:
                head.append(mask_bit | 126)
                head += struct_mod.pack(">H", n)
            else:
                head.append(mask_bit | 127)
                head += struct_mod.pack(">Q", n)
            if key is None:
                return bytes(head) + bytes(payload)
            body = bytes(
                b ^ key[i % 4] for i, b in enumerate(payload)
            )
            return bytes(head) + bytes(key) + body

        rng = np.random.default_rng(17)
        sizes = [0, 1, 125, 126, 4096, 65535, 65536, 70001]
        payloads = [bytes(rng.integers(0, 256, n, np.uint8))
                    for n in sizes]

        # Server (unmasked) endpoint: _send == encode_server_frame ==
        # the reference, for every size regime.
        for payload in payloads:
            out = io.BytesIO()
            wsock = ws_lib.WebSocket(io.BytesIO(), out, mask=False)
            wsock.send_binary(payload)
            wire_bytes = out.getvalue()
            assert wire_bytes == reference_frame(
                ws_lib.OP_BINARY, payload
            )
            assert wire_bytes == ws_lib.encode_server_frame(
                ws_lib.OP_BINARY, payload
            )

        # Client (masked) endpoint, deterministic key: byte-for-byte
        # the reference masked frame — and the caller's buffer is NOT
        # scrambled by the in-place mask (it masks a copy).
        key = b"\xa1\x07\x5c\xf3"
        monkeypatch.setattr(ws_lib.os, "urandom",
                            lambda n: (key * 8)[:n])
        for payload in payloads:
            keep = bytearray(payload)
            out = io.BytesIO()
            wsock = ws_lib.WebSocket(io.BytesIO(), out, mask=True)
            wsock.send_binary(keep)
            assert out.getvalue() == reference_frame(
                ws_lib.OP_BINARY, payload, key=key
            )
            assert bytes(keep) == payload, "caller buffer scrambled"

        # The in-place bytearray contract: same object back, involutive.
        data = bytearray(payloads[4])
        ret = ws_lib._mask(data, key)
        assert ret is data
        assert bytes(data) != payloads[4]
        assert bytes(ws_lib._mask(data, key)) == payloads[4]
        # bytes stay immutable-in, fresh-out.
        frozen = payloads[4]
        masked = ws_lib._mask(frozen, key)
        assert isinstance(masked, bytes) and frozen == payloads[4]
        assert ws_lib._mask(masked, key) == frozen

        # Round-trip through the receive path (readinto + in-place
        # unmask): a masked reference frame decodes to the payload.
        for payload in payloads:
            raw = reference_frame(ws_lib.OP_BINARY, payload, key=key)
            wsock = ws_lib.WebSocket(
                io.BytesIO(raw), io.BytesIO(), mask=False
            )
            op, got = wsock.recv()
            assert op == ws_lib.OP_BINARY
            assert bytes(got) == payload
